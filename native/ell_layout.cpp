// Native ELL layout builder — the host hot path of every mixed/sparse
// linear fit (ops/ell_scatter.py::ell_layout).
//
// The numpy builder costs ~1.2 us/slot (argsort + two searchsorted
// passes + np.add.at + large temporaries): ~32 s for the default
// product fit's 26M slots — about as long as the training itself.  The
// layout is a counting-sort problem: indices live in [0, rows*128), so
// one count pass + one placement pass per step does everything in O(n)
// with no sort.  Semantics exactly mirror _ell_one_step:
//   - sentinel indices (>= rows*128, streaming pad rows) drop out
//   - a slot's pos = its stable rank among ALL of its table row's slots
//   - heavy = run length (== index count) > heavy_threshold; whole run
//     leaves the grid for the (H, batch) count/value-sum matrix
//   - keep = pos < 128 && !heavy; the rest spill to the overflow list
//     in sorted order
//   - P[row, lane] = (inclusive count of kept slots with lane' <= lane)
//     - 1, clamped at 0, mask = count > 0
//
// Capacity protocol: the caller passes ovf_cap/heavy_cap and
// preallocated outputs; per-step needs are always written to
// need_ovf/need_heavy.  Returns 0 on success, 1 when any step's needs
// exceed a cap (outputs are then partial garbage — the caller re-calls
// with caps >= the returned needs).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>
#include <algorithm>

namespace {

struct Spill {
  int64_t sorted_pos;
  int32_t idx;
  int32_t src;
  float val;
};

}  // namespace

extern "C" {

// flat: (steps*batch*nnz) int32; values: same shape float32 or nullptr.
// Outputs (caller-allocated, row-major):
//   src  (steps, rows, 128) int32     pos (steps, rows, 128) int32
//   mask (steps, rows, 128) float32   val (steps, rows, 128) f32 | null
//   ovf_idx/ovf_src (steps, ovf_cap) int32, ovf_val f32 | null
//   heavy_idx (steps, heavy_cap) int32
//   heavy_cnt (steps, heavy_cap, batch) int16 without values, f32 with
//   need_ovf/need_heavy (steps,) int32
static void build_steps(const int32_t* flat, const float* values,
                        int64_t s_begin, int64_t s_end,
                        int64_t batch, int64_t nnz, int64_t rows,
                        int64_t heavy_threshold, int64_t ovf_cap,
                        int64_t heavy_cap,
                        int32_t* src, int32_t* pos, float* mask, float* val,
                        int32_t* ovf_idx, int32_t* ovf_src, float* ovf_val,
                        int32_t* heavy_idx, void* heavy_cnt,
                        int32_t* need_ovf, int32_t* need_heavy,
                        std::atomic<int>* rc_out) {
  const int64_t d = rows * 128;
  const int64_t n = batch * nnz;
  const int64_t grid = rows * 128;
  std::vector<int32_t> cnt(d), offs(d);
  std::vector<int32_t> hist(grid);
  std::vector<int64_t> row_start(rows);
  std::vector<Spill> spills;
  std::vector<int32_t> hvec;
  std::vector<Spill> heavy_slots;

  for (int64_t s = s_begin; s < s_end; ++s) {
    const int32_t* f = flat + s * n;
    const float* fv = values ? values + s * n : nullptr;
    std::memset(cnt.data(), 0, d * sizeof(int32_t));
    for (int64_t i = 0; i < n; ++i) {
      int32_t idx = f[i];
      if (idx >= 0 && idx < d) cnt[idx]++;
    }
    // exclusive prefix; also remember each table row's first sorted slot
    int64_t run = 0;
    for (int64_t r = 0; r < rows; ++r) {
      row_start[r] = run;
      const int64_t base = r << 7;
      for (int64_t l = 0; l < 128; ++l) {
        offs[base + l] = static_cast<int32_t>(run);
        run += cnt[base + l];
      }
    }

    int32_t* src_s = src + s * grid;
    int32_t* pos_s = pos + s * grid;
    float* mask_s = mask + s * grid;
    float* val_s = val ? val + s * grid : nullptr;
    for (int64_t i = 0; i < grid; ++i) src_s[i] = static_cast<int32_t>(batch);
    if (val_s) std::memset(val_s, 0, grid * sizeof(float));
    std::memset(hist.data(), 0, grid * sizeof(int32_t));
    spills.clear();
    hvec.clear();
    heavy_slots.clear();

    // stable placement in original order; offs[idx] walks the sorted
    // position of each slot without materializing the sorted array
    for (int64_t i = 0; i < n; ++i) {
      const int32_t idx = f[i];
      if (idx < 0 || idx >= d) continue;   // sentinel / padding row
      const int32_t b = static_cast<int32_t>(i / nnz);
      const int64_t p = offs[idx]++;
      const int64_t r = idx >> 7;
      const bool heavy = cnt[idx] > heavy_threshold;
      if (heavy) {
        bool seen = false;
        for (int32_t h : hvec) {
          if (h == idx) { seen = true; break; }
        }
        if (!seen) hvec.push_back(idx);
        heavy_slots.push_back({p, idx, b, fv ? fv[i] : 0.0f});
        continue;
      }
      const int64_t rank = p - row_start[r];
      if (rank < 128) {
        src_s[(r << 7) + rank] = b;
        if (val_s) val_s[(r << 7) + rank] = fv[i];
        hist[(r << 7) + (idx & 127)]++;
      } else {
        spills.push_back({p, idx, b, fv ? fv[i] : 0.0f});
      }
    }

    // P / mask from the kept-slot histogram
    for (int64_t r = 0; r < rows; ++r) {
      int32_t acc = 0;
      const int64_t base = r << 7;
      for (int64_t l = 0; l < 128; ++l) {
        acc += hist[base + l];
        const int32_t p_incl = acc - 1;
        mask_s[base + l] = p_incl >= 0 ? 1.0f : 0.0f;
        pos_s[base + l] = p_incl >= 0 ? p_incl : 0;
      }
    }

    // overflow list, in sorted order (parity with the numpy builder)
    need_ovf[s] = static_cast<int32_t>(spills.size());
    need_heavy[s] = static_cast<int32_t>(hvec.size());
    if (static_cast<int64_t>(spills.size()) > ovf_cap ||
        static_cast<int64_t>(hvec.size()) > heavy_cap) {
      rc_out->store(1);
      continue;  // still fill remaining steps' needs
    }
    std::sort(spills.begin(), spills.end(),
              [](const Spill& a, const Spill& b) {
                return a.sorted_pos < b.sorted_pos;
              });
    int32_t* oi = ovf_idx + s * ovf_cap;
    int32_t* os = ovf_src + s * ovf_cap;
    float* ov = ovf_val ? ovf_val + s * ovf_cap : nullptr;
    for (int64_t i = 0; i < ovf_cap; ++i) {
      oi[i] = 0;
      os[i] = static_cast<int32_t>(batch);
      if (ov) ov[i] = 0.0f;
    }
    for (size_t i = 0; i < spills.size(); ++i) {
      oi[i] = spills[i].idx;
      os[i] = spills[i].src;
      if (ov) ov[i] = spills[i].val;
    }

    // heavy: unique sorted indices + per-source count/value-sum matrix
    std::sort(hvec.begin(), hvec.end());
    int32_t* hi = heavy_idx + s * heavy_cap;
    for (int64_t i = 0; i < heavy_cap; ++i) hi[i] = 0;
    for (size_t i = 0; i < hvec.size(); ++i) hi[i] = hvec[i];
    if (values) {
      float* hc = static_cast<float*>(heavy_cnt) + s * heavy_cap * batch;
      std::memset(hc, 0, heavy_cap * batch * sizeof(float));
      for (const Spill& hs : heavy_slots) {
        const int64_t rank =
            std::lower_bound(hvec.begin(), hvec.end(), hs.idx) - hvec.begin();
        hc[rank * batch + hs.src] += hs.val;
      }
    } else {
      int16_t* hc = static_cast<int16_t*>(heavy_cnt) + s * heavy_cap * batch;
      std::memset(hc, 0, heavy_cap * batch * sizeof(int16_t));
      for (const Spill& hs : heavy_slots) {
        const int64_t rank =
            std::lower_bound(hvec.begin(), hvec.end(), hs.idx) - hvec.begin();
        hc[rank * batch + hs.src] += 1;
      }
    }
  }
}

// Entry point: steps are independent (disjoint output slices), so they
// split across hardware threads, each with its own ~9 MB scratch.  On
// the 1-core bench host this degenerates to the serial loop.
int ell_build(const int32_t* flat, const float* values,
              int64_t steps, int64_t batch, int64_t nnz, int64_t rows,
              int64_t heavy_threshold, int64_t ovf_cap, int64_t heavy_cap,
              int32_t* src, int32_t* pos, float* mask, float* val,
              int32_t* ovf_idx, int32_t* ovf_src, float* ovf_val,
              int32_t* heavy_idx, void* heavy_cnt,
              int32_t* need_ovf, int32_t* need_heavy) {
  std::atomic<int> rc(0);
  int64_t hw = static_cast<int64_t>(std::thread::hardware_concurrency());
  if (hw < 1) hw = 1;
  const int64_t n_threads = std::min<int64_t>(std::min<int64_t>(hw, 8),
                                              steps);
  if (n_threads <= 1) {
    build_steps(flat, values, 0, steps, batch, nnz, rows, heavy_threshold,
                ovf_cap, heavy_cap, src, pos, mask, val, ovf_idx, ovf_src,
                ovf_val, heavy_idx, heavy_cnt, need_ovf, need_heavy, &rc);
    return rc.load();
  }
  std::vector<std::thread> pool;
  const int64_t per = (steps + n_threads - 1) / n_threads;
  for (int64_t t = 0; t < n_threads; ++t) {
    const int64_t b = t * per;
    const int64_t e = std::min(steps, b + per);
    if (b >= e) break;
    pool.emplace_back(build_steps, flat, values, b, e, batch, nnz, rows,
                      heavy_threshold, ovf_cap, heavy_cap, src, pos, mask,
                      val, ovf_idx, ovf_src, ovf_val, heavy_idx, heavy_cnt,
                      need_ovf, need_heavy, &rc);
  }
  for (auto& th : pool) th.join();
  return rc.load();
}

}  // extern "C"
