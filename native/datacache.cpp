// Native IO core for the host-side epoch data cache.
//
// TPU-native replacement for the reference's spill-to-disk record log
// (flink-ml-iteration datacache/nonkeyed/DataCacheWriter.java:36-145,
// DataCacheReader.java:35-139).  The reference streams serialized records
// through the JVM; here segments are raw columnar byte ranges and the native
// layer provides:
//   - dc_write / dc_read: positioned bulk IO (pread/pwrite loops)
//   - dc_prefetch: posix_fadvise(WILLNEED) readahead so the NEXT epoch batch
//     is in page cache while the device computes the current one (the
//     double-buffering that keeps the TPU fed without host stalls)
//   - a background prefetch thread pool so prefetch calls return immediately
//
// Built as a plain shared library, bound from Python via ctypes (no pybind11
// in this image).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

extern "C" {

// Positioned read: returns bytes read, or -1 on error.
int64_t dc_read(const char* path, int64_t offset, int64_t nbytes, void* out) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  int64_t done = 0;
  char* dst = static_cast<char*>(out);
  while (done < nbytes) {
    ssize_t n = ::pread(fd, dst + done, nbytes - done, offset + done);
    if (n < 0) { ::close(fd); return -1; }
    if (n == 0) break;  // EOF
    done += n;
  }
  ::close(fd);
  return done;
}

// Positioned/appending write: returns bytes written, or -1 on error.
int64_t dc_write(const char* path, const void* buf, int64_t nbytes,
                 int append) {
  int flags = O_WRONLY | O_CREAT | (append ? O_APPEND : O_TRUNC);
  int fd = ::open(path, flags, 0644);
  if (fd < 0) return -1;
  int64_t done = 0;
  const char* src = static_cast<const char*>(buf);
  while (done < nbytes) {
    ssize_t n = ::write(fd, src + done, nbytes - done);
    if (n < 0) { ::close(fd); return -1; }
    done += n;
  }
  ::close(fd);
  return done;
}

int64_t dc_file_size(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  off_t size = ::lseek(fd, 0, SEEK_END);
  ::close(fd);
  return static_cast<int64_t>(size);
}

// ---------------------------------------------------------------------------
// Async prefetch pool
// ---------------------------------------------------------------------------

namespace {

struct PrefetchTask {
  std::string path;
  int64_t offset;
  int64_t nbytes;
};

class PrefetchPool {
 public:
  PrefetchPool() : stop_(false), pending_(0) {
    for (int i = 0; i < 2; ++i) {
      workers_.emplace_back([this] { Run(); });
    }
  }

  ~PrefetchPool() {
    {
      std::lock_guard<std::mutex> lock(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }

  void Enqueue(PrefetchTask task) {
    {
      std::lock_guard<std::mutex> lock(mu_);
      queue_.push_back(std::move(task));
      ++pending_;
    }
    cv_.notify_one();
  }

  int64_t Pending() { return pending_.load(); }

  void Drain() {
    std::unique_lock<std::mutex> lock(mu_);
    drained_.wait(lock, [this] { return pending_.load() == 0; });
  }

 private:
  void Run() {
    for (;;) {
      PrefetchTask task;
      {
        std::unique_lock<std::mutex> lock(mu_);
        cv_.wait(lock, [this] { return stop_ || !queue_.empty(); });
        if (stop_ && queue_.empty()) return;
        task = std::move(queue_.front());
        queue_.pop_front();
      }
      int fd = ::open(task.path.c_str(), O_RDONLY);
      if (fd >= 0) {
#ifdef POSIX_FADV_WILLNEED
        ::posix_fadvise(fd, task.offset, task.nbytes, POSIX_FADV_WILLNEED);
#endif
        // Touch the range to force it into page cache even on filesystems
        // that ignore fadvise; 1MB stride keeps syscall count low.
        static thread_local std::vector<char> scratch(1 << 20);
        int64_t done = 0;
        while (done < task.nbytes) {
          ssize_t n = ::pread(fd, scratch.data(),
                              std::min<int64_t>(scratch.size(),
                                                task.nbytes - done),
                              task.offset + done);
          if (n <= 0) break;
          done += n;
        }
        ::close(fd);
      }
      if (--pending_ == 0) {
        std::lock_guard<std::mutex> lock(mu_);
        drained_.notify_all();
      }
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable drained_;
  std::deque<PrefetchTask> queue_;
  std::vector<std::thread> workers_;
  bool stop_;
  std::atomic<int64_t> pending_;
};

PrefetchPool* pool() {
  static PrefetchPool* p = new PrefetchPool();
  return p;
}

}  // namespace

// Enqueue background readahead of [offset, offset+nbytes) of path.
void dc_prefetch(const char* path, int64_t offset, int64_t nbytes) {
  pool()->Enqueue(PrefetchTask{std::string(path), offset, nbytes});
}

int64_t dc_prefetch_pending() { return pool()->Pending(); }

void dc_prefetch_drain() { pool()->Drain(); }

}  // extern "C"
