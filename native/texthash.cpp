// Native batch text hashing for host-side featurization.
//
// The hashing trick (HashingTF / FeatureHasher, mirroring the Flink ML 2.x
// feature surface) hashes every token with 64-bit FNV-1a.  In Python that
// inner loop runs per BYTE per token (~100 ns/byte); this library does the
// same arithmetic at native speed over one contiguated buffer:
//   - th_fnv1a_batch: hash n strings given (bytes, offsets)
//   - th_hashing_tf: the whole HashingTF document-term fill in one call
// The Python binding (flink_ml_tpu/utils/native_text.py) concatenates the
// tokens once and falls back to the pure-Python path when the library is
// unavailable.  Hash values are identical to models/feature/text.py::_fnv1a
// (64-bit wrap-around), so native and fallback outputs are bit-equal.

#include <cstdint>

extern "C" {

static inline uint64_t fnv1a(const uint8_t* data, int64_t len) {
  uint64_t h = 14695981039346656037ull;
  for (int64_t i = 0; i < len; ++i) {
    h = (h ^ data[i]) * 1099511628211ull;
  }
  return h;
}

// Hash n strings; string i occupies bytes [offsets[i], offsets[i+1]).
void th_fnv1a_batch(const uint8_t* bytes, const int64_t* offsets, int64_t n,
                    uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] = fnv1a(bytes + offsets[i], offsets[i + 1] - offsets[i]);
  }
}

// HashingTF fill: docs are consecutive runs of tokens — doc i holds
// doc_counts[i] tokens; token j (global index) occupies
// bytes [tok_offsets[j], tok_offsets[j+1]).  out is (n_docs, m) row-major,
// zero-initialized by the caller; binary != 0 marks presence instead of
// counting.
void th_hashing_tf(const uint8_t* bytes, const int64_t* tok_offsets,
                   const int64_t* doc_counts, int64_t n_docs, int64_t m,
                   int binary, double* out) {
  int64_t tok = 0;
  for (int64_t i = 0; i < n_docs; ++i) {
    double* row = out + i * m;
    for (int64_t t = 0; t < doc_counts[i]; ++t, ++tok) {
      uint64_t h = fnv1a(bytes + tok_offsets[tok],
                         tok_offsets[tok + 1] - tok_offsets[tok]);
      int64_t slot = static_cast<int64_t>(h % static_cast<uint64_t>(m));
      if (binary) {
        row[slot] = 1.0;
      } else {
        row[slot] += 1.0;
      }
    }
  }
}

}  // extern "C"
