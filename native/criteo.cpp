// Native parser for Criteo-format TSV (the BASELINE.md north-star input):
//
//   label \t I1 .. I13 \t C1 .. C26 \n
//
// where I* are small integers (possibly empty) and C* are 8-hex-char
// categorical tokens (possibly empty).  Each call consumes whole lines
// from a byte buffer and emits the framework's mixed layout directly:
// dense f32 (13 per row, missing -> 0), hashed categorical int32 (26 per
// row) and f32 labels.  Categorical hashing is 64-bit FNV-1a over
// "C{field}={token}" — the same function and salt convention as
// FeatureHasher (models/feature/text.py) — folded into
// [n_reserved, n_reserved + hash_space) so hashed slots can never alias
// the dense weight slots of the mixed layout.  Empty categorical fields
// hash the empty token (a per-field "missing" slot), matching the Python
// fallback parser bit for bit.
//
// Returns the number of rows parsed; *consumed gets the byte count of the
// whole lines consumed (callers carry the tail of a chunk into the next
// read).  Malformed lines (wrong field count) are skipped.
//
// Throughput design (the single-core rate IS the north-star ingest floor):
// the FNV chain is 4-5 cycles of xor+mul LATENCY per byte, so hashing one
// token at a time caps the parser near ~400 MB/s.  The 26 per-row chains
// are independent, so the hot path hashes them INTERLEAVED — scalar
// interleaving pipelines the multiplies (mul throughput is 1/cycle), and
// an AVX-512DQ variant (runtime-dispatched; vpmullq = 8 chains/vector
// with per-lane length masks) cuts it further.  Both produce bit-exact
// FNV-1a — same values as the Python twin, token at a time.

#include <array>
#include <cstdint>
#include <cstdio>
#include <cstring>

#if defined(__x86_64__)
#include <immintrin.h>
#endif

extern "C" {

static inline uint64_t fnv1a64(const uint8_t* data, int64_t len,
                               uint64_t h) {
  for (int64_t i = 0; i < len; ++i) {
    h = (h ^ data[i]) * 1099511628211ull;
  }
  return h;
}

static const uint64_t kFnvOffset = 14695981039346656037ull;
static const uint64_t kFnvPrime = 1099511628211ull;

// Hash the 26 categorical tokens of one row with the chains interleaved.
// Tokens are (start, len) pairs into buf; out[f] = FNV-1a(salt_f, token_f).
static inline void hash26_interleaved(const uint8_t* buf,
                                      const int64_t* starts,
                                      const int64_t* lens,
                                      const uint64_t* salts,
                                      uint64_t* out) {
  uint64_t h[26];
  int64_t maxlen = 0;
  for (int f = 0; f < 26; ++f) {
    h[f] = salts[f];
    if (lens[f] > maxlen) maxlen = lens[f];
  }
  for (int64_t j = 0; j < maxlen; ++j) {
    for (int f = 0; f < 26; ++f) {
      if (j < lens[f]) {
        h[f] = (h[f] ^ buf[starts[f] + j]) * kFnvPrime;
      }
    }
  }
  std::memcpy(out, h, sizeof(h));
}

#if defined(__x86_64__)
__attribute__((target("avx512f,avx512dq,avx512bw,avx512vl")))
static void hash26_avx512(const uint8_t* buf, const int64_t* starts,
                          const int64_t* lens, const uint64_t* salts,
                          uint64_t* out) {
  // 26 chains in 4 vectors of 8 lanes (last 6 lanes idle).  Token words
  // load 8 bytes at a time; per byte-round j, lanes with len <= j are
  // mask-frozen, so results are exact FNV-1a for any length mix.
  alignas(64) uint64_t w[32];    // current 8-byte window per field
  alignas(64) int64_t  l[32];
  alignas(64) uint64_t hs[32];
  int64_t maxlen = 0;
  for (int f = 0; f < 26; ++f) {
    l[f] = lens[f];
    hs[f] = salts[f];
    if (lens[f] > maxlen) maxlen = lens[f];
  }
  for (int f = 26; f < 32; ++f) { l[f] = 0; hs[f] = 0; w[f] = 0; }
  const __m512i prime = _mm512_set1_epi64(static_cast<long long>(kFnvPrime));
  const __m512i bytemask = _mm512_set1_epi64(0xFF);
  __m512i hv[4], lv[4];
  for (int g = 0; g < 4; ++g) {
    hv[g] = _mm512_load_si512(hs + 8 * g);
    lv[g] = _mm512_load_si512(l + 8 * g);
  }
  for (int64_t base = 0; base < maxlen; base += 8) {
    // refill 8-byte windows (unaligned safe loads; token data is inside
    // the line so reading 8 bytes from start+base can only run past the
    // token into the same buffer chunk — memcpy keeps it UB-free, and
    // lanes past len are mask-frozen anyway)
    for (int f = 0; f < 26; ++f) {
      w[f] = 0;
      int64_t m = lens[f] - base;
      if (m > 0) {
        std::memcpy(&w[f], buf + starts[f] + base, m > 8 ? 8 : m);
      }
    }
    __m512i wv[4];
    for (int g = 0; g < 4; ++g) wv[g] = _mm512_load_si512(w + 8 * g);
    const int64_t round_end = maxlen - base < 8 ? maxlen - base : 8;
    for (int64_t j = 0; j < round_end; ++j) {
      const __m512i jv = _mm512_set1_epi64(base + j);
      for (int g = 0; g < 4; ++g) {
        __mmask8 active = _mm512_cmpgt_epi64_mask(lv[g], jv);
        __m512i b = _mm512_and_si512(wv[g], bytemask);
        __m512i mixed = _mm512_mullo_epi64(
            _mm512_xor_si512(hv[g], b), prime);
        hv[g] = _mm512_mask_mov_epi64(hv[g], active, mixed);
        wv[g] = _mm512_srli_epi64(wv[g], 8);
      }
    }
  }
  alignas(64) uint64_t hout[32];
  for (int g = 0; g < 4; ++g) _mm512_store_si512(hout + 8 * g, hv[g]);
  std::memcpy(out, hout, 26 * sizeof(uint64_t));
}
#endif

typedef void (*hash26_fn)(const uint8_t*, const int64_t*, const int64_t*,
                          const uint64_t*, uint64_t*);

static hash26_fn pick_hash26() {
#if defined(__x86_64__)
  if (__builtin_cpu_supports("avx512f") &&
      __builtin_cpu_supports("avx512dq") &&
      __builtin_cpu_supports("avx512bw") &&
      __builtin_cpu_supports("avx512vl")) {
    return hash26_avx512;
  }
#endif
  return hash26_interleaved;
}

// Post-salt FNV states for "C1=".."C26=" — row-invariant, computed once
// (thread-safe C++11 static init) instead of 26 snprintf+FNV per row.
static std::array<uint64_t, 26> make_salts() {
  std::array<uint64_t, 26> salts;
  for (int f = 0; f < 26; ++f) {
    char salt[8];
    int n = std::snprintf(salt, sizeof(salt), "C%d=", f + 1);
    salts[f] = fnv1a64(reinterpret_cast<const uint8_t*>(salt), n,
                       kFnvOffset);
  }
  return salts;
}

// Emit one validated 40-field line into the output rows.  starts/lens
// index into buf; returns nothing (caller already checked nf == 40).
static inline void emit_row(const uint8_t* buf, const int64_t* starts,
                            const int64_t* lens, int64_t hash_space,
                            int64_t n_reserved, hash26_fn hash26,
                            const uint64_t* salts, int64_t row,
                            float* dense, int32_t* cat, float* label) {
  float* drow = dense + row * 13;
  int32_t* crow = cat + row * 26;
  // label
  label[row] = (lens[0] > 0 && buf[starts[0]] == '1') ? 1.0f : 0.0f;
  // 13 integer fields: optional '-', then digits only; anything else
  // (or > 18 digits, which would overflow int64) parses as 0 — the
  // Python twin replicates these exact rules
  for (int f = 0; f < 13; ++f) {
    int64_t s = starts[1 + f], len = lens[1 + f];
    if (len == 0) {
      drow[f] = 0.0f;
      continue;
    }
    bool neg = buf[s] == '-';
    int64_t ndig = len - (neg ? 1 : 0);
    int64_t v = 0;
    if (ndig >= 1 && ndig <= 18) {
      for (int64_t i = s + (neg ? 1 : 0); i < s + len; ++i) {
        if (buf[i] < '0' || buf[i] > '9') { v = 0; break; }
        v = v * 10 + (buf[i] - '0');
      }
    }
    // v == 0 emits +0.0 (not -0.0) for true bit parity with the twin
    drow[f] = v == 0 ? 0.0f
                     : (neg ? -static_cast<float>(v)
                            : static_cast<float>(v));
  }
  // 26 categorical fields: interleaved FNV-1a (see hash26_* above)
  uint64_t hashes[26];
  hash26(buf, starts + 14, lens + 14, salts, hashes);
  for (int f = 0; f < 26; ++f) {
    crow[f] = static_cast<int32_t>(
        n_reserved
        + static_cast<int64_t>(hashes[f]
                               % static_cast<uint64_t>(hash_space)));
  }
}

// Scalar delimiter walk (fallback; also the reference semantics).
static int64_t parse_scalar(const uint8_t* buf, int64_t nbytes,
                            int64_t max_rows, int64_t hash_space,
                            int64_t n_reserved, hash26_fn hash26,
                            const uint64_t* salts, float* dense,
                            int32_t* cat, float* label,
                            int64_t* consumed) {
  int64_t rows = 0;
  int64_t pos = 0;
  *consumed = 0;
  while (rows < max_rows) {
    const void* nl = std::memchr(buf + pos, '\n', nbytes - pos);
    if (nl == nullptr) break;  // partial line: leave for the next chunk
    const int64_t eol = static_cast<const uint8_t*>(nl) - buf;

    int64_t starts[40], lens[40];
    int nf = 0;
    int64_t fs = pos;
    for (int64_t i = pos; i < eol && nf < 40; ++i) {
      if (buf[i] == '\t') {
        starts[nf] = fs;
        lens[nf] = i - fs;
        ++nf;
        fs = i + 1;
      }
    }
    if (nf < 40) {  // final field ends at eol
      starts[nf] = fs;
      lens[nf] = eol - fs;
      ++nf;
      fs = eol + 1;
    }
    // exactly 40 fields: fs must have advanced past the final (eol)
    // terminator — a 41st field would leave fs <= eol and the line skips,
    // matching the Python twin's len(fields) == 40 check
    if (nf == 40 && fs == eol + 1) {
      emit_row(buf, starts, lens, hash_space, n_reserved, hash26, salts,
               rows, dense, cat, label);
      ++rows;
    }
    pos = eol + 1;
    *consumed = pos;
  }
  return rows;
}

#if defined(__x86_64__)
// Single-pass AVX2 walk: 32-byte blocks -> tab|newline bitmasks, fields
// closed per set bit (simdjson-style structural scan).  ~2x the scalar
// split on Criteo-shaped lines; output is byte-identical.
__attribute__((target("avx2")))
static int64_t parse_avx2(const uint8_t* buf, int64_t nbytes,
                          int64_t max_rows, int64_t hash_space,
                          int64_t n_reserved, hash26_fn hash26,
                          const uint64_t* salts, float* dense,
                          int32_t* cat, float* label,
                          int64_t* consumed) {
  const __m256i vtab = _mm256_set1_epi8('\t');
  const __m256i vnl = _mm256_set1_epi8('\n');
  int64_t rows = 0;
  *consumed = 0;
  int64_t starts[41], lens[41];
  int nf = 0;           // fields closed on the current line
  bool overflow = false;  // line had > 40 fields
  int64_t fs = 0;       // current field start
  for (int64_t base = 0; base < nbytes && rows < max_rows; base += 32) {
    uint32_t mask;
    if (base + 32 <= nbytes) {
      const __m256i v = _mm256_loadu_si256(
          reinterpret_cast<const __m256i*>(buf + base));
      mask = static_cast<uint32_t>(_mm256_movemask_epi8(
          _mm256_or_si256(_mm256_cmpeq_epi8(v, vtab),
                          _mm256_cmpeq_epi8(v, vnl))));
    } else {
      mask = 0;
      for (int64_t i = base; i < nbytes; ++i) {
        if (buf[i] == '\t' || buf[i] == '\n') mask |= 1u << (i - base);
      }
    }
    while (mask != 0 && rows < max_rows) {
      const int bit = __builtin_ctz(mask);
      mask &= mask - 1;
      const int64_t i = base + bit;
      if (buf[i] == '\t') {
        if (nf < 40) {
          starts[nf] = fs;
          lens[nf] = i - fs;
          ++nf;
        } else {
          overflow = true;
        }
        fs = i + 1;
      } else {  // newline: close the final field, maybe emit, reset
        if (nf < 40) {
          starts[nf] = fs;
          lens[nf] = i - fs;
          ++nf;
        } else {
          overflow = true;
        }
        if (nf == 40 && !overflow) {
          emit_row(buf, starts, lens, hash_space, n_reserved, hash26,
                   salts, rows, dense, cat, label);
          ++rows;
        }
        nf = 0;
        overflow = false;
        fs = i + 1;
        *consumed = i + 1;
      }
    }
  }
  return rows;
}
#endif

int64_t ct_parse(const uint8_t* buf, int64_t nbytes, int64_t max_rows,
                 int64_t hash_space, int64_t n_reserved,
                 float* dense, int32_t* cat, float* label,
                 int64_t* consumed) {
  static const std::array<uint64_t, 26> kSalts = make_salts();
  static const hash26_fn hash26 = pick_hash26();
#if defined(__x86_64__)
  static const bool use_avx2 = __builtin_cpu_supports("avx2");
  if (use_avx2) {
    return parse_avx2(buf, nbytes, max_rows, hash_space, n_reserved,
                      hash26, kSalts.data(), dense, cat, label, consumed);
  }
#endif
  return parse_scalar(buf, nbytes, max_rows, hash_space, n_reserved,
                      hash26, kSalts.data(), dense, cat, label, consumed);
}

}  // extern "C"
