// Native parser for Criteo-format TSV (the BASELINE.md north-star input):
//
//   label \t I1 .. I13 \t C1 .. C26 \n
//
// where I* are small integers (possibly empty) and C* are 8-hex-char
// categorical tokens (possibly empty).  Each call consumes whole lines
// from a byte buffer and emits the framework's mixed layout directly:
// dense f32 (13 per row, missing -> 0), hashed categorical int32 (26 per
// row) and f32 labels.  Categorical hashing is 64-bit FNV-1a over
// "C{field}={token}" — the same function and salt convention as
// FeatureHasher (models/feature/text.py) — folded into
// [n_reserved, n_reserved + hash_space) so hashed slots can never alias
// the dense weight slots of the mixed layout.  Empty categorical fields
// hash the empty token (a per-field "missing" slot), matching the Python
// fallback parser bit for bit.
//
// Returns the number of rows parsed; *consumed gets the byte count of the
// whole lines consumed (callers carry the tail of a chunk into the next
// read).  Malformed lines (wrong field count) are skipped.

#include <array>
#include <cstdint>
#include <cstdio>

extern "C" {

static inline uint64_t fnv1a64(const uint8_t* data, int64_t len,
                               uint64_t h) {
  for (int64_t i = 0; i < len; ++i) {
    h = (h ^ data[i]) * 1099511628211ull;
  }
  return h;
}

static const uint64_t kFnvOffset = 14695981039346656037ull;

// Post-salt FNV states for "C1=".."C26=" — row-invariant, computed once
// (thread-safe C++11 static init) instead of 26 snprintf+FNV per row.
static std::array<uint64_t, 26> make_salts() {
  std::array<uint64_t, 26> salts;
  for (int f = 0; f < 26; ++f) {
    char salt[8];
    int n = std::snprintf(salt, sizeof(salt), "C%d=", f + 1);
    salts[f] = fnv1a64(reinterpret_cast<const uint8_t*>(salt), n,
                       kFnvOffset);
  }
  return salts;
}

int64_t ct_parse(const uint8_t* buf, int64_t nbytes, int64_t max_rows,
                 int64_t hash_space, int64_t n_reserved,
                 float* dense, int32_t* cat, float* label,
                 int64_t* consumed) {
  int64_t rows = 0;
  int64_t pos = 0;
  *consumed = 0;
  while (rows < max_rows) {
    // find end of line
    int64_t eol = pos;
    while (eol < nbytes && buf[eol] != '\n') ++eol;
    if (eol >= nbytes) break;  // partial line: leave for the next chunk

    // split into 40 tab-separated fields
    int64_t starts[40], lens[40];
    int nf = 0;
    int64_t fs = pos;
    for (int64_t i = pos; i <= eol && nf < 40; ++i) {
      if (i == eol || buf[i] == '\t') {
        starts[nf] = fs;
        lens[nf] = i - fs;
        ++nf;
        fs = i + 1;
      }
    }
    int64_t line_end = eol + 1;
    // exactly 40 fields: fs must have advanced past the final (eol)
    // terminator — a 41st field would leave fs <= eol and the line skips,
    // matching the Python twin's len(fields) == 40 check
    if (nf == 40 && fs == eol + 1) {
      static const std::array<uint64_t, 26> kSalts = make_salts();
      float* drow = dense + rows * 13;
      int32_t* crow = cat + rows * 26;
      // label
      label[rows] = (lens[0] > 0 && buf[starts[0]] == '1') ? 1.0f : 0.0f;
      // 13 integer fields: optional '-', then digits only; anything else
      // (or > 18 digits, which would overflow int64) parses as 0 — the
      // Python twin replicates these exact rules
      for (int f = 0; f < 13; ++f) {
        int64_t s = starts[1 + f], len = lens[1 + f];
        if (len == 0) {
          drow[f] = 0.0f;
          continue;
        }
        bool neg = buf[s] == '-';
        int64_t ndig = len - (neg ? 1 : 0);
        int64_t v = 0;
        if (ndig >= 1 && ndig <= 18) {
          for (int64_t i = s + (neg ? 1 : 0); i < s + len; ++i) {
            if (buf[i] < '0' || buf[i] > '9') { v = 0; break; }
            v = v * 10 + (buf[i] - '0');
          }
        }
        // v == 0 emits +0.0 (not -0.0) for true bit parity with the twin
        drow[f] = v == 0 ? 0.0f
                         : (neg ? -static_cast<float>(v)
                                : static_cast<float>(v));
      }
      // 26 categorical fields: FNV-1a("C{field}=") continued over token
      for (int f = 0; f < 26; ++f) {
        uint64_t h = fnv1a64(buf + starts[14 + f], lens[14 + f],
                             kSalts[f]);
        crow[f] = static_cast<int32_t>(
            n_reserved
            + static_cast<int64_t>(h % static_cast<uint64_t>(hash_space)));
      }
      ++rows;
    }
    pos = line_end;
    *consumed = pos;
  }
  return rows;
}

}  // extern "C"
