"""Pluggable-by-name distance measures.

Mirror of ``flink-ml-api/.../distance/DistanceMeasure.java:27-43`` (registry
by name, ``distance(v1, v2)``) — extended with the **batched pairwise** form
``pairwise(points, centroids)`` which is what actually runs on the TPU: a
single MXU matmul per metric instead of a Python double loop.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax.numpy as jnp
import numpy as np

__all__ = ["DistanceMeasure", "register_distance_measure"]

_REGISTRY: Dict[str, "DistanceMeasure"] = {}


def register_distance_measure(name: str) -> Callable[[type], type]:
    def deco(cls: type) -> type:
        _REGISTRY[name] = cls()
        cls.name = name
        return cls
    return deco


class DistanceMeasure:
    """Base class; resolve with ``DistanceMeasure.get_instance(name)``
    (``DistanceMeasure.java:27-36``)."""

    name = "base"

    @staticmethod
    def get_instance(name: str) -> "DistanceMeasure":
        if name not in _REGISTRY:
            raise ValueError(
                f"distanceMeasure {name!r} is not supported; "
                f"available: {sorted(_REGISTRY)}")
        return _REGISTRY[name]

    # -- scalar form (API parity) ------------------------------------------
    def distance(self, v1, v2) -> float:
        a = np.asarray(getattr(v1, "values", v1), dtype=np.float64)
        b = np.asarray(getattr(v2, "values", v2), dtype=np.float64)
        return float(self.pairwise(a[None, :], b[None, :])[0, 0])

    # -- batched device form (the hot path) --------------------------------
    def pairwise(self, points, centroids):
        """``(n, d) x (k, d) -> (n, k)`` distance matrix.  Implementations are
        jnp-traceable so they inline into jitted estimator steps."""
        raise NotImplementedError

    # -- host float64 form --------------------------------------------------
    def pairwise_host64(self, points, centroids) -> np.ndarray:
        """Full-precision host pairwise matrix.  For consumers whose results
        are precision-critical (e.g. hierarchical merge ordering): the f32
        ||x||^2 - 2xy device expansion catastrophically cancels for data far
        from the origin."""
        raise NotImplementedError


@register_distance_measure("euclidean")
class EuclideanDistanceMeasure(DistanceMeasure):
    """``distance/EuclideanDistanceMeasure.java:36-44``.

    Pairwise form uses the ||x||² - 2x·c + ||c||² expansion: the cross term is
    one MXU matmul; relative ordering (what KMeans argmins over) is exact."""

    def pairwise(self, points, centroids):
        p2 = jnp.sum(points * points, axis=-1, keepdims=True)          # (n, 1)
        c2 = jnp.sum(centroids * centroids, axis=-1)[None, :]          # (1, k)
        cross = jnp.dot(points, centroids.T,
                        preferred_element_type=jnp.float32)            # (n, k)
        sq = jnp.maximum(p2 - 2.0 * cross + c2, 0.0)
        return jnp.sqrt(sq)

    def pairwise_host64(self, points, centroids) -> np.ndarray:
        p = np.asarray(points, np.float64)
        c = np.asarray(centroids, np.float64)
        # same expansion, but f64: cancellation error ~1e-16 relative, fine
        # for any practical coordinate magnitude
        sq = ((p * p).sum(1)[:, None] - 2.0 * (p @ c.T)
              + (c * c).sum(1)[None, :])
        return np.sqrt(np.maximum(sq, 0.0))


@register_distance_measure("cosine")
class CosineDistanceMeasure(DistanceMeasure):
    def pairwise(self, points, centroids):
        pn = points / (jnp.linalg.norm(points, axis=-1, keepdims=True) + 1e-12)
        cn = centroids / (jnp.linalg.norm(centroids, axis=-1, keepdims=True) + 1e-12)
        return 1.0 - jnp.dot(pn, cn.T, preferred_element_type=jnp.float32)

    def pairwise_host64(self, points, centroids) -> np.ndarray:
        p = np.asarray(points, np.float64)
        c = np.asarray(centroids, np.float64)
        pn = p / (np.linalg.norm(p, axis=-1, keepdims=True) + 1e-12)
        cn = c / (np.linalg.norm(c, axis=-1, keepdims=True) + 1e-12)
        return 1.0 - pn @ cn.T


@register_distance_measure("manhattan")
class ManhattanDistanceMeasure(DistanceMeasure):
    def pairwise(self, points, centroids):
        # (n, 1, d) - (1, k, d) — fine for moderate k; KMeans default metric
        # is euclidean which avoids the broadcast blow-up.
        return jnp.sum(jnp.abs(points[:, None, :] - centroids[None, :, :]), axis=-1)

    def pairwise_host64(self, points, centroids) -> np.ndarray:
        p = np.asarray(points, np.float64)
        c = np.asarray(centroids, np.float64)
        out = np.empty((len(p), len(c)))
        chunk = max(1, (1 << 24) // max(len(c) * p.shape[1], 1))
        for s0 in range(0, len(p), chunk):  # bound the (chunk, k, d) temp
            out[s0:s0 + chunk] = np.abs(
                p[s0:s0 + chunk, None, :] - c[None, :, :]).sum(-1)
        return out
