"""The continuous-learning driver: training and serving as ONE system.

The reference's headline capability is iteration over *unbounded*
streams (``Iterations.iterateUnboundedStreams``) — the loop never
closes; models improve while they serve.  :class:`ContinuousLearner`
closes our loop: it runs the streaming trainer *forever* off the PR 5
write-ahead window log and, at every chunk-boundary cut, pushes the
params straight into the live serving generation as a delta
(``publish.py``) — no reload, no warm-up, zero new lowerings in steady
state.

The exactly-once chain across ingest -> train -> publish:

1. **Ingest**: every live window is durably appended to the
   :class:`~flink_ml_tpu.data.wal.WindowLog` BEFORE the trainer sees it.
2. **Train**: ``sgd_fit_outofcore`` cuts a validated checkpoint
   (params + window cursor, CRC manifest + commit marker) every
   ``publish_every_steps`` windows.
3. **Publish**: the cut's params publish AFTER the save — the served
   state is never ahead of the durable one — ordered by the train-step
   cursor, idempotent on replays (``publish.DeltaPublisher``).

A crash anywhere (mid-chunk, mid-publish, torn newest checkpoint, torn
newest WAL tail) is healed by :func:`~flink_ml_tpu.robustness
.supervisor.resilient_fit`: restore the newest VALID cut, replay the
WAL past the cursor, re-run — deterministic replay reproduces the same
params at every subsequent cut, so replayed publishes are digest-
verified no-ops and the served model converges to the same bits as the
uninterrupted run (asserted in tests/test_faults.py).  The model served
after the cut at step T is bit-exact with an offline
``sgd_fit_outofcore`` over WAL windows <= T (tests/test_online.py).

Hosted ``iterate`` bodies (online KMeans, FTRL-style logistic
regression) join the same publish protocol through
:class:`PublishingListener`, which rides the iteration's
``on_checkpoint_saved`` hook.
"""

from __future__ import annotations

import logging

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..data.wal import WindowBatchReader, WindowLog
from ..iteration.body import IterationListener
from ..obs.trace import tracer
from .delta import DeltaBaseMismatch
from .publish import DeltaEncoder, DeltaPublisher, PublishResult
from .staleness import StalenessPolicy

__all__ = ["ContinuousLearner", "PublishingListener", "encode_and_publish"]

log = logging.getLogger("flink_ml_tpu.online")


def encode_and_publish(encoder: DeltaEncoder, publisher: DeltaPublisher,
                       step: int, params: Any) -> PublishResult:
    """One cut through the protocol: encode against the encoder's base,
    apply at the publisher, heal a base mismatch (the encoder's view
    went stale across a crash) with a full re-anchor, and ack only a
    landed publish — the shared producer-side state machine of the
    driver and the hosted-iterate listener."""
    update = encoder.encode(step, params, publisher.stats)
    try:
        result = publisher.apply(update)
    except DeltaBaseMismatch:
        log.warning("delta base went stale at step %d; re-anchoring "
                    "with a full update", step)
        encoder.reset()
        result = publisher.apply(
            encoder.encode(step, params, publisher.stats))
    encoder.ack()
    return result


class ContinuousLearner:
    """Run the dense streaming SGD trainer forever off a WAL, publishing
    chunk-boundary cuts into a live serving generation.

    ``source`` is the LIVE feed (any iterable of fixed-row window
    Tables); ``wal_dir`` is its write-ahead log.  ``endpoint`` names the
    serving side: its registry entry must already hold a deployed
    generation of a delta-capable family (the linear servables — deploy
    an offline-fitted or zero-init model first); the driver's publishes
    land on that entry and account on its metrics.

    ``run()`` wraps the whole loop in ``resilient_fit``; every restart
    rebuilds a fresh :class:`WindowLog` over the same live source (the
    crash-heal path replays logged-but-unacknowledged windows first).
    """

    def __init__(self, *, loss_fn: Callable, num_features: int,
                 source: Any, wal_dir: str,
                 endpoint: Optional[Any] = None,
                 registry: Optional[Any] = None, name: str = "default",
                 batch_rows: int, config: Optional[Any] = None,
                 checkpoint: Any = None,
                 publish_every_steps: int = 8,
                 policy: Optional[StalenessPolicy] = None,
                 keep_snapshots: int = 4,
                 features_key: str = "features",
                 label_key: str = "label",
                 weight_key: Optional[str] = None,
                 max_restarts: int = 3,
                 backoff: Optional[Any] = None,
                 **fit_kwargs: Any):
        from ..models.common.sgd import SGDConfig

        if endpoint is not None:
            registry = endpoint.registry
            name = endpoint._name
            metrics = endpoint.metrics
        elif registry is not None:
            metrics = registry.metrics
        else:
            raise ValueError("pass endpoint= or registry=")
        if checkpoint is None:
            raise ValueError(
                "ContinuousLearner needs checkpoint= (a CheckpointConfig/"
                "Manager): the exactly-once loop hangs off durable cuts")
        if publish_every_steps < 1:
            raise ValueError("publish_every_steps must be >= 1")
        self._loss_fn = loss_fn
        self._num_features = num_features
        self._source = source
        self._wal_dir = wal_dir
        self._registry = registry
        self._name = name
        self._batch_rows = int(batch_rows)
        self._config = config or SGDConfig(max_epochs=1, tol=0.0)
        if self._config.max_epochs != 1:
            raise ValueError(
                "continuous learning is single-pass by construction "
                "(an unbounded stream has no epochs): use "
                "SGDConfig(max_epochs=1); multi-epoch refinement belongs "
                "to the offline fit")
        self._checkpoint = checkpoint
        self._every = int(publish_every_steps)
        self._keep = keep_snapshots
        self._keys = dict(features_key=features_key, label_key=label_key,
                          weight_key=weight_key)
        self._max_restarts = max_restarts
        self._backoff = backoff
        self._fit_kwargs = fit_kwargs
        # cuts land at chunk boundaries, so a publish cadence finer than
        # the dispatch chunk would silently coarsen to it — align the
        # default chunk with the cadence (callers can still override)
        self._fit_kwargs.setdefault("steps_per_dispatch",
                                    min(8, self._every))
        self.policy = policy or StalenessPolicy()
        self.encoder = DeltaEncoder(policy=self.policy)
        self.publisher = DeltaPublisher(registry, name, metrics=metrics)
        self.publish_log: List[PublishResult] = []
        self._wal: Optional[WindowLog] = None

    # -- the cut hook --------------------------------------------------------
    def _on_cut(self, step: int,
                params_fn: Callable[[], Dict[str, np.ndarray]]) -> None:
        # the cut index derives from the STEP cursor (not a local
        # counter) so a replayed cut makes the same publish/skip
        # decision as the original run — determinism across restarts.
        # ``params_fn`` is the fit's lazy host-fetch thunk: a skipped
        # cut never pays the device->host sync it exists to avoid.
        if not self.policy.due(step // self._every, self.publisher.stats):
            self.publisher.stats.skips += 1
            # the cadence skip is a real event on the cut timeline: a
            # trace showing cut T with no publish must say WHY
            tracer.instant("publish_skip", cat="publish", step=step)
        else:
            result = encode_and_publish(self.encoder, self.publisher,
                                        step, params_fn())
            if result.mode != "noop":
                self.publish_log.append(result)
        if self._wal is not None:
            # WAL truncation horizon: snapshot positions trail the live
            # cursor by keep_snapshots cuts, which must cover the
            # prefetch lead plus a quarantined-newest-checkpoint
            # fallback — the WindowLog raises loudly if sized too small
            self._wal.snapshot()

    # -- the supervised loop -------------------------------------------------
    def run(self, max_windows: Optional[int] = None,
            resume: bool = True, report: Optional[Any] = None):
        """Train-and-serve until the source ends (or ``max_windows``).
        Returns ``(LinearState, loss_log)`` from the underlying fit —
        unbounded sources never return; bounded runs (benches, tests)
        do.  ``resume=True`` (default) continues from the newest valid
        checkpoint + WAL cursor, which is also what every crash restart
        does."""
        from ..models.common.sgd import sgd_fit_outofcore
        from ..robustness.supervisor import resilient_fit

        self._registry.current(self._name)   # serving must be live first

        def fit(checkpoint, resume):
            # fresh WindowLog per attempt over the SAME live source: the
            # heal path replays logged-but-unacknowledged windows first
            self._wal = WindowLog(self._source, self._wal_dir,
                                  keep_snapshots=self._keep)
            reader = WindowBatchReader(self._wal, self._batch_rows,
                                       max_windows=max_windows)
            return sgd_fit_outofcore(
                self._loss_fn, lambda: reader,
                num_features=self._num_features, config=self._config,
                checkpoint=checkpoint,
                checkpoint_every_steps=self._every,
                resume=resume, publish_cb=self._on_cut,
                **self._keys, **self._fit_kwargs)

        return resilient_fit(fit, checkpoint=self._checkpoint,
                             max_restarts=self._max_restarts,
                             backoff=self._backoff, resume=resume,
                             report=report)


class PublishingListener(IterationListener):
    """Publish hosted-``iterate`` state into a live serving generation —
    the continuous-learning path for online KMeans / FTRL-style bodies.

    Rides ``on_checkpoint_saved`` by default, so every publish is of a
    state that is already durable (the driver's exactly-once ordering);
    ``publish_on="epoch"`` publishes at watermarks instead for
    iterations run without a checkpoint manager (no exactly-once claim
    there — a crash may re-serve older bits until the stream re-trains).

    ``params_of`` maps the iteration state to the canonical publish
    pytree of the deployed model family (e.g. online-KMeans state ->
    ``{"centroids": ...}``); ``every`` thins the cadence."""

    def __init__(self, publisher: DeltaPublisher, *,
                 params_of: Callable[[Any], Any] = lambda s: s,
                 every: int = 1, publish_on: str = "checkpoint",
                 policy: Optional[StalenessPolicy] = None):
        if publish_on not in ("checkpoint", "epoch"):
            raise ValueError('publish_on must be "checkpoint" or "epoch"')
        if every < 1:
            raise ValueError("every must be >= 1")
        self.publisher = publisher
        self.encoder = DeltaEncoder(policy=policy or StalenessPolicy())
        self._params_of = params_of
        self._every = every
        self._on = publish_on
        self.publish_log: List[PublishResult] = []

    def _publish(self, epoch: int, context) -> None:
        step = epoch + 1               # cuts/watermarks are post-epoch
        if step % self._every:
            return
        import jax

        params = jax.tree_util.tree_map(
            np.asarray, jax.device_get(self._params_of(context.state)))
        result = encode_and_publish(self.encoder, self.publisher,
                                    step, params)
        if result.mode != "noop":
            self.publish_log.append(result)

    def on_checkpoint_saved(self, epoch: int, context) -> None:
        if self._on == "checkpoint":
            self._publish(epoch, context)

    def on_epoch_watermark_incremented(self, epoch: int, context) -> None:
        if self._on == "epoch":
            self._publish(epoch, context)
