"""Param-delta codec: the unit the train->serve publish protocol ships.

The reference's unbounded iteration emits a *model-data stream* — each
version is a full table write.  Successive generations of a continuously
trained model are same-shape pytrees that differ in a (often small)
subset of slots, so the publish path ships a **delta**: per leaf, the
changed element indices and their NEW raw values.  Carrying raw new
values (not arithmetic differences) is what makes the codec **bit-exact
by construction**: ``apply_delta(base, diff_params(base, new)) == new``
bitwise, including NaN payloads and signed zeros — an f32 ``base +
(new - base)`` would re-round and break the served-bits == trained-bits
acceptance.

Every update carries CRC32 digests of the base and result trees.
``apply_delta`` verifies BOTH: the base digest catches a delta applied
to the wrong generation (the consumer's copy drifted — e.g. a full
update was lost), the result digest catches a torn/corrupted payload.
Together they are the publish protocol's exactly-once teeth: a replayed
delta either reproduces the identical tree (digest no-op) or fails
loudly; it can never half-apply (application happens on a copy, swapped
in only after verification).

Change detection compares **raw bytes**, not values: ``NaN != NaN``
would mark every NaN slot changed forever, and ``-0.0 == 0.0`` would
miss a real bit flip.
"""

from __future__ import annotations

import zlib

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["ParamDelta", "FullUpdate", "DeltaShapeChanged",
           "DeltaBaseMismatch", "DeltaCorrupt", "tree_digest",
           "diff_params", "apply_delta", "flatten_params",
           "unflatten_params", "SPARSE_DENSITY_THRESHOLD"]


class DeltaShapeChanged(ValueError):
    """Base and new trees differ in structure/shape/dtype — a delta
    cannot express this; the caller must fall back to a full publish
    (the registry load->warm->swap path)."""


class DeltaBaseMismatch(ValueError):
    """The consumer's base tree is not the generation this delta was
    diffed against; applying would produce garbage.  Heal by re-sending
    a full update."""


class DeltaCorrupt(ValueError):
    """Applying the delta did not reproduce the producer's result
    digest: the payload was torn or the codec's bit-exactness contract
    was violated.  Never serve this."""


#: Leaves whose changed fraction is below this encode sparsely
#: ((indices, values) pairs, 8 bytes/slot f32); denser leaves ship the
#: full buffer (4 bytes/slot) — the 2x index overhead crosses over at
#: 50%, and the margin below that keeps the decision stable for leaves
#: hovering at the boundary.
SPARSE_DENSITY_THRESHOLD = 0.25


# -- pytree <-> flat dict ----------------------------------------------------

def flatten_params(tree: Any) -> Dict[str, np.ndarray]:
    """Flatten a params pytree (nested dicts/lists/tuples of arrays) to
    ``{"/"-joined path: contiguous np.ndarray}`` in deterministic key
    order — the codec's canonical form."""
    import jax

    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(_path_token(p) for p in path)
        arr = np.asarray(leaf)
        if not arr.flags["C_CONTIGUOUS"]:
            # NOTE: not ascontiguousarray unconditionally — it promotes
            # 0-d scalars to shape (1,), breaking shape fidelity
            arr = np.ascontiguousarray(arr)
        flat[key] = arr
    return flat


def _path_token(entry: Any) -> str:
    key = getattr(entry, "key", None)
    if key is None:
        key = getattr(entry, "idx", None)
    if key is None:
        key = getattr(entry, "name", entry)
    return str(key)


def unflatten_params(template: Any, flat: Dict[str, np.ndarray]) -> Any:
    """Rebuild a pytree shaped like ``template`` from the codec's flat
    dict (inverse of :func:`flatten_params` for same-structure trees)."""
    import jax

    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    keys = ["/".join(_path_token(p) for p in path) for path, _ in paths]
    missing = [k for k in keys if k not in flat]
    if missing or len(keys) != len(flat):
        raise DeltaShapeChanged(
            f"flat params keys {sorted(flat)} do not match the template's "
            f"{sorted(keys)}")
    return jax.tree_util.tree_unflatten(treedef, [flat[k] for k in keys])


# -- digests ----------------------------------------------------------------

def _leaf_digest(arr: np.ndarray) -> int:
    header = f"{arr.dtype.str}:{arr.shape}".encode()
    return zlib.crc32(arr.tobytes(), zlib.crc32(header))


def tree_digest(tree: Any) -> int:
    """CRC32 over every leaf's dtype/shape/raw bytes in canonical path
    order — the generation fingerprint both publish digests use."""
    flat = tree if isinstance(tree, dict) and all(
        isinstance(v, np.ndarray) for v in tree.values()) \
        else flatten_params(tree)
    acc = 0
    for key in sorted(flat):
        acc = zlib.crc32(key.encode(), acc)
        acc = zlib.crc32(_leaf_digest(flat[key]).to_bytes(4, "little"), acc)
    return acc


# -- update payloads ---------------------------------------------------------

@dataclass(frozen=True)
class _LeafDelta:
    """One changed leaf: either the full new buffer (``idx is None``) or
    the changed flat indices + their new raw values."""
    idx: Optional[np.ndarray]     # int64 flat indices, or None = full
    values: np.ndarray            # new raw values (flat when sparse)

    @property
    def payload_bytes(self) -> int:
        n = 0 if self.idx is None else self.idx.size * self.idx.itemsize
        return n + self.values.size * self.values.itemsize


@dataclass(frozen=True)
class ParamDelta:
    """An incremental update: apply to the exact base generation only."""
    step: int                     # producer's train cursor at the cut
    base_digest: int
    new_digest: int
    leaves: Dict[str, _LeafDelta] = field(default_factory=dict)

    @property
    def payload_bytes(self) -> int:
        """Bytes this update would put on a wire (values + sparse
        indices; digests/headers are O(1))."""
        return sum(d.payload_bytes for d in self.leaves.values())

    @property
    def changed_leaves(self) -> List[str]:
        return sorted(self.leaves)


@dataclass(frozen=True)
class FullUpdate:
    """A full re-anchor: replaces the consumer's base outright (first
    publish, shape/schema change, dense delta, periodic re-anchor)."""
    step: int
    new_digest: int
    params: Dict[str, np.ndarray] = field(default_factory=dict)

    @property
    def payload_bytes(self) -> int:
        return sum(a.size * a.itemsize for a in self.params.values())


def full_update(step: int, new: Any) -> FullUpdate:
    flat = flatten_params(new)
    return FullUpdate(step=step, new_digest=tree_digest(flat), params=flat)


def diff_params(base: Any, new: Any, step: int = 0,
                sparse_threshold: float = SPARSE_DENSITY_THRESHOLD,
                base_digest: Optional[int] = None) -> ParamDelta:
    """Encode ``new`` against ``base``.  Raises :class:`DeltaShapeChanged`
    when the trees differ structurally (different keys, shapes, or
    dtypes) — the caller falls back to a full publish.

    ``base_digest`` lets a caller that already knows the base's digest
    (the encoder: it is exactly the previous publish's ``new_digest``)
    skip the whole-tree re-CRC on the publish latency path."""
    fb, fn = flatten_params(base), flatten_params(new)
    if set(fb) != set(fn):
        raise DeltaShapeChanged(
            f"param tree changed: base leaves {sorted(fb)} vs new "
            f"{sorted(fn)}")
    leaves: Dict[str, _LeafDelta] = {}
    for key in sorted(fn):
        a, b = fb[key], fn[key]
        if a.shape != b.shape or a.dtype != b.dtype:
            raise DeltaShapeChanged(
                f"leaf {key!r} changed shape/dtype: "
                f"{a.dtype}{a.shape} -> {b.dtype}{b.shape}")
        if a.tobytes() == b.tobytes():
            continue
        if b.ndim == 0 or b.size == 0:
            leaves[key] = _LeafDelta(idx=None, values=b.copy())
            continue
        # raw-byte change mask (value compares would miss -0.0 flips and
        # mark NaNs changed forever)
        itemsize = b.dtype.itemsize
        av = a.reshape(-1).view(np.uint8).reshape(a.size, itemsize)
        bv = b.reshape(-1).view(np.uint8).reshape(b.size, itemsize)
        changed = np.nonzero(np.any(av != bv, axis=1))[0]
        if changed.size <= sparse_threshold * b.size:
            leaves[key] = _LeafDelta(idx=changed.astype(np.int64),
                                     values=b.reshape(-1)[changed].copy())
        else:
            leaves[key] = _LeafDelta(idx=None, values=b.copy())
    return ParamDelta(
        step=step,
        base_digest=(base_digest if base_digest is not None
                     else tree_digest(fb)),
        new_digest=tree_digest(fn), leaves=leaves)


def apply_delta(base: Any, delta: ParamDelta) -> Dict[str, np.ndarray]:
    """Apply ``delta`` to ``base``; returns the NEW flat params dict.
    Verifies the base digest before touching anything and the result
    digest before returning — on either failure the consumer's base is
    untouched (application happens on copies)."""
    flat = flatten_params(base)
    have = tree_digest(flat)
    if have != delta.base_digest:
        raise DeltaBaseMismatch(
            f"delta for step {delta.step} was diffed against generation "
            f"digest {delta.base_digest:#010x} but the live base digests "
            f"{have:#010x}; request a full update")
    out: Dict[str, np.ndarray] = {}
    for key, arr in flat.items():
        d = delta.leaves.get(key)
        if d is None:
            out[key] = arr
        elif d.idx is None:
            out[key] = d.values
        else:
            new = arr.copy().reshape(-1)
            new[d.idx] = d.values
            out[key] = new.reshape(arr.shape)
    got = tree_digest(out)
    if got != delta.new_digest:
        raise DeltaCorrupt(
            f"applying delta for step {delta.step} produced digest "
            f"{got:#010x}, producer recorded {delta.new_digest:#010x}: "
            "torn payload — refusing to serve")
    return out
