"""The train->serve publish protocol: deltas into live generations.

Producer side (:class:`DeltaEncoder`) runs next to the trainer: it
remembers the last published params and encodes each chunk-boundary cut
as a :class:`~.delta.ParamDelta` (or a :class:`~.delta.FullUpdate` when
the staleness policy, a structural change, or payload accounting says
re-anchor).

Consumer side (:class:`DeltaPublisher`) runs next to the registry: it
keeps its own base copy of the served params, applies each update under
digest verification (:func:`~.delta.apply_delta`), rebuilds the model
object around the new params, and publishes by **rebinding** the live
:class:`~flink_ml_tpu.serving.executor.ServableModel` — a shallow clone
pointing at the new model, marked ready WITHOUT warm-up.  That is safe
precisely for the specialized executor families (linear / KMeans /
WideDeep, ``rebind_safe``): their compiled score programs live in the
module-global serving jit cache with the params as *runtime arguments*,
so a same-shape generation hits only already-compiled executables —
publish is a device-resident buffer swap, zero new lowerings (asserted
in tests/test_online.py with the JAX lowering counter).  Families whose
transform bakes params into the program fall back to the full
``registry.deploy`` load->warm->swap path.

Exactly-once across replays: updates are ordered by the producer's
train-step cursor.  A replayed cut (crash between checkpoint and the
next one) arrives with ``step <= last applied``; at ``step ==`` the
publisher *verifies* the replay reproduced the identical digest — the
deterministic-replay guarantee made observable — and no-ops, at ``step
<`` it skips (serving never moves backward).  A delta against a base
the publisher does not hold raises :class:`~.delta.DeltaBaseMismatch`
and the encoder re-anchors with a full update.  The registry swap
itself is one reference assignment under the registry lock, so a crash
mid-publish can never expose a half-applied generation — in-flight
requests finish on the version their batch captured.
"""

from __future__ import annotations

import copy
import threading
import time

from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

from ..obs.trace import tracer
from .delta import (
    DeltaShapeChanged,
    FullUpdate,
    ParamDelta,
    apply_delta,
    diff_params,
    flatten_params,
    tree_digest,
    unflatten_params,
)
from .staleness import PublishStats, StalenessPolicy

__all__ = ["DeltaEncoder", "DeltaPublisher", "PublishResult",
           "DeterminismViolation", "params_of_model", "model_with_params"]


class DeterminismViolation(RuntimeError):
    """A replayed cut (same train step) produced different bits than the
    original publish — the deterministic-replay contract the exactly-once
    design rests on is broken.  Never serve silently past this."""


# -- model family adapters ---------------------------------------------------
#
# The canonical published-params form is the TRAINER's pytree (f32 —
# what the chunk-boundary cut holds); the adapters rebuild a servable
# model object around it.  Kept as isinstance dispatch (the
# make_servable stance) so the family list lives in one place.

def params_of_model(model: Any) -> Any:
    """The live model's params as the canonical publish pytree."""
    from ..models.clustering.kmeans import KMeansModel
    from ..models.common.linear import LinearModelBase
    from ..models.recommendation.widedeep import WideDeepModel

    if isinstance(model, LinearModelBase):
        model._require_model()
        # f64 LinearState holds f32-trained values: the f32 cast is
        # value-exact and restores the trainer's canonical form
        return {"w": np.asarray(model._state.coefficients, np.float32),
                "b": np.asarray(model._state.intercept, np.float32)}
    if isinstance(model, KMeansModel):
        model._require_model()
        return {"centroids": np.asarray(model._centroids, np.float32)}
    if isinstance(model, WideDeepModel):
        import jax

        return jax.tree_util.tree_map(
            lambda a: np.asarray(a), model._params)
    from ..retrieval.ivf import IVFIndex

    if isinstance(model, IVFIndex):
        # the index's params dict IS the canonical pytree (posting-list
        # row blocks + centroids + codebooks); posting-list edits touch
        # few rows, so the sparse delta codec pays off exactly as it
        # does for embedding tables
        return {name: np.asarray(arr)
                for name, arr in model.params.items()}
    raise TypeError(
        f"{type(model).__name__} has no params_of_model adapter; "
        "delta publishing covers the specialized servable families "
        "(linear / KMeans / WideDeep) and IVFIndex — use the full "
        "deploy path")


def model_with_params(model: Any, params: Any) -> Any:
    """A shallow clone of ``model`` carrying ``params`` — the object the
    rebound servable scores with.  The clone shares everything immutable
    (param map, vocab sizes, column names) and replaces only the fitted
    state."""
    from ..models.clustering.kmeans import KMeansModel
    from ..models.common.linear import LinearModelBase
    from ..models.common.sgd import LinearState
    from ..models.recommendation.widedeep import WideDeepModel

    clone = copy.copy(model)
    if isinstance(model, LinearModelBase):
        clone._state = LinearState(
            np.asarray(params["w"], np.float64),
            float(np.asarray(params["b"])),
            planned_impl="online-delta")
        return clone
    if isinstance(model, KMeansModel):
        clone._centroids = np.asarray(params["centroids"], np.float32)
        return clone
    if isinstance(model, WideDeepModel):
        import jax.numpy as jnp

        clone._params = _map_like(model._params,
                                  lambda a: jnp.asarray(a))(params)
        return clone
    from ..retrieval.ivf import IVFIndex

    if isinstance(model, IVFIndex):
        # host bookkeeping (the id->vector store) stays with the
        # producer's authoritative index; the serve-side clone only
        # needs the device params
        return model.rebound(params)
    raise TypeError(
        f"{type(model).__name__} has no model_with_params adapter")


def _map_like(template, fn):
    import jax

    def apply(tree):
        return jax.tree_util.tree_map(lambda _, b: fn(b), template, tree)

    return apply


# -- producer side -----------------------------------------------------------

class DeltaEncoder:
    """Trainer-side half: turns each cut's params into the update the
    policy calls for, tracking the last ACKNOWLEDGED base.  ``encode``
    never mutates its base until the caller confirms the publish landed
    (``ack``) — a publish that raises leaves the encoder anchored on the
    generation serving traffic, so the next encode diffs against
    reality."""

    def __init__(self, policy: Optional[StalenessPolicy] = None):
        self.policy = policy or StalenessPolicy()
        self._base: Optional[Dict[str, np.ndarray]] = None
        #: digest of ``_base`` — the previous publish's new_digest,
        #: cached so each cut skips one whole-tree CRC (encode is on the
        #: publish latency path)
        self._base_digest: Optional[int] = None
        self._pending: Optional[Dict[str, np.ndarray]] = None
        self._pending_digest: Optional[int] = None

    def encode(self, step: int, params: Any,
               stats: Optional[PublishStats] = None):
        """-> :class:`FullUpdate` | :class:`ParamDelta` for this cut."""
        stats = stats if stats is not None else PublishStats()
        flat = flatten_params(params)
        if self._base is None or self.policy.wants_full(stats):
            return self._pend(FullUpdate(
                step=step, new_digest=tree_digest(flat), params=flat))
        try:
            delta = diff_params(self._base, flat, step=step,
                                base_digest=self._base_digest)
        except DeltaShapeChanged:
            return self._pend(FullUpdate(
                step=step, new_digest=tree_digest(flat), params=flat))
        full_bytes = sum(a.size * a.itemsize for a in flat.values())
        if self.policy.choose(delta.payload_bytes, full_bytes,
                              stats) == "full":
            return self._pend(FullUpdate(
                step=step, new_digest=delta.new_digest, params=flat))
        return self._pend(delta, flat)

    def _pend(self, update, flat: Optional[Dict[str, np.ndarray]] = None):
        self._pending = flat if flat is not None else update.params
        self._pending_digest = update.new_digest
        return update

    def ack(self) -> None:
        """The last encoded update landed: its params become the base the
        next delta diffs against."""
        if self._pending is not None:
            self._base = self._pending
            self._base_digest = self._pending_digest
            self._pending = None
            self._pending_digest = None

    def reset(self) -> None:
        """Drop the base (next encode ships full) — the heal move after
        :class:`~.delta.DeltaBaseMismatch`."""
        self._base = None
        self._base_digest = None
        self._pending = None
        self._pending_digest = None


# -- consumer side -----------------------------------------------------------

@dataclass(frozen=True)
class PublishResult:
    generation: int         # live generation after this call
    mode: str               # "delta" | "full" | "full-redeploy" | "noop"
    step: int
    payload_bytes: int
    publish_s: float        # wall time inside apply()


class DeltaPublisher:
    """Serving-side half: applies updates to its base copy and swaps the
    result into the registry as the next generation of ``name``."""

    def __init__(self, registry: Any, name: str = "default", *,
                 metrics: Optional[Any] = None):
        self._registry = registry
        self._name = name
        self._metrics = metrics if metrics is not None \
            else getattr(registry, "metrics", None)
        self._lock = threading.Lock()
        self._base: Optional[Dict[str, np.ndarray]] = None
        self._template: Any = None
        #: generation of the last publish WE made — when the live entry
        #: moved past it (an external deploy/hot_swap), our cached base
        #: no longer describes what serves and must re-anchor on it
        self._last_generation: Optional[int] = None
        self.stats = PublishStats()

    # -- base management ----------------------------------------------------
    def _ensure_base(self) -> None:
        if self._base is not None:
            return
        live = self._registry.current(self._name)
        self._template = params_of_model(live.servable.model)
        self._base = flatten_params(self._template)

    @property
    def last_step(self) -> Optional[int]:
        return self.stats.last_published_step

    # -- the publish --------------------------------------------------------
    def apply(self, update) -> PublishResult:
        """Apply one update (:class:`FullUpdate` / :class:`ParamDelta`)
        and publish the result atomically.  Thread-safe; idempotent on
        replays (see module doc).  A concurrent external deploy landing
        between validation and swap loses us the compare-and-swap
        (:class:`~flink_ml_tpu.serving.registry.GenerationConflict`):
        ONE retry re-validates against the new generation — sequential
        semantics, just later."""
        from ..serving.registry import GenerationConflict

        t0 = time.perf_counter()
        with self._lock, \
                tracer.span("delta_publish", cat="publish",
                            step=int(update.step)) as span:
            try:
                result = self._apply_locked(update, t0)
            except GenerationConflict:
                # drop every cached view of the entry (the drift check
                # alone misses a first-publish race) and re-validate
                self._base = None
                self._template = None
                result = self._apply_locked(update, t0)
            # the publish span carries BOTH halves of the correlation
            # chain: the trainer's cut step and the serving generation
            # it became — the join point of "cut T -> generation G"
            span.note(generation=result.generation, x_mode=result.mode)
            return result

    def _apply_locked(self, update, t0: float) -> PublishResult:
        live = self._registry.current(self._name)
        drifted = (self._last_generation is not None
                   and live.generation != self._last_generation)
        if drifted:
            # someone else deployed into this entry (operator hot_swap,
            # registry deploy): our cached base/template describe a
            # generation that no longer serves.  Re-anchor on the LIVE
            # model — a pending delta then base-mismatches (the caller
            # heals with a full re-anchor) and a FullUpdate shape-checks
            # against what actually serves, never against stale shapes.
            self._base = None
            self._template = None
        last = self.stats.last_published_step
        if last is not None and update.step <= last:
            if update.step == last and not drifted:
                # replayed cut (crash between this cut and the next):
                # deterministic replay MUST reproduce the exact bits.
                # (After an external deploy the base is the OTHER
                # model's — the check would be against the wrong tree.)
                self._ensure_base()
                if update.new_digest != tree_digest(self._base):
                    raise DeterminismViolation(
                        f"replayed cut at step {update.step} digests "
                        f"{update.new_digest:#010x}, original publish "
                        f"digested {tree_digest(self._base):#010x}")
            self.stats.skips += 1
            return PublishResult(generation=live.generation, mode="noop",
                                 step=update.step, payload_bytes=0,
                                 publish_s=time.perf_counter() - t0)
        if isinstance(update, FullUpdate):
            new_flat = dict(update.params)
            if tree_digest(new_flat) != update.new_digest:
                from .delta import DeltaCorrupt

                raise DeltaCorrupt(
                    f"full update at step {update.step} digests "
                    f"differently than its header — torn payload")
            mode = "full"
            # a delta is shape-guarded by its base digest; a FULL update
            # must be checked here, or a shape-incompatible publish
            # would ride the rebind fast path (which skips the warm-up
            # that catches exactly this) and break every later request.
            # A real shape/schema change needs a new example and a
            # warmed deploy — the registry path, outside this protocol.
            self._ensure_base()
            if (set(new_flat) != set(self._base)
                    or any(new_flat[k].shape != self._base[k].shape
                           or new_flat[k].dtype != self._base[k].dtype
                           for k in new_flat)):
                raise DeltaShapeChanged(
                    f"full update at step {update.step} does not match "
                    "the live generation's param shapes/dtypes; a "
                    "shape/schema change must go through "
                    "registry.deploy() with a fresh example (warmed at "
                    "the new shapes), not the publish fast path")
        elif isinstance(update, ParamDelta):
            self._ensure_base()
            new_flat = apply_delta(self._base, update)
            mode = "delta"
        else:
            raise TypeError(f"not a publishable update: {update!r}")

        if self._template is None:
            self._template = params_of_model(live.servable.model)
        new_params = unflatten_params(self._template, new_flat)
        new_model = model_with_params(live.servable.model, new_params)
        if getattr(live.servable, "rebind_safe", False):
            # per-generation re-calibration rides this call (ISSUE 18):
            # an int8 servable's rebind re-runs its bind path, which
            # re-derives quantization scales from new_model's params
            # BEFORE the conditional swap below — in-flight requests
            # finish on the old generation's codes+scales, and stale
            # scales never serve the new params
            servable = live.servable.rebind(new_model)
            deployed = self._registry.publish_servable(
                self._name, servable,
                source=f"<{mode}:step={update.step}>",
                metrics=self._metrics, mode=mode,
                payload_bytes=update.payload_bytes,
                # compare-and-swap: everything above validated against
                # live.generation — refuse to clobber a deploy that
                # landed since (apply() retries through re-validation)
                expected_generation=live.generation)
        else:
            # params baked into the transform program: full path (warm
            # off the serving path, then swap) — correctness over speed
            mode = "full-redeploy"
            deployed = self._registry.deploy(
                self._name, new_model, metrics=self._metrics)
            if self._metrics is not None \
                    and hasattr(self._metrics, "on_publish"):
                # deploy() only records on_deploy: account the publish
                # (staleness gauge, full counter) here too, or a
                # continuously-trained generic-family endpoint reads as
                # never published
                self._metrics.on_publish(
                    deployed.generation, mode="full",
                    payload_bytes=update.payload_bytes)
        self._base = new_flat
        self._last_generation = deployed.generation
        now = time.time()
        st = self.stats
        st.publishes += 1
        st.last_publish_at = now
        st.last_published_step = int(update.step)
        if mode == "delta":
            st.deltas += 1
            st.delta_bytes += update.payload_bytes
        else:
            st.fulls += 1
            st.full_bytes += update.payload_bytes
        return PublishResult(generation=deployed.generation, mode=mode,
                             step=int(update.step),
                             payload_bytes=update.payload_bytes,
                             publish_s=time.perf_counter() - t0)
