"""Staleness policy: when to publish, and delta vs full.

The continuous driver cuts at chunk boundaries; this policy decides what
each cut becomes:

- ``"skip"``  — not due yet (``publish_every`` cuts coalesce into one
  publish; serving keeps the previous generation).
- ``"delta"`` — the steady-state path: same-shape params, incremental
  encode, device-resident buffer swap (no reload, no warm-up).
- ``"full"``  — re-anchor: first publish after (re)start, a structural
  change (:class:`~.delta.DeltaShapeChanged` upstream), every
  ``full_every`` publishes (bounds how long a consumer that lost one
  update stays unable to resync), or when the sparse encoding would not
  actually save bytes.

The decision rule is deliberately *proactive*, not reactive: a delta
whose payload is >= ``full_ratio`` of the full tree ships as a full
update — same bits served either way (both carry raw new values), but
the full update additionally re-anchors the consumer's base, so it is
strictly more robust at equal cost.

``max_staleness_s`` is the freshness floor: even when ``publish_every``
says skip, a cut older than this publishes anyway — the gauge the
serving metrics expose (``staleness_seconds``) is the same number this
policy bounds.
"""

from __future__ import annotations

import time

from dataclasses import dataclass, field
from typing import Callable, Optional

__all__ = ["StalenessPolicy", "PublishStats"]


@dataclass
class PublishStats:
    """Rolling publish accounting the policy consults (and the driver /
    bench read back)."""
    publishes: int = 0
    deltas: int = 0
    fulls: int = 0
    skips: int = 0
    last_publish_at: Optional[float] = None
    last_published_step: Optional[int] = None
    delta_bytes: int = 0
    full_bytes: int = 0

    def staleness_s(self, now: Optional[float] = None) -> float:
        if self.last_publish_at is None:
            return float("inf")
        return (now if now is not None else time.time()) \
            - self.last_publish_at


@dataclass
class StalenessPolicy:
    #: publish every Nth cut (1 = every chunk boundary)
    publish_every: int = 1
    #: force a full re-anchor every Nth PUBLISH (0 = never; the first
    #: publish is always full regardless)
    full_every: int = 0
    #: publish regardless of cadence once the served model is this stale
    max_staleness_s: Optional[float] = None
    #: ship full when the delta payload reaches this fraction of the
    #: full tree's bytes (re-anchoring is free at that point)
    full_ratio: float = 0.9
    #: injectable clock (tests pin it)
    clock: Callable[[], float] = field(default=time.time)

    def __post_init__(self):
        if self.publish_every < 1:
            raise ValueError("publish_every must be >= 1")
        if not 0.0 < self.full_ratio <= 1.0:
            raise ValueError("full_ratio must be in (0, 1]")

    def due(self, cut_index: int, stats: PublishStats) -> bool:
        """Should cut number ``cut_index`` (0-based, monotonically
        increasing across the driver's life) publish at all?"""
        if cut_index % self.publish_every == 0:
            return True
        if (self.max_staleness_s is not None
                and stats.staleness_s(self.clock()) >= self.max_staleness_s):
            return True
        return False

    def wants_full(self, stats: PublishStats) -> bool:
        """Full re-anchor due by cadence (independent of shape changes,
        which force full upstream)?"""
        if stats.publishes == 0:
            return True
        return bool(self.full_every) and \
            stats.publishes % self.full_every == 0

    def choose(self, delta_bytes: int, full_bytes: int,
               stats: PublishStats) -> str:
        """``"delta"`` or ``"full"`` for a publish that CAN be a delta."""
        if self.wants_full(stats):
            return "full"
        if delta_bytes >= self.full_ratio * full_bytes:
            return "full"
        return "delta"
