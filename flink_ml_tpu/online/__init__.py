"""Continuous learning: train-while-serve with incremental delta
publishes (ROADMAP item 1 — the reference's unbounded-iteration
capability closed end to end).

- :mod:`.delta` — bit-exact param-delta codec with digest verification
- :mod:`.publish` — producer/consumer publish protocol; device-resident
  buffer swaps into live serving generations
- :mod:`.staleness` — publish cadence + delta-vs-full decision rule
- :mod:`.driver` — the supervised forever-loop off the WAL, and the
  hosted-``iterate`` publishing listener
"""

from .delta import (
    DeltaBaseMismatch,
    DeltaCorrupt,
    DeltaShapeChanged,
    FullUpdate,
    ParamDelta,
    apply_delta,
    diff_params,
    flatten_params,
    full_update,
    tree_digest,
    unflatten_params,
)
from .driver import ContinuousLearner, PublishingListener, encode_and_publish
from .publish import (
    DeltaEncoder,
    DeltaPublisher,
    DeterminismViolation,
    PublishResult,
    model_with_params,
    params_of_model,
)
from .staleness import PublishStats, StalenessPolicy

__all__ = [
    "ContinuousLearner", "DeltaBaseMismatch", "DeltaCorrupt",
    "DeltaEncoder", "DeltaPublisher", "DeltaShapeChanged",
    "DeterminismViolation", "FullUpdate", "ParamDelta", "PublishResult",
    "PublishStats", "PublishingListener", "StalenessPolicy",
    "apply_delta", "diff_params", "encode_and_publish", "flatten_params",
    "full_update", "model_with_params", "params_of_model", "tree_digest",
    "unflatten_params",
]
