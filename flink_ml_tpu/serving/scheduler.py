"""Multi-tenant serving scheduler — one chip, hundreds of models (ISSUE 14).

The PR 2 topology gives every model its own endpoint: one queue, one
batcher, one serve thread.  That is the wrong shape for the north star —
serving millions of users means hundreds of models sharing one device,
with zipfian traffic (a few hot tenants, a long tail) and mixed
workloads (a human waiting on a click next to a nightly bulk scorer).
:class:`SharedScheduler` replaces it with ONE admission/placement layer:

- **Global micro-batching per (servable, bucket).**  Pending requests
  coalesce across every tenant mapped to the same servable, so a hot
  schema fills its power-of-two bucket faster than any per-endpoint
  queue could (tenants sharing one model — traffic multi-tenancy — ride
  one batch; tenants with their own models still share the COMPILED
  program via the kernel registry, see below).
- **SLO classes with priority shedding.**  Every tenant is
  ``interactive`` / ``standard`` / ``bulk``.  Admission is one global
  queue budget with per-class thresholds: bulk admits only while the
  queue is under its (lowest) threshold, standard under its higher one,
  interactive up to full capacity — so under a load ramp, bulk is shed
  strictly before standard, and standard strictly before interactive
  ever sheds.  Classes are also strict dispatch priorities: the
  scheduler never forms a bulk batch while an interactive request is
  queued, and a coalescing wait on a lower class is PREEMPTED the
  moment a higher class goes pending.  Shedding is wired into the PR 5
  degradation states: the scheduler's ``health`` gauge flips
  ``SERVING`` -> ``DEGRADED`` while load is being shed and heals once
  the queue recedes below every class threshold.
- **Weighted fair queuing within a class.**  Each tenant carries a
  virtual-finish tag (start-time fair queuing): serving ``rows`` from a
  tenant advances its tag by ``rows / weight``, the scheduler always
  picks the lowest tag in the highest non-empty class, and a tenant
  going from idle to backlogged re-enters at the class's virtual time
  (no banked credit).  Backlogged same-class tenants therefore share
  throughput in proportion to their weights — one zipfian-head tenant
  cannot starve the tail (asserted in ``tests/test_scheduler.py``).
- **Admission is compilation-free.**  The kernel registry (PR 10)
  already dedupes compiled programs by ``(plan, schema, bucket)`` with
  params as runtime arguments, and the AOT cache (PR 12) persists them
  across processes.  So admitting tenant N+1 whose model shares an
  already-served schema costs ZERO new XLA lowerings — its warm-up is a
  cache-hit walk, proven per admission by the tenant's
  ``admission_report`` (the warm-up source attribution from
  ``kernel_stats.thread_counts``) and lowering-counter-asserted in
  tests.  The scheduler is purely admission + placement; there is no
  new dispatch surface.

Observability: every tenant owns a full :class:`ServingMetrics` subtree
under ``scheduler.tenants.<name>.*`` (queue depth, shed count, p50/p99
latency rings, generation, publish/staleness gauges), the scheduler
itself exports class-labeled shed counters and the health gauge, and
serving spans carry the ``tenant`` correlation key
(``obs.CORRELATION_KEYS``) so one Perfetto trace shows cross-tenant
interleaving on the shared device.

Threading model: ``submit`` from any number of client threads (with a
LOCK-FREE overload fast path — under saturation, shed decisions never
serialize on the queue lock); ONE scheduler thread runs the
pick → coalesce → dispatch loop, so per-servable execution is serial by
construction (the single-consumer contract the embedding-row cache
relies on, ``serving/embcache.py``).
"""

from __future__ import annotations

import logging
import threading
import time

from collections import deque
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

from ..data.table import Table
from ..obs.trace import tracer
from ..robustness.faults import (InjectedChipDown, InjectedChipFlap,
                                 fault_point)
from ..robustness.retry import DeadlineExceededError
from ..utils.metrics import MetricGroup
from .batcher import (ServingOverloadedError, ServingRequest,
                      concat_request_tables)
from .metrics import HEALTH_DEGRADED, HEALTH_SERVING, ServingMetrics
from .registry import ModelRegistry


log = logging.getLogger("flink_ml_tpu.serving")


__all__ = [
    "DISPATCH_SCOPE",
    "SLO_BULK",
    "SLO_CLASSES",
    "SLO_INTERACTIVE",
    "SLO_STANDARD",
    "SharedScheduler",
    "Tenant",
]

#: the dispatch-boundary fault seam (ISSUE 20): fired at the TOP of
#: ``_dispatch``, BEFORE the batch's predict runs — an injected
#: ``chip_down``/``chip_flap`` there loses nothing (the picked requests
#: requeue at the front of their tenants' queues with futures intact)
#: and each dispatch is one deterministic invocation index, so seeded
#: schedules replay exactly.
DISPATCH_SCOPE = "serving.dispatch"


#: SLO classes in strict priority order (dispatch AND shed order: the
#: last class is shed first and served last).
SLO_INTERACTIVE = "interactive"
SLO_STANDARD = "standard"
SLO_BULK = "bulk"
SLO_CLASSES = (SLO_INTERACTIVE, SLO_STANDARD, SLO_BULK)


#: Default per-class admission thresholds as fractions of the global
#: queue capacity.  Interactive is pinned to 1.0 by construction — it
#: only sheds when the queue is FULL; the lower classes shed earlier,
#: which is what guarantees the shed order under a load ramp.
DEFAULT_ADMIT_FRACTIONS = {
    SLO_INTERACTIVE: 1.0,
    SLO_STANDARD: 0.8,
    SLO_BULK: 0.5,
}


class Tenant:
    """One admitted tenant: its registry entry, SLO class, WFQ weight,
    pending queue, and a full per-tenant :class:`ServingMetrics`
    subtree.  Constructed by :meth:`SharedScheduler.add_tenant`."""

    def __init__(self, name: str, serve_name: str, slo: str,
                 weight: float, metrics: ServingMetrics):
        self.name = name
        #: the registry key this tenant's requests are served from —
        #: equals ``name`` unless the tenant shares another tenant's
        #: servable (``servable_of``)
        self.serve_name = serve_name
        self.slo = slo
        self.weight = weight
        #: the admission-time weight — ``apply_placement`` rescales
        #: ``weight`` by the tenant's chip count RELATIVE to this, so
        #: placements compose instead of compounding
        self.base_weight = weight
        self.metrics = metrics
        self.pending: deque = deque()
        #: WFQ virtual-finish tag (rows served / weight, class-relative)
        self.vft = 0.0
        #: total rows served — the fairness-share evidence
        self.rows_served = 0
        #: warm-up source attribution of this tenant's admission (None
        #: for shared-servable tenants: nothing was deployed) — the
        #: "admission is compilation-free" receipt
        self.admission_report: Optional[dict] = None
        #: the precision this tenant's servable scores at ("f32" /
        #: "int8") — shared-servable tenants inherit the sharing
        #: tenant's; mirrored as a per-tenant string gauge
        self.precision = "f32"


class SharedScheduler:
    """One admission/placement layer multiplexing many servables on one
    device (module doc).  ``add_tenant`` deploys + warms, ``start()``
    spawns the scheduler thread, ``submit``/``predict`` take the tenant
    name."""

    def __init__(self, registry: Optional[ModelRegistry] = None, *,
                 max_batch_rows: int = 256, max_wait_ms: float = 2.0,
                 queue_capacity: int = 1024,
                 admit_fractions: Optional[Dict[str, float]] = None,
                 bulk_batch_rows: Optional[int] = None,
                 request_deadline_ms: Optional[float] = None,
                 group: Optional[MetricGroup] = None,
                 busy_clock: Optional[Any] = None):
        if max_batch_rows <= 0:
            raise ValueError("max_batch_rows must be positive")
        if max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        if queue_capacity <= 0:
            raise ValueError("queue_capacity must be positive")
        if request_deadline_ms is not None and request_deadline_ms <= 0:
            raise ValueError("request_deadline_ms must be positive "
                             "(or None to disable the deadline check)")
        self.registry = registry or ModelRegistry()
        self.max_batch_rows = max_batch_rows
        self.max_wait_s = max_wait_ms / 1e3
        self.queue_capacity = queue_capacity
        fractions = dict(DEFAULT_ADMIT_FRACTIONS)
        fractions.update(admit_fractions or {})
        if set(fractions) != set(SLO_CLASSES):
            raise ValueError(
                f"admit_fractions keys must be {SLO_CLASSES}, got "
                f"{tuple(sorted(fractions))}")
        last = 1.0 + 1e-9
        for slo in SLO_CLASSES:
            frac = fractions[slo]
            if not 0.0 < frac <= 1.0:
                raise ValueError(
                    f"admit fraction for {slo!r} must be in (0, 1], got "
                    f"{frac}")
            if frac > last:
                raise ValueError(
                    "admit fractions must be non-increasing in priority "
                    f"order {SLO_CLASSES} — a lower class admitting above "
                    "a higher one inverts the shed-order contract")
            last = frac
        #: per-class admission threshold in REQUESTS: a class sheds once
        #: the global queue depth reaches its limit
        self.admit_limits = {
            slo: max(1, int(round(queue_capacity * fractions[slo])))
            for slo in SLO_CLASSES}
        self.admit_limits[SLO_INTERACTIVE] = queue_capacity
        #: per-class batch-row cap.  A dispatched batch is not
        #: preemptible, so a FULL bulk batch is the worst head-of-line
        #: block an interactive arrival can hit — capping bulk batches
        #: at a quarter of the device batch (default; still a real
        #: bucket) bounds that block at ~1/4 of a batch service, a
        #: deliberate bulk-throughput-for-interactive-latency trade.
        #: Interactive/standard keep the full batch.
        if bulk_batch_rows is None:
            bulk_batch_rows = min(max_batch_rows,
                                  max(8, max_batch_rows // 4))
        if not 0 < bulk_batch_rows <= max_batch_rows:
            raise ValueError(
                f"bulk_batch_rows must be in (0, {max_batch_rows}], got "
                f"{bulk_batch_rows}")
        self.batch_rows = {SLO_INTERACTIVE: max_batch_rows,
                           SLO_STANDARD: max_batch_rows,
                           SLO_BULK: bulk_batch_rows}
        #: SLO deadline in seconds (ISSUE 20): a REQUEUED request (a
        #: chip died under its dispatch) already past this deadline
        #: sheds with :class:`DeadlineExceededError` instead of burning
        #: survivor capacity on an answer its caller stopped waiting
        #: for.  None = never expire (the default; first-dispatch
        #: requests are never deadline-checked — only the requeue path
        #: can make a request old enough to matter).
        self.request_deadline_s = (None if request_deadline_ms is None
                                   else request_deadline_ms / 1e3)

        self.group = group or MetricGroup("scheduler")
        self._batches = self.group.counter("batches")
        self._requests = self.group.counter("requests")
        self._queue_depth = self.group.gauge("queue_depth")
        self._queue_depth.set(0)
        self._health = self.group.gauge("health")
        self._health.set(HEALTH_SERVING)
        #: class-labeled shed counters — the shed-order evidence
        self._shed = {slo: self.group.counter(f"shed_{slo}")
                      for slo in SLO_CLASSES}
        #: brownout (ISSUE 20): level L sheds the bottom L SLO classes
        #: at ADMISSION while failover has the fleet capacity-short —
        #: bulk first, interactive protected by construction (the
        #: ladder tops out below the highest class).  Plain int read by
        #: the lock-free submit path, written by ``set_brownout``.
        self._brownout = 0
        self._brownout_gauge = self.group.gauge("brownout_level")
        self._brownout_gauge.set(0)
        #: requests put BACK at the head of their queues after an
        #: injected chip fault at the dispatch boundary (futures intact
        #: — the zero-drop evidence), and requests shed at requeue for
        #: blowing their SLO deadline
        self._requeued = self.group.counter("requeued_requests")
        self._deadline_shed = self.group.counter("deadline_shed")
        #: the attached failover driver (None until a FailoverDriver
        #: binds itself) — the dispatch seam hands it chip faults
        self._failover: Optional[Any] = None
        #: per-SLO-class queue depth gauges (ISSUE 17: the autoscale
        #: policy keys its pressure trigger on the INTERACTIVE depth,
        #: which the aggregate gauge hides under a bulk flood)
        self._class_depth = {slo: self.group.gauge(f"queue_depth_{slo}")
                             for slo in SLO_CLASSES}
        for gauge in self._class_depth.values():
            gauge.set(0)
        #: tenants serving quantized (ISSUE 18): the capacity planner's
        #: models-per-chip arithmetic needs to know how many tenants
        #: ride the int8 footprint; the per-tenant ``precision`` string
        #: gauge says WHICH (graftscope snapshots show generation +
        #: precision together)
        self._int8_tenants = self.group.gauge("int8_tenants")
        self._int8_tenants.set(0)
        #: chip-idle accounting (ISSUE 17): busy seconds accumulate
        #: around dispatch on ONE clock (``busy_clock``, injectable for
        #: tests), and ``chip_idle_fraction`` is windowed between
        #: snapshot() calls on that SAME clock — idle is
        #: 1 - busy/wall with both deltas from one domain, never a
        #: cross-clock ratio.  NaN until the first complete window
        #: (absent, not faked — the obs export stance).
        self._busy_clock = busy_clock or time.perf_counter
        self._busy_s = 0.0
        self._idle_window_start: Optional[float] = None
        self._idle_window_busy = 0.0
        self._idle_fraction = self.group.gauge("chip_idle_fraction")
        self._idle_fraction.set(float("nan"))
        #: the placement generation last applied via apply_placement —
        #: -1 until the autoscale controller first moves this scheduler
        self._placement_generation = self.group.gauge(
            "placement_generation")
        self._placement_generation.set(-1)
        self._tenant_group = self.group.add_group("tenants")

        self._tenants: Dict[str, Tenant] = {}
        #: names mid-admission (reserved before their slow unlocked
        #: deploy so a concurrent same-name admit loses BEFORE it can
        #: leave an orphaned generation in the registry)
        self._admitting: set = set()
        self._cond = threading.Condition()
        #: total queued requests across every tenant.  Plain int: the
        #: submit fast path reads it WITHOUT the lock (a stale read can
        #: only mis-shed at the saturation boundary, where shedding is
        #: the correct behavior anyway); all writes happen under
        #: ``_cond``.
        self._depth = 0
        #: per-class virtual time: the largest finish tag served so far
        #: — an idle tenant re-enters here instead of replaying banked
        #: credit against the tenants that kept the device busy
        self._vclass = {slo: 0.0 for slo in SLO_CLASSES}
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    # -- tenancy -------------------------------------------------------------
    def add_tenant(self, name: str, model: Any = None,
                   example: Optional[Table] = None, *,
                   slo: str = SLO_STANDARD, weight: float = 1.0,
                   servable_of: Optional[str] = None,
                   **servable_kwargs: Any) -> Tenant:
        """Admit a tenant: deploy ``model`` (instance or saved-stage
        path) under the tenant's name and warm it — or, with
        ``servable_of``, share an existing tenant's servable (traffic
        multi-tenancy: N tenants, one model, one batch stream).

        Admission happens OFF the serving path (warm-up runs on this
        thread while every admitted tenant keeps serving), and is
        compilation-free for already-served schemas: the returned
        tenant's ``admission_report`` carries the warm-up source
        attribution — a same-schema join reads 0 compiles, all
        cache/aot hits."""
        if slo not in SLO_CLASSES:
            raise ValueError(f"unknown SLO class {slo!r}; one of "
                             f"{SLO_CLASSES}")
        if weight <= 0:
            raise ValueError("weight must be positive")
        # RESERVE the name before the (slow, unlocked) deploy: two
        # concurrent admits of one name must not both reach the
        # registry — the loser's deploy would stay live and serve the
        # winner's traffic with the wrong model
        with self._cond:
            if name in self._tenants or name in self._admitting:
                raise ValueError(f"tenant {name!r} already admitted")
            self._admitting.add(name)
        try:
            # spaced expensive-gauge refresh: ONE loop drives every
            # tenant's metrics, so per-batch O(window) quantile work
            # would multiply by the tenant count and come straight out
            # of serving latency
            metrics = ServingMetrics(
                group=self._tenant_group.add_group(name),
                min_publish_interval_s=0.02)
            # the class label rides the tenant's own subtree so signal
            # consumers (autoscale) can group tenants per SLO from one
            # snapshot; a string gauge stays out of prometheus exports
            metrics.group.gauge("slo").set(slo)
            if servable_of is not None:
                if model is not None or example is not None:
                    raise ValueError(
                        "servable_of shares an existing servable — do "
                        "not pass model/example")
                sharing = self._tenants.get(servable_of)
                if sharing is None:
                    raise KeyError(f"servable_of={servable_of!r} is not "
                                   "an admitted tenant")
                serve_name = sharing.serve_name
                report = None
                precision = sharing.precision
            else:
                if model is None:
                    raise ValueError("admitting a tenant needs a model "
                                     "(or servable_of=)")
                serve_name = name
                servable_kwargs.setdefault("max_batch_rows",
                                           self.max_batch_rows)
                deployed = self.registry.deploy(
                    name, model, example, metrics=metrics,
                    **servable_kwargs)
                report = getattr(deployed.servable, "warmup_report", None)
                precision = getattr(deployed.servable, "precision",
                                    "f32")
            # the precision label rides the tenant subtree like the SLO
            # class: graftscope snapshots show which generation serves
            # at which precision (a string gauge stays out of
            # prometheus exports, the slo-gauge stance)
            metrics.group.gauge("precision").set(precision)
            tenant = Tenant(name, serve_name, slo, weight, metrics)
            tenant.admission_report = report
            tenant.precision = precision
            with self._cond:
                self._tenants[name] = tenant
        finally:
            with self._cond:
                self._admitting.discard(name)
        tracer.instant("tenant_admitted", cat="serving", tenant=name,
                       op=slo)
        return tenant

    def tenant(self, name: str) -> Tenant:
        tenant = self._tenants.get(name)
        if tenant is None:
            raise KeyError(f"unknown tenant {name!r}; admitted: "
                           f"{sorted(self._tenants)}")
        return tenant

    def tenants(self) -> List[str]:
        with self._cond:
            return sorted(self._tenants)

    def delta_publisher(self, name: str):
        """A continuous-learning publisher bound to this tenant's
        registry entry and metrics — a delta push to one tenant swaps
        ONLY that tenant's generation; every other tenant's servable,
        compiled programs, and latency accounting are untouched (the
        chaos contract, ``tests/test_scheduler.py``)."""
        from ..online.publish import DeltaPublisher

        tenant = self.tenant(name)
        return DeltaPublisher(self.registry, tenant.serve_name,
                              metrics=tenant.metrics)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "SharedScheduler":
        if self._thread is not None:
            raise RuntimeError("scheduler already started")
        for tenant in self._tenants.values():
            deployed = self.registry.current(tenant.serve_name)
            if not deployed.servable.ready:
                raise RuntimeError(
                    f"tenant {tenant.name!r} servable is not warmed — "
                    "add_tenant warms automatically; a custom deploy "
                    "must warm_up() before start()")
        self._thread = threading.Thread(
            target=self._serve_loop, daemon=True,
            name="flink-ml-tpu-scheduler")
        self._thread.start()
        return self

    def close(self, timeout: float = 10.0) -> None:
        """Stop admitting, drain queued requests, join the loop."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    # -- request path --------------------------------------------------------
    def submit(self, name: str, table: Table) -> Future:
        """Enqueue one request for ``name``; sheds with
        :class:`ServingOverloadedError` once the global queue reaches
        the tenant's CLASS threshold (bulk first, interactive last).

        The overload check runs TWICE: a lock-free fast path on the
        plain depth counter — under saturation every shed returns
        without ever touching the queue lock, so admission control
        cannot serialize the very load spike it exists to absorb — and
        the authoritative re-check under the lock for admits near the
        boundary."""
        tenant = self.tenant(name)
        rows = table.num_rows
        if rows == 0:
            raise ValueError("cannot serve an empty (0-row) request")
        if rows > self.batch_rows[tenant.slo]:
            raise ValueError(
                f"request has {rows} rows > the {tenant.slo!r} class's "
                f"batch cap {self.batch_rows[tenant.slo]}; split it "
                "client-side")
        # brownout gate (ISSUE 20): while failover has the fleet
        # capacity-short, level L refuses the bottom L classes outright
        # — lock-free like the overload fast path, and accounted as a
        # shed (it IS one, just triggered by capacity instead of depth)
        brownout = self._brownout
        if (brownout > 0 and self._class_rank(tenant.slo)
                >= len(SLO_CLASSES) - brownout):
            raise self._brownout_error(tenant, brownout)
        limit = self.admit_limits[tenant.slo]
        if self._depth >= limit:          # lock-free fast path
            raise self._shed_error(tenant, self._depth, limit)
        with self._cond:
            if self._closed:
                raise RuntimeError("scheduler is closed")
            if self._depth >= limit:      # authoritative re-check
                raise self._shed_error(tenant, self._depth, limit)
            request = ServingRequest(table, rows)
            if not tenant.pending:
                # idle -> backlogged: re-enter at the class virtual time
                tenant.vft = max(tenant.vft, self._vclass[tenant.slo])
            tenant.pending.append(request)
            self._depth += 1
            self._cond.notify_all()
        tenant.metrics.on_submit(len(tenant.pending))
        return request.future

    def predict(self, name: str, table: Table,
                timeout: Optional[float] = 30.0) -> Table:
        return self.submit(name, table).result(timeout)

    def _shed_error(self, tenant: Tenant, depth: int,
                    limit: int) -> ServingOverloadedError:
        """Account one shed (class counter, tenant metrics with the live
        generation stamped, health -> DEGRADED, tracer instant) and
        build the admission-control error.  Deliberately lock-free:
        counter bumps and the registry's unlocked generation read."""
        self._shed[tenant.slo].inc()
        generation = self.registry.live_generation(tenant.serve_name)
        tenant.metrics.on_shed(len(tenant.pending), generation=generation)
        self._health.set(HEALTH_DEGRADED)
        tracer.instant("shed", cat="serving", tenant=tenant.name,
                       generation=generation)
        return ServingOverloadedError(
            f"scheduler queue depth {depth} >= {limit} (class "
            f"{tenant.slo!r} threshold of capacity "
            f"{self.queue_capacity}); request shed — queue full for this "
            "class; retry with backoff or lower the offered load")

    def _brownout_error(self, tenant: Tenant,
                        level: int) -> ServingOverloadedError:
        """Account a brownout refusal exactly like an overload shed
        (class counter, tenant metrics, DEGRADED, tracer) — the cause
        differs (capacity short, not queue full), the contract does
        not."""
        self._shed[tenant.slo].inc()
        generation = self.registry.live_generation(tenant.serve_name)
        tenant.metrics.on_shed(len(tenant.pending), generation=generation)
        self._health.set(HEALTH_DEGRADED)
        tracer.instant("shed", cat="serving", tenant=tenant.name,
                       generation=generation, x_brownout=str(level))
        return ServingOverloadedError(
            f"brownout level {level}: class {tenant.slo!r} is shed while "
            "the serving fleet is capacity-short after a chip loss; "
            "retry after the fleet recovers")

    # -- the scheduler loop --------------------------------------------------
    def _serve_loop(self) -> None:
        while True:
            try:
                formed = self._next_batch(timeout=0.05)
            except Exception:  # noqa: BLE001 — ONE loop serves every
                # tenant; it must survive anything batch formation throws
                log.exception("scheduler batch formation failed")
                continue
            if formed is not None:
                try:
                    self._dispatch(*formed)
                except Exception:  # noqa: BLE001 — futures are already
                    # resolved/failed by _dispatch; this guards the
                    # post-resolution accounting
                    log.exception("scheduler dispatch accounting failed")
            else:
                with self._cond:
                    if self._closed and self._depth == 0:
                        return

    def _class_rank(self, slo: str) -> int:
        return SLO_CLASSES.index(slo)

    def _pick_head(self) -> Optional[Tenant]:
        """Highest non-empty class, lowest virtual-finish tag (name as
        the deterministic tiebreak).  Caller holds the lock."""
        best: Optional[Tenant] = None
        for tenant in self._tenants.values():
            if not tenant.pending:
                continue
            if best is None:
                best = tenant
                continue
            rank, best_rank = (self._class_rank(tenant.slo),
                               self._class_rank(best.slo))
            if (rank, tenant.vft, tenant.name) < (best_rank, best.vft,
                                                  best.name):
                best = tenant
        return best

    def _drain_into(self, picked: List[Tuple[Tenant, ServingRequest]],
                    serve_name: str, slo: str, rows: int) -> int:
        """Coalesce pending same-class requests for ``serve_name`` in
        WFQ order while they fit the class's batch cap.  Caller holds
        the lock."""
        cap = self.batch_rows[slo]
        while True:
            cands = [t for t in self._tenants.values()
                     if t.slo == slo and t.serve_name == serve_name
                     and t.pending
                     and rows + t.pending[0].rows <= cap]
            if not cands:
                return rows
            tenant = min(cands, key=lambda t: (t.vft, t.name))
            request = tenant.pending.popleft()
            self._depth -= 1
            tenant.vft += request.rows / tenant.weight
            self._vclass[slo] = max(self._vclass[slo], tenant.vft)
            picked.append((tenant, request))
            rows += request.rows

    def _next_batch(self, timeout: Optional[float] = None):
        """Form the next micro-batch: pick the WFQ head in the highest
        pending class, then coalesce same-class arrivals for the same
        servable under the max-wait deadline — preempted early if a
        HIGHER class goes pending (its requests must never queue behind
        a lower class's coalescing window)."""
        with self._cond:
            if self._depth == 0:
                if self._closed:
                    return None
                self._cond.wait(timeout)
                if self._depth == 0:
                    return None
            head = self._pick_head()
            serve_name, slo = head.serve_name, head.slo
            picked: List[Tuple[Tenant, ServingRequest]] = []
            rows = 0
            deadline = time.perf_counter() + self.max_wait_s
            while True:
                rows = self._drain_into(picked, serve_name, slo, rows)
                if rows >= self.batch_rows[slo] or self._closed \
                        or self._depth > 0:
                    # full — or OTHER work is queued (a higher class, a
                    # different servable, a request that didn't fit):
                    # the coalescing deadline may hold the device only
                    # when it would otherwise idle, never while any
                    # request waits — ship now, re-pick next loop (a
                    # pending higher class preempts a lower batch's
                    # window here)
                    break
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            depth = self._depth
        self._queue_depth.set(depth)
        if not picked:
            return None
        return serve_name, picked

    # -- dispatch ------------------------------------------------------------
    def _requeue(self,
                 picked: List[Tuple[Tenant, ServingRequest]]) -> int:
        """Put a formed-but-undispatched batch BACK: each request
        returns to the FRONT of its tenant's queue (reversed, so the
        original order is restored), the WFQ tags and depth roll back,
        and the futures stay untouched — the retried dispatch answers
        them bit-identically, so a chip death drops ZERO requests.  A
        requeued request already past its SLO deadline sheds with
        :class:`DeadlineExceededError` instead (futures failed OUTSIDE
        the lock).  Returns the number requeued."""
        deadline_s = self.request_deadline_s
        now = time.perf_counter() if deadline_s is not None else 0.0
        expired: List[Tuple[Tenant, ServingRequest]] = []
        requeued: Dict[str, int] = {}
        with self._cond:
            for tenant, request in reversed(picked):
                # roll the WFQ advance back first — it happened in
                # _drain_into for every picked request, served or not
                tenant.vft -= request.rows / tenant.weight
                if (deadline_s is not None
                        and now - request.submitted_at > deadline_s):
                    expired.append((tenant, request))
                    continue
                tenant.pending.appendleft(request)
                self._depth += 1
                requeued[tenant.name] = requeued.get(tenant.name, 0) + 1
            if requeued:
                self._cond.notify_all()
        n = sum(requeued.values())
        if n:
            self._requeued.inc(n)
        for name, count in requeued.items():
            self._tenants[name].metrics.on_requeue(count)
        for tenant, request in expired:
            self._deadline_shed.inc()
            self._shed[tenant.slo].inc()
            generation = self.registry.live_generation(tenant.serve_name)
            tenant.metrics.on_shed(len(tenant.pending),
                                   generation=generation)
            tracer.instant("deadline_shed", cat="serving",
                           tenant=tenant.name, generation=generation,
                           request_id=request.request_id)
            request.future.set_exception(DeadlineExceededError(
                f"request for tenant {tenant.name!r} requeued after a "
                f"chip fault is already {now - request.submitted_at:.3f}s"
                f" old > the {deadline_s:.3f}s SLO deadline; shed "
                "instead of burning survivor capacity"))
        return n

    def _dispatch(self, serve_name: str,
                  picked: List[Tuple[Tenant, ServingRequest]]) -> None:
        # the chip-fault seam (ISSUE 20): fired BEFORE anything else —
        # an injected chip_down/chip_flap here requeues the batch with
        # futures intact (lossless by construction) and hands the fault
        # to the attached FailoverDriver, which re-places and retries
        try:
            fault_point(DISPATCH_SCOPE)
        except (InjectedChipDown, InjectedChipFlap) as exc:
            requeued = self._requeue(picked)
            driver = self._failover
            if driver is not None:
                driver.on_chip_fault(exc, requeued=requeued)
            return
        # ONE registry capture per batch — the hot-swap atomicity point
        # (every request in the batch runs on one fully-warmed version).
        # Any failure before the futures resolve is delivered TO them:
        # a caller must never hang on a batch the loop gave up on.
        try:
            deployed = self.registry.current(serve_name)
        except BaseException as exc:  # noqa: BLE001 — e.g. undeployed
            for _, request in picked:
                request.future.set_exception(exc)
            return
        servable = deployed.servable
        rows = sum(r.rows for _, r in picked)
        batch_tenants = ",".join(sorted({t.name for t, _ in picked}))
        if tracer.enabled:
            formed = time.perf_counter()
            for tenant, request in picked:
                tracer.add("queue_wait", request.submitted_at, formed,
                           cat="serving", request_id=request.request_id,
                           generation=deployed.generation,
                           tenant=tenant.name)
        busy_t0 = self._busy_clock()
        try:
            with tracer.span("serve_batch", cat="serving",
                             generation=deployed.generation,
                             bucket=servable.bucket_for(rows),
                             tenant=batch_tenants):
                for _, request in picked:
                    servable.check_schema(request.table)
                table = concat_request_tables(
                    [r.table for _, r in picked])
                out = servable.predict(table)
        except BaseException as exc:  # noqa: BLE001 — delivered per-request
            for _, request in picked:
                request.future.set_exception(exc)
            return
        finally:
            # device-busy accounting: even a failed dispatch occupied
            # the chip — idle means NOTHING dispatched, not "nothing
            # succeeded"
            self._busy_s += self._busy_clock() - busy_t0
        offset = 0
        now = time.perf_counter()
        per_tenant: Dict[str, List] = {}
        for tenant, request in picked:
            if tracer.enabled:
                # committed BEFORE the future resolves (a woken caller
                # can already see its own span — the PR 13 contract)
                tracer.add("request", request.submitted_at, now,
                           cat="serving", request_id=request.request_id,
                           generation=deployed.generation,
                           tenant=tenant.name)
            request.future.set_result(
                out.slice(offset, offset + request.rows))
            offset += request.rows
            bucket_n, bucket_rows_, lats = per_tenant.setdefault(
                tenant.name, [0, 0, []])
            per_tenant[tenant.name] = [
                bucket_n + 1, bucket_rows_ + request.rows,
                lats + [now - request.submitted_at]]
        bucket = servable.bucket_for(rows)
        for name, (n_requests, t_rows, latencies) in per_tenant.items():
            tenant = self._tenants[name]
            tenant.rows_served += t_rows
            tenant.metrics.on_batch(
                n_requests=n_requests, rows=t_rows, bucket=bucket,
                latencies_s=latencies, queue_depth=len(tenant.pending),
                generation=deployed.generation)
        self._batches.inc()
        self._requests.inc(len(picked))
        depth = self._depth
        self._queue_depth.set(depth)
        # heal: once the queue recedes below EVERY class threshold,
        # nothing is being shed anymore — degradation is over.  An
        # active brownout blocks the heal: admission is still refusing
        # whole classes, so the scheduler IS degraded however shallow
        # the queue looks
        if (self._health.value != HEALTH_SERVING
                and depth < min(self.admit_limits.values())
                and self._brownout == 0):
            self._health.set(HEALTH_SERVING)

    # -- placement (ISSUE 17) ------------------------------------------------
    def apply_placement(self, pmap: Any) -> Dict[str, float]:
        """Adopt an autoscale :class:`~flink_ml_tpu.autoscale.placement.\
PlacementMap`: every placed tenant's WFQ weight becomes
        ``base_weight * chip_count`` — capacity share tracks the chip
        share the controller granted — and unplaced tenants keep their
        admission weight.  Pure bookkeeping on this (single-device)
        scheduler: no queue is touched, no batch re-formed; in-flight
        requests are unaffected.  Returns the applied name -> weight
        map (the actuation receipt the controller logs)."""
        with self._cond:
            applied: Dict[str, float] = {}
            for tenant in self._tenants.values():
                chips = len(pmap.chips_for(tenant.name))
                if chips > 0:
                    tenant.weight = tenant.base_weight * chips
                    applied[tenant.name] = tenant.weight
                else:
                    tenant.weight = tenant.base_weight
            self._placement_generation.set(pmap.generation)
        tracer.instant("placement_applied", cat="serving",
                       generation=pmap.generation,
                       x_tenants=str(len(applied)))
        return applied

    # -- failover (ISSUE 20) -------------------------------------------------
    def attach_failover(self, driver: Any) -> None:
        """Bind the :class:`~flink_ml_tpu.serving.failover.\
FailoverDriver`: the dispatch seam hands it injected chip faults
        (after requeueing the batch) and it drives ``set_brownout``."""
        self._failover = driver

    def set_brownout(self, level: int) -> int:
        """Set the brownout ladder rung: level L sheds the bottom L SLO
        classes at admission (0 = none).  Clamped so the highest class
        can NEVER be browned out — interactive protection is by
        construction, not configuration.  Lowering to 0 re-checks the
        heal condition (brownout blocks it while active)."""
        level = max(0, min(int(level), len(SLO_CLASSES) - 1))
        self._brownout = level
        self._brownout_gauge.set(level)
        if level > 0:
            self._health.set(HEALTH_DEGRADED)
        elif (self._health.value != HEALTH_SERVING
                and self._depth < min(self.admit_limits.values())):
            self._health.set(HEALTH_SERVING)
        return level

    @property
    def brownout_level(self) -> int:
        return self._brownout

    # -- observability -------------------------------------------------------
    @property
    def health(self) -> str:
        return self._health.value

    def shed_counts(self) -> Dict[str, int]:
        return {slo: c.value for slo, c in self._shed.items()}

    def _refresh_gauges(self) -> None:
        """Export-time gauge refresh: per-class queue depths (summed
        under the lock — the dispatch path never pays for them) and the
        windowed chip-idle fraction, both deltas on ``_busy_clock``."""
        with self._cond:
            depths = {slo: 0 for slo in SLO_CLASSES}
            int8_tenants = 0
            for tenant in self._tenants.values():
                depths[tenant.slo] += len(tenant.pending)
                int8_tenants += tenant.precision == "int8"
            busy = self._busy_s
        for slo, depth in depths.items():
            self._class_depth[slo].set(depth)
        self._int8_tenants.set(int8_tenants)
        now = self._busy_clock()
        if self._idle_window_start is not None:
            wall = now - self._idle_window_start
            if wall > 0:
                frac = 1.0 - (busy - self._idle_window_busy) / wall
                self._idle_fraction.set(min(1.0, max(0.0, frac)))
        self._idle_window_start = now
        self._idle_window_busy = busy

    def snapshot(self) -> Dict[str, Any]:
        """The scheduler's full metric subtree (scheduler gauges +
        per-tenant ServingMetrics) — a MetricsTree provider.  Tenant
        bundles space their expensive gauge refresh between batches
        (``min_publish_interval_s``), so the export path force-publishes
        each one first — exports never read interval-stale quantiles
        (the ``ServingMetrics.snapshot`` contract, kept here because
        this provider reads the shared group directly)."""
        with self._cond:
            tenants = list(self._tenants.values())
        for tenant in tenants:
            tenant.metrics.publish(force=True)
        self._refresh_gauges()
        return self.group.snapshot()
