"""Request queue + dynamic micro-batcher.

The serving problem on an accelerator is the inverse of the training
problem: traffic arrives as many SMALL concurrent requests (single rows to
a few dozen), but the device only earns its keep on large fixed-shape
batches.  The micro-batcher closes that gap: concurrent requests coalesce
into one batch under a **max-wait deadline** — the first request of a
batch never waits longer than ``max_wait_ms`` for company — and the batch
then pads to a power-of-two bucket downstream (``utils/padding.py``) so
the executor runs one of a bounded set of warm-compiled programs.

Admission control is the bounded queue: when ``queue_capacity`` requests
are already pending the submit is SHED with :class:`ServingOverloadedError`
(the documented backpressure signal — callers retry with jitter or spill
to a replica) instead of growing an unbounded latency tail.

Threading model: ``submit`` is called from any number of client threads;
``next_batch`` is called by exactly one consumer (the endpoint's serve
loop).  One condition variable covers both sides.
"""

from __future__ import annotations

import itertools
import threading
import time

from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from ..data.table import Table

__all__ = ["MicroBatcher", "ServingRequest", "ServingOverloadedError",
           "concat_request_tables"]


def concat_request_tables(tables) -> Table:
    """One batch Table from the requests' tables, in batch order — THE
    shared micro-batch assembly (endpoint serve loop + multi-tenant
    scheduler): column-aligned concat, zero copies for a single-request
    batch."""
    if len(tables) == 1:
        return tables[0]
    names = tables[0].column_names
    return Table({
        name: np.concatenate([t[name] for t in tables], axis=0)
        for name in names})

#: process-wide request-id source — THE ``request_id`` correlation id of
#: the span-tracing contract (``obs/trace.py``): assigned at submit,
#: carried by the request through queue-wait/serve spans, unique across
#: every endpoint in the process so one exported trace never aliases
#: two requests
_REQUEST_IDS = itertools.count(1)


class ServingOverloadedError(RuntimeError):
    """The serving queue is full; this request was shed (admission
    control).  The request was NOT enqueued — retry later or route to
    another replica."""


@dataclass
class ServingRequest:
    """One in-flight request: the input rows, the Future the caller awaits
    (resolves to the output Table slice for exactly these rows), and the
    submit timestamp the latency metrics are measured from."""
    table: Table
    rows: int
    future: Future = field(default_factory=Future)
    submitted_at: float = field(default_factory=time.perf_counter)
    request_id: int = field(default_factory=lambda: next(_REQUEST_IDS))


class MicroBatcher:
    """Bounded request queue with deadline-coalescing batch formation.

    ``next_batch`` drains pending requests into one batch while the total
    row count fits ``max_batch_rows``, waiting up to ``max_wait_ms`` (from
    the moment the first request is seen) for more arrivals; a request
    that would overflow the batch stays queued for the next one.  Requests
    are never split across batches, so a single request may hold at most
    ``max_batch_rows`` rows (validated at submit).
    """

    def __init__(self, *, max_batch_rows: int = 256,
                 max_wait_ms: float = 2.0,
                 queue_capacity: int = 1024):
        if max_batch_rows <= 0:
            raise ValueError("max_batch_rows must be positive")
        if max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        if queue_capacity <= 0:
            raise ValueError("queue_capacity must be positive")
        self.max_batch_rows = max_batch_rows
        self.max_wait_s = max_wait_ms / 1e3
        self.queue_capacity = queue_capacity
        self._pending: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        #: lock-free shed fast path (ISSUE 14 satellite): when True
        #: (production default), a submit against an already-full queue
        #: sheds on ONE unlocked read of the queue length — under
        #: saturation, thousands of shed decisions per second must not
        #: serialize on the hot queue lock they would otherwise all
        #: contend for.  The read is racy by design: it can only fire
        #: when the queue is AT capacity, where a concurrent drain
        #: making one slot free means at worst one spurious shed at the
        #:  saturation boundary — admission control's documented
        #: semantics either way.  The authoritative check under the
        #: lock still guards every admit.  (Toggle exists for the
        #: bench_multitenant A/B.)
        self.fast_shed = True

    def _shed_error(self) -> ServingOverloadedError:
        return ServingOverloadedError(
            f"serving queue full ({self.queue_capacity} requests "
            "pending); request shed — retry with backoff or route "
            "to another replica")

    # -- producer side ------------------------------------------------------
    def submit(self, table: Table) -> ServingRequest:
        rows = table.num_rows
        if rows == 0:
            raise ValueError("cannot serve an empty (0-row) request")
        if rows > self.max_batch_rows:
            raise ValueError(
                f"request has {rows} rows > max_batch_rows="
                f"{self.max_batch_rows}; split it client-side")
        # len(deque) is a single atomic read under the GIL — no lock
        if self.fast_shed and len(self._pending) >= self.queue_capacity \
                and not self._closed:
            raise self._shed_error()
        with self._cond:
            if self._closed:
                raise RuntimeError("serving endpoint is closed")
            if len(self._pending) >= self.queue_capacity:
                raise self._shed_error()
            request = ServingRequest(table, rows)
            self._pending.append(request)
            self._cond.notify_all()
        return request

    # -- consumer side ------------------------------------------------------
    def next_batch(self, timeout: Optional[float] = None
                   ) -> Optional[List[ServingRequest]]:
        """Form the next micro-batch.  Blocks up to ``timeout`` seconds for
        a first request (None = forever); returns None when nothing
        arrived (or the batcher is closed and drained).  Once a first
        request is in hand, coalesces arrivals until the batch is full or
        ``max_wait_ms`` has elapsed."""
        with self._cond:
            if not self._pending:
                if self._closed:
                    return None
                self._cond.wait(timeout)
                if not self._pending:
                    return None
            batch: List[ServingRequest] = []
            rows = 0
            deadline = time.perf_counter() + self.max_wait_s
            while True:
                while (self._pending
                       and rows + self._pending[0].rows
                       <= self.max_batch_rows):
                    request = self._pending.popleft()
                    batch.append(request)
                    rows += request.rows
                if rows >= self.max_batch_rows or self._pending \
                        or self._closed:
                    # full, or the next request doesn't fit, or closing:
                    # ship what we have
                    break
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            return batch

    def requeue(self, batch: List[ServingRequest]) -> int:
        """Put a formed-but-undispatched batch BACK at the FRONT of the
        queue (reversed, restoring the original order), futures and
        request ids untouched — the chip-fault path (ISSUE 20): the
        retried dispatch answers the same futures bit-identically, so
        a chip death at the dispatch boundary drops ZERO requests.
        Deliberately bypasses the capacity check: these requests were
        already admitted once, and bouncing them now WOULD be a drop."""
        with self._cond:
            for request in reversed(batch):
                self._pending.appendleft(request)
            if batch:
                self._cond.notify_all()
        return len(batch)

    # -- lifecycle ----------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return len(self._pending)

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def empty(self) -> bool:
        return not self._pending

    def close(self) -> None:
        """Stop admitting; already-queued requests still drain through
        ``next_batch``."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
