"""Serving observability: per-endpoint latency quantiles + throughput.

Reuses the framework's :class:`~flink_ml_tpu.utils.metrics.MetricGroup`
registry (the Flink metric-group analog) so an endpoint's gauges flatten
into the same ``snapshot()`` namespace as training metrics.  The latency
quantiles come from a bounded ring buffer — O(window) memory for a
process-lifetime endpoint, quantiles over the most recent ``window``
requests (the operationally relevant horizon for p99).
"""

from __future__ import annotations

import threading
import time

from typing import Dict, List, Optional

import numpy as np

from ..utils.metrics import MetricGroup

__all__ = ["LatencyTracker", "ServingMetrics", "HEALTH_SERVING",
           "HEALTH_DEGRADED"]

#: Endpoint health states (the ``health`` gauge).  SERVING = the live
#: generation is the intended one; DEGRADED = the newest deploy failed
#: and traffic is riding the rolled-back previous generation — correct
#: answers, stale model, page the operator.
HEALTH_SERVING = "SERVING"
HEALTH_DEGRADED = "DEGRADED"


class LatencyTracker:
    """Ring buffer of the most recent ``window`` request latencies
    (seconds); thread-safe, constant memory."""

    def __init__(self, window: int = 4096):
        if window <= 0:
            raise ValueError("window must be positive")
        self._buf = np.zeros((window,), np.float64)
        self._idx = 0
        self._count = 0
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        with self._lock:
            self._buf[self._idx] = seconds
            self._idx = (self._idx + 1) % self._buf.shape[0]
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    def quantile(self, q: float) -> float:
        """Latency quantile in SECONDS over the retained window (0.0 when
        nothing recorded yet)."""
        return self.quantiles((q,))[0]

    def quantiles(self, qs) -> List[float]:
        """Several quantiles under ONE lock acquisition / ring copy (the
        p50+p99 publish pair)."""
        with self._lock:
            n = min(self._count, self._buf.shape[0])
            if n == 0:
                return [0.0 for _ in qs]
            vals = np.quantile(self._buf[:n], list(qs))
        return [float(v) for v in vals]


class ServingMetrics:
    """The per-endpoint metric bundle: queue depth, batch fill ratio,
    p50/p99 latency, requests/sec, shed count — all living in one
    ``MetricGroup`` subtree so ``group.snapshot()`` exports them next to
    every other framework metric."""

    def __init__(self, group: Optional[MetricGroup] = None,
                 latency_window: int = 4096,
                 min_publish_interval_s: float = 0.0):
        #: minimum spacing between the EXPENSIVE publish work (the
        #: O(window) quantile pass + the kernel-gauge republish).  The
        #: default 0.0 keeps the classic refresh-per-batch behavior;
        #: the multi-tenant scheduler sets a small interval on its
        #: per-tenant bundles — ONE serve loop drives every tenant's
        #: metrics, so per-batch O(window) work there multiplies by the
        #: tenant count and comes straight out of serving latency
        #: (ISSUE 14).  Counters/gauges on the request path are always
        #: live; only the derived quantile/kernel gauges are spaced,
        #: and ``snapshot()`` forces a refresh so exports never read
        #: stale.
        self._min_publish_interval = min_publish_interval_s
        self._last_expensive_publish = 0.0
        self.group = group or MetricGroup("serving")
        self.requests = self.group.counter("requests")
        self.batches = self.group.counter("batches")
        self.shed = self.group.counter("shed")
        #: requests returned to the queue head after a chip fault at
        #: the dispatch boundary (ISSUE 20) — futures intact, answered
        #: by the retried dispatch; a nonzero count with zero drops is
        #: the failover losslessness receipt
        self.requeued = self.group.counter("requeued")
        #: failed hot-swaps healed by rolling back to the live generation
        self.rollbacks = self.group.counter("rollbacks")
        #: continuous-learning publish accounting (ISSUE 7): how the live
        #: generation last changed — device-resident delta swaps vs full
        #: load->warm->swap deploys — plus model freshness
        self.publishes_delta = self.group.counter("publishes_delta")
        self.publishes_full = self.group.counter("publishes_full")
        self._staleness = self.group.gauge("model_staleness_seconds")
        #: never-published = NaN (absent in exports), never a fake age
        self._staleness.set(float("nan"))
        self._publish_rate = self.group.gauge("publishes_per_sec")
        self._publish_bytes = self.group.gauge("last_publish_bytes")
        self._last_publish_at: Optional[float] = None
        self._publish_rate_value = 0.0
        self._health = self.group.gauge("health")
        self._health.set(HEALTH_SERVING)
        #: generation live at the most recent shed (NaN = never shed —
        #: absent in exports, the staleness-gauge stance)
        self._shed_generation = self.group.gauge("last_shed_generation")
        self._shed_generation.set(float("nan"))
        self._queue_depth = self.group.gauge("queue_depth")
        self._fill = self.group.gauge("batch_fill_ratio")
        self._p50 = self.group.gauge("latency_p50_ms")
        self._p99 = self.group.gauge("latency_p99_ms")
        #: retrieval quality (ISSUE 19): sampled-query recall@k against
        #: an exact scan (``retrieval/metrics.py::RecallProbe``); NaN =
        #: no probe has published — absent in exports, never a fake 1.0
        self._recall_probe = self.group.gauge("recall_probe")
        self._recall_probe.set(float("nan"))
        self._rate = self.group.gauge("requests_per_sec")
        self._generation = self.group.gauge("model_generation")
        self.latency = LatencyTracker(latency_window)
        self._rate_lock = threading.Lock()
        self._rate_t: Optional[float] = None
        self._rate_value = 0.0
        self._published_count = 0    # nothing recorded -> nothing to publish
        #: kernel-registry observability (ISSUE 10): the endpoint
        #: re-exports the process-wide dispatch surface's compile-count /
        #: cache-hit / dispatch-latency gauges into its own subtree, so
        #: cross-consumer compile reuse (warm-up vs steady state, CV
        #: folds, hot-swap generations) is visible per endpoint snapshot
        self._kernel_group = self.group.add_group("kernels")
        self._kernel_published = -1

    def on_requeue(self, n: int = 1) -> None:
        """``n`` of this tenant's in-flight requests went back to the
        queue head after a chip fault (see ``requeued`` counter doc)."""
        self.requeued.inc(n)

    def on_shed(self, queue_depth: int,
                generation: Optional[int] = None) -> None:
        """One shed (admission control dropped a request).  ``generation``
        stamps the live model generation serving at the time — the
        publish-correlation hook (never-shed endpoints read NaN, the
        absent-in-exports sentinel, like staleness)."""
        self.shed.inc()
        self._queue_depth.set(queue_depth)
        if generation is not None:
            self._shed_generation.set(generation)

    @property
    def health(self) -> str:
        return self._health.value

    def on_rollback(self) -> None:
        """A hot-swap failed load/warm-up and the registry rolled back:
        the endpoint keeps serving the previous generation (no dropped
        requests) but the intended model never went live — DEGRADED
        until a deploy succeeds."""
        self.rollbacks.inc()
        self._health.set(HEALTH_DEGRADED)

    def on_deploy(self, generation: int) -> None:
        """A deploy published: record the live generation and (re)assert
        SERVING — a successful swap heals a DEGRADED endpoint."""
        self._generation.set(generation)
        self._health.set(HEALTH_SERVING)

    def on_publish(self, generation: int, *, mode: str = "full",
                   payload_bytes: Optional[int] = None,
                   now: Optional[float] = None) -> None:
        """A continuous-learning publish landed (``mode`` "delta" for a
        device-resident buffer swap, anything else counts as full).
        Resets the staleness gauge and feeds the publishes/sec EWMA (the
        on_batch requests/sec stance)."""
        self.on_deploy(generation)
        (self.publishes_delta if mode == "delta"
         else self.publishes_full).inc()
        if payload_bytes is not None:
            self._publish_bytes.set(int(payload_bytes))
        now = time.time() if now is None else now
        with self._rate_lock:
            if self._last_publish_at is not None:
                inst = 1.0 / max(now - self._last_publish_at, 1e-9)
                self._publish_rate_value = (
                    0.8 * self._publish_rate_value + 0.2 * inst
                    if self._publish_rate_value else inst)
                self._publish_rate.set(round(self._publish_rate_value, 3))
            self._last_publish_at = now
        self._staleness.set(0.0)

    def touch_staleness(self, now: Optional[float] = None) -> None:
        """Refresh the model-staleness gauge (seconds since the last
        publish).  Called from the serve loop per batch — one
        ``time.time()`` — so the gauge stays live between publishes; a
        never-published endpoint reads NaN (unknown, not fresh — and
        NaN, not the old ``-1`` sentinel, so snapshot consumers and the
        Prometheus writer emit ABSENT instead of a fake negative age;
        ISSUE 13 satellite, regression-tested in tests/test_obs.py)."""
        if self._last_publish_at is None:
            self._staleness.set(float("nan"))
            return
        now = time.time() if now is None else now
        self._staleness.set(round(now - self._last_publish_at, 3))

    @property
    def staleness_seconds(self) -> float:
        return self._staleness.value

    def on_recall_probe(self, value: float) -> None:
        """A retrieval recall probe published its running mean (see
        ``retrieval/metrics.py::RecallProbe.publish``)."""
        self._recall_probe.set(float(value))

    @property
    def recall_probe(self) -> float:
        return self._recall_probe.value

    def on_submit(self, queue_depth: int) -> None:
        self._queue_depth.set(queue_depth)

    def on_batch(self, *, n_requests: int, rows: int, bucket: int,
                 latencies_s: List[float], queue_depth: int,
                 generation: Optional[int] = None) -> None:
        """Record one served micro-batch.  ``bucket`` is the padded batch
        size the executor compiled for — ``rows / bucket`` is the fill
        ratio (1.0 = the padding overhead was zero)."""
        now = time.perf_counter()
        self.batches.inc()
        self.requests.inc(n_requests)
        for lat in latencies_s:
            self.latency.record(lat)
        self._queue_depth.set(queue_depth)
        self._fill.set(round(rows / max(bucket, 1), 4))
        self.touch_staleness(time.time())
        self.publish()
        if generation is not None:
            self._generation.set(generation)
        with self._rate_lock:
            if self._rate_t is not None:
                dt = max(now - self._rate_t, 1e-9)
                inst = n_requests / dt
                # EWMA over batches: smooth enough to gauge, cheap enough
                # to update on every batch
                self._rate_value = (0.8 * self._rate_value + 0.2 * inst
                                    if self._rate_value else inst)
                self._rate.set(round(self._rate_value, 2))
            self._rate_t = now

    def publish(self, force: bool = False) -> None:
        """Refresh the p50/p99 gauges from the latency ring — ONE
        np.quantile pass for both, and skipped entirely when no new
        samples arrived since the last publish (an idle endpoint's metric
        tick must not pay an O(window) sort under the ring lock every
        time), or when ``min_publish_interval_s`` hasn't elapsed
        (``force`` — the snapshot path — overrides).  Kernel-registry
        gauges refresh on the same cadence (skip-if-unchanged on the
        dispatch counter)."""
        from ..kernels.registry import kernel_stats

        if self._min_publish_interval and not force:
            now = time.monotonic()
            if now - self._last_expensive_publish \
                    < self._min_publish_interval:
                return
            self._last_expensive_publish = now
        if kernel_stats.dispatches != self._kernel_published:
            kernel_stats.publish(self._kernel_group)
            self._kernel_published = kernel_stats.dispatches
        count = self.latency.count
        if count == self._published_count:
            return
        p50, p99 = self.latency.quantiles((0.50, 0.99))
        self._p50.set(round(1e3 * p50, 3))
        self._p99.set(round(1e3 * p99, 3))
        self._published_count = count

    def snapshot(self) -> Dict[str, object]:
        self.publish(force=True)    # exports never read interval-stale
        return self.group.snapshot()
