"""Serving fleet failover — chip-loss detection, re-placement, brownout
(ISSUE 20).

Training got its failure story in PR 15: a heartbeat lease table over
workers, churn injected through the seeded fault seams, and a death
mid-chunk degrading to a bit-exact restore onto the survivors.  Serving
had none — the multi-tenant fabric (PR 14), the autoscale control plane
(PR 17), and the int8/retrieval servables (PRs 18–19) all assumed every
serving chip stays healthy forever.  This module is the serving-side
analog, built from the same parts:

- :class:`FleetHealth` — the PR 15 lease-table idiom over serving
  **chips**: injectable clock, per-chip leases with deterministic
  expiry (``lease_timeout_s=None`` disables it for the single-process
  harness), an ``epoch``/``transitions``/``counters`` audit surface,
  and a :meth:`FleetHealth.poll` that fires the ``serving.chip`` fault
  scope so seeded ``chip_down``/``chip_flap`` faults translate into
  deterministic, replayable chip transitions (the
  ``elastic.membership`` pattern).
- :class:`FailoverDriver` — detection to recovery.  On a chip loss it
  re-places the dead chip's tenants onto survivors through the PR 17
  :class:`~flink_ml_tpu.autoscale.placement.PlacementStore` CAS path
  (failover and the autoscaler share ONE placement generation stream,
  so a racing ``tick()`` resolves through one
  :class:`~flink_ml_tpu.autoscale.placement.PlacementConflict` retry
  instead of a fight), re-admits moved tenants (an AOT-cache-warm
  admission: the servable is already ready, so the re-placement
  publish costs ZERO new lowerings — and the generation bump is what
  lets an in-flight :class:`~flink_ml_tpu.online.publish.DeltaPublisher`
  notice the move and re-anchor, its existing idempotent heal), and
  drives the **brownout ladder** while capacity is short.
- **Lossless in-flight requests.**  The ``chip_down``/``chip_flap``
  kinds raise at the scheduler's DISPATCH boundary
  (:data:`~flink_ml_tpu.serving.scheduler.DISPATCH_SCOPE`), BEFORE the
  batch's predict runs; the scheduler requeues the picked requests at
  the front of their tenants' queues with their futures untouched.
  Scoring is idempotent and the batcher owns the request futures, so
  ZERO requests drop and every retried request is answered
  bit-identically to an unfailed run (the chaos contract,
  ``tests/test_faults.py``).  A requeued request already past its SLO
  deadline sheds with
  :class:`~flink_ml_tpu.robustness.retry.DeadlineExceededError`
  (fatal-not-retryable) instead of burning survivor capacity.
- **SLO-aware brownout with hysteresis.**  Capacity-short operation
  extends shed-order-by-construction into a per-class ladder: level L
  sheds the bottom L SLO classes at admission (bulk first, interactive
  protected by the strict dispatch priority — the ladder maxes out at
  ``len(SLO_CLASSES) - 1``).  Raising the level is immediate; lowering
  waits ``hysteresis_s`` of stable fleet on the injected clock, and a
  recovered chip's placement is only restored after the same window —
  so a flapping chip costs at most one placement move per stability
  window, never a thrash.
- **N-way replication for high-SLO tenants.**  The registry shares one
  executable per schema, so :meth:`FailoverDriver.ensure_replicas` is
  params-only HBM cost: a replicated tenant keeps a surviving chip
  through any single loss and its failover window is ONE dispatch (no
  re-admission, no warm), while an unreplicated tenant pays the
  re-warm window.  The A/B is measured in ``bench.py::bench_failover``.

Observability: fleet-health gauges under the ``failover`` metric group
(``chips_live``/``chips_down``/``brownout_level``/counters), and
``chip_lost``/``failover_complete``/``failover_restore`` tracer
instants carrying the correlation contract (``generation``, ``tenant``;
chip ids ride ``x_``-prefixed experiment keys).
"""

from __future__ import annotations

import threading
import time

from dataclasses import dataclass
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Tuple)

from ..obs.trace import tracer
from ..robustness.faults import (InjectedChipDown, InjectedChipFlap,
                                 fault_point)
from ..utils.metrics import MetricGroup
from .scheduler import DISPATCH_SCOPE, SLO_CLASSES

__all__ = ["CHIP_SCOPE", "DISPATCH_SCOPE", "ChipLease", "FleetHealth",
           "FailoverDriver", "FailoverReport"]

#: the health-poll fault seam: each :meth:`FleetHealth.poll` is one
#: invocation, so a seeded ``chip_down``/``chip_flap`` schedule maps to
#: deterministic poll indices (the ``elastic.membership`` idiom)
CHIP_SCOPE = "serving.chip"


@dataclass
class ChipLease:
    """One serving chip's lease: refreshed by :meth:`FleetHealth.\
heartbeat`, reaped by :meth:`FleetHealth.expire` once ``expires_at``
    passes (``None`` = expiry disabled).  ``order`` is the admission
    sequence — the LIFO victim order injected faults use, mirroring the
    elastic coordinator's preemption choice."""

    chip: int
    joined_at: float
    expires_at: Optional[float]
    order: int


class FleetHealth:
    """The serving-side lease table (PR 15 idiom over chips).

    All transitions are deterministic functions of (clock, schedule):
    explicit :meth:`fail`/:meth:`recover`, lease :meth:`expire` on the
    injected clock, and :meth:`poll` — the periodic health boundary
    that fires :data:`CHIP_SCOPE` and translates injected
    ``chip_down``/``chip_flap`` faults into LIFO-victim deaths (a flap
    schedules its own recovery ``flap_recovery_polls`` polls later).
    ``transitions`` is the audit log chaos tests read; ``epoch`` bumps
    on every membership change so consumers can cheaply detect drift.
    """

    SCOPE = CHIP_SCOPE

    def __init__(self, chips: Iterable[int], *,
                 lease_timeout_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 flap_recovery_polls: int = 2):
        if lease_timeout_s is not None and lease_timeout_s <= 0:
            raise ValueError("lease_timeout_s must be positive (or None "
                             "to disable expiry)")
        if flap_recovery_polls < 1:
            raise ValueError("flap_recovery_polls must be >= 1")
        self.clock = clock
        self.lease_timeout_s = lease_timeout_s
        self.flap_recovery_polls = flap_recovery_polls
        self._lock = threading.Lock()
        self._leases: Dict[int, ChipLease] = {}
        #: chip -> clock stamp of its death (declared-dead set)
        self._down: Dict[int, float] = {}
        #: chip -> clock stamp it (re)joined — the hysteresis input
        self._live_since: Dict[int, float] = {}
        #: chip -> polls until a flap's scheduled recovery
        self._flap_pending: Dict[int, int] = {}
        self._order = 0
        self._epoch = 0
        self.transitions: List[Tuple[str, int, int]] = []
        self.counters: Dict[str, int] = {
            "deaths": 0, "flaps": 0, "expiries": 0, "recoveries": 0,
            "suppressed": 0, "polls": 0,
        }
        now = self.clock()
        for chip in sorted(int(c) for c in chips):
            if chip in self._leases:
                raise ValueError(f"chip {chip} admitted twice")
            self._leases[chip] = ChipLease(
                chip=chip, joined_at=now,
                expires_at=self._lease_deadline(now), order=self._order)
            self._live_since[chip] = now
            self._order += 1
        if not self._leases:
            raise ValueError("FleetHealth needs at least one chip")

    def _lease_deadline(self, now: float) -> Optional[float]:
        if self.lease_timeout_s is None:
            return None
        return now + self.lease_timeout_s

    # -- reads ---------------------------------------------------------------
    @property
    def epoch(self) -> int:
        return self._epoch

    def live(self) -> List[int]:
        with self._lock:
            return sorted(self._leases)

    def down(self) -> List[int]:
        with self._lock:
            return sorted(self._down)

    def is_live(self, chip: int) -> bool:
        return chip in self._leases

    def live_since(self, chip: int) -> Optional[float]:
        """Clock stamp the chip last (re)joined — None while down."""
        with self._lock:
            if chip not in self._leases:
                return None
            return self._live_since.get(chip)

    # -- transitions ---------------------------------------------------------
    def _record(self, kind: str, chip: int) -> None:
        """Caller holds the lock."""
        self._epoch += 1
        self.transitions.append((kind, chip, self._epoch))

    def heartbeat(self, chip: int) -> bool:
        """Refresh ``chip``'s lease.  A heartbeat from a declared-dead
        chip is SUPPRESSED (counted, not honored) — a zombie must come
        back through :meth:`recover`, never by out-racing the reaper
        (the elastic coordinator's suppression stance)."""
        with self._lock:
            lease = self._leases.get(chip)
            if lease is None:
                self.counters["suppressed"] += 1
                self.transitions.append(("suppressed", chip, self._epoch))
                return False
            lease.expires_at = self._lease_deadline(self.clock())
            return True

    def fail(self, chip: int, *, flap: bool = False,
             cause: str = "injected") -> bool:
        """Declare ``chip`` dead.  ``flap=True`` schedules its recovery
        ``flap_recovery_polls`` polls from now (the deterministic flap
        model).  Returns False when the chip was already down."""
        with self._lock:
            if chip not in self._leases:
                return False
            del self._leases[chip]
            self._live_since.pop(chip, None)
            self._down[chip] = self.clock()
            self.counters["deaths"] += 1
            if flap:
                self.counters["flaps"] += 1
                self._flap_pending[chip] = self.flap_recovery_polls
            self._record("flap_down" if flap else "down", chip)
        tracer.instant("chip_lost", cat="serving", x_chip=str(chip),
                       x_cause=cause)
        return True

    def recover(self, chip: int) -> bool:
        """A dead chip rejoined: re-lease it.  ``live_since`` restarts —
        the driver's hysteresis window measures from here."""
        with self._lock:
            if chip in self._leases or chip not in self._down:
                return False
            del self._down[chip]
            self._flap_pending.pop(chip, None)
            now = self.clock()
            self._leases[chip] = ChipLease(
                chip=chip, joined_at=now,
                expires_at=self._lease_deadline(now), order=self._order)
            self._order += 1
            self._live_since[chip] = now
            self.counters["recoveries"] += 1
            self._record("up", chip)
        return True

    def expire(self) -> List[int]:
        """Reap chips whose leases lapsed (missed heartbeats past
        ``lease_timeout_s`` on the injected clock) — the detection path
        for silent deaths, deterministic under a fake clock."""
        if self.lease_timeout_s is None:
            return []
        now = self.clock()
        with self._lock:
            dead = [c for c, lease in self._leases.items()
                    if lease.expires_at is not None
                    and lease.expires_at <= now]
            for chip in dead:
                del self._leases[chip]
                self._live_since.pop(chip, None)
                self._down[chip] = now
                self.counters["expiries"] += 1
                self.counters["deaths"] += 1
                self._record("expired", chip)
        for chip in dead:
            tracer.instant("chip_lost", cat="serving", x_chip=str(chip),
                           x_cause="lease_expired")
        return sorted(dead)

    def _victim(self) -> Optional[int]:
        """LIFO victim for injected faults: the newest lease (the
        elastic coordinator's preemption order), deterministic."""
        with self._lock:
            if not self._leases:
                return None
            return max(self._leases.values(), key=lambda l: l.order).chip

    def poll(self) -> List[Tuple[str, int]]:
        """One health tick: fire the :data:`CHIP_SCOPE` fault seam
        (seeded ``chip_down``/``chip_flap`` schedules land here,
        raise-before-anything so the tick itself is lossless), advance
        pending flap recoveries, then reap expired leases.  Returns
        this tick's transitions as ``(kind, chip)`` — ``"down"`` /
        ``"up"`` — in deterministic order."""
        self.counters["polls"] += 1
        events: List[Tuple[str, int]] = []
        try:
            fault_point(self.SCOPE)
        except InjectedChipDown:
            victim = self._victim()
            if victim is not None and self.fail(victim, cause="chip_down"):
                events.append(("down", victim))
        except InjectedChipFlap:
            victim = self._victim()
            if victim is not None and self.fail(victim, flap=True,
                                                cause="chip_flap"):
                events.append(("down", victim))
        recovered: List[int] = []
        with self._lock:
            for chip in sorted(self._flap_pending):
                self._flap_pending[chip] -= 1
                if self._flap_pending[chip] <= 0:
                    recovered.append(chip)
        for chip in recovered:
            if self.recover(chip):
                events.append(("up", chip))
        for chip in self.expire():
            events.append(("down", chip))
        return events

    # -- observability -------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "chips_live": len(self._leases),
                "chips_down": len(self._down),
                "epoch": self._epoch,
                **{k: int(v) for k, v in self.counters.items()},
            }

    def publish(self, group: MetricGroup) -> None:
        snap = self.snapshot()
        for key in ("chips_live", "chips_down", "epoch"):
            group.gauge(key).set(snap[key])


@dataclass(frozen=True)
class FailoverReport:
    """One failover, detection to recovery — the audit record chaos
    tests and ``bench_failover`` read.  ``moved`` tenants lost every
    chip and paid the re-admission (re-warm) window; ``replicated``
    tenants kept a surviving replica, so their window was one dispatch.
    ``generation`` is the placement generation the re-placement
    published (-1 when the CAS retry also lost — the next tick
    re-derives)."""

    detected_at: float
    resolved_at: float
    dead_chips: Tuple[int, ...]
    generation: int
    moved: Tuple[str, ...]
    replicated: Tuple[str, ...]
    requeued: int
    conflicts: int
    cause: str

    @property
    def wall_s(self) -> float:
        return self.resolved_at - self.detected_at


class FailoverDriver:
    """Detection -> re-placement -> brownout, one driver per scheduler.

    Construction attaches the driver to the scheduler's dispatch
    boundary (:meth:`SharedScheduler.attach_failover`): an injected
    ``chip_down``/``chip_flap`` there requeues the in-flight batch and
    lands in :meth:`on_chip_fault`; lease expiries and health-poll
    faults land through :meth:`tick`.  Both paths converge on the same
    failover: evict the dead chips from the live
    :class:`~flink_ml_tpu.autoscale.placement.PlacementMap`, publish
    via CAS on the shared generation stream (ONE retry on
    :class:`~flink_ml_tpu.autoscale.placement.PlacementConflict` — a
    racing autoscale tick re-derives from the fresh map, neither side
    thrashes), apply to the scheduler, re-admit fully-evicted tenants
    (ready servable -> zero lowerings; the generation bump re-anchors
    in-flight delta publishers), and set the brownout level for the
    new capacity deficit.
    """

    def __init__(self, scheduler: Any, store: Any, *,
                 health: Optional[FleetHealth] = None,
                 chips: Optional[Iterable[int]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 lease_timeout_s: Optional[float] = None,
                 flap_recovery_polls: int = 2,
                 hysteresis_s: float = 0.0,
                 brownout_deficits: Sequence[float] = (1e-9, 0.5),
                 group: Optional[MetricGroup] = None):
        if hysteresis_s < 0:
            raise ValueError("hysteresis_s must be >= 0")
        if len(brownout_deficits) > len(SLO_CLASSES) - 1:
            raise ValueError(
                f"at most {len(SLO_CLASSES) - 1} brownout rungs: the "
                "highest class is protected by construction")
        if list(brownout_deficits) != sorted(brownout_deficits):
            raise ValueError("brownout_deficits must be non-decreasing")
        self.scheduler = scheduler
        self.store = store
        self.clock = clock
        self.hysteresis_s = hysteresis_s
        #: rung thresholds: crossing ``brownout_deficits[i]`` of the
        #: fleet down raises the brownout to level ``i + 1`` (level 1
        #: sheds bulk, level 2 sheds standard too; interactive never)
        self.brownout_deficits = tuple(float(d) for d in brownout_deficits)
        if health is None:
            if chips is None:
                current = store.current().serving_chips()
                chips = current or range(getattr(store, "total_chips", 1))
            health = FleetHealth(chips, lease_timeout_s=lease_timeout_s,
                                 clock=clock,
                                 flap_recovery_polls=flap_recovery_polls)
        self.health = health
        #: chip -> {tenant: its chip tuple before the eviction} — what
        #: a post-hysteresis restore puts back
        self._evicted: Dict[int, Dict[str, Tuple[int, ...]]] = {}
        self._level = 0
        #: pending LOWER level + since-when (raising is immediate;
        #: lowering dwells ``hysteresis_s`` so a flap can't thrash)
        self._pending_level: Optional[int] = None
        self._pending_since = 0.0
        self.reports: List[FailoverReport] = []

        self.group = group or MetricGroup("failover")
        self._failovers = self.group.counter("failovers")
        self._chips_lost = self.group.counter("chips_lost")
        self._requeued = self.group.counter("requeued_requests")
        self._conflicts = self.group.counter("placement_conflicts")
        self._restores = self.group.counter("restores")
        self._brownout_gauge = self.group.gauge("brownout_level")
        self._brownout_gauge.set(0)
        self._wall_gauge = self.group.gauge("last_failover_wall_s")
        self._wall_gauge.set(float("nan"))   # never failed over: absent
        self.health.publish(self.group)
        attach = getattr(scheduler, "attach_failover", None)
        if attach is not None:
            attach(self)

    @property
    def brownout_level(self) -> int:
        return self._level

    @property
    def conflicts(self) -> int:
        return int(self._conflicts.value)

    # -- entry points --------------------------------------------------------
    def on_chip_fault(self, exc: BaseException,
                      requeued: int = 0) -> Optional[FailoverReport]:
        """The scheduler's dispatch boundary caught an injected chip
        fault (the batch is already requeued, futures intact): pick the
        deterministic LIFO victim, declare it dead, and fail over."""
        victim = self.health._victim()
        if victim is None:
            return None
        flap = isinstance(exc, InjectedChipFlap)
        if not self.health.fail(victim, flap=flap,
                                cause="dispatch_fault"):
            return None
        return self._failover([victim], requeued=requeued,
                              cause="dispatch")

    def tick(self) -> Optional[FailoverReport]:
        """The periodic health boundary: poll the lease table (seeded
        faults + flap recoveries + lease expiry), fail over any new
        deaths, restore recovered chips past the hysteresis window, and
        settle the brownout level.  Returns this tick's report (None
        when nothing died)."""
        events = self.health.poll()
        dead = [chip for kind, chip in events if kind == "down"]
        report = None
        if dead:
            report = self._failover(dead, requeued=0, cause="poll")
        self._maybe_restore()
        self._settle_brownout()
        return report

    # -- the failover itself -------------------------------------------------
    def _evict(self, base: Any, dead: List[int]
               ) -> Tuple[Dict[str, List[int]], List[str], List[str]]:
        """The re-placement edit: drop ``dead`` from every tenant's chip
        set; a tenant left with survivors is ``replicated`` (its
        failover window is one dispatch), a tenant left with NOTHING is
        ``moved`` onto the least-loaded live chip (deterministic
        tiebreak by chip id) and pays the re-admission window."""
        dead_set = set(dead)
        live = [c for c in self.health.live() if c not in dead_set]
        servables = {name: list(chips)
                     for name, chips in base.servables.items()}
        moved: List[str] = []
        replicated: List[str] = []
        for name in sorted(servables):
            chips = servables[name]
            survivors = [c for c in chips if c not in dead_set]
            if survivors == chips:
                continue
            for chip in chips:
                if chip in dead_set:
                    self._evicted.setdefault(chip, {}).setdefault(
                        name, tuple(chips))
            if survivors:
                servables[name] = survivors
                replicated.append(name)
            else:
                target = self._least_loaded(live, servables)
                servables[name] = [target] if target is not None else []
                moved.append(name)
        return servables, moved, replicated

    @staticmethod
    def _least_loaded(live: List[int],
                      servables: Dict[str, List[int]]) -> Optional[int]:
        if not live:
            return None
        load = {c: 0 for c in live}
        for chips in servables.values():
            for c in chips:
                if c in load:
                    load[c] += 1
        return min(live, key=lambda c: (load[c], c))

    def _publish_cas(self, edit: Callable[[Any], Dict[str, List[int]]]
                     ) -> Tuple[Optional[Any], int]:
        """Publish ``edit(base)`` through the SHARED generation stream
        with compare-and-swap, retrying ONCE against a fresh map on
        :class:`PlacementConflict` (the racing writer is the autoscale
        tick; both sides re-derive, neither clobbers).  Returns
        ``(pmap_or_None, conflicts)``."""
        from ..autoscale.placement import PlacementConflict

        conflicts = 0
        for _ in range(2):
            base = self.store.current()
            try:
                return self.store.publish(
                    edit(base), base.learner_workers,
                    expected_generation=base.generation), conflicts
            except PlacementConflict:
                conflicts += 1
                self._conflicts.inc()
        return None, conflicts

    def _failover(self, dead: List[int], *, requeued: int,
                  cause: str) -> FailoverReport:
        t0 = self.clock()
        moved_out: List[str] = []
        replicated_out: List[str] = []

        def edit(base):
            moved_out.clear()
            replicated_out.clear()
            servables, moved, replicated = self._evict(base, dead)
            moved_out.extend(moved)
            replicated_out.extend(replicated)
            return servables

        pmap, conflicts = self._publish_cas(edit)
        if pmap is not None:
            self.scheduler.apply_placement(pmap)
            self._readmit(moved_out)
        # raising the brownout is immediate — capacity is short NOW
        self._settle_brownout()
        t1 = self.clock()
        report = FailoverReport(
            detected_at=t0, resolved_at=t1, dead_chips=tuple(dead),
            generation=pmap.generation if pmap is not None else -1,
            moved=tuple(moved_out), replicated=tuple(replicated_out),
            requeued=requeued, conflicts=conflicts, cause=cause)
        self.reports.append(report)
        self._failovers.inc()
        self._chips_lost.inc(len(dead))
        if requeued:
            self._requeued.inc(requeued)
        self._wall_gauge.set(report.wall_s)
        self.health.publish(self.group)
        tracer.instant(
            "failover_complete", cat="serving",
            generation=report.generation,
            x_dead=",".join(str(c) for c in dead), x_cause=cause,
            x_moved=str(len(moved_out)),
            x_replicated=str(len(replicated_out)),
            x_requeued=str(requeued), x_wall_s=f"{report.wall_s:.6f}")
        return report

    def _readmit(self, moved: List[str]) -> None:
        """Re-placement IS an admission (the PR 14 contract): confirm
        each fully-evicted tenant's servable ready (an already-served
        schema is an AOT cache-hit walk — zero new lowerings,
        counter-asserted in tests) and stamp a fresh registry
        generation, so serving-side consumers — an in-flight
        :class:`DeltaPublisher` above all — observe the move and
        re-anchor onto the re-placed generation (their existing
        ``GenerationConflict`` heal, idempotent by construction)."""
        from .registry import GenerationConflict

        registry = getattr(self.scheduler, "registry", None)
        if registry is None:
            return
        done = set()
        for name in moved:
            try:
                tenant = self.scheduler.tenant(name)
            except KeyError:
                continue            # placed but not admitted: no-op
            if tenant.serve_name in done:
                continue            # shared servable: readmit ONCE
            done.add(tenant.serve_name)
            try:
                deployed = registry.current(tenant.serve_name)
            except KeyError:
                continue
            servable = deployed.servable
            if not getattr(servable, "ready", True):
                servable.warm_up()
            try:
                registry.publish_servable(
                    tenant.serve_name, servable,
                    source="<failover-readmit>", metrics=tenant.metrics,
                    mode="full",
                    expected_generation=deployed.generation)
            except GenerationConflict:
                # a concurrent publish already moved the generation —
                # the consumer will re-anchor onto THAT one; idempotent
                pass

    # -- recovery + hysteresis -----------------------------------------------
    def _maybe_restore(self) -> None:
        """Put a recovered chip's tenants back — but only once the chip
        has stayed live for ``hysteresis_s`` on the injected clock.  A
        flapping chip therefore costs at most ONE eviction per
        stability window and zero restores while it flaps."""
        now = self.clock()
        ready = []
        for chip in sorted(self._evicted):
            since = self.health.live_since(chip)
            if since is not None and now - since >= self.hysteresis_s:
                ready.append(chip)
        for chip in ready:
            record = self._evicted.pop(chip)

            def edit(base, record=record):
                servables = {name: list(chips)
                             for name, chips in base.servables.items()}
                for name, original in record.items():
                    if name not in servables:
                        continue
                    restored = [c for c in original
                                if self.health.is_live(c)]
                    if restored:
                        servables[name] = restored
                return servables

            pmap, _ = self._publish_cas(edit)
            if pmap is None:
                self._evicted[chip] = record    # retry next tick
                continue
            self.scheduler.apply_placement(pmap)
            self._restores.inc()
            tracer.instant("failover_restore", cat="serving",
                           generation=pmap.generation, x_chip=str(chip))

    def _settle_brownout(self) -> None:
        """Map the capacity deficit onto the ladder: raising is
        immediate, lowering dwells ``hysteresis_s`` of stable target on
        the injected clock."""
        snap = self.health.snapshot()
        total = snap["chips_live"] + snap["chips_down"]
        deficit = snap["chips_down"] / total if total else 0.0
        target = 0
        for rung, threshold in enumerate(self.brownout_deficits):
            if deficit >= threshold:
                target = rung + 1
        if target >= self._level:
            if target > self._level:
                self._apply_brownout(target)
            self._pending_level = None
            return
        now = self.clock()
        if self._pending_level != target:
            self._pending_level = target
            self._pending_since = now
            return
        if now - self._pending_since >= self.hysteresis_s:
            self._apply_brownout(target)
            self._pending_level = None

    def _apply_brownout(self, level: int) -> None:
        self._level = level
        set_brownout = getattr(self.scheduler, "set_brownout", None)
        if set_brownout is not None:
            set_brownout(level)
        self._brownout_gauge.set(level)
        tracer.instant("brownout", cat="serving", x_level=str(level))

    # -- replication ---------------------------------------------------------
    def ensure_replicas(self, name: str, n: int) -> Any:
        """Grow ``name``'s placement to ``n`` distinct live chips
        (least-loaded first, deterministic).  The registry shares one
        executable per schema, so each added replica is params-only HBM
        cost and ZERO new lowerings — and a replicated tenant survives
        any single chip loss with a surviving chip already placed: its
        failover window is one dispatch, never a re-warm.  Returns the
        published map (or the current one when already satisfied)."""
        if n < 1:
            raise ValueError("replica count must be >= 1")
        base = self.store.current()
        if len(base.chips_for(name)) >= n:
            return base

        def edit(base):
            servables = {tname: list(chips)
                         for tname, chips in base.servables.items()}
            chips = list(servables.get(name, ()))
            while len(chips) < n:
                live = [c for c in self.health.live() if c not in chips]
                target = self._least_loaded(live, servables)
                if target is None:
                    break           # fleet smaller than n: best effort
                chips.append(target)
                servables[name] = sorted(chips)
            return servables

        pmap, _ = self._publish_cas(edit)
        if pmap is None:
            return self.store.current()
        self.scheduler.apply_placement(pmap)
        tracer.instant("replica_placed", cat="serving", tenant=name,
                       generation=pmap.generation,
                       x_replicas=str(len(pmap.chips_for(name))))
        return pmap

    # -- observability -------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """MetricsTree provider (``default_tree(failover=...)``): the
        driver's counters/gauges plus the lease table's fleet view."""
        self.health.publish(self.group)
        out = self.group.snapshot()
        out["health_epoch"] = self.health.epoch
        out["evicted_chips_pending_restore"] = len(self._evicted)
        return out
