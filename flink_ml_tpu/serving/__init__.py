"""Online serving runtime (the request path of the ROADMAP north star).

Training produces fitted ``Model``\\s; this package turns them into
endpoints that keep the accelerator saturated under many small concurrent
requests while bounding tail latency:

- :mod:`.batcher` — bounded request queue + dynamic micro-batcher
  (max-wait coalescing, shed-on-full admission control),
- :mod:`.executor` — ``ServableModel``: bucketed power-of-two batch
  shapes, eager per-bucket warm-up, donated-input jitted scores for the
  specialized families — zero steady-state retraces, bit-exact with
  offline ``transform()``,
- :mod:`.registry` — versioned model registry with atomic hot-swap under
  a generation counter (warm-up off the serving path; in-flight batches
  finish on the version they started on),
- :mod:`.endpoint` — the serve loop wiring them together, with
  per-endpoint ``MetricGroup`` gauges (queue depth, fill ratio, p50/p99
  latency, requests/sec, shed count),
- :mod:`.metrics` — the latency/throughput instrumentation, plus the
  endpoint ``health`` gauge (SERVING/DEGRADED) and rollback counter the
  self-healing hot-swap drives (``endpoint.hot_swap(path)`` — a deploy
  that fails load/warm-up rolls back to the live generation and keeps
  serving; see ``flink_ml_tpu/robustness/``),
- :mod:`.scheduler` — the multi-tenant serving fabric (ISSUE 14): ONE
  admission/placement layer multiplexing many servables on one device
  — global micro-batching per (servable, bucket) across tenants,
  per-tenant SLO classes (interactive/standard/bulk) with priority
  shedding, weighted fair queuing within a class, per-tenant metric
  subtrees and ``tenant``-keyed trace spans,
- :mod:`.embcache` — device-resident LRU embedding-row blocks for
  WideDeep's long-tail vocab: only the zipfian-hot blocks live in HBM,
  scores stay bit-exact with offline ``transform``,
- :mod:`.failover` — serving fleet failover (ISSUE 20): a chip-lease
  health table (the PR 15 idiom over serving chips), seeded
  ``chip_down``/``chip_flap`` injection at the dispatch boundary with
  lossless requeue (zero dropped requests, bit-identical retried
  answers), CAS re-placement of a dead chip's tenants onto survivors
  through the shared placement generation stream, an SLO-aware
  brownout ladder with hysteresis, and optional N-way replication for
  high-SLO tenants (params-only cost; failover window = one dispatch).

Quick start::

    from flink_ml_tpu.serving import serve_model

    endpoint = serve_model(fitted_model, example_request_table)
    prediction = endpoint.predict(request_table)     # == offline transform
    endpoint.registry.deploy("default", "/path/v2")  # atomic hot-swap
    endpoint.close()

Multi-tenant (one process, many models, one device)::

    from flink_ml_tpu.serving import SharedScheduler

    sched = SharedScheduler(queue_capacity=4096)
    sched.add_tenant("checkout", model_a, example_a, slo="interactive")
    sched.add_tenant("nightly", model_b, example_b, slo="bulk", weight=0.5)
    sched.start()
    prediction = sched.predict("checkout", request_table)
    sched.close()
"""

from .batcher import MicroBatcher, ServingOverloadedError, ServingRequest
from .embcache import CachedWideDeepServable, EmbeddingRowCache
from .endpoint import ServingEndpoint, serve_model
from .executor import ServableModel, make_servable
from .failover import (CHIP_SCOPE, FailoverDriver, FailoverReport,
                       FleetHealth)
from .metrics import (HEALTH_DEGRADED, HEALTH_SERVING, LatencyTracker,
                      ServingMetrics)
from .registry import DeployedModel, ModelRegistry
from .scheduler import (DISPATCH_SCOPE, SLO_BULK, SLO_CLASSES,
                        SLO_INTERACTIVE, SLO_STANDARD, SharedScheduler,
                        Tenant)

__all__ = [
    "MicroBatcher", "ServingOverloadedError", "ServingRequest",
    "ServingEndpoint", "serve_model",
    "ServableModel", "make_servable",
    "LatencyTracker", "ServingMetrics",
    "HEALTH_SERVING", "HEALTH_DEGRADED",
    "DeployedModel", "ModelRegistry",
    "SharedScheduler", "Tenant",
    "SLO_INTERACTIVE", "SLO_STANDARD", "SLO_BULK", "SLO_CLASSES",
    "EmbeddingRowCache", "CachedWideDeepServable",
    "CHIP_SCOPE", "DISPATCH_SCOPE",
    "FleetHealth", "FailoverDriver", "FailoverReport",
]
