"""Online serving runtime (the request path of the ROADMAP north star).

Training produces fitted ``Model``\\s; this package turns them into
endpoints that keep the accelerator saturated under many small concurrent
requests while bounding tail latency:

- :mod:`.batcher` — bounded request queue + dynamic micro-batcher
  (max-wait coalescing, shed-on-full admission control),
- :mod:`.executor` — ``ServableModel``: bucketed power-of-two batch
  shapes, eager per-bucket warm-up, donated-input jitted scores for the
  specialized families — zero steady-state retraces, bit-exact with
  offline ``transform()``,
- :mod:`.registry` — versioned model registry with atomic hot-swap under
  a generation counter (warm-up off the serving path; in-flight batches
  finish on the version they started on),
- :mod:`.endpoint` — the serve loop wiring them together, with
  per-endpoint ``MetricGroup`` gauges (queue depth, fill ratio, p50/p99
  latency, requests/sec, shed count),
- :mod:`.metrics` — the latency/throughput instrumentation, plus the
  endpoint ``health`` gauge (SERVING/DEGRADED) and rollback counter the
  self-healing hot-swap drives (``endpoint.hot_swap(path)`` — a deploy
  that fails load/warm-up rolls back to the live generation and keeps
  serving; see ``flink_ml_tpu/robustness/``).

Quick start::

    from flink_ml_tpu.serving import serve_model

    endpoint = serve_model(fitted_model, example_request_table)
    prediction = endpoint.predict(request_table)     # == offline transform
    endpoint.registry.deploy("default", "/path/v2")  # atomic hot-swap
    endpoint.close()
"""

from .batcher import MicroBatcher, ServingOverloadedError, ServingRequest
from .endpoint import ServingEndpoint, serve_model
from .executor import ServableModel, make_servable
from .metrics import (HEALTH_DEGRADED, HEALTH_SERVING, LatencyTracker,
                      ServingMetrics)
from .registry import DeployedModel, ModelRegistry

__all__ = [
    "MicroBatcher", "ServingOverloadedError", "ServingRequest",
    "ServingEndpoint", "serve_model",
    "ServableModel", "make_servable",
    "LatencyTracker", "ServingMetrics",
    "HEALTH_SERVING", "HEALTH_DEGRADED",
    "DeployedModel", "ModelRegistry",
]
