"""Model registry with atomic hot-swap.

Versions load from the framework's persistence layout
(``utils/persist.py`` — ``{path}/metadata`` + ``{path}/data``; load
failures surface as diagnosable ``IOError``\\s naming the path and the
stored class name), adapt through :func:`~.executor.make_servable`, and
warm up OFF the serving path: the deploying thread compiles every bucket
while the previous version keeps answering traffic.  Only then does the
new version publish, as ONE reference assignment under the registry lock
tagged with a monotonically increasing **generation**.

Atomicity contract: a reader (the endpoint's serve loop) takes
``current(name)`` exactly once per micro-batch, so every request in a
batch runs on one fully-warmed version; in-flight batches keep their
(old) servable alive by plain reference and finish on it.  No request can
ever observe a half-loaded model, because nothing is published before
``warm_up`` returns.
"""

from __future__ import annotations

import threading
import time

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ..data.table import Table
from ..utils import persist
from .executor import ServableModel, make_servable

__all__ = ["DeployedModel", "ModelRegistry"]


@dataclass(frozen=True)
class DeployedModel:
    """One published version: immutable, so a reference captured at batch
    formation stays internally consistent for the batch's lifetime."""
    name: str
    servable: ServableModel
    generation: int
    source: str
    deployed_at: float


class ModelRegistry:
    """name -> live :class:`DeployedModel`, swapped atomically."""

    def __init__(self, servable_factory: Optional[Callable] = None):
        self._factory = servable_factory or make_servable
        self._live: Dict[str, DeployedModel] = {}
        self._lock = threading.Lock()

    def deploy(self, name: str, model: Any,
               example: Optional[Table] = None,
               **servable_kwargs: Any) -> DeployedModel:
        """Load (if ``model`` is a saved-stage path), adapt, warm up, then
        atomically publish as the next generation of ``name``.  On a
        re-deploy, ``example`` (and servable config) may be omitted to
        inherit the incumbent's."""
        if isinstance(model, str):
            source = model
            model = persist.load_stage(model)
        else:
            source = f"<memory:{type(model).__name__}>"
        incumbent = self._live.get(name)
        if example is None:
            if incumbent is None:
                raise ValueError(
                    f"first deploy of {name!r} needs an example Table "
                    "(the request schema warm-up tiles over)")
            example = incumbent.servable.example
            if not servable_kwargs:
                servable_kwargs = {
                    "max_batch_rows": incumbent.servable.max_batch_rows,
                    "min_bucket": incumbent.servable.min_bucket,
                    "output_cols": incumbent.servable.output_cols,
                }
        servable = self._factory(model, example, **servable_kwargs)
        servable.warm_up()   # off the serving path: old version still live
        with self._lock:
            previous = self._live.get(name)
            generation = (previous.generation + 1) if previous else 1
            deployed = DeployedModel(name=name, servable=servable,
                                     generation=generation, source=source,
                                     deployed_at=time.time())
            self._live[name] = deployed   # THE swap: one dict assignment
        return deployed

    def current(self, name: str) -> DeployedModel:
        """The live version — one atomic read; callers serving a batch
        call this ONCE and use the returned reference throughout."""
        with self._lock:
            deployed = self._live.get(name)
        if deployed is None:
            raise KeyError(
                f"no model deployed under {name!r}; call deploy() first "
                f"(deployed: {self.names()})")
        return deployed

    def generation(self, name: str) -> int:
        return self.current(name).generation

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._live)

    def undeploy(self, name: str) -> None:
        with self._lock:
            self._live.pop(name, None)
