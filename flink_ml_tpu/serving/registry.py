"""Model registry with atomic hot-swap.

Versions load from the framework's persistence layout
(``utils/persist.py`` — ``{path}/metadata`` + ``{path}/data``; load
failures surface as diagnosable ``IOError``\\s naming the path and the
stored class name), adapt through :func:`~.executor.make_servable`, and
warm up OFF the serving path: the deploying thread compiles every bucket
while the previous version keeps answering traffic.  Only then does the
new version publish, as ONE reference assignment under the registry lock
tagged with a monotonically increasing **generation**.

Atomicity contract: a reader (the endpoint's serve loop) takes
``current(name)`` exactly once per micro-batch, so every request in a
batch runs on one fully-warmed version; in-flight batches keep their
(old) servable alive by plain reference and finish on it.  No request can
ever observe a half-loaded model, because nothing is published before
``warm_up`` returns.

Self-healing (robustness PR): ``deploy(..., rollback=True)`` turns a
failed load/warm-up — corrupt model directory, injected fault, any
exception before the publish point — into a ROLLBACK: the incumbent
generation stays live (it was never unpublished, so zero requests are
dropped), the health gauge flips SERVING -> DEGRADED and the rollback
counter increments (``serving/metrics.py``), and the incumbent is
returned so callers observe which generation is actually serving.  A
``retry_policy`` additionally retries classified-transient *load*
failures before declaring the deploy failed.
"""

from __future__ import annotations

import logging
import threading
import time

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ..data.table import Table
from ..obs.trace import tracer
from ..robustness.faults import fault_point
from ..utils import persist
from .executor import ServableModel, make_servable

__all__ = ["DeployedModel", "GenerationConflict", "ModelRegistry"]


class GenerationConflict(RuntimeError):
    """A conditional publish lost the race to a concurrent deploy: the
    live generation is not the one the caller validated against."""

log = logging.getLogger("flink_ml_tpu.robustness")


@dataclass(frozen=True)
class DeployedModel:
    """One published version: immutable, so a reference captured at batch
    formation stays internally consistent for the batch's lifetime."""
    name: str
    servable: ServableModel
    generation: int
    source: str
    deployed_at: float


class ModelRegistry:
    """name -> live :class:`DeployedModel`, swapped atomically."""

    def __init__(self, servable_factory: Optional[Callable] = None,
                 metrics: Optional[Any] = None,
                 retry_policy: Optional[Any] = None):
        self._factory = servable_factory or make_servable
        self._live: Dict[str, DeployedModel] = {}
        self._lock = threading.Lock()
        #: a serving.metrics.ServingMetrics — health/rollback accounting
        self.metrics = metrics
        #: a robustness.retry.RetryPolicy for transient LOAD failures
        self._retry = retry_policy

    def _load(self, path: str):
        fault_point("serving.load")
        return persist.load_stage(path)

    def deploy(self, name: str, model: Any,
               example: Optional[Table] = None,
               rollback: bool = False,
               metrics: Optional[Any] = None,
               **servable_kwargs: Any) -> DeployedModel:
        """Load (if ``model`` is a saved-stage path), adapt, warm up, then
        atomically publish as the next generation of ``name``.  On a
        re-deploy, ``example`` (and servable config) may be omitted to
        inherit the incumbent's.

        ``rollback=True``: a failure anywhere before the publish point
        (unloadable/corrupt directory, warm-up crash) keeps the incumbent
        generation live and RETURNS it instead of raising — health flips
        to DEGRADED and the rollback counter increments when a
        ``ServingMetrics`` is attached.  With no incumbent there is
        nothing to roll back to, so the failure raises either way.

        ``metrics`` overrides the registry-level ``ServingMetrics`` for
        THIS deploy — with several endpoints sharing one registry, each
        hot-swap accounts health/rollback on the endpoint that asked for
        it, not on whichever endpoint touched the registry first."""
        metrics = metrics if metrics is not None else self.metrics
        try:
            if isinstance(model, str):
                source = model
                model = (self._retry.call(self._load, model)
                         if self._retry is not None else self._load(model))
            else:
                source = f"<memory:{type(model).__name__}>"
            incumbent = self._live.get(name)
            if example is None:
                if incumbent is None:
                    raise ValueError(
                        f"first deploy of {name!r} needs an example Table "
                        "(the request schema warm-up tiles over)")
                example = incumbent.servable.example
                if not servable_kwargs:
                    servable_kwargs = {
                        "max_batch_rows": incumbent.servable.max_batch_rows,
                        "min_bucket": incumbent.servable.min_bucket,
                        "output_cols": incumbent.servable.output_cols,
                    }
            servable = self._factory(model, example, **servable_kwargs)
            servable.warm_up()   # off the serving path: old version live
            rep = getattr(servable, "warmup_report", None)
            if rep:
                # the cold-start one-liner (ISSUE 12): how long readiness
                # took and how much of it the persistent AOT cache saved
                log.info(
                    "warm-up of %r: %d buckets in %.3fs (%d compiled, "
                    "%d aot-loaded, %d cache-hit)", name,
                    len(rep["buckets"]), rep["wall_s"], rep["compiled"],
                    rep["aot_loaded"], rep["cache_hits"])
        except Exception as exc:  # noqa: BLE001 — rollback decision below
            with self._lock:
                incumbent = self._live.get(name)
            if not rollback or incumbent is None:
                raise
            # ROLLBACK: nothing was ever published, so the incumbent kept
            # serving throughout — zero dropped requests by construction.
            log.warning(
                "hot-swap of %r failed (%r); rolled back to generation "
                "%d (%s)", name, exc, incumbent.generation,
                incumbent.source)
            if metrics is not None:
                metrics.on_rollback()
            return incumbent
        with self._lock:
            previous = self._live.get(name)
            generation = (previous.generation + 1) if previous else 1
            deployed = DeployedModel(name=name, servable=servable,
                                     generation=generation, source=source,
                                     deployed_at=time.time())
            self._live[name] = deployed   # THE swap: one dict assignment
        tracer.instant("deploy", cat="publish", generation=generation)
        if metrics is not None:
            metrics.on_deploy(generation)
        return deployed

    def publish_servable(self, name: str, servable: ServableModel, *,
                         source: str = "<publish>",
                         metrics: Optional[Any] = None,
                         mode: str = "delta",
                         payload_bytes: Optional[int] = None,
                         expected_generation: Optional[int] = None
                         ) -> DeployedModel:
        """Swap an already-READY servable in as the next generation of
        ``name`` — the continuous-learning publish fast path.  Unlike
        :meth:`deploy` there is no load and no warm-up here: the caller
        (:class:`~flink_ml_tpu.online.publish.DeltaPublisher`) rebound a
        live servable around same-shape params, so every compiled
        executor it can reach already exists.  The swap itself is the
        same single reference assignment under the registry lock, so the
        atomicity contract (in-flight batches finish on their captured
        version; no request ever sees a half-published model) is
        identical to a full deploy.

        ``mode``/``payload_bytes`` flow to
        ``ServingMetrics.on_publish`` for the delta-vs-full counters and
        the staleness gauge.

        ``expected_generation`` makes the swap CONDITIONAL: if the live
        generation moved past it (a concurrent external deploy landed
        between the caller's read and this swap), the publish is
        refused with :class:`GenerationConflict` instead of silently
        clobbering the newer model — the compare-and-swap the publish
        protocol's validation-then-swap sequence needs."""
        if not servable.ready:
            raise RuntimeError(
                f"publish_servable({name!r}): servable is not ready — "
                "rebind() preserves readiness; anything else must "
                "warm_up() first (or go through deploy())")
        # chaos seam: the chunk-boundary publish is a crash site the
        # exactly-once tests exercise (crash BEFORE the swap => the old
        # generation keeps serving; the replayed cut republishes)
        fault_point("serving.publish")
        metrics = metrics if metrics is not None else self.metrics
        with self._lock:
            previous = self._live.get(name)
            if (expected_generation is not None and previous is not None
                    and previous.generation != expected_generation):
                raise GenerationConflict(
                    f"publish of {name!r} expected generation "
                    f"{expected_generation} but {previous.generation} is "
                    "live (a concurrent deploy landed); re-validate "
                    "against the new generation and retry")
            generation = (previous.generation + 1) if previous else 1
            deployed = DeployedModel(name=name, servable=servable,
                                     generation=generation, source=source,
                                     deployed_at=time.time())
            self._live[name] = deployed   # THE swap: one dict assignment
        tracer.instant("publish_swap", cat="publish",
                       generation=generation)
        if metrics is not None:
            if hasattr(metrics, "on_publish"):
                metrics.on_publish(generation, mode=mode,
                                   payload_bytes=payload_bytes)
            else:
                metrics.on_deploy(generation)
        return deployed

    def live_generation(self, name: str) -> Optional[int]:
        """LOCK-FREE best-effort read of the live generation (None when
        nothing is deployed).  The shed paths stamp their events with
        this — under saturation thousands of sheds per second must not
        serialize on the registry lock the serve loops and deploys
        contend on.  Safe: the dict read is GIL-atomic and the held
        ``DeployedModel`` is immutable."""
        deployed = self._live.get(name)
        return deployed.generation if deployed is not None else None

    def current(self, name: str) -> DeployedModel:
        """The live version — one atomic read; callers serving a batch
        call this ONCE and use the returned reference throughout."""
        with self._lock:
            deployed = self._live.get(name)
        if deployed is None:
            raise KeyError(
                f"no model deployed under {name!r}; call deploy() first "
                f"(deployed: {self.names()})")
        return deployed

    def generation(self, name: str) -> int:
        return self.current(name).generation

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._live)

    def undeploy(self, name: str) -> None:
        with self._lock:
            self._live.pop(name, None)
