"""The serving endpoint: queue -> micro-batcher -> compiled executor.

One background serve thread per endpoint drives the loop:

1. ``next_batch`` coalesces concurrent requests under the max-wait
   deadline (``batcher.py``),
2. the live :class:`~.registry.DeployedModel` is captured ONCE for the
   batch (hot-swap atomicity: every request in a batch runs on one fully
   warmed version; later batches pick up a swapped version on their next
   capture),
3. request tables concatenate into one batch table, the executor pads it
   to the power-of-two bucket and runs the warm-compiled predict,
4. each request's Future resolves to ITS slice of the output rows.

Backpressure is the batcher's bounded queue (shed-on-full with
:class:`~.batcher.ServingOverloadedError`); per-endpoint gauges/counters
(queue depth, batch fill ratio, p50/p99 latency, requests/sec, shed
count) live in a ``utils.metrics.MetricGroup`` via
:class:`~.metrics.ServingMetrics`.
"""

from __future__ import annotations

import threading
import time

from concurrent.futures import Future
from typing import Any, List, Optional

from ..data.table import Table
from ..obs.trace import tracer
from ..robustness.faults import (InjectedChipDown, InjectedChipFlap,
                                 fault_point)
from .batcher import (MicroBatcher, ServingOverloadedError,
                      ServingRequest, concat_request_tables)
from .metrics import ServingMetrics
from .registry import ModelRegistry
from .scheduler import DISPATCH_SCOPE


__all__ = ["ServingEndpoint", "serve_model"]


class ServingEndpoint:
    """Serve one registry entry.  ``submit`` returns a Future resolving to
    the output Table for exactly the submitted rows; ``predict`` is the
    blocking convenience.  Construct, then ``start()`` once the model is
    deployed and warmed — ``start`` refuses to serve an unwarmed model so
    readiness implies zero steady-state retraces."""

    def __init__(self, registry: ModelRegistry, name: str = "default", *,
                 max_batch_rows: int = 256, max_wait_ms: float = 2.0,
                 queue_capacity: int = 1024,
                 metrics: Optional[ServingMetrics] = None):
        self._registry = registry
        self._name = name
        self._batcher = MicroBatcher(max_batch_rows=max_batch_rows,
                                     max_wait_ms=max_wait_ms,
                                     queue_capacity=queue_capacity)
        self.metrics = metrics or ServingMetrics()
        self._thread: Optional[threading.Thread] = None

    @property
    def registry(self) -> ModelRegistry:
        """The backing registry — hot-swap via
        ``endpoint.registry.deploy(name, new_version)``."""
        return self._registry

    def delta_publisher(self):
        """A :class:`~flink_ml_tpu.online.publish.DeltaPublisher` bound
        to this endpoint's registry entry and metrics — the serving-side
        half of the continuous-learning publish protocol.  Publishes
        account (delta/full counters, staleness gauge) on THIS
        endpoint."""
        from ..online.publish import DeltaPublisher

        return DeltaPublisher(self._registry, self._name,
                              metrics=self.metrics)

    def hot_swap(self, model, **deploy_kwargs):
        """Self-healing hot-swap: deploy ``model`` as the next generation
        with ``rollback=True`` — a failed load/warm-up (corrupt
        directory, injected fault) keeps the live generation serving,
        flips THIS endpoint's health gauge to DEGRADED and bumps its
        rollback counter, and returns the incumbent.  In-flight and
        concurrent requests are untouched either way (the publish point
        is one reference assignment that never happens on failure)."""
        return self._registry.deploy(self._name, model, rollback=True,
                                     metrics=self.metrics, **deploy_kwargs)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ServingEndpoint":
        deployed = self._registry.current(self._name)   # raises if absent
        if not deployed.servable.ready:
            raise RuntimeError(
                f"model {self._name!r} (gen {deployed.generation}) is not "
                "warmed up; deploy() warms automatically — a custom "
                "servable must warm_up() before the endpoint starts")
        if self._thread is not None:
            raise RuntimeError("endpoint already started")
        self._thread = threading.Thread(
            target=self._serve_loop, daemon=True,
            name=f"flink-ml-tpu-serve-{self._name}")
        self._thread.start()
        return self

    @property
    def ready(self) -> bool:
        if self._thread is None or not self._thread.is_alive():
            return False
        try:
            return self._registry.current(self._name).servable.ready
        except KeyError:
            return False

    @property
    def warmup_report(self) -> Optional[dict]:
        """The live servable's readiness accounting (ISSUE 12): wall
        time to ready plus per-bucket compile-vs-aot-vs-cache source —
        None before the first deploy (or for custom servables that skip
        the standard warm-up)."""
        try:
            servable = self._registry.current(self._name).servable
        except KeyError:
            return None
        return getattr(servable, "warmup_report", None)

    def close(self, timeout: float = 10.0) -> None:
        """Stop admitting, drain queued requests, join the serve loop."""
        self._batcher.close()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    # -- request path -------------------------------------------------------
    def submit(self, table: Table) -> Future:
        """Enqueue one request; sheds with ``ServingOverloadedError`` when
        the bounded queue is full.  A shed is stamped with the LIVE
        generation serving at the time (gauge + tracer instant), so an
        overload correlated with a publish — e.g. a warm-up stealing
        cycles from the serve loop — is attributable in the trace
        instead of an anonymous counter bump (ISSUE 14 satellite)."""
        try:
            request = self._batcher.submit(table)
        except ServingOverloadedError:
            # lock-free generation read: the shed path must not
            # serialize on the registry lock under the very saturation
            # it exists to absorb
            generation = self._registry.live_generation(self._name)
            self.metrics.on_shed(self._batcher.queue_depth,
                                 generation=generation)
            tracer.instant("shed", cat="serving", generation=generation)
            raise
        self.metrics.on_submit(self._batcher.queue_depth)
        return request.future

    def predict(self, table: Table, timeout: Optional[float] = 30.0
                ) -> Table:
        return self.submit(table).result(timeout)

    # -- serve loop ---------------------------------------------------------
    def _serve_loop(self) -> None:
        while True:
            batch = self._batcher.next_batch(timeout=0.05)
            if batch:
                self._process(batch)
            elif self._batcher.closed and self._batcher.empty:
                return

    def _process(self, batch: List[ServingRequest]) -> None:
        # the chip-fault seam (ISSUE 20): same dispatch-boundary
        # contract as the shared scheduler — an injected chip fault
        # fires BEFORE the predict, the batch goes back to the queue
        # head with futures intact, the retried dispatch answers them
        # bit-identically.  The single-endpoint topology has no
        # failover driver; losslessness alone is the contract here.
        try:
            fault_point(DISPATCH_SCOPE)
        except (InjectedChipDown, InjectedChipFlap):
            self._batcher.requeue(batch)
            self.metrics.on_requeue(len(batch))
            return
        # ONE capture per batch: the hot-swap atomicity point.  Every
        # request below runs on this (immutable, fully warmed) version
        # even if a deploy publishes mid-predict.
        deployed = self._registry.current(self._name)
        servable = deployed.servable
        rows = sum(r.rows for r in batch)
        if tracer.enabled:
            # queue-wait is recorded RETROACTIVELY from the request's
            # submit stamp — the submit path itself never touches the
            # tracer (no lock, no clock read, under load)
            formed = time.perf_counter()
            for request in batch:
                tracer.add("queue_wait", request.submitted_at, formed,
                           cat="serving", request_id=request.request_id,
                           generation=deployed.generation)
        try:
            with tracer.span("batch_assembly", cat="serving",
                             generation=deployed.generation):
                for request in batch:
                    servable.check_schema(request.table)
                table = concat_request_tables([r.table for r in batch])
            with tracer.span("serve_batch", cat="serving",
                             generation=deployed.generation,
                             bucket=servable.bucket_for(rows)):
                # nested inside: bucket_pad -> registry dispatch ->
                # device_execute (the kernel-servable path instruments
                # those in api/chain.py + kernels/registry.py)
                out = servable.predict(table)
        except BaseException as exc:  # noqa: BLE001 — delivered per-request
            for request in batch:
                request.future.set_exception(exc)
            return
        offset = 0
        now = time.perf_counter()
        latencies = []
        for request in batch:
            if tracer.enabled:
                # committed BEFORE the future resolves, so a caller woken
                # by predict() can already see its own request span
                tracer.add("request", request.submitted_at, now,
                           cat="serving", request_id=request.request_id,
                           generation=deployed.generation)
            request.future.set_result(
                out.slice(offset, offset + request.rows))
            offset += request.rows
            latencies.append(now - request.submitted_at)
        self.metrics.on_batch(
            n_requests=len(batch), rows=rows,
            bucket=servable.bucket_for(rows), latencies_s=latencies,
            queue_depth=self._batcher.queue_depth,
            generation=deployed.generation)


def serve_model(model: Any, example: Table, *, name: str = "default",
                max_batch_rows: int = 256, max_wait_ms: float = 2.0,
                queue_capacity: int = 1024,
                **servable_kwargs: Any) -> ServingEndpoint:
    """One-call serving for a single fitted model: build a registry,
    deploy + warm the model, start the endpoint.  Hot-swap later versions
    with ``endpoint.registry.deploy(name, new_model)``."""
    metrics = ServingMetrics()
    registry = ModelRegistry(metrics=metrics)
    registry.deploy(name, model, example,
                    max_batch_rows=max_batch_rows, **servable_kwargs)
    endpoint = ServingEndpoint(registry, name,
                               max_batch_rows=max_batch_rows,
                               max_wait_ms=max_wait_ms,
                               queue_capacity=queue_capacity,
                               metrics=metrics)
    return endpoint.start()
