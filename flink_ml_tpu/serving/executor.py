"""Compiled executors: ``ServableModel`` wraps a fitted Model for serving.

The adapter's contract:

- **Bucketed shapes.**  Every predict pads its rows to a power-of-two
  bucket (``utils/padding.py``), so the full space of request/batch sizes
  in ``[1, max_batch_rows]`` maps onto ``log2`` many compiled programs.
- **Eager warm-up.**  ``warm_up()`` runs one predict per bucket BEFORE the
  endpoint reports ready, so steady-state traffic of mixed sizes triggers
  zero new XLA compiles (asserted in ``tests/test_serving.py`` with a JAX
  lowering counter).
- **Bit-exact with offline ``transform()``.**  The served computation is
  either literally ``model.transform`` (the generic adapter — same jit
  cache, same host post-processing) or an expression-identical jitted
  score function for the specialized families; pad rows are inert in
  every row-independent predict, so serving a request returns exactly the
  rows offline ``transform`` would.
- **Donated inputs.**  The specialized executors donate the padded feature
  buffer to the jitted score on TPU backends (the per-request transfer
  buffer is dead after the call — donation lets XLA reuse the HBM
  allocation instead of holding both).  Donation is skipped on backends
  that ignore it (CPU) to avoid spurious warnings.
"""

from __future__ import annotations

import copy
import threading

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data.table import Table
from ..robustness.faults import fault_point
from ..utils.padding import (
    DEFAULT_BUCKET_CAP,
    DEFAULT_MIN_BUCKET,
    bucket_rows,
    bucket_sizes,
    pad_rows_to_bucket,
)

__all__ = ["ServableModel", "make_servable"]


# One jit per (name) shared by every servable instance — deploys of new
# model versions hit the same compile cache, so a hot-swap warm-up only
# pays tracing for shapes the process has never seen.
_JIT_CACHE: Dict[str, Callable] = {}
_JIT_LOCK = threading.Lock()


def _serving_jit(name: str, fn: Callable, donate_argnums: Tuple[int, ...],
                 static_argnums: Tuple[int, ...] = ()) -> Callable:
    with _JIT_LOCK:
        cached = _JIT_CACHE.get(name)
        if cached is None:
            donate = (donate_argnums
                      if jax.default_backend() == "tpu" else ())
            cached = jax.jit(fn, donate_argnums=donate,
                             static_argnums=static_argnums)
            _JIT_CACHE[name] = cached
    return cached


class ServableModel:
    """A fitted Model adapted for online serving: schema-checked,
    bucket-padded, warm-compiled predict.

    ``example`` is a small Table carrying the REQUEST schema (the columns
    clients send — typically one row of the training table minus the
    label); warm-up tiles it to every bucket size.  The generic adapter
    serves ANY stage whose ``transform`` is row-independent; the
    specialized subclasses below add donated-input jitted score paths for
    the families the serving layer optimizes.
    """

    def __init__(self, model, example: Table, *,
                 max_batch_rows: int = 256,
                 min_bucket: int = DEFAULT_MIN_BUCKET,
                 output_cols: Optional[Sequence[str]] = None):
        if not hasattr(model, "transform"):
            raise TypeError(
                f"{type(model).__name__} has no transform(); only fitted "
                "Models/Transformers are servable")
        if example.num_rows == 0:
            raise ValueError("example must carry at least one row")
        if max_batch_rows > DEFAULT_BUCKET_CAP:
            raise ValueError(
                f"max_batch_rows={max_batch_rows} exceeds the bucket cap "
                f"({DEFAULT_BUCKET_CAP}) above which predict paths keep "
                "exact shapes — the zero-retrace warm-up cannot cover it")
        self.model = model
        self.example = example
        self.min_bucket = min_bucket
        self.max_batch_rows = max_batch_rows
        self.buckets = bucket_sizes(max_batch_rows, min_bucket)
        self.output_cols = tuple(output_cols) if output_cols else None
        self._schema = set(example.column_names)
        self._ready = False

    #: True for executor families whose compiled score programs take the
    #: params as RUNTIME arguments (the module-global serving jit cache):
    #: a same-shape new generation can :meth:`rebind` without warm-up —
    #: the continuous-learning delta-publish fast path.  The generic
    #: adapter serves through ``model.transform``, whose jit caches may
    #: bake params in as constants, so it stays False.
    rebind_safe = False

    def rebind(self, model) -> "ServableModel":
        """A ready clone of this servable scoring with ``model`` (same
        example/buckets/output schema).  Only meaningful when
        ``rebind_safe``: the clone inherits readiness WITHOUT a warm-up
        because every compiled program it can reach is already compiled
        (params are runtime args) — publish becomes a buffer swap.
        Callers own the same-shape contract; a shape change must go
        through the full deploy path instead."""
        if not self.rebind_safe:
            raise TypeError(
                f"{type(self).__name__} is not rebind-safe: its transform "
                "path may bake params into compiled programs — deploy the "
                "new version through the registry (load->warm->swap)")
        clone = copy.copy(self)
        clone.model = model
        return clone

    # -- predict ------------------------------------------------------------
    def check_schema(self, table: Table) -> None:
        names = set(table.column_names)
        if names != self._schema:
            raise ValueError(
                f"request schema {sorted(names)} does not match the "
                f"endpoint's example schema {sorted(self._schema)}")

    def bucket_for(self, rows: int) -> int:
        return bucket_rows(rows, min_bucket=self.min_bucket)

    def predict(self, table: Table) -> Table:
        """Serve one (micro-)batch: returns the transform output for
        exactly ``table``'s rows, computed at the padded bucket shape."""
        fault_point("serving.predict")
        out = self._run(table)
        if self.output_cols:
            out = out.select(*self.output_cols)
        return out

    def _run(self, table: Table) -> Table:
        # generic adapter: the model's own transform IS the compiled
        # executor — its predict entry points bucket-pad internally
        # (utils/padding.py), so this path shares the offline jit cache
        # and is bit-exact with offline transform by construction
        return self.model.transform(table)[0]

    # -- warm-up ------------------------------------------------------------
    def _tiled_example(self, rows: int) -> Table:
        reps = -(-rows // self.example.num_rows)
        return Table({
            name: np.concatenate([col] * reps, axis=0)[:rows]
            for name, col in self.example.to_dict().items()})

    def warm_up(self) -> "ServableModel":
        """Compile every bucket eagerly (one predict per ladder rung) so
        the endpoint only reports ready once steady state is retrace-free.
        Runs on the deploying thread — OFF the serving path, so a hot-swap
        warms the incoming version while the old one keeps serving."""
        fault_point("serving.warm_up")
        for bucket in self.buckets:
            self._run(self._tiled_example(bucket))
        self._ready = True
        return self

    @property
    def ready(self) -> bool:
        return self._ready


# -- specialized executors ---------------------------------------------------

def _linear_margins(X, w, b):
    from ..models.common.linear import _stable_margins

    return _stable_margins(X, w, b)


class _LinearServable(ServableModel):
    """Linear family (LogisticRegression / LinearRegression / LinearSVC):
    dense features score through a donated-input jitted margin; sparse and
    mixed layouts fall back to the model's own (bucket-routed) transform."""

    rebind_safe = True

    def _run(self, table: Table) -> Table:
        from ..models.common.linear import resolve_features

        model = self.model
        kind, feats = resolve_features(table, model.get_features_col())
        if kind != "dense":
            return model.transform(table)[0]
        model._require_model()
        w = jnp.asarray(model._state.coefficients, jnp.float32)
        b = jnp.asarray(model._state.intercept, jnp.float32)
        (X,), n = pad_rows_to_bucket((feats.astype(np.float32),),
                                     min_bucket=self.min_bucket)
        fn = _serving_jit("linear_margins", _linear_margins, (0,))
        margins = np.asarray(fn(X, w, b), np.float64)[:n]
        out = table.with_column(model.get_prediction_col(),
                                model._decision(margins))
        raw_col = model.get_raw_prediction_col()
        if raw_col:
            out = out.with_column(raw_col, model._raw(margins))
        return out


def _kmeans_assign(measure, points, centroids):
    return jnp.argmin(measure.pairwise(points, centroids), axis=1)


class _KMeansServable(ServableModel):
    """KMeansModel: donated-input jitted nearest-centroid assign."""

    rebind_safe = True

    def _run(self, table: Table) -> Table:
        from ..distance import DistanceMeasure
        from ..linalg import stack_vectors

        model = self.model
        model._require_model()
        measure = DistanceMeasure.get_instance(model.get_distance_measure())
        points = stack_vectors(
            table[model.get_features_col()]).astype(np.float32)
        (points,), n = pad_rows_to_bucket((points,),
                                          min_bucket=self.min_bucket)
        fn = _serving_jit("kmeans_assign", _kmeans_assign,
                         (1,), static_argnums=(0,))
        assign = np.asarray(
            fn(measure, points, jnp.asarray(model._centroids)))[:n]
        return table.with_column(model.get_prediction_col(),
                                 assign.astype(np.int64))


def _widedeep_scores(params, dense, cat_ids):
    from ..models.recommendation.widedeep import forward

    return jax.nn.sigmoid(forward(params, dense, cat_ids))


class _WideDeepServable(ServableModel):
    """WideDeepModel: donated-input jitted sigmoid(forward)."""

    rebind_safe = True

    def _run(self, table: Table) -> Table:
        from ..models.recommendation.widedeep import _validate_cat_ids

        model = self.model
        model._require_model()
        dense = np.asarray(table[model.DENSE_FEATURES_COL], np.float32)
        cat = np.asarray(table[model.CAT_FEATURES_COL], np.int32)
        cat = _validate_cat_ids(cat, model._vocab_sizes)
        (dense, cat), n = pad_rows_to_bucket((dense, cat),
                                             min_bucket=self.min_bucket)
        fn = _serving_jit("widedeep_scores", _widedeep_scores, (1, 2))
        scores = np.asarray(fn(model._params, dense, cat), np.float64)[:n]
        out = table.with_column(model.get_raw_prediction_col(), scores)
        return out.with_column(model.get_prediction_col(),
                               (scores > 0.5).astype(np.int64))


class _PipelineServable(ServableModel):
    """PipelineModel: the whole chain (preprocess + score) compiles into
    fused segments (``api/chain.py``) at deploy time — a fully-chainable
    pipeline serves every micro-batch in ONE jitted dispatch.  ``warm_up``
    (inherited) tiles the example through every bucket, so each segment
    compiles per bucket OFF the serving path; plans with the same stage
    types share compiled executables across hot-swapped generations via
    the plan-static segment jit."""

    def __init__(self, model, example: Table, **kwargs: Any):
        super().__init__(model, example, **kwargs)
        from ..api.chain import compile_pipeline, raw_schema

        self._plan_schema = raw_schema(example)
        try:
            # the plan must pad with THIS servable's bucket floor —
            # warm_up tiles buckets from self.min_bucket, and a plan
            # padding to a different ladder would compile on the serving
            # path after the endpoint reported ready
            plan = compile_pipeline(model, example,
                                    min_bucket=self.min_bucket)
            self._plan = plan if plan.worthwhile else None
        except Exception:           # unported stage mix: stagewise serve
            self._plan = None

    def _run(self, table: Table) -> Table:
        # the plan's kernel admissibility was decided on the EXAMPLE's
        # raw dtypes (exact-compare stages decline f64); a request with
        # a different raw schema routes through model.transform, whose
        # own plan cache keys on the request schema
        if self._plan is not None:
            from ..api.chain import raw_schema

            if raw_schema(table) == self._plan_schema:
                return self._plan.transform(table)[0]
        return self.model.transform(table)[0]


def make_servable(model, example: Table, **kwargs: Any) -> ServableModel:
    """Adapt a fitted Model for serving, picking the specialized executor
    for the covered families (linear / KMeans / Wide&Deep; whole
    PipelineModels fuse their chainable stage runs into single-dispatch
    segments; GBT and every other row-independent transform serve through
    the generic adapter, whose predict entry points are bucket-routed
    since this PR)."""
    from ..api.pipeline import PipelineModel
    from ..models.clustering.kmeans import KMeansModel
    from ..models.common.linear import LinearModelBase
    from ..models.recommendation.widedeep import WideDeepModel

    if isinstance(model, PipelineModel):
        cls: type = _PipelineServable
    elif isinstance(model, LinearModelBase):
        cls = _LinearServable
    elif isinstance(model, KMeansModel):
        cls = _KMeansServable
    elif isinstance(model, WideDeepModel):
        cls = _WideDeepServable
    else:
        cls = ServableModel
    return cls(model, example, **kwargs)
