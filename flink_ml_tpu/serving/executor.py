"""Compiled executors: ``ServableModel`` wraps a fitted Model for serving.

The adapter's contract:

- **Bucketed shapes.**  Every predict pads its rows to a power-of-two
  bucket (``utils/padding.py``), so the full space of request/batch sizes
  in ``[1, max_batch_rows]`` maps onto ``log2`` many compiled programs.
- **Eager warm-up.**  ``warm_up()`` runs one predict per bucket BEFORE the
  endpoint reports ready, so steady-state traffic of mixed sizes triggers
  zero new XLA compiles (asserted in ``tests/test_serving.py`` with a JAX
  lowering counter).
- **Bit-exact with offline ``transform()``.**  The served computation is
  either literally ``model.transform`` (the generic adapter — same jit
  cache, same host post-processing) or an expression-identical jitted
  score function for the specialized families; pad rows are inert in
  every row-independent predict, so serving a request returns exactly the
  rows offline ``transform`` would.
- **One compiled surface.**  The specialized executors dispatch their
  model's chain-kernel ``(fn, static)`` plan through the kernel
  registry's shared plan-static jit (``kernels/registry.py``) — the
  same executable the fused pipelines and the models' own ``transform``
  entry points run, so warm-up anywhere is a compile-cache hit
  everywhere, and the registry's compile/cache-hit gauges account it.
  On TPU the shared jit donates the padded column dict (the per-request
  transfer buffer is dead after the call — donation lets XLA reuse the
  HBM allocation instead of holding both); donation is skipped on
  backends that ignore it (CPU) to avoid spurious warnings.
"""

from __future__ import annotations

import copy

from typing import Any, Optional, Sequence

import jax
import numpy as np

from ..data.table import Table
from ..robustness.faults import fault_point
from ..utils.padding import (
    DEFAULT_BUCKET_CAP,
    DEFAULT_MIN_BUCKET,
    bucket_rows,
    bucket_sizes,
)

__all__ = ["ServableModel", "make_servable"]


# The per-family serving jits collapsed into the kernel registry's ONE
# dispatch surface (kernels/registry.py, PR 10): the specialized
# executors below run their model's chain-kernel (fn, static) plan
# through the same plan-static jit the fused pipelines and the models'
# own predict entry points use, so a shape warmed by ANY consumer is a
# compile-cache hit for serving (and vice versa).  Donation of the
# per-request transfer buffer on TPU moved into the shared jit.


class ServableModel:
    """A fitted Model adapted for online serving: schema-checked,
    bucket-padded, warm-compiled predict.

    ``example`` is a small Table carrying the REQUEST schema (the columns
    clients send — typically one row of the training table minus the
    label); warm-up tiles it to every bucket size.  The generic adapter
    serves ANY stage whose ``transform`` is row-independent; the
    specialized subclasses below add donated-input jitted score paths for
    the families the serving layer optimizes.
    """

    #: precisions this executor family can serve at.  "int8" means the
    #: bind path quantizes the published params (per-channel max-abs,
    #: ``kernels/quantize.py``) and scores through the op's "int8"
    #: registry backend; only the registry-dispatched families support
    #: it — the generic ``model.transform`` adapter and the fused
    #: pipeline plan have no quantized param seam, so they refuse at
    #: construction rather than silently serving f32.
    supported_precisions = ("f32",)

    def __init__(self, model, example: Table, *,
                 max_batch_rows: int = 256,
                 min_bucket: int = DEFAULT_MIN_BUCKET,
                 output_cols: Optional[Sequence[str]] = None,
                 precision: str = "f32"):
        if precision not in self.supported_precisions:
            raise TypeError(
                f"{type(self).__name__} cannot serve at precision "
                f"{precision!r} (supports {self.supported_precisions}); "
                "int8 covers the registry-dispatched families only")
        self.precision = precision
        if not hasattr(model, "transform"):
            raise TypeError(
                f"{type(model).__name__} has no transform(); only fitted "
                "Models/Transformers are servable")
        if example.num_rows == 0:
            raise ValueError("example must carry at least one row")
        if max_batch_rows > DEFAULT_BUCKET_CAP:
            raise ValueError(
                f"max_batch_rows={max_batch_rows} exceeds the bucket cap "
                f"({DEFAULT_BUCKET_CAP}) above which predict paths keep "
                "exact shapes — the zero-retrace warm-up cannot cover it")
        self.model = model
        self.example = example
        self.min_bucket = min_bucket
        self.max_batch_rows = max_batch_rows
        self.buckets = bucket_sizes(max_batch_rows, min_bucket)
        self.output_cols = tuple(output_cols) if output_cols else None
        self._schema = set(example.column_names)
        self._ready = False
        #: readiness accounting (ISSUE 12): wall time to ready and the
        #: per-bucket executable source — populated by :meth:`warm_up`
        self.warmup_report: Optional[dict] = None

    #: True for executor families whose compiled score programs take the
    #: params as RUNTIME arguments (the module-global serving jit cache):
    #: a same-shape new generation can :meth:`rebind` without warm-up —
    #: the continuous-learning delta-publish fast path.  The generic
    #: adapter serves through ``model.transform``, whose jit caches may
    #: bake params in as constants, so it stays False.
    rebind_safe = False

    def rebind(self, model) -> "ServableModel":
        """A ready clone of this servable scoring with ``model`` (same
        example/buckets/output schema).  Only meaningful when
        ``rebind_safe``: the clone inherits readiness WITHOUT a warm-up
        because every compiled program it can reach is already compiled
        (params are runtime args) — publish becomes a buffer swap.
        Callers own the same-shape contract; a shape change must go
        through the full deploy path instead."""
        if not self.rebind_safe:
            raise TypeError(
                f"{type(self).__name__} is not rebind-safe: its transform "
                "path may bake params into compiled programs — deploy the "
                "new version through the registry (load->warm->swap)")
        clone = copy.copy(self)
        clone.model = model
        return clone

    # -- predict ------------------------------------------------------------
    def check_schema(self, table: Table) -> None:
        names = set(table.column_names)
        if names != self._schema:
            raise ValueError(
                f"request schema {sorted(names)} does not match the "
                f"endpoint's example schema {sorted(self._schema)}")

    def bucket_for(self, rows: int) -> int:
        return bucket_rows(rows, min_bucket=self.min_bucket)

    def predict(self, table: Table) -> Table:
        """Serve one (micro-)batch: returns the transform output for
        exactly ``table``'s rows, computed at the padded bucket shape."""
        fault_point("serving.predict")
        out = self._run(table)
        if self.output_cols:
            out = out.select(*self.output_cols)
        return out

    def _run(self, table: Table) -> Table:
        # generic adapter: the model's own transform IS the compiled
        # executor — its predict entry points bucket-pad internally
        # (utils/padding.py), so this path shares the offline jit cache
        # and is bit-exact with offline transform by construction
        return self.model.transform(table)[0]

    # -- warm-up ------------------------------------------------------------
    def _tiled_example(self, rows: int) -> Table:
        reps = -(-rows // self.example.num_rows)
        return Table({
            name: np.concatenate([col] * reps, axis=0)[:rows]
            for name, col in self.example.to_dict().items()})

    def warm_up(self) -> "ServableModel":
        """Compile every bucket eagerly (one predict per ladder rung) so
        the endpoint only reports ready once steady state is retrace-free.
        Runs on the deploying thread — OFF the serving path, so a hot-swap
        warms the incoming version while the old one keeps serving.

        Populates :attr:`warmup_report`: total wall to ready plus, per
        bucket, whether readiness cost a live XLA **compile**, a
        persistent-cache **aot** load (``kernels/aot.py``), or rode an
        in-process **cache** hit — diffed from the registry's
        THIS-THREAD counters (``kernel_stats.thread_counts``), so
        cold-start composition is attributed, not guessed, and a
        hot-swap warming on the deploy thread is never mislabeled by
        the old generation's concurrent serving dispatches.  (Servables
        whose predict path does not go through the registry dispatch —
        the generic ``model.transform`` adapter — report
        ``untracked``.)"""
        import time as _time

        from ..kernels.registry import kernel_stats

        fault_point("serving.warm_up")
        report: dict = {"wall_s": None, "precision": self.precision,
                        "buckets": {}}
        t_start = _time.perf_counter()
        for bucket in self.buckets:
            compiles0, aot0, hits0 = kernel_stats.thread_counts()
            t0 = _time.perf_counter()
            self._run(self._tiled_example(bucket))
            ms = (_time.perf_counter() - t0) * 1e3
            compiles1, aot1, hits1 = kernel_stats.thread_counts()
            if compiles1 > compiles0:
                source = "compile"
            elif aot1 > aot0:
                source = "aot"
            elif hits1 > hits0:
                source = "cache"
            else:
                source = "untracked"
            report["buckets"][bucket] = {"source": source,
                                         "ms": round(ms, 3),
                                         "precision": self.precision}
        report["wall_s"] = round(_time.perf_counter() - t_start, 4)
        sources = [b["source"] for b in report["buckets"].values()]
        report["compiled"] = sources.count("compile")
        report["aot_loaded"] = sources.count("aot")
        report["cache_hits"] = sources.count("cache")
        self.warmup_report = report
        self._ready = True
        return self

    @property
    def ready(self) -> bool:
        return self._ready


# -- specialized executors ---------------------------------------------------

class _KernelServable(ServableModel):
    """Families whose model exposes a chain ``transform_kernel``: serving
    runs that kernel's ``(fn, static)`` plan through the kernel
    registry's shared dispatch surface (``api/chain.py::run_kernel``).

    The plan is built once per generation from the EXAMPLE schema and
    its params are device-put once, so steady-state requests pay one
    dispatch with zero host->device param traffic — and because the
    compiled program identity is the same (fn, static) pair the fused
    pipelines and the model's own ``transform`` dispatch, a bucket
    warmed by any consumer is a compile-cache hit here (and a serving
    warm-up pre-compiles the offline paths).  ``rebind`` (the
    continuous-learning delta-publish fast path) rebuilds only the
    cached params — same plan, same shapes, zero new lowerings."""

    rebind_safe = True
    op_label: Optional[str] = None
    supported_precisions = ("f32", "int8")

    def __init__(self, model, example: Table, **kwargs: Any):
        super().__init__(model, example, **kwargs)
        self._build_kernel()

    def _build_kernel(self) -> None:
        # transform_kernel's "unported config" signal is returning None
        # (all three families); a RAISE here is a genuine defect (e.g.
        # an unfitted model) and must surface at construction, not
        # silently degrade every request to the generic transform path
        kernel = self.model.transform_kernel(self.example.schema())
        if kernel is None and self.precision == "int8":
            # no chain plan for this config (e.g. sparse linear layouts)
            # means no quantized path either; silently serving f32 under
            # an int8 contract would lie to the capacity planner
            raise TypeError(
                f"{type(self.model).__name__} has no chain kernel for "
                "this example schema — precision='int8' requires the "
                "registry-dispatched plan; serve this config at f32")
        if kernel is not None and self.precision == "int8":
            # THE calibration capture point: quantize this generation's
            # params and swap the plan's fn for the op's "int8" registry
            # backend.  rebind() re-runs this bind on the clone, so a
            # delta publish re-derives scales from the NEW params before
            # the swap — stale scales never serve (ARCHITECTURE.md
            # "Int8 serving").  Same (fn, static) plan identity across
            # generations => rebind stays zero-new-lowerings.
            import dataclasses

            from ..kernels.quantize import quantize_stage_params
            from ..kernels.registry import lookup

            entry = lookup(self.op_label, backend="int8")
            kernel = dataclasses.replace(
                kernel, fn=entry.fn,
                params=quantize_stage_params(self.op_label,
                                             kernel.params))
        self._kernel = kernel
        self._kernel_params = (jax.device_put(kernel.params)
                               if kernel is not None else None)

    def rebind(self, model) -> "ServableModel":
        clone = super().rebind(model)
        clone._build_kernel()
        return clone

    def _run(self, table: Table) -> Table:
        from ..api.chain import UnsafeColumnValues, run_kernel

        kernel = self._kernel
        if kernel is None:
            return self.model.transform(table)[0]
        # kernel admissibility was decided on the EXAMPLE schema; a
        # request re-spelling a consumed column as object dtype (e.g. a
        # SparseVector features column under the same name) must route
        # to the model's own transform, exactly like the pre-registry
        # per-request resolve_features fallback did
        if any(np.asarray(table[n]).dtype.kind not in "fiub"
               for n in kernel.consumes):
            return self.model.transform(table)[0]
        try:
            cols = run_kernel(kernel, table, params=self._kernel_params,
                              min_bucket=self.min_bucket, op=self.op_label)
        except (UnsafeColumnValues, KeyError):
            # f32-unsafe int batch, or a request schema the kernel's
            # columns don't cover — the model's own transform owns those
            return self.model.transform(table)[0]
        out = table
        for name in (n for n in cols if n not in kernel.produces):
            out = out.with_column(name, cols[name])
        return out


class _LinearServable(_KernelServable):
    """Linear family (LogisticRegression / LinearRegression / LinearSVC):
    dense features score through the registry-dispatched margin kernel;
    sparse and mixed layouts fall back to the model's own (bucket-routed)
    transform (their ``transform_kernel`` is None)."""

    op_label = "linear_margins"


class _KMeansServable(_KernelServable):
    """KMeansModel: registry-dispatched nearest-centroid assign."""

    op_label = "kmeans_assign"


class _WideDeepServable(_KernelServable):
    """WideDeepModel: registry-dispatched sigmoid(forward) (the id range
    check runs as the kernel's host ``pre``, the in-kernel offset is an
    exact int add)."""

    op_label = "widedeep_scores"


class _RetrieveServable(_KernelServable):
    """IVFIndex — the first NON-model servable: the fused IVF / IVF-PQ
    scan+top-k plan serves through exactly the kernel seams the model
    families do (same plan identity as the index's own ``transform``, so
    warmed buckets are compile-cache hits; rebind swaps posting-list
    params with zero new lowerings).  No "int8" registry backend — PQ
    codes ARE the compressed representation, carried by the f32 plan."""

    op_label = "retrieve"
    supported_precisions = ("f32",)


class _PipelineServable(ServableModel):
    """PipelineModel: the whole chain (preprocess + score) compiles into
    fused segments (``api/chain.py``) at deploy time — a fully-chainable
    pipeline serves every micro-batch in ONE jitted dispatch.  ``warm_up``
    (inherited) tiles the example through every bucket, so each segment
    compiles per bucket OFF the serving path; plans with the same stage
    types share compiled executables across hot-swapped generations via
    the plan-static segment jit."""

    def __init__(self, model, example: Table, **kwargs: Any):
        super().__init__(model, example, **kwargs)
        from ..api.chain import compile_pipeline, raw_schema

        self._plan_schema = raw_schema(example)
        try:
            # the plan must pad with THIS servable's bucket floor —
            # warm_up tiles buckets from self.min_bucket, and a plan
            # padding to a different ladder would compile on the serving
            # path after the endpoint reported ready
            plan = compile_pipeline(model, example,
                                    min_bucket=self.min_bucket)
            self._plan = plan if plan.worthwhile else None
        except Exception:           # unported stage mix: stagewise serve
            self._plan = None

    def _run(self, table: Table) -> Table:
        # the plan's kernel admissibility was decided on the EXAMPLE's
        # raw dtypes (exact-compare stages decline f64); a request with
        # a different raw schema routes through model.transform, whose
        # own plan cache keys on the request schema
        if self._plan is not None:
            from ..api.chain import raw_schema

            if raw_schema(table) == self._plan_schema:
                return self._plan.transform(table)[0]
        return self.model.transform(table)[0]


def make_servable(model, example: Table, *, emb_cache: bool = False,
                  **kwargs: Any) -> ServableModel:
    """Adapt a fitted Model for serving, picking the specialized executor
    for the covered families (linear / KMeans / Wide&Deep; whole
    PipelineModels fuse their chainable stage runs into single-dispatch
    segments; GBT and every other row-independent transform serve through
    the generic adapter, whose predict entry points are bucket-routed
    since this PR).

    ``emb_cache=True`` (WideDeep only) serves through the
    device-resident embedding-row cache (``serving/embcache.py``,
    ISSUE 14): only the hot table blocks live in HBM;
    ``cache_block_rows`` / ``cache_capacity_blocks`` size it.

    ``precision="int8"`` (the registry-dispatched families + the cached
    WideDeep path) quantizes the published params at bind time
    (per-channel max-abs, ``kernels/quantize.py``) and scores through
    the op's "int8" registry backend — roughly 4x smaller resident
    params (2x for the row cache's codes+scales pools) at an accuracy
    envelope the parity matrix gates.  Families without a quantized
    seam raise TypeError rather than silently serving f32."""
    from ..api.pipeline import PipelineModel
    from ..models.clustering.kmeans import KMeansModel
    from ..models.common.linear import LinearModelBase
    from ..models.recommendation.widedeep import WideDeepModel
    from ..retrieval.ivf import IVFIndex

    if isinstance(model, PipelineModel):
        cls: type = _PipelineServable
    elif isinstance(model, LinearModelBase):
        cls = _LinearServable
    elif isinstance(model, KMeansModel):
        cls = _KMeansServable
    elif isinstance(model, IVFIndex):
        cls = _RetrieveServable
    elif isinstance(model, WideDeepModel):
        if emb_cache:
            from .embcache import CachedWideDeepServable

            return CachedWideDeepServable(model, example, **kwargs)
        cls = _WideDeepServable
    else:
        cls = ServableModel
    if emb_cache:
        raise TypeError(
            f"emb_cache=True only applies to WideDeepModel (its stacked "
            f"vocab tables are the cacheable operand), not "
            f"{type(model).__name__}")
    return cls(model, example, **kwargs)
