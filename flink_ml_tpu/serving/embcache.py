"""Hot embedding-row cache — device-resident LRU row blocks (ISSUE 14).

WideDeep's stacked tables are the one serving operand that does NOT
amortize across tenants: a ``(total_vocab, emb_dim)`` table per tenant
at production vocab sizes exhausts HBM long before the chip runs out of
compute.  Zipfian traffic is the way out — most lookups hit a small hot
set — so :class:`EmbeddingRowCache` keeps only the HOT row blocks
device-resident and streams cold blocks in on demand:

- **Fixed device pools.**  One preallocated pool per table, shape
  ``(capacity_blocks, block_rows, *row_shape)``.  All device programs
  see CONSTANT shapes: a miss writes a block into a pool slot through
  one jitted ``dynamic_update_slice`` (compiled once per table), and a
  batch lookup is one jitted ``pool[slots, locals]`` gather (compiled
  once per request bucket) — zero steady-state retraces however the
  resident set churns.
- **LRU over blocks, not rows.**  The slot map (``block_id -> slot``)
  and recency order live on the host; eviction frees the least
  recently TOUCHED block's slot (touch = any lookup that read the
  block).  Rows inside a block ride together — the block is the
  device-transfer and residency granule, which is what makes the
  zipfian head cheap (hot ids cluster into few blocks).
- **Exactness.**  A cached gather returns bitwise the same rows as
  indexing the host table: blocks are exact ``device_put`` copies and
  the gather is pure indexing.  ``CachedWideDeepServable`` feeds the
  gathered rows through the SAME ``forward_from_rows`` expression the
  full-table forward uses, so served scores are bit-exact with
  ``model.transform`` (asserted in ``tests/test_scheduler.py``).

**Int8 row pools** (ISSUE 18): ``precision="int8"`` stores matrix-row
tables as int8 CODES plus one f32 per-row scale, quantized ONCE from the
host table at construction (publish-time calibration — ``rebind``'s
fresh cache re-calibrates each generation).  The codes pool plus the
scales pool cost ~(1 + 4/row_dim)/4 of the f32 pool at the same
``capacity_blocks`` — so at a FIXED device byte budget an int8 cache
holds ~2x the resident rows (the models-per-chip multiplier
``bench_int8`` measures).  A lookup gathers codes and scales and
dequantizes the gathered rows in-program (one exact cast + one f32
multiply; the f32 table never materializes); the oversized-batch bypass
dequantizes the SAME codes host-side, so cached and bypassed batches
return identical bits.  Scalar-row (1-d) tables — WideDeep's
``wide_cat`` — stay f32: codes + a per-row scale would cost more than
the f32 they replace.

**Single-consumer contract**: ``lookup`` mutates the slot map and the
pools without a lock — exactly one thread may call it (the scheduler's
serve loop / an endpoint's serve thread; warm-up of a NEW servable
sharing a cache with a concurrently-serving one is NOT supported — give
each generation its own cache, which ``rebind`` does automatically).
Hit/miss/eviction counters publish as gauges for the PR 13 metrics tree
(``snapshot()`` is a ``MetricsTree`` provider).
"""

from __future__ import annotations

import time

from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ..data.table import Table
from ..kernels.registry import tpu_only
from .executor import ServableModel

__all__ = ["EmbeddingRowCache", "CachedWideDeepServable"]


_POOL_SET: list = []
_POOL_GATHER: list = []
_POOL_GATHER_DEQ: list = []


def _pool_setter():
    """ONE jitted slot write per process: ``pool.at[slot].set(block)``
    with the slot as a runtime scalar — every miss of every cache hits
    the same compiled program (per pool shape).  Donated on TPU so the
    update is in-place in HBM; CPU ignores donation (skipped to avoid
    the spurious warning — the executor stance)."""
    if not _POOL_SET:
        donate = (0,) if tpu_only() else ()
        _POOL_SET.append(jax.jit(
            lambda pool, slot, block: pool.at[slot].set(block),
            donate_argnums=donate))
    return _POOL_SET[0]


def _pool_gather():
    if not _POOL_GATHER:
        _POOL_GATHER.append(jax.jit(
            lambda pool, slots, local: pool[slots, local]))
    return _POOL_GATHER[0]


def _pool_gather_deq():
    """The int8-pool gather: codes and per-row scales gather together
    and the GATHERED rows dequantize in the same program — the f32
    table (or block) never materializes on device."""
    if not _POOL_GATHER_DEQ:
        import jax.numpy as jnp

        _POOL_GATHER_DEQ.append(jax.jit(
            lambda pool, spool, slots, local:
            pool[slots, local].astype(jnp.float32)
            * spool[slots, local][..., None]))
    return _POOL_GATHER_DEQ[0]


class EmbeddingRowCache:
    """LRU of device-resident row blocks over host-resident tables
    (module doc).  ``tables`` maps name -> host array sharing one
    leading (vocab) dim — WideDeep passes ``{"wide_cat": (V,),
    "emb": (V, E)}``."""

    def __init__(self, tables: Dict[str, Any], *, block_rows: int = 512,
                 capacity_blocks: int = 64, precision: str = "f32"):
        if not tables:
            raise ValueError("tables must not be empty")
        if block_rows <= 0:
            raise ValueError("block_rows must be positive")
        if capacity_blocks <= 0:
            raise ValueError("capacity_blocks must be positive")
        if precision not in ("f32", "int8"):
            raise ValueError(f"unknown cache precision {precision!r}")
        self.precision = precision
        self._host = {name: np.asarray(t) for name, t in tables.items()}
        sizes = {name: t.shape[0] for name, t in self._host.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(
                f"tables must share one vocab dim, got {sizes}")
        self.vocab = next(iter(sizes.values()))
        if self.vocab == 0:
            raise ValueError("tables must carry at least one row")
        # int8: matrix-row tables become codes + per-row scales, ONCE,
        # from this generation's host table (publish-time calibration;
        # module doc).  Scalar-row tables stay f32.
        self._host_scales: Dict[str, np.ndarray] = {}
        if precision == "int8":
            from ..kernels.quantize import quantize_rows

            for name, t in self._host.items():
                if t.ndim >= 2:
                    codes, scales = quantize_rows(t)
                    self._host[name] = codes
                    self._host_scales[name] = scales
        self.block_rows = block_rows
        self.n_blocks = -(-self.vocab // block_rows)
        #: a cache bigger than the table is just the table — cap it so
        #: the accounting (resident fraction, pool bytes) stays honest
        self.capacity_blocks = min(capacity_blocks, self.n_blocks)
        self._pools = {
            name: jax.device_put(np.zeros(
                (self.capacity_blocks, block_rows) + t.shape[1:],
                t.dtype))
            for name, t in self._host.items()}
        self._scale_pools = {
            name: jax.device_put(np.zeros(
                (self.capacity_blocks, block_rows), np.float32))
            for name in self._host_scales}
        self._slot_of: Dict[int, int] = {}
        self._lru: "OrderedDict[int, int]" = OrderedDict()
        self._free = list(range(self.capacity_blocks - 1, -1, -1))
        self.hits = 0            # per-id lookups served from a resident block
        self.misses = 0          # per-id lookups that had to fault a block in
        self.block_faults = 0    # blocks transferred host -> device
        self.evictions = 0
        self.lookups = 0         # lookup() calls
        self.bypasses = 0        # batches served uncached (working set
        #                          bigger than the whole cache)
        self._fault_s = 0.0

    # -- core ----------------------------------------------------------------
    def _pad_block(self, table: np.ndarray, block: int) -> np.ndarray:
        lo = block * self.block_rows
        chunk = table[lo:lo + self.block_rows]
        if chunk.shape[0] == self.block_rows:
            return chunk
        pad = np.zeros((self.block_rows - chunk.shape[0],)
                       + table.shape[1:], table.dtype)
        return np.concatenate([chunk, pad], axis=0)

    def _host_block(self, name: str, block: int) -> np.ndarray:
        return self._pad_block(self._host[name], block)

    def _admit(self, block: int, pinned) -> int:
        """Fault one block in (single-consumer; see module doc).
        ``pinned`` blocks — the ones the CURRENT lookup touches — are
        exempt from eviction: they must all be resident simultaneously
        when the batch gather runs after the admit loop."""
        if self._free:
            slot = self._free.pop()
        else:
            for old_block in self._lru:
                if old_block not in pinned:
                    break
            else:  # unreachable: lookup() bypasses oversized batches
                raise RuntimeError("no evictable block")
            slot = self._lru.pop(old_block)
            del self._slot_of[old_block]
            self.evictions += 1
        t0 = time.perf_counter()
        setter = _pool_setter()
        slot_idx = np.int32(slot)
        for name in self._pools:
            self._pools[name] = setter(self._pools[name], slot_idx,
                                       self._host_block(name, block))
        for name in self._scale_pools:
            self._scale_pools[name] = setter(
                self._scale_pools[name], slot_idx,
                self._pad_block(self._host_scales[name], block))
        self._fault_s += time.perf_counter() - t0
        self.block_faults += 1
        self._slot_of[block] = slot
        self._lru[block] = slot
        return slot

    def lookup(self, ids: Any) -> Dict[str, jax.Array]:
        """Device rows for ``ids`` (any int shape), one entry per table:
        output shape is ``ids.shape + row_shape``.  Faults missing
        blocks in (LRU-evicting), touches resident ones."""
        ids = np.asarray(ids)
        if ids.size == 0:
            raise ValueError("lookup needs at least one id")
        if ids.min() < 0 or ids.max() >= self.vocab:
            raise ValueError(
                f"id out of range [0, {self.vocab}) — offset/validate "
                "ids before the cache (WideDeep's _validate_cat_ids)")
        self.lookups += 1
        blocks = ids // self.block_rows
        local = ids % self.block_rows
        unique, inverse, counts = np.unique(
            blocks, return_inverse=True, return_counts=True)
        if unique.shape[0] > self.capacity_blocks:
            # one batch's working set exceeds the whole cache: every
            # admit would evict a block THIS gather still needs.  Serve
            # the batch uncached (exact host gather — bitwise the same
            # rows), leave the resident set untouched, and account it:
            # a rising bypass counter says capacity_blocks is undersized
            # for the traffic, not that results degraded.
            self.bypasses += 1
            self.misses += int(ids.size)
            # int8 tables dequantize host-side from the SAME codes the
            # pools hold — one f32 cast + one f32 multiply, elementwise,
            # so bypassed batches are bitwise the cached batches
            return {
                name: jax.device_put(
                    table[ids].astype(np.float32)
                    * self._host_scales[name][ids][..., None]
                    if name in self._host_scales else table[ids])
                for name, table in self._host.items()}
        pinned = {int(b) for b in unique}
        slots = np.empty((unique.shape[0],), np.int32)
        for i, block in enumerate(unique):
            block = int(block)
            slot = self._slot_of.get(block)
            if slot is None:
                slot = self._admit(block, pinned)
                self.misses += int(counts[i])
            else:
                self._lru.move_to_end(block)
                self.hits += int(counts[i])
            slots[i] = slot
        slot_ids = slots[inverse].reshape(ids.shape)
        local = local.astype(np.int32)
        gather = _pool_gather()
        gather_deq = _pool_gather_deq() if self._scale_pools else None
        return {
            name: gather_deq(pool, self._scale_pools[name], slot_ids,
                             local)
            if name in self._scale_pools else gather(pool, slot_ids,
                                                     local)
            for name, pool in self._pools.items()}

    # -- observability -------------------------------------------------------
    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else float("nan")

    @property
    def resident_blocks(self) -> int:
        return len(self._lru)

    @property
    def pool_bytes(self) -> int:
        import itertools

        return sum(int(np.prod(p.shape)) * p.dtype.itemsize
                   for p in itertools.chain(self._pools.values(),
                                            self._scale_pools.values()))

    def reset_counters(self) -> None:
        """Zero the hit/miss ledger (bench legs separate warm-up from
        the measured window); the resident set is untouched."""
        self.hits = self.misses = 0
        self.block_faults = self.evictions = self.lookups = 0
        self.bypasses = 0
        self._fault_s = 0.0

    def snapshot(self) -> Dict[str, Any]:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hit_rate, 4)
            if self.hits + self.misses else None,
            "block_faults": self.block_faults,
            "evictions": self.evictions,
            "lookups": self.lookups,
            "bypasses": self.bypasses,
            "fault_ms": round(self._fault_s * 1e3, 3),
            "resident_blocks": self.resident_blocks,
            "capacity_blocks": self.capacity_blocks,
            "n_blocks": self.n_blocks,
            "block_rows": self.block_rows,
            "pool_bytes": self.pool_bytes,
            "precision": self.precision,
        }

    def publish(self, group) -> None:
        """Refresh gauges on ``group`` (the ``KernelStats.publish``
        idiom) — hit/miss/eviction visibility on the PR 13 metrics
        tree."""
        snap = self.snapshot()
        for name in ("hits", "misses", "block_faults", "evictions",
                     "lookups", "bypasses", "resident_blocks",
                     "capacity_blocks", "pool_bytes"):
            group.gauge(name).set(snap[name])
        group.gauge("hit_rate").set(
            snap["hit_rate"] if snap["hit_rate"] is not None
            else float("nan"))


# ---------------------------------------------------------------------------
# the WideDeep adopter
# ---------------------------------------------------------------------------

@jax.jit
def _cached_scores(rest, dense, wide_rows, emb_rows):
    """Expression-identical to the model's ``_jit_scores`` with the
    table gathers hoisted out: ``forward`` IS
    ``forward_from_rows(params, dense, wide_cat[ids], emb[ids])``, so
    feeding cache-gathered rows through the same function scores
    bit-exactly."""
    from ..models.recommendation.widedeep import forward_from_rows

    return jax.nn.sigmoid(forward_from_rows(rest, dense, wide_rows,
                                            emb_rows))


@jax.jit
def _cached_scores_int8(qrest, dense, wide_rows, emb_rows):
    """The int8 twin of ``_cached_scores``: the gathered rows arrive
    already dequantized (the cache pools' gather-then-dequantize), the
    dense-tower matrices dequantize here, and the expression after the
    rebuild is the SAME ``forward_from_rows`` — so a generation's
    scores are bit-stable call-to-call while tracking f32 within the
    parity matrix's accuracy envelope."""
    from ..kernels.quantize import dequantize_widedeep_rest
    from ..models.recommendation.widedeep import forward_from_rows

    return jax.nn.sigmoid(forward_from_rows(
        dequantize_widedeep_rest(qrest), dense, wide_rows, emb_rows))


class CachedWideDeepServable(ServableModel):
    """WideDeep serving through the embedding-row cache: only hot table
    blocks are device-resident; scores are bit-exact with
    ``model.transform`` (module doc).  ``rebind`` (delta publish) gets a
    FRESH cache over the new generation's tables — cached rows of the
    old generation must never serve the new one."""

    rebind_safe = True
    supported_precisions = ("f32", "int8")

    def __init__(self, model, example: Table, *,
                 cache_block_rows: int = 512,
                 cache_capacity_blocks: int = 64, **kwargs: Any):
        super().__init__(model, example, **kwargs)
        self._cache_block_rows = cache_block_rows
        self._cache_capacity_blocks = cache_capacity_blocks
        self._bind(model)

    def _bind(self, model) -> None:
        model._require_model()
        params = model._params
        self._vocab_sizes = model._vocab_sizes
        # int8 calibration capture point for the cached path: the cache
        # quantizes THIS generation's tables and the dense tower
        # quantizes here — rebind() re-binds the clone, so every delta
        # publish re-derives scales before the swap (stale scales never
        # serve)
        self.cache = EmbeddingRowCache(
            {"wide_cat": params["wide_cat"], "emb": params["emb"]},
            block_rows=self._cache_block_rows,
            capacity_blocks=self._cache_capacity_blocks,
            precision=self.precision)
        if self.precision == "int8":
            from ..kernels.quantize import quantize_widedeep_rest

            self._rest = jax.device_put(quantize_widedeep_rest(params))
            self._scores = _cached_scores_int8
        else:
            self._rest = jax.device_put({
                k: params[k] for k in ("wide_dense", "wide_b", "mlp")})
            self._scores = _cached_scores

    def rebind(self, model) -> "ServableModel":
        clone = super().rebind(model)
        clone._bind(model)
        return clone

    def _run(self, table: Table) -> Table:
        from ..models.recommendation.widedeep import _validate_cat_ids
        from ..utils.padding import pad_rows_to_bucket

        model = self.model
        dense = np.asarray(table[model.DENSE_FEATURES_COL], np.float32)
        cat = np.asarray(table[model.CAT_FEATURES_COL], np.int32)
        gids = _validate_cat_ids(cat, self._vocab_sizes)
        # pad ids are 0 = the first stacked slot, always a valid row
        # (the transform stance); pad rows slice away below
        (dense_p, gids_p), n = pad_rows_to_bucket(
            (dense, gids), min_bucket=self.min_bucket)
        rows = self.cache.lookup(gids_p)
        scores = np.asarray(
            self._scores(self._rest, dense_p, rows["wide_cat"],
                         rows["emb"]), np.float64)[:n]
        out = table.with_column(model.get_raw_prediction_col(), scores)
        return out.with_column(model.get_prediction_col(),
                               (scores > 0.5).astype(np.int64))
