"""Swing — item-item similarity from user-item-user graph structure.

Member of the Flink ML 2.x recommendation surface (``recommendation/
swing``; the reference snapshot ships no recommenders — SURVEY §2.8).
AlgoOperator: transform(user-item interaction table) -> one row per item
with its top-k similar items and scores:

    sim(i, j) = sum over unordered user pairs {u, v} in U_i ∩ U_j of
                w_u * w_v / (alpha2 + |I_u ∩ I_v|),
    w_u = (|I_u| + alpha1) ** -beta

TPU design: after host-side id indexing and behavior filtering, the
whole score tensor is device matmul work over the binary user-item
matrix B — the user-user co-count matrix ``B @ B.T`` builds the pair
kernel K once, and each item's row of similarities is
``colsum((B ⊙ b_i) ⊙ (K @ (B ⊙ b_i)))``, a ``lax.scan`` of MXU matmuls
rather than the reference family's per-pair hash-set intersections.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import AlgoOperator
from ...data.table import Table
from ...params.param import FloatParam, IntParam, ParamValidators
from .als import ALSModelParams

__all__ = ["Swing"]


class SwingParams(AlgoOperator):
    USER_COL = ALSModelParams.USER_COL
    ITEM_COL = ALSModelParams.ITEM_COL
    K = IntParam("k", "Max similar items per item.", default=100,
                 validator=ParamValidators.gt(0))
    MIN_USER_BEHAVIOR = IntParam(
        "minUserBehavior", "Drop users with fewer interactions.", default=10,
        validator=ParamValidators.gt(0))
    MAX_USER_BEHAVIOR = IntParam(
        "maxUserBehavior", "Drop users with more interactions.",
        default=1000, validator=ParamValidators.gt(0))
    MAX_USER_NUM_PER_ITEM = IntParam(
        "maxUserNumPerItem",
        "Random user subsample per item above this size.", default=1000,
        validator=ParamValidators.gt(0))
    ALPHA1 = IntParam("alpha1", "User-weight smoothing.", default=15,
                      validator=ParamValidators.gt_eq(0))
    ALPHA2 = IntParam("alpha2", "Pair-kernel smoothing.", default=0,
                      validator=ParamValidators.gt_eq(0))
    BETA = FloatParam("beta", "User-weight decay exponent.", default=0.3,
                      validator=ParamValidators.gt_eq(0.0))

    def get_user_col(self) -> str:
        return self.get(SwingParams.USER_COL)

    def set_user_col(self, value: str):
        return self.set(SwingParams.USER_COL, value)

    def get_item_col(self) -> str:
        return self.get(SwingParams.ITEM_COL)

    def set_item_col(self, value: str):
        return self.set(SwingParams.ITEM_COL, value)

    def get_k(self) -> int:
        return self.get(SwingParams.K)

    def set_k(self, value: int):
        return self.set(SwingParams.K, value)

    def get_min_user_behavior(self) -> int:
        return self.get(SwingParams.MIN_USER_BEHAVIOR)

    def set_min_user_behavior(self, value: int):
        return self.set(SwingParams.MIN_USER_BEHAVIOR, value)

    def get_max_user_behavior(self) -> int:
        return self.get(SwingParams.MAX_USER_BEHAVIOR)

    def set_max_user_behavior(self, value: int):
        return self.set(SwingParams.MAX_USER_BEHAVIOR, value)

    def get_max_user_num_per_item(self) -> int:
        return self.get(SwingParams.MAX_USER_NUM_PER_ITEM)

    def set_max_user_num_per_item(self, value: int):
        return self.set(SwingParams.MAX_USER_NUM_PER_ITEM, value)

    def get_alpha1(self) -> int:
        return self.get(SwingParams.ALPHA1)

    def set_alpha1(self, value: int):
        return self.set(SwingParams.ALPHA1, value)

    def get_alpha2(self) -> int:
        return self.get(SwingParams.ALPHA2)

    def set_alpha2(self, value: int):
        return self.set(SwingParams.ALPHA2, value)

    def get_beta(self) -> float:
        return self.get(SwingParams.BETA)

    def set_beta(self, value: float):
        return self.set(SwingParams.BETA, value)


@jax.jit
def _swing_scores(B, alpha1, alpha2, beta):
    """(n_users, n_items) binary matrix -> (n_items, n_items) Swing
    similarity.  Unordered user pairs: ordered-sum / 2 with a zeroed
    kernel diagonal."""
    counts = jnp.sum(B, axis=1)                         # |I_u|
    # zero-count users (filtered out) must carry zero weight — with
    # alpha1=0 their (0)**-beta would be inf and poison K via 0*inf=NaN
    w = jnp.where(counts > 0, (counts + alpha1) ** (-beta), 0.0)
    uu = B @ B.T                                        # |I_u ∩ I_v|
    # a user pair in U_i ∩ U_j always shares >= 2 items, so uu == 0 pairs
    # contribute nothing; zeroing them also guards alpha2=0 division
    K = jnp.where(uu > 0,
                  (w[:, None] * w[None, :]) / (alpha2 + uu), 0.0)
    K = K * (1.0 - jnp.eye(B.shape[0], dtype=B.dtype))  # exclude u == v

    def per_item(_, b_i):
        M = B * b_i[:, None]                            # users of item i
        sim_i = jnp.sum(M * (K @ M), axis=0)            # (n_items,)
        return None, sim_i

    _, S = jax.lax.scan(per_item, None, B.T)
    return S / 2.0


class Swing(SwingParams):
    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        users_raw = np.asarray(table[self.get_user_col()])
        items_raw = np.asarray(table[self.get_item_col()])
        user_vals, u_idx = np.unique(users_raw, return_inverse=True)
        item_vals, i_idx = np.unique(items_raw, return_inverse=True)
        n_users, n_items = len(user_vals), len(item_vals)

        B = np.zeros((n_users, n_items), np.float32)
        B[u_idx, i_idx] = 1.0

        # behavior filtering: users outside [min, max] interactions drop out
        per_user = B.sum(axis=1)
        keep = ((per_user >= self.get_min_user_behavior())
                & (per_user <= self.get_max_user_behavior()))
        B[~keep] = 0.0

        # per-item user-count cap: deterministic seeded subsample
        cap = self.get_max_user_num_per_item()
        rng = np.random.default_rng(0)
        for j in range(n_items):
            users_j = np.flatnonzero(B[:, j])
            if len(users_j) > cap:
                drop = rng.choice(users_j, len(users_j) - cap, replace=False)
                B[drop, j] = 0.0

        S = np.asarray(_swing_scores(
            jnp.asarray(B), jnp.float32(self.get_alpha1()),
            jnp.float32(self.get_alpha2()),
            jnp.float32(self.get_beta())), np.float64)
        np.fill_diagonal(S, 0.0)

        k = self.get_k()
        sim_items = np.empty((n_items,), object)
        sim_scores = np.empty((n_items,), object)
        for j in range(n_items):
            order = np.argsort(-S[j], kind="stable")
            order = order[S[j][order] > 0][:k]
            sim_items[j] = list(item_vals[order])
            sim_scores[j] = [float(s) for s in S[j][order]]

        return [Table({
            self.get_item_col(): item_vals,
            "similar_items": sim_items,
            "scores": sim_scores,
        })]
