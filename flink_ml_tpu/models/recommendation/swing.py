"""Swing — item-item similarity from user-item-user graph structure.

Member of the Flink ML 2.x recommendation surface (``recommendation/
swing``; the reference snapshot ships no recommenders — SURVEY §2.8).
AlgoOperator: transform(user-item interaction table) -> one row per item
with its top-k similar items and scores:

    sim(i, j) = sum over unordered user pairs {u, v} in U_i ∩ U_j of
                w_u * w_v / (alpha2 + |I_u ∩ I_v|),
    w_u = (|I_u| + alpha1) ** -beta

TPU design: after host-side id indexing and behavior filtering, the
whole score tensor is device matmul work over the binary user-item
matrix B.  The user-pair kernel ``K[u,v] = w_u w_v / (alpha2 +
|I_u ∩ I_v|)`` is accumulated in USER CHUNKS — each chunk builds only a
(chunk, n_users) co-count slice, so memory stays O(chunk * n_users)
instead of the full O(n_users^2) kernel — and each item's similarity
row is a ``lax.scan`` of MXU matmuls over the chunk, rather than the
reference family's per-pair hash-set intersections.
"""

from __future__ import annotations

from functools import partial
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import AlgoOperator
from ...data.table import Table
from ...params.param import FloatParam, IntParam, ParamValidators
from ...params.shared import HasSeed
from .als import ALSModelParams

__all__ = ["Swing"]


class SwingParams(AlgoOperator, HasSeed):
    USER_COL = ALSModelParams.USER_COL
    ITEM_COL = ALSModelParams.ITEM_COL
    K = IntParam("k", "Max similar items per item.", default=100,
                 validator=ParamValidators.gt(0))
    MIN_USER_BEHAVIOR = IntParam(
        "minUserBehavior", "Drop users with fewer interactions.", default=10,
        validator=ParamValidators.gt(0))
    MAX_USER_BEHAVIOR = IntParam(
        "maxUserBehavior", "Drop users with more interactions.",
        default=1000, validator=ParamValidators.gt(0))
    MAX_USER_NUM_PER_ITEM = IntParam(
        "maxUserNumPerItem",
        "Random user subsample per item above this size.", default=1000,
        validator=ParamValidators.gt(0))
    ALPHA1 = IntParam("alpha1", "User-weight smoothing.", default=15,
                      validator=ParamValidators.gt_eq(0))
    ALPHA2 = IntParam("alpha2", "Pair-kernel smoothing.", default=0,
                      validator=ParamValidators.gt_eq(0))
    BETA = FloatParam("beta", "User-weight decay exponent.", default=0.3,
                      validator=ParamValidators.gt_eq(0.0))

    def get_user_col(self) -> str:
        return self.get(SwingParams.USER_COL)

    def set_user_col(self, value: str):
        return self.set(SwingParams.USER_COL, value)

    def get_item_col(self) -> str:
        return self.get(SwingParams.ITEM_COL)

    def set_item_col(self, value: str):
        return self.set(SwingParams.ITEM_COL, value)

    def get_k(self) -> int:
        return self.get(SwingParams.K)

    def set_k(self, value: int):
        return self.set(SwingParams.K, value)

    def get_min_user_behavior(self) -> int:
        return self.get(SwingParams.MIN_USER_BEHAVIOR)

    def set_min_user_behavior(self, value: int):
        return self.set(SwingParams.MIN_USER_BEHAVIOR, value)

    def get_max_user_behavior(self) -> int:
        return self.get(SwingParams.MAX_USER_BEHAVIOR)

    def set_max_user_behavior(self, value: int):
        return self.set(SwingParams.MAX_USER_BEHAVIOR, value)

    def get_max_user_num_per_item(self) -> int:
        return self.get(SwingParams.MAX_USER_NUM_PER_ITEM)

    def set_max_user_num_per_item(self, value: int):
        return self.set(SwingParams.MAX_USER_NUM_PER_ITEM, value)

    def get_alpha1(self) -> int:
        return self.get(SwingParams.ALPHA1)

    def set_alpha1(self, value: int):
        return self.set(SwingParams.ALPHA1, value)

    def get_alpha2(self) -> int:
        return self.get(SwingParams.ALPHA2)

    def set_alpha2(self, value: int):
        return self.set(SwingParams.ALPHA2, value)

    def get_beta(self) -> float:
        return self.get(SwingParams.BETA)

    def set_beta(self, value: float):
        return self.set(SwingParams.BETA, value)


# user-chunk size for the pair kernel: memory is O(chunk * n_users)
# instead of the full O(n_users^2) K matrix, so user counts in the 10^5+
# range stay well under HBM while each chunk is still MXU-sized work
_USER_CHUNK = 2048


@partial(jax.jit, static_argnums=(4,))
def _swing_scores(B, alpha1, alpha2, beta, user_chunk=_USER_CHUNK):
    """(n_users, n_items) binary matrix -> (n_items, n_items) Swing
    similarity.  Unordered user pairs: ordered-sum / 2 with a zeroed
    kernel diagonal.

    The user-pair kernel ``K[u, v] = w_u w_v / (alpha2 + |I_u ∩ I_v|)``
    is never materialised whole: ``S = Σ_chunks Mᶜᵀ (Kᶜ M)`` accumulates
    over user chunks, where ``M[u, i] = B[u, i]`` masked per item — each
    chunk needs only a (chunk, n_users) slice of co-counts.

    Compute scaling: the per-item ``K @ Mv`` inside the chunk scan makes
    the total FLOPs ``O(n_users^2 * n_items^2)`` — the chunking bounds
    MEMORY, not compute.  Practical reach on one v5e chip is therefore
    ~10^4 users x ~10^3 items (minutes); the documented 10^5-user range
    needs ``maxUserNumPerItem`` to thin B first (which is exactly its
    purpose).  A compute-bounded reformulation (accumulating via masked
    three-way products per item-pair block) is future work."""
    n_users, n_items = B.shape
    # small inputs take one right-sized chunk instead of padding to the
    # full default (B.shape is static at trace time)
    user_chunk = min(user_chunk, n_users)
    counts = jnp.sum(B, axis=1)                         # |I_u|
    # zero-count users (filtered out) must carry zero weight — with
    # alpha1=0 their (0)**-beta would be inf and poison K via 0*inf=NaN
    w = jnp.where(counts > 0, (counts + alpha1) ** (-beta), 0.0)

    pad = (-n_users) % user_chunk
    Bp = jnp.pad(B, ((0, pad), (0, 0)))
    wp = jnp.pad(w, (0, pad))
    n_chunks = Bp.shape[0] // user_chunk
    Bc = Bp.reshape(n_chunks, user_chunk, n_items)
    wc = wp.reshape(n_chunks, user_chunk)
    offs = jnp.arange(n_chunks) * user_chunk

    def per_chunk(acc, chunk):
        Bi, wi, off = chunk                              # (c, n_items), (c,)
        uu = Bi @ B.T                                    # (c, n_users)
        # a user pair in U_i ∩ U_j always shares >= 2 items, so uu == 0
        # pairs contribute nothing; zeroing also guards alpha2=0 division
        K = jnp.where(uu > 0, (wi[:, None] * w[None, :]) / (alpha2 + uu),
                      0.0)
        # exclude u == v (the diagonal lives where global index matches)
        cols = jnp.arange(n_users)[None, :]
        rows = off + jnp.arange(user_chunk)[:, None]
        K = jnp.where(rows == cols, 0.0, K)

        def per_item(_, b_i_padded):
            # b_i over padded users: static head = all users, dynamic
            # window = this chunk's users of item i
            b_i = b_i_padded[:n_users]
            Mv = B * b_i[:, None]                        # (n_users, items)
            KM = K @ Mv                                  # (c, items)
            chunk_b = jax.lax.dynamic_slice_in_dim(b_i_padded, off,
                                                   user_chunk)
            return None, jnp.sum(chunk_b[:, None] * Bi * KM, axis=0)

        _, Sc = jax.lax.scan(per_item, None, Bp.T)       # (items, items)
        return acc + Sc, None

    S0 = jnp.zeros((n_items, n_items), B.dtype)
    S, _ = jax.lax.scan(per_chunk, S0, (Bc, wc, offs))
    return S / 2.0


class Swing(SwingParams):
    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        users_raw = np.asarray(table[self.get_user_col()])
        items_raw = np.asarray(table[self.get_item_col()])
        user_vals, u_idx = np.unique(users_raw, return_inverse=True)
        item_vals, i_idx = np.unique(items_raw, return_inverse=True)
        n_users, n_items = len(user_vals), len(item_vals)

        B = np.zeros((n_users, n_items), np.float32)
        B[u_idx, i_idx] = 1.0

        # behavior filtering: users outside [min, max] interactions drop out
        per_user = B.sum(axis=1)
        keep = ((per_user >= self.get_min_user_behavior())
                & (per_user <= self.get_max_user_behavior()))
        B[~keep] = 0.0

        # per-item user-count cap: deterministic seeded subsample
        cap = self.get_max_user_num_per_item()
        rng = np.random.default_rng(self.get_seed())
        for j in range(n_items):
            users_j = np.flatnonzero(B[:, j])
            if len(users_j) > cap:
                drop = rng.choice(users_j, len(users_j) - cap, replace=False)
                B[drop, j] = 0.0

        S = np.asarray(_swing_scores(
            jnp.asarray(B), jnp.float32(self.get_alpha1()),
            jnp.float32(self.get_alpha2()),
            jnp.float32(self.get_beta())), np.float64)
        np.fill_diagonal(S, 0.0)

        k = self.get_k()
        sim_items = np.empty((n_items,), object)
        sim_scores = np.empty((n_items,), object)
        for j in range(n_items):
            order = np.argsort(-S[j], kind="stable")
            order = order[S[j][order] > 0][:k]
            sim_items[j] = list(item_vals[order])
            sim_scores[j] = [float(s) for s in S[j][order]]

        return [Table({
            self.get_item_col(): item_vals,
            "similar_items": sim_items,
            "scores": sim_scores,
        })]
