"""ALS — alternating least squares matrix factorization, TPU-native.

Part of the Flink ML 2.x library surface (the reference snapshot ships only
KMeans — SURVEY §2.8 — but the lib module is "the algorithm library"; ALS is
the canonical recommendation member of that line).  Supports explicit
feedback (ALS-WR: per-row regularization scaled by the row's rating count)
and implicit feedback (Hu/Koren confidence weighting,
``c = 1 + alpha * |r|``).

TPU-native shape of one half-epoch (solve all users against fixed item
factors):

- gather   — ``y = V[item_idx]`` for every rating, chunked by ``lax.scan``
             so the (chunk, rank, rank) outer products stay bounded in HBM
             regardless of nnz
- reduce   — normal equations accumulated with ``.at[].add`` scatter-adds
             into dense ``(n_users, rank, rank)`` / ``(n_users, rank)``
             operands (the reference's analog would be a keyed shuffle +
             per-key reduce)
- solve    — ONE batched Cholesky solve over all users at once
             (``jax.scipy.linalg.cho_solve``) — a big batched MXU op instead
             of the per-user host loops of CPU implementations

Both half-epochs make one epoch, driven by the ``iterate`` runtime in fused
mode: the whole ``max_iter`` loop compiles to a single XLA program, factors
never leave HBM between epochs.

Ratings with weight 0 are padding and contribute nothing (all their
normal-equation contributions are multiplied by the weight).  Users/items
with no observed ratings keep their previous factors (their normal equations
would be singular).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator, Model
from ...data.table import Table
from ...iteration import (
    IterationBodyResult,
    IterationConfig,
    Workset,
    iterate,
)
from ...params.param import (
    BoolParam,
    FloatParam,
    IntParam,
    ParamValidators,
    StringParam,
)
from ...params.shared import HasMaxIter, HasPredictionCol, HasSeed
from ...utils import persist

__all__ = ["ALS", "ALSModel", "ALSParams", "ALSModelParams"]

_CHUNK = 65536  # ratings per scan step: (chunk, rank^2) is the HBM high-water

#: sorted-path chunk: (chunk, rank^2) outer-product transient per scan
#: step (134 MB at rank 64) — smaller than _CHUNK because the sorted
#: path materializes the outers for its MXU contraction
_SORTED_CHUNK = 8192

#: 'auto' picks the sorted path only while every chunk's group band
#: stays this narrow: per-chunk MXU work scales with span, so long-tail
#: data (most groups with 1-2 ratings — the common recommendation
#: shape) can drive span toward the chunk size and make the one-hot
#: contraction orders of magnitude more work than the scatter it
#: replaces.  Span is known at host plan-build time, so the fallback is
#: free to decide.
_NEQ_AUTO_SPAN_CAP = 256


def _neq_plan_span(group_idx: np.ndarray, chunk: int = _SORTED_CHUNK) -> int:
    """The chunk-band span :class:`NeqPlan` would compute for
    ``group_idx``, WITHOUT the plan's O(nnz log nnz) argsort or its
    O(nnz) local-rank arrays: within a sorted chunk the band maximum
    sits at the chunk's last slot, so span needs only the sorted group
    value at each chunk boundary — and the sorted sequence is fully
    determined by ``np.bincount`` (each group id repeated by its
    count).  O(nnz + n_groups) time, O(n_groups) memory.  'auto' mode
    consults this BEFORE building a plan, so long-tail datasets — the
    common recommendation shape, which falls back to scatter — skip
    both argsorts entirely."""
    group_idx = np.asarray(group_idx)
    nnz = group_idx.shape[0]
    if nnz == 0:
        return 1
    chunk = int(min(chunk, nnz))
    cum = np.cumsum(np.bincount(group_idx))
    n_chunks = -(-nnz // chunk)
    starts = np.arange(n_chunks) * chunk
    # the plan pads the tail chunk by repeating the last sorted group,
    # so its band ends at sorted position nnz - 1
    ends = np.minimum(starts + chunk - 1, nnz - 1)
    lo = np.searchsorted(cum, starts, side="right")
    hi = np.searchsorted(cum, ends, side="right")
    return int((hi - lo).max()) + 1


class NeqPlan:
    """Static routing for :func:`_normal_equations_sorted` — one host
    sort per fit side (the ratings are fixed for the whole fit, the
    same replay insight as the LR/WDL static routes).

    Sorting by group makes each scan chunk's groups a NARROW CONTIGUOUS
    band ``[g_lo, g_lo + span)`` (``span`` = static max band over
    chunks), so the normal-equation accumulation becomes one small MXU
    contraction + one dynamic-slice add per chunk instead of per-rating
    scatter-adds.  A group whose run crosses a chunk boundary simply
    keeps accumulating into the same rows from the next chunk — heavy
    groups need no special path.
    """

    def __init__(self, group_idx: np.ndarray, chunk: int = _SORTED_CHUNK):
        group_idx = np.asarray(group_idx)
        nnz = group_idx.shape[0]
        self.chunk = int(min(chunk, max(nnz, 1)))
        self.order = np.argsort(group_idx, kind="stable").astype(np.int64)
        sg = group_idx[self.order].astype(np.int32)
        pad = (-nnz) % self.chunk
        if pad:
            sg = np.concatenate([sg, np.full(pad, sg[-1] if nnz else 0,
                                             np.int32)])
        self.nnz, self.pad = nnz, pad
        n_chunks = sg.shape[0] // self.chunk
        self.g_lo = sg[np.arange(n_chunks) * self.chunk].astype(np.int32)
        local = sg - np.repeat(self.g_lo, self.chunk)
        self.span = int(local.max(initial=0)) + 1
        self.local_rank = local.astype(np.int32)

    def sort_pad(self, a: np.ndarray, fill=0) -> np.ndarray:
        """``a`` reordered by the plan's sort, padded to the chunk
        multiple with ``fill`` (pad weights MUST be 0 — every
        accumulator term is weight-scaled, which is what makes the pad
        slots inert)."""
        out = np.asarray(a)[self.order]
        if self.pad:
            out = np.concatenate(
                [out, np.full((self.pad,) + out.shape[1:], fill,
                              out.dtype)])
        return out


def _normal_equations_sorted(factors, other_idx, ratings, weights,
                             local_rank, g_lo, n_groups: int, span: int,
                             chunk: int, implicit: bool, alpha: float):
    """Sorted-path normal equations: inputs are PRE-SORTED by group and
    padded (see :class:`NeqPlan`).  Equals :func:`_normal_equations` up
    to f32 summation order, with zero scatters."""
    rank = factors.shape[1]
    n_chunks = other_idx.shape[0] // chunk
    span_iota = jnp.arange(span, dtype=jnp.int32)

    def scan_step(carry, xs):
        A, b, cnt = carry
        o, r, w, lr_, glo = xs
        y = factors[o]                                   # (chunk, rank)
        oh = lr_[:, None] == span_iota[None, :]          # (chunk, span)
        if implicit:
            conf_m1 = alpha * jnp.abs(r) * w             # c - 1, weighted
            aw, bw = conf_m1, w + conf_m1
        else:
            aw, bw = w, w * r
        outer = (y[:, :, None] * y[:, None, :]).reshape(-1, rank * rank)
        A_part = jax.lax.dot_general(
            jnp.where(oh, aw[:, None], 0.0), outer,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).reshape(span, rank, rank)
        b_part = jax.lax.dot_general(
            jnp.where(oh, bw[:, None], 0.0), y,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (span, rank)
        cnt_part = jnp.sum(jnp.where(oh, w[:, None], 0.0), axis=0)
        A = jax.lax.dynamic_update_slice(
            A, jax.lax.dynamic_slice(
                A, (glo, 0, 0), (span, rank, rank)) + A_part, (glo, 0, 0))
        b = jax.lax.dynamic_update_slice(
            b, jax.lax.dynamic_slice(b, (glo, 0), (span, rank)) + b_part,
            (glo, 0))
        cnt = jax.lax.dynamic_update_slice(
            cnt, jax.lax.dynamic_slice(cnt, (glo,), (span,)) + cnt_part,
            (glo,))
        return (A, b, cnt), None

    # `span` rows of slack so the last band's slice stays in bounds
    init = (jnp.zeros((n_groups + span, rank, rank), factors.dtype),
            jnp.zeros((n_groups + span, rank), factors.dtype),
            jnp.zeros((n_groups + span,), factors.dtype))
    xs = tuple(x.reshape(n_chunks, chunk, *x.shape[1:])
               for x in (other_idx, ratings, weights, local_rank))
    (A, b, cnt), _ = jax.lax.scan(scan_step, init, xs + (g_lo,))
    return A[:n_groups], b[:n_groups], cnt[:n_groups]


class ALSModelParams(HasPredictionCol):
    USER_COL = StringParam("userCol", "User id column.", default="user")
    ITEM_COL = StringParam("itemCol", "Item id column.", default="item")

    def get_user_col(self) -> str:
        return self.get(ALSModelParams.USER_COL)

    def set_user_col(self, value: str):
        return self.set(ALSModelParams.USER_COL, value)

    def get_item_col(self) -> str:
        return self.get(ALSModelParams.ITEM_COL)

    def set_item_col(self, value: str):
        return self.set(ALSModelParams.ITEM_COL, value)


class ALSParams(ALSModelParams, HasMaxIter, HasSeed):
    RATING_COL = StringParam("ratingCol", "Rating column.", default="rating")
    RANK = IntParam("rank", "Factor dimension.", default=10,
                    validator=ParamValidators.gt_eq(1))
    REG_PARAM = FloatParam("regParam", "L2 regularization.", default=0.1,
                           validator=ParamValidators.gt_eq(0))
    IMPLICIT_PREFS = BoolParam(
        "implicitPrefs", "Implicit-feedback (confidence-weighted) mode.",
        default=False)
    ALPHA = FloatParam("alpha", "Implicit-feedback confidence scale.",
                       default=1.0, validator=ParamValidators.gt_eq(0))
    NEQ_IMPL = StringParam(
        "normalEquationsImpl",
        "Normal-equation accumulation: 'sorted' (default via 'auto') — "
        "one static host sort per fit turns the per-rating scatter-adds "
        "into chunked MXU contractions over narrow contiguous group "
        "bands (the LR/WDL static-routing insight applied to ALS); "
        "'scatter' keeps the jnp .at[].add form.  Both are exact up to "
        "f32 summation order.",
        default="auto",
        validator=ParamValidators.in_array(("auto", "sorted", "scatter")))

    def get_rating_col(self) -> str:
        return self.get(ALSParams.RATING_COL)

    def set_rating_col(self, value: str):
        return self.set(ALSParams.RATING_COL, value)

    def get_rank(self) -> int:
        return self.get(ALSParams.RANK)

    def set_rank(self, value: int):
        return self.set(ALSParams.RANK, value)

    def get_reg_param(self) -> float:
        return self.get(ALSParams.REG_PARAM)

    def set_reg_param(self, value: float):
        return self.set(ALSParams.REG_PARAM, value)

    def get_implicit_prefs(self) -> bool:
        return self.get(ALSParams.IMPLICIT_PREFS)

    def set_implicit_prefs(self, value: bool):
        return self.set(ALSParams.IMPLICIT_PREFS, value)

    def get_alpha(self) -> float:
        return self.get(ALSParams.ALPHA)

    def set_alpha(self, value: float):
        return self.set(ALSParams.ALPHA, value)

    WORKSET_TOL = FloatParam(
        "worksetTol",
        "Delta/workset iteration threshold (0 disables): a user/item "
        "whose neighborhood factors all moved less than this (L2 row "
        "movement) last round keeps its previous factors — its solve "
        "result is masked out (the fused program still evaluates the "
        "dense normal equations; the wall-clock win today is that the "
        "while_loop exits as soon as every movement settles below the "
        "threshold, instead of always running maxIter epochs).  "
        "Approximate by construction (masked updates would have moved "
        "< tol); the fit records a per-round report in "
        "estimator.last_workset_report.",
        default=0.0, validator=ParamValidators.gt_eq(0))

    def get_workset_tol(self) -> float:
        return self.get(ALSParams.WORKSET_TOL)

    def set_workset_tol(self, value: float):
        return self.set(ALSParams.WORKSET_TOL, value)


def _normal_equations(factors, group_idx, other_idx, ratings, weights,
                      n_groups: int, implicit: bool, alpha: float):
    """Accumulate per-group A (n_groups, r, r), b (n_groups, r) and observed
    counts, scanning the ratings in fixed-size chunks."""
    rank = factors.shape[1]
    nnz = group_idx.shape[0]
    chunk = min(_CHUNK, nnz)
    n_chunks = -(-nnz // chunk)
    pad = n_chunks * chunk - nnz
    if pad:
        group_idx = jnp.concatenate([group_idx, jnp.zeros(pad, group_idx.dtype)])
        other_idx = jnp.concatenate([other_idx, jnp.zeros(pad, other_idx.dtype)])
        ratings = jnp.concatenate([ratings, jnp.zeros(pad, ratings.dtype)])
        weights = jnp.concatenate([weights, jnp.zeros(pad, weights.dtype)])

    def scan_step(carry, xs):
        A, b, cnt = carry
        g, o, r, w = xs
        y = factors[o]                                    # (chunk, rank)
        if implicit:
            # Hu/Koren: A += (c-1) y y^T per observed pair, b += c p y with
            # p = 1; the shared Y^T Y term is added by the caller.
            conf_m1 = alpha * jnp.abs(r) * w              # c - 1, weighted
            A = A.at[g].add(conf_m1[:, None, None]
                            * y[:, :, None] * y[:, None, :])
            # weighted Hu/Koren b-term: w * (1 + alpha|r|) * y = (w + conf_m1)
            # * y — NOT (1 + conf_m1) * w, which would square fractional
            # weights relative to the A term above.
            b = b.at[g].add((w + conf_m1)[:, None] * y)
        else:
            A = A.at[g].add(w[:, None, None] * y[:, :, None] * y[:, None, :])
            b = b.at[g].add((w * r)[:, None] * y)
        cnt = cnt.at[g].add(w)
        return (A, b, cnt), None

    init = (jnp.zeros((n_groups, rank, rank), factors.dtype),
            jnp.zeros((n_groups, rank), factors.dtype),
            jnp.zeros((n_groups,), factors.dtype))
    xs = tuple(x.reshape(n_chunks, chunk, *x.shape[1:])
               for x in (group_idx, other_idx, ratings, weights))
    (A, b, cnt), _ = jax.lax.scan(scan_step, init, xs)
    return A, b, cnt


def _solve_from_neq(prev, factors, A, b, cnt, reg: float, implicit: bool):
    """The solve tail shared by both normal-equation forms: regularize,
    batched Cholesky, keep previous factors for unobserved/singular
    groups."""
    rank = factors.shape[1]
    eye = jnp.eye(rank, dtype=factors.dtype)
    if implicit:
        gram = factors.T @ factors                         # shared Y^T Y
        A = A + gram[None, :, :] + reg * eye[None, :, :]
    else:
        # ALS-WR: per-row lambda scaled by the row's rating count.
        A = A + (reg * jnp.maximum(cnt, 1.0))[:, None, None] * eye[None, :, :]
    chol = jax.scipy.linalg.cho_factor(A)
    solved = jax.scipy.linalg.cho_solve(chol, b[..., None])[..., 0]
    # A singular system (regParam=0 + fewer ratings than rank) factors to
    # NaN; keep the previous factors rather than letting NaN spread through
    # the next half-epoch's gathers.
    ok = ((cnt > 0)[:, None]
          & jnp.all(jnp.isfinite(solved), axis=1, keepdims=True))
    return jnp.where(ok, solved, prev)


def _solve_side(prev, factors, group_idx, other_idx, ratings, weights,
                n_groups: int, reg: float, implicit: bool, alpha: float):
    """One half-epoch: re-solve ``prev``-side factors against fixed
    ``factors``.  Groups with zero observed weight keep their previous
    factors."""
    A, b, cnt = _normal_equations(factors, group_idx, other_idx, ratings,
                                  weights, n_groups, implicit, alpha)
    return _solve_from_neq(prev, factors, A, b, cnt, reg, implicit)


def _solve_side_sorted(prev, factors, plan: "NeqPlan", other_idx, ratings,
                       weights, local_rank, g_lo, n_groups: int,
                       reg: float, implicit: bool, alpha: float):
    """Sorted-path half-epoch (arrays pre-sorted by this side's group)."""
    A, b, cnt = _normal_equations_sorted(
        factors, other_idx, ratings, weights, local_rank, g_lo,
        n_groups, plan.span, plan.chunk, implicit, alpha)
    return _solve_from_neq(prev, factors, A, b, cnt, reg, implicit)


def als_epoch_step(n_users: int, n_items: int, reg: float, implicit: bool,
                   alpha: float, plans=None):
    """One ALS epoch (users then items) as an ``iterate`` body.

    ``plans=(plan_u, plan_v)`` (:class:`NeqPlan`) switches to the
    sorted normal equations — the data tuple is then the pre-sorted
    per-side arrays (see :meth:`ALS.fit`) instead of the raw
    ``(u_idx, i_idx, r, w)``."""

    def body(state, epoch, data):
        U, V = state
        # TPU f32 matmuls default to bf16 inputs; the normal equations and
        # triangular solves need true f32 or convergence stalls well short
        # of the CPU result (rank is tiny, so "highest" costs nothing).
        with jax.default_matmul_precision("highest"):
            if plans is None:
                u_idx, i_idx, r, w = data
                U = _solve_side(U, V, u_idx, i_idx, r, w, n_users, reg,
                                implicit, alpha)
                V = _solve_side(V, U, i_idx, u_idx, r, w, n_items, reg,
                                implicit, alpha)
            else:
                plan_u, plan_v = plans
                (ou, ru, wu, lru, glu,
                 ov, rv, wv, lrv, glv) = data
                U = _solve_side_sorted(U, V, plan_u, ou, ru, wu, lru, glu,
                                       n_users, reg, implicit, alpha)
                V = _solve_side_sorted(V, U, plan_v, ov, rv, wv, lrv, glv,
                                       n_items, reg, implicit, alpha)
        return IterationBodyResult(feedback=(U, V))

    return body


def als_workset_epoch_step(n_users: int, n_items: int, reg: float,
                           implicit: bool, alpha: float, tol: float):
    """One workset ALS epoch: the delta-iteration port of
    :func:`als_epoch_step`.

    The workset masks the two factor sides independently
    (``mask={"users": (n_users,), "items": (n_items,)}``): a group stays
    active only while something in its NEIGHBORHOOD still moves — user
    ``u`` re-solves while any item it rated moved ≥ ``tol`` (L2 row
    movement) last round, and symmetrically for items.  A masked group
    keeps its previous factors; since its normal equations are built from
    neighbor rows that all moved < ``tol``, the discarded update would
    have been sub-threshold too — that is the approximation accepted in
    exchange for settling.  Fixed shapes mean the dense solve is still
    evaluated each round (what a compacting backend would skip); the
    wall-clock saving today is the exit: when every movement settles
    below ``tol`` both masks drain and the driver's active-fraction
    criterion ends the fused while_loop strictly before ``maxIter``.

    Uses the raw-index (scatter) data tuple — the movement aggregation
    needs the per-rating (user, item) ids that the sorted NeqPlan layout
    deliberately discards."""

    def body(state, ws, epoch, data):
        U, V = state
        u_idx, i_idx, r, w = data
        m_u, m_i = ws.mask["users"], ws.mask["items"]
        # same precision pin as the BSP body (als_epoch_step)
        with jax.default_matmul_precision("highest"):
            U_solved = _solve_side(U, V, u_idx, i_idx, r, w, n_users, reg,
                                   implicit, alpha)
            U_new = jnp.where(m_u[:, None] > 0, U_solved, U)
            V_solved = _solve_side(V, U_new, i_idx, u_idx, r, w, n_items,
                                   reg, implicit, alpha)
            V_new = jnp.where(m_i[:, None] > 0, V_solved, V)
        du = jnp.sqrt(jnp.sum(jnp.square(U_new - U), axis=1))  # (n_users,)
        dv = jnp.sqrt(jnp.sum(jnp.square(V_new - V), axis=1))  # (n_items,)
        # neighborhood max-movement via scatter-max over the ratings
        moved_u = jnp.zeros((n_users,), du.dtype).at[u_idx].max(dv[i_idx])
        moved_i = jnp.zeros((n_items,), dv.dtype).at[i_idx].max(du[u_idx])
        new_ws = Workset({"users": (moved_u >= tol).astype(jnp.float32),
                          "items": (moved_i >= tol).astype(jnp.float32)})
        return IterationBodyResult(feedback=((U_new, V_new), new_ws))

    return body


@jax.jit
def _predict_pairs(U, V, u_idx, i_idx, known):
    preds = jnp.sum(U[u_idx] * V[i_idx], axis=1)
    return jnp.where(known, preds, jnp.nan)


class ALSModel(ALSModelParams, Model):
    """Prediction: ``U[u] . V[i]`` per (user, item) row; ids unseen at fit
    time predict NaN (the "cold start = nan" convention)."""

    def __init__(self):
        super().__init__()
        self._user_ids: Optional[np.ndarray] = None
        self._item_ids: Optional[np.ndarray] = None
        self._user_factors: Optional[np.ndarray] = None
        self._item_factors: Optional[np.ndarray] = None

    def set_model_data(self, *inputs) -> "ALSModel":
        (t,) = inputs
        self._user_ids = np.asarray(t["userIds"][0])
        self._item_ids = np.asarray(t["itemIds"][0])
        self._user_factors = np.asarray(t["userFactors"][0], np.float32)
        self._item_factors = np.asarray(t["itemFactors"][0], np.float32)
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"userIds": self._user_ids[None],
                       "itemIds": self._item_ids[None],
                       "userFactors": self._user_factors[None],
                       "itemFactors": self._item_factors[None]})]

    def _require_model(self) -> None:
        if self._user_factors is None:
            raise RuntimeError("ALSModel has no model data; call "
                               "set_model_data() or fit an ALS first")

    def _lookup(self, values, ids):
        """Map raw ids to dense indices; (indices, known_mask)."""
        idx = np.searchsorted(ids, values)
        idx = np.clip(idx, 0, len(ids) - 1)
        known = ids[idx] == values
        return idx.astype(np.int32), known

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        users = np.asarray(table[self.get_user_col()])
        items = np.asarray(table[self.get_item_col()])
        u_idx, u_known = self._lookup(users, self._user_ids)
        i_idx, i_known = self._lookup(items, self._item_ids)
        preds = np.asarray(_predict_pairs(
            jnp.asarray(self._user_factors), jnp.asarray(self._item_factors),
            jnp.asarray(u_idx), jnp.asarray(i_idx),
            jnp.asarray(u_known & i_known)))
        return [table.with_column(self.get_prediction_col(),
                                  preds.astype(np.float64))]

    def recommend_for_users(self, users, k: int,
                            exclude: Optional[Table] = None) -> Table:
        """Top-k items per user: ONE ``U_sel @ V.T`` MXU matmul scores
        everything, then a host ``argpartition`` (O(items), not a full
        sort) ranks the k winners — the producer shape
        ``RankingEvaluator`` consumes (each output cell is that user's
        ranked item-id list).

        ``exclude`` optionally REMOVES already-seen (user, item) pairs
        (the usual train-interaction filter) given as a Table carrying
        this model's user/item columns; a user with fewer than k
        non-excluded items gets a shorter list.  Unknown user ids
        raise."""
        self._require_model()
        if k <= 0:
            raise ValueError(f"k must be positive, got {k}")
        k = min(k, len(self._item_ids))
        users = np.asarray(users)
        u_idx, known = self._lookup(users, self._user_ids)
        if not known.all():
            raise ValueError(
                f"unknown user id {users[~known][0]!r}; recommendations "
                "need users seen at fit time")

        # np.array (copy): the device result is read-only and the exclude
        # mask writes -inf in place
        scores = np.array(
            jnp.asarray(self._user_factors)[jnp.asarray(u_idx)]
            @ jnp.asarray(self._item_factors).T)
        if exclude is not None:
            eu_idx, eu_known = self._lookup(
                np.asarray(exclude[self.get_user_col()]), self._user_ids)
            ei_idx, ei_known = self._lookup(
                np.asarray(exclude[self.get_item_col()]), self._item_ids)
            valid = eu_known & ei_known
            eu, ei = eu_idx[valid], ei_idx[valid]
            # vectorized (pair -> request rows) expansion: request rows
            # sorted by user, each exclude pair covers its searchsorted
            # range (the ragged-range trick — no per-pair Python loop)
            order = np.argsort(u_idx, kind="stable")
            su = u_idx[order]
            left = np.searchsorted(su, eu, side="left")
            right = np.searchsorted(su, eu, side="right")
            counts = right - left
            total = int(counts.sum())
            if total:
                starts = np.repeat(left, counts)
                offsets = np.arange(total) - np.repeat(
                    np.cumsum(counts) - counts, counts)
                rows = order[starts + offsets]
                scores[rows, np.repeat(ei, counts)] = -np.inf

        part = np.argpartition(-scores, kth=k - 1, axis=1)[:, :k]
        part_scores = np.take_along_axis(scores, part, axis=1)
        rank = np.argsort(-part_scores, axis=1, kind="stable")
        top = np.take_along_axis(part, rank, axis=1)
        top_scores = np.take_along_axis(part_scores, rank, axis=1)

        recs = np.empty(len(users), object)
        rec_scores = np.empty(len(users), object)
        for r in range(len(users)):
            keep = ~np.isneginf(top_scores[r])   # drop excluded items
            recs[r] = list(self._item_ids[top[r][keep]])
            rec_scores[r] = [float(s) for s in top_scores[r][keep]]
        return Table({self.get_user_col(): users,
                      "recommendations": recs, "scores": rec_scores})

    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {
            "userIds": self._user_ids, "itemIds": self._item_ids,
            "userFactors": self._user_factors,
            "itemFactors": self._item_factors})

    @classmethod
    def load(cls, path: str) -> "ALSModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._user_ids = data["userIds"]
        model._item_ids = data["itemIds"]
        model._user_factors = data["userFactors"].astype(np.float32)
        model._item_factors = data["itemFactors"].astype(np.float32)
        return model


class ALS(ALSParams, Estimator[ALSModel]):
    def fit(self, *inputs) -> ALSModel:
        (table,) = inputs
        # report describes THIS fit only — a reused estimator must not
        # serve a stale report from an earlier workset fit
        self.last_workset_report = None
        users = np.asarray(table[self.get_user_col()])
        items = np.asarray(table[self.get_item_col()])
        ratings = np.asarray(table[self.get_rating_col()], np.float32)
        if len(ratings) == 0:
            raise ValueError("ALS.fit requires at least one rating")
        if self.get_implicit_prefs() and np.any(ratings < 0):
            raise ValueError("implicitPrefs expects non-negative ratings "
                             "(interaction strengths)")

        user_ids, u_idx = np.unique(users, return_inverse=True)
        item_ids, i_idx = np.unique(items, return_inverse=True)
        rank = self.get_rank()
        rng = np.random.default_rng(self.get_seed())
        scale = 1.0 / np.sqrt(rank)
        U0 = (rng.normal(size=(len(user_ids), rank)) * scale).astype(
            np.float32)
        V0 = (rng.normal(size=(len(item_ids), rank)) * scale).astype(
            np.float32)

        weights = np.ones(len(ratings), np.float32)
        ws_tol = self.get_workset_tol()
        if ws_tol > 0:
            return self._fit_workset(user_ids, item_ids, u_idx, i_idx,
                                     ratings, weights, U0, V0, ws_tol)
        neq_mode = self.get(ALSParams.NEQ_IMPL)
        plans = None
        if neq_mode in ("auto", "sorted"):
            # 'auto' bounds the span from a cheap bincount FIRST: the
            # long-tail common case falls back to scatter without ever
            # paying the plan's two O(nnz log nnz) argsorts
            if (neq_mode == "auto"
                    and max(_neq_plan_span(u_idx), _neq_plan_span(i_idx))
                    > _NEQ_AUTO_SPAN_CAP):
                pass   # long-tail data: scatter wins; no plan is built
            else:
                # one static host sort per side (the ratings are fixed
                # for the whole fit); the data tuple ships pre-sorted,
                # so no per-epoch permute exists on device
                plan_u = NeqPlan(u_idx)
                plan_v = NeqPlan(i_idx)
                plans = (plan_u, plan_v)
        if plans is not None:
            data = tuple(jnp.asarray(a) for a in (
                plan_u.sort_pad(i_idx.astype(np.int32)),
                plan_u.sort_pad(ratings),
                plan_u.sort_pad(weights),
                plan_u.local_rank, plan_u.g_lo,
                plan_v.sort_pad(u_idx.astype(np.int32)),
                plan_v.sort_pad(ratings),
                plan_v.sort_pad(weights),
                plan_v.local_rank, plan_v.g_lo))
        else:
            plans = None
            data = (jnp.asarray(u_idx, jnp.int32),
                    jnp.asarray(i_idx, jnp.int32),
                    jnp.asarray(ratings), jnp.asarray(weights))
        result = iterate(
            als_epoch_step(len(user_ids), len(item_ids),
                           self.get_reg_param(), self.get_implicit_prefs(),
                           self.get_alpha(), plans=plans),
            (jnp.asarray(U0), jnp.asarray(V0)),
            data,
            max_epochs=self.get_max_iter(),
            config=IterationConfig(mode="fused"),
        )
        U, V = (np.asarray(jax.device_get(x)) for x in result.state)

        model = ALSModel()
        model.copy_params_from(self)
        model.set_model_data(Table({
            "userIds": user_ids[None], "itemIds": item_ids[None],
            "userFactors": U[None], "itemFactors": V[None]}))
        return model

    def _fit_workset(self, user_ids, item_ids, u_idx, i_idx, ratings,
                     weights, U0, V0, ws_tol: float) -> ALSModel:
        """Workset (delta-iteration) fit: raw-index data, both sides
        masked, convergence-driven while_loop exit (see
        :func:`als_workset_epoch_step`)."""
        data = (jnp.asarray(u_idx, jnp.int32),
                jnp.asarray(i_idx, jnp.int32),
                jnp.asarray(ratings), jnp.asarray(weights))
        ws0 = Workset({"users": jnp.ones((len(user_ids),), jnp.float32),
                       "items": jnp.ones((len(item_ids),), jnp.float32)})
        result = iterate(
            als_workset_epoch_step(len(user_ids), len(item_ids),
                                   self.get_reg_param(),
                                   self.get_implicit_prefs(),
                                   self.get_alpha(), ws_tol),
            (jnp.asarray(U0), jnp.asarray(V0)),
            data,
            max_epochs=self.get_max_iter(),
            workset=ws0,
            config=IterationConfig(mode="fused"),
        )
        trace = result.side.get("epoch_trace", {})
        self.last_workset_report = {
            "rounds": result.num_epochs,
            "max_epochs": self.get_max_iter(),
            "active_fraction": np.asarray(
                trace.get("active_fraction", ()), np.float64),
            "n_groups": len(user_ids) + len(item_ids),
        }
        U, V = (np.asarray(jax.device_get(x)) for x in result.state)
        model = ALSModel()
        model.copy_params_from(self)
        model.set_model_data(Table({
            "userIds": user_ids[None], "itemIds": item_ids[None],
            "userFactors": U[None], "itemFactors": V[None]}))
        return model

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)

    @classmethod
    def load(cls, path: str) -> "ALS":
        return persist.load_stage_param(path)
