from .als import ALS, ALSModel, ALSModelParams, ALSParams  # noqa: F401
from .swing import Swing, SwingParams  # noqa: F401
from .widedeep import WideDeep, WideDeepModel, WideDeepParams  # noqa: F401
