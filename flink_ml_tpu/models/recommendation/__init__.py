from .widedeep import WideDeep, WideDeepModel, WideDeepParams  # noqa: F401
