"""Wide&Deep two-tower recommender (BASELINE.json stretch config 5).

Not present in the reference (its iteration runtime was never stretched to
DNNs — that's the point of this config): a wide linear tower over
categorical ids + dense features, and a deep tower of embeddings + MLP,
trained jointly with Adam on binary cross-entropy.

TPU-native design:
- one stacked embedding table ``(total_vocab, emb_dim)`` — lookups are a
  single gather, MXU-friendly; per-field vocabularies are offset into it
- the whole multi-epoch training loop is fused (``iterate`` + inner
  ``lax.scan`` over mini-batches), parameters and optimizer state live in
  HBM between epochs
- sharding: batch over the mesh's ``data`` axis; with a ``model`` axis the
  embedding dim and MLP hidden dims shard over it (tensor parallelism) —
  see ``build_sharded_train_step`` which __graft_entry__ dry-runs multichip
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...api.stage import Estimator, Model
from ...data.table import Table
from ...iteration import IterationBodyResult, IterationConfig, iterate
from ...params.param import (
    BoolParam,
    FloatParam,
    IntArrayParam,
    IntParam,
    ParamValidators,
    StringParam,
)
from ...params.shared import (
    HasGlobalBatchSize,
    HasLabelCol,
    HasMaxIter,
    HasPredictionCol,
    HasRawPredictionCol,
    HasSeed,
)
from ...parallel.mesh import default_mesh, replicate
from ...utils import persist
from ..common.losses import logistic_loss
from ..common.sgd import (
    DEFAULT_GLOBAL_BATCH,
    plan_epoch_layout,
    prepare_epoch_tensor,
)

__all__ = ["WideDeep", "WideDeepModel", "WideDeepParams"]


class WideDeepParams(HasLabelCol, HasPredictionCol, HasRawPredictionCol,
                     HasMaxIter, HasGlobalBatchSize, HasSeed):
    DENSE_FEATURES_COL = StringParam(
        "denseFeaturesCol", "Dense feature matrix column.",
        default="denseFeatures")
    CAT_FEATURES_COL = StringParam(
        "catFeaturesCol", "Categorical id matrix column (int).",
        default="catFeatures")
    VOCAB_SIZES = IntArrayParam(
        "vocabSizes", "Vocabulary size per categorical field.",
        default=None, validator=lambda v: v is None or (len(v) > 0 and
                                                        all(s > 0 for s in v)))
    EMBEDDING_DIM = IntParam("embeddingDim", "Embedding width per field.",
                             default=8, validator=ParamValidators.gt(0))
    HIDDEN_UNITS = IntArrayParam("hiddenUnits", "Deep-tower MLP widths.",
                                 default=(64, 32))
    LEARNING_RATE = FloatParam("learningRate", "Adam learning rate.",
                               default=1e-2, validator=ParamValidators.gt(0))
    LAZY_EMB_OPT = BoolParam(
        "lazyEmbeddingOptimizer",
        "LazyAdam for the embedding/wide-cat tables: Adam state and "
        "parameters update only at the rows each batch touches; "
        "untouched rows keep param AND optimizer state exactly (no "
        "momentum tail) — the standard LazyAdam semantic deviation "
        "from dense Adam.  NOTE the r4 TPU measurement: at 2^20 total "
        "vocab the dense streams WIN (18.8 vs 42.5 ms/step — XLA's "
        "213k-row scatter costs more than streaming the whole table), "
        "so this stays opt-in for its semantics, and for vocabularies "
        "large enough that full-table m/v/param streams dominate or "
        "cannot fit.",
        default=False)
    ROUTED_EMB_GRAD = StringParam(
        "routedEmbeddingGrad",
        "Statically-routed table gradients (ops/emb_grad.py) for the "
        "dense-Adam fit: the bounded fit replays a fixed epoch tensor, "
        "so the per-step slot->row sort is computed once on the host "
        "and every training step's embedding/wide-table scatter becomes "
        "conflict-free streaming work (sorted permutation gather + "
        "segmented fold + unique sorted scatter-set) instead of XLA's "
        "per-slot random read-modify-write — the same static-routing "
        "insight as the LR family's ELL kernels.  Results equal the "
        "scatter-add up to f32 summation order.  'auto' (default) = on "
        "for the in-memory dense-Adam fit(), off for streaming fits "
        "(their batches are not replayed) and under "
        "lazyEmbeddingOptimizer; 'on' forces it (error if lazy); "
        "'off' keeps the autodiff scatter.",
        default="auto",
        validator=ParamValidators.in_array(("auto", "on", "off")))

    def get_vocab_sizes(self):
        return self.get(WideDeepParams.VOCAB_SIZES)

    def set_vocab_sizes(self, v):
        return self.set(WideDeepParams.VOCAB_SIZES, v)


def _field_offsets(vocab_sizes) -> np.ndarray:
    return np.concatenate([[0], np.cumsum(vocab_sizes)[:-1]]).astype(np.int32)


def init_params(rng: np.random.Generator, d_dense: int, vocab_sizes,
                emb_dim: int, hidden) -> Dict[str, Any]:
    total_vocab = int(np.sum(vocab_sizes))
    n_fields = len(vocab_sizes)
    deep_in = d_dense + n_fields * emb_dim
    layers = []
    fan_in = deep_in
    for h in list(hidden) + [1]:
        scale = np.sqrt(2.0 / fan_in)
        layers.append({
            "w": (rng.normal(size=(fan_in, h)) * scale).astype(np.float32),
            "b": np.zeros((h,), np.float32),
        })
        fan_in = h
    return {
        "wide_cat": np.zeros((total_vocab,), np.float32),
        "wide_dense": np.zeros((d_dense,), np.float32),
        "wide_b": np.zeros((), np.float32),
        "emb": (rng.normal(size=(total_vocab, emb_dim)) * 0.05
                ).astype(np.float32),
        "mlp": layers,
    }


def forward_from_rows(params: Dict[str, Any], dense: jnp.ndarray,
                      wide_rows: jnp.ndarray, emb_rows: jnp.ndarray
                      ) -> jnp.ndarray:
    """Logits from already-gathered table rows (``wide_rows (b, fields)``,
    ``emb_rows (b, fields, emb)``).  The routed-gradient step
    differentiates THROUGH the rows (treating the gathers as inputs) so
    it can route the table gradients itself; ``params`` needs only the
    non-table leaves here."""
    from ..common.linear import _stable_margins

    # k=1 contractions (the wide matvec, the final (h, 1) layer) go
    # through the context-stable GEMM form: their loop-fusion
    # accumulation order otherwise differs between the standalone score
    # program and a fused chain segment (see _stable_margins), breaking
    # the fused pipeline's bit-exactness at d >= 8.
    wide = (_stable_margins(dense, params["wide_dense"], 0.0)
            + jnp.sum(wide_rows, axis=1)
            + params["wide_b"])
    deep = jnp.concatenate(
        [dense, emb_rows.reshape(emb_rows.shape[0], -1)], axis=1)
    for i, layer in enumerate(params["mlp"]):
        if layer["w"].shape[1] == 1:
            deep = _stable_margins(deep, layer["w"][:, 0],
                                   layer["b"][0])[:, None]
        else:
            deep = deep @ layer["w"] + layer["b"]
        if i + 1 < len(params["mlp"]):
            deep = jax.nn.relu(deep)
    return wide + deep[:, 0]


def forward(params: Dict[str, Any], dense: jnp.ndarray,
            cat_ids: jnp.ndarray) -> jnp.ndarray:
    """Logits for a batch.  ``cat_ids`` are already offset into the stacked
    vocab (shape (batch, n_fields))."""
    return forward_from_rows(params, dense, params["wide_cat"][cat_ids],
                             params["emb"][cat_ids])


def bce_loss(params, dense, cat_ids, labels, mask):
    # Identical to the linear family's masked binary log-loss — one shared
    # implementation of the {0,1}->±1 softplus form and padding epsilon.
    return logistic_loss(forward(params, dense, cat_ids), labels, mask)


def _validate_cat_ids(cat: np.ndarray, vocab_sizes) -> np.ndarray:
    """Range-check raw per-field ids, then offset into the stacked vocab.
    Both fit() and transform() go through here: a jitted gather silently
    CLAMPS out-of-range indices, so serving an unseen id would otherwise
    return another field's embedding with no error."""
    if cat.shape[1] != len(vocab_sizes):
        raise ValueError(
            f"catFeatures has {cat.shape[1]} fields, vocabSizes has "
            f"{len(vocab_sizes)}")
    if np.any(cat < 0) or np.any(cat >= np.asarray(vocab_sizes)[None, :]):
        raise ValueError("categorical id out of vocab range")
    return cat + _field_offsets(vocab_sizes)[None, :]


class WideDeep(WideDeepParams, Estimator["WideDeepModel"]):
    """fit(table with denseFeatures (n,d) float, catFeatures (n,f) int,
    label (n,) {0,1})."""

    def fit(self, *inputs) -> "WideDeepModel":
        (table,) = inputs
        vocab_sizes = self.get_vocab_sizes()
        if vocab_sizes is None:
            raise ValueError("WideDeep requires vocabSizes to be set")
        mesh = default_mesh()
        n_dev = int(mesh.shape["data"])

        dense = np.asarray(table[self.DENSE_FEATURES_COL],
                           np.float32)
        cat = np.asarray(table[self.CAT_FEATURES_COL], np.int32)
        labels = np.asarray(table[self.get_label_col()], np.float32)
        cat = _validate_cat_ids(cat, vocab_sizes)

        n = dense.shape[0]
        steps, batch, perm = plan_epoch_layout(
            n, self.get_global_batch_size() or DEFAULT_GLOBAL_BATCH, n_dev,
            self.get_seed())

        def layout(arr):
            return prepare_epoch_tensor(arr, perm, steps, batch)

        mask = layout(np.ones((n,), np.float32))
        X = layout(dense)
        C = layout(cat)
        y = layout(labels)

        lazy = bool(self.LAZY_EMB_OPT)
        routed_mode = self.get(WideDeepParams.ROUTED_EMB_GRAD)
        route = None
        if routed_mode == "on" or (routed_mode == "auto" and not lazy):
            from ...ops.emb_grad import emb_grad_route

            # the epoch tensor C is replayed every epoch, so the
            # slot->row sort is static — built once here, host-side
            # (device=False: replicate() below does the one device_put;
            # placement="auto": gather until the inverse map outgrows
            # its budget at large vocab x many steps, then scatter)
            route = emb_grad_route(C, int(np.sum(vocab_sizes)),
                                   device=False, placement="auto")

        bsh = NamedSharding(mesh, P(None, "data"))
        X = jax.device_put(X, NamedSharding(mesh, P(None, "data", None)))
        C = jax.device_put(C, NamedSharding(mesh, P(None, "data", None)))
        y, mask = jax.device_put(y, bsh), jax.device_put(mask, bsh)
        route_data = ()
        if route is not None:
            route_data = tuple(replicate(a, mesh)
                               for a in route.stacked_arrays())

        rng = np.random.default_rng(self.get_seed() + 1)  # init-draw stream
        params = replicate(
            init_params(rng, dense.shape[1], vocab_sizes,
                        self.EMBEDDING_DIM,
                        self.HIDDEN_UNITS), mesh)
        step_fn, opt_state = _make_train_ops(
            params, self.LEARNING_RATE, lazy, route=route)
        opt_state = replicate(opt_state, mesh)

        def epoch_body(state, epoch, data):
            Xd, Cd, yd, md = data[:4]
            rt = data[4:]
            params, opt_state, loss_log = state

            def batch_step(carry, i):
                params, opt_state = carry
                params, opt_state, loss = step_fn(
                    params, opt_state, Xd[i], Cd[i], yd[i], md[i],
                    *(a[i] for a in rt))
                return (params, opt_state), loss

            (params, opt_state), losses = jax.lax.scan(
                batch_step, (params, opt_state),
                jnp.arange(steps, dtype=jnp.int32))
            loss_log = loss_log.at[epoch].set(jnp.mean(losses))
            return IterationBodyResult((params, opt_state, loss_log))

        max_epochs = self.get_max_iter()
        init_state = (params, opt_state,
                      jnp.full((max_epochs,), jnp.nan, jnp.float32))
        result = iterate(epoch_body, init_state, (X, C, y, mask) + route_data,
                         max_epochs=max_epochs,
                         config=IterationConfig(mode="fused"))
        fitted, _, loss_buf = result.state

        model = WideDeepModel()
        model.copy_params_from(self)
        model._params = jax.device_get(fitted)
        model._vocab_sizes = tuple(int(v) for v in vocab_sizes)
        model._loss_log = list(np.asarray(jax.device_get(loss_buf)))
        return model

    def fit_outofcore(self, make_reader, *, mesh=None,
                      prefetch_depth: int = 2, prefetch_workers: int = 1,
                      prefetch_put_workers: int = 1,
                      prefetch_stats=None,
                      steps_per_dispatch: int = 8,
                      checkpoint=None,
                      checkpoint_every_steps: int = 0,
                      resume: bool = False,
                      membership=None) -> "WideDeepModel":
        """Out-of-core ``fit``: epochs stream from ``make_reader()`` (the
        ``sgd_fit_outofcore`` reader protocol — a fresh per-epoch
        iterator of host batch dicts with this estimator's column names;
        epoch-aware factories receive ``epoch=``) instead of holding the
        (rows, fields) epoch tensors in HBM — the Criteo-scale shape for
        the stretch config.  Batches pad to the first batch's row count
        (padding rows carry mask 0 and are inert in BOTH optimizers: the
        loss is mask-weighted and the lazy table update drops weight-0
        ids), transfer via :func:`prefetch_to_device` overlapping the
        jitted Adam step, and the model/optimizer state never leaves
        device memory between epochs.  The mesh's ``data`` axis shards
        each batch.

        **Chunked dispatch** (``steps_per_dispatch=W``, default 8):
        single-process fits stack ``W`` consecutive batches into one
        device chunk and run all ``W`` Adam steps as one jitted
        ``lax.scan`` with a donated carry — one host dispatch per ``W``
        steps (the ``sgd_fit_outofcore`` posture; see its docstring).
        The final short chunk pads with a validity mask whose dead
        steps freeze params AND optimizer state, so any two ``W``
        values are bit-exact on the same stream.

        **Multi-host**: pass a process-spanning mesh and call from EVERY
        process with a reader over THAT process's data shard (the
        ``sgd_fit_outofcore`` posture): the global batch is the per-step
        concatenation over processes, assembled inside the prefetch
        pipeline, and every process must deliver the SAME number of
        equal-sized batches per epoch (mismatches deadlock in the
        collectives).  Multi-process fits keep the classic per-batch
        loop (chunk assembly is per-process-local).

        **Checkpoints + elastic membership** (``checkpoint=``,
        ``checkpoint_every_steps=``, ``resume=``, ``membership=`` —
        the ``sgd_fit_outofcore`` protocol, chunked single-process
        path): cuts land at chunk boundaries carrying params, Adam
        state, the running loss accumulators AND mesh-shape metadata;
        ``resume=True`` restores the newest valid cut, re-seeks the
        reader and continues deterministically.  With an
        :class:`~flink_ml_tpu.parallel.elastic.ElasticCoordinator` the
        fit polls membership once per chunk boundary and a changed
        fleet cuts a checkpoint and raises
        :class:`~flink_ml_tpu.parallel.elastic.ResizeRequested` for
        ``resilient_fit(elastic=...)`` to restore onto the new mesh —
        params and optimizer state are replicated, so the re-shard is
        pure placement and the resize is bit-exact vs a fixed fleet of
        the new size restoring the same cut.  Elastic fits shard the
        batch over EVERY mesh axis jointly (dcn x data)."""
        from ...data.prefetch import prefetch_to_device
        from ...parallel.mesh import (
            assemble_process_local,
            fetch_replicated,
            local_axis_multiple,
            mesh_process_count,
        )
        from ...utils.padding import FixedRowBatcher
        from ..common.sgd import _reader_for_epoch

        vocab_sizes = self.get_vocab_sizes()
        if vocab_sizes is None:
            raise ValueError("WideDeep requires vocabSizes to be set")
        if self.get(WideDeepParams.ROUTED_EMB_GRAD) == "on":
            raise ValueError(
                "routedEmbeddingGrad='on' cannot apply to the streaming "
                "fit: its batches are not replayed, so no static route "
                "exists — use 'auto' (streams on the autodiff scatter) "
                "or the in-memory fit()")
        mesh = mesh or default_mesh()
        put_fn = (assemble_process_local
                  if mesh_process_count(mesh) > 1 else None)
        chunked = mesh_process_count(mesh) == 1

        from ...iteration.checkpoint import (
            CheckpointConfig,
            CheckpointManager,
            mesh_shape_meta,
        )

        manager = None
        if isinstance(checkpoint, CheckpointManager):
            manager = checkpoint
        elif isinstance(checkpoint, CheckpointConfig):
            manager = CheckpointManager(checkpoint)
        if manager is not None and not chunked:
            raise ValueError(
                "checkpointing the streaming WideDeep fit needs the "
                "chunked single-process path (cuts land at chunk "
                "boundaries)")
        if membership is not None and manager is None:
            raise ValueError(
                "elastic membership requires a checkpoint manager: a "
                "resize IS a restore onto the new mesh")
        batch_axes = "data"
        row_multiple = local_axis_multiple(mesh)
        if membership is not None and len(mesh.axis_names) > 1:
            # elastic fleet: the batch shards over every mesh axis
            # jointly (dcn x data) so the resized dcn extent changes the
            # shard count, not the math
            batch_axes = tuple(str(a) for a in mesh.axis_names)
            row_multiple = int(np.prod([int(mesh.shape[a])
                                        for a in mesh.axis_names]))
        batcher = FixedRowBatcher(row_multiple)
        dense_col, cat_col = self.DENSE_FEATURES_COL, self.CAT_FEATURES_COL
        label_col = self.get_label_col()

        rng = np.random.default_rng(self.get_seed() + 1)
        # params/step build lazily at the first batch (d_dense comes
        # from the stream, matching fit()'s init-draw RNG sequence)
        params = step_fn = opt_state = None

        def to_host_batch(b):
            dense = np.asarray(b[dense_col], np.float32)
            cat = _validate_cat_ids(np.asarray(b[cat_col], np.int32),
                                    vocab_sizes)
            y = np.asarray(b[label_col], np.float32)
            mask = np.ones((y.shape[0],), np.float32)
            # padding rows: mask 0 + cat id 0 — inert in both optimizers
            # (mask-weighted loss; lazy update drops weight-0 ids)
            return batcher.pad((dense, cat, y, mask), have=y.shape[0])

        specs = (P(batch_axes, None), P(batch_axes, None), P(batch_axes),
                 P(batch_axes))
        # chunked dispatch (single-process): W batches per jitted scan —
        # W=1 is the bit-exact fallback through the SAME scan program
        W = max(1, int(steps_per_dispatch)) if chunked else 1
        if chunked:
            from ...data.prefetch import chunk_consumer_plan

            sharding, chunk_depth = chunk_consumer_plan(
                mesh, specs, W, prefetch_depth)
        else:
            sharding = tuple(NamedSharding(mesh, p) for p in specs)

        def _build_chunk_step(raw_step):
            # the shared masked scan freezes the WHOLE carried state —
            # here (params, opt_state), so dead (padded) steps freeze
            # the optimizer moments too — bit-exact vs the unpadded
            # stream
            from ...data.prefetch import masked_chunk_scan

            def step(state, *batch):
                params, opt_state = state
                params, opt_state, loss = raw_step(params, opt_state,
                                                   *batch)
                return (params, opt_state), loss

            def _chunk_runner(state, loss_sum, chunk, cmask):
                return masked_chunk_scan(step, state, loss_sum, chunk,
                                         cmask)

            return jax.jit(_chunk_runner, donate_argnums=(0, 1))

        def _lazy_init(d_dense: int):
            # init + optax state build on HOST values, then replicate
            # both: optax.init on a non-addressable process-spanning
            # array would create mismatched local state (every process
            # seeds identically)
            host_params = init_params(
                rng, d_dense, vocab_sizes,
                self.EMBEDDING_DIM, self.HIDDEN_UNITS)
            raw_step, host_opt = _make_train_ops(
                host_params, self.LEARNING_RATE,
                bool(self.LAZY_EMB_OPT))
            return (replicate(host_params, mesh),
                    replicate(host_opt, mesh), raw_step)

        epoch_sums: List = []   # per-epoch (device scalar, n_batches):
        max_epochs = self.get_max_iter()  # fetched ONCE after the loop so
        add = jax.jit(jnp.add)            # epoch boundaries never sync

        global_step = 0         # checkpoint tick: batches over all epochs
        start_epoch = 0
        skip_steps = 0          # batches already consumed in start_epoch
        resume_loss_sum = None
        resume_n_batches = 0
        if manager is not None and resume:
            restored = manager.restore_latest()
            if restored is not None:
                global_step, saved, meta = restored
                host_params = jax.device_get(saved["params"])
                raw_step, _ = _make_train_ops(
                    host_params, self.LEARNING_RATE,
                    bool(self.LAZY_EMB_OPT))
                params = replicate(host_params, mesh)
                opt_state = replicate(jax.device_get(saved["opt_state"]),
                                      mesh)
                step_fn = (_build_chunk_step(raw_step) if chunked
                           else jax.jit(raw_step, donate_argnums=(0, 1)))
                start_epoch = int(meta["train_epoch"])
                skip_steps = int(meta["step_in_epoch"])
                resume_n_batches = int(meta["n_batches"])
                if resume_n_batches:
                    resume_loss_sum = jnp.asarray(saved["loss_sum"],
                                                  jnp.float32)
                epoch_sums = [(jnp.asarray(s, jnp.float32), int(n))
                              for s, n in saved["epoch_sums"]]

        def _save(epoch, step_in_epoch, loss_sum, n_batches):
            manager.save(global_step, {
                "params": params, "opt_state": opt_state,
                "loss_sum": (loss_sum if loss_sum is not None
                             else jnp.zeros((), jnp.float32)),
                "epoch_sums": [(s, int(n)) for s, n in epoch_sums],
            }, {
                "train_epoch": epoch, "step_in_epoch": step_in_epoch,
                "n_batches": n_batches,
                **mesh_shape_meta(mesh, participant_count=row_multiple),
            })

        for epoch in range(start_epoch, max_epochs):
            reader = _reader_for_epoch(make_reader, epoch)
            if epoch == start_epoch and skip_steps:
                from ..common.sgd import _seek_or_skip

                reader = _seek_or_skip(reader, skip_steps)
            loss_sum = resume_loss_sum
            n_batches = resume_n_batches
            step_in_epoch = skip_steps
            resume_loss_sum, resume_n_batches, skip_steps = None, 0, 0
            if chunked:
                # closed explicitly on every exit so a supervised
                # restart (resize/crash recovery) never races a zombie
                # reader thread for the shared source
                pipeline = prefetch_to_device(
                    reader, depth=chunk_depth,
                    transform=to_host_batch, sharding=sharding,
                    workers=prefetch_workers,
                    put_workers=prefetch_put_workers,
                    stats=prefetch_stats, chunks=W)
                try:
                    for chunk, cmask, n_valid in pipeline:
                        if step_fn is None:
                            params, opt_state, raw_step = _lazy_init(
                                int(chunk[0].shape[2]))
                            step_fn = _build_chunk_step(raw_step)
                        if loss_sum is None:
                            loss_sum = jnp.zeros((), jnp.float32)
                        (params, opt_state), loss_sum = step_fn(
                            (params, opt_state), loss_sum, chunk, cmask)
                        n_batches += n_valid
                        step_in_epoch += n_valid
                        global_step += n_valid
                        cut_done = False
                        if (manager is not None
                                and checkpoint_every_steps > 0
                                and step_in_epoch // checkpoint_every_steps
                                > (step_in_epoch - n_valid)
                                // checkpoint_every_steps):
                            _save(epoch, step_in_epoch, loss_sum,
                                  n_batches)
                            cut_done = True
                        # elastic membership: one poll per chunk
                        # boundary; a changed fleet cuts here and hands
                        # the resize to the supervisor
                        if membership is not None \
                                and membership.poll(global_step):
                            if manager is not None and not cut_done:
                                _save(epoch, step_in_epoch, loss_sum,
                                      n_batches)
                            from ...parallel.elastic import ResizeRequested

                            raise ResizeRequested(
                                step=global_step,
                                fleet_size=membership.fleet_size,
                                membership_epoch=(
                                    membership.membership_epoch))
                finally:
                    pipeline.close()
            else:
                for dev_batch in prefetch_to_device(
                        reader, depth=prefetch_depth,
                        transform=to_host_batch, sharding=sharding,
                        workers=prefetch_workers,
                        put_workers=prefetch_put_workers,
                        stats=prefetch_stats, put_fn=put_fn):
                    if step_fn is None:
                        params, opt_state, raw_step = _lazy_init(
                            int(dev_batch[0].shape[1]))
                        step_fn = jax.jit(raw_step, donate_argnums=(0, 1))
                    params, opt_state, loss = step_fn(params, opt_state,
                                                      *dev_batch)
                    loss_sum = (loss if loss_sum is None
                                else add(loss_sum, loss))
                    n_batches += 1
            if loss_sum is None:
                raise ValueError("make_reader() returned an empty epoch")
            epoch_sums.append((loss_sum, n_batches))
            if manager is not None:
                _save(epoch + 1, 0, None, 0)   # epoch-boundary cut
        loss_log = [float(np.asarray(fetch_replicated(s))) / n
                    for s, n in epoch_sums]

        model = WideDeepModel()
        model.copy_params_from(self)
        model._params = fetch_replicated(params)
        model._vocab_sizes = tuple(int(v) for v in vocab_sizes)
        model._loss_log = loss_log
        return model

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)

    @classmethod
    def load(cls, path: str) -> "WideDeep":
        return persist.load_stage_param(path)


@jax.jit
def _jit_scores(params, dense, cat_ids):
    return jax.nn.sigmoid(forward(params, dense, cat_ids))


def _widedeep_chain_kernel(static, params, cols):
    """Chain-terminal scores — expression-identical to ``_jit_scores``;
    the raw per-field ids offset into the stacked vocab in-device (an
    exact int add; the range check runs host-side as the kernel's
    ``pre``)."""
    (dcol, ccol, scol) = static
    dense = cols[dcol].astype(jnp.float32)
    cat = cols[ccol] + params["offsets"][None, :]
    return {scol: jax.nn.sigmoid(forward(params["net"], dense, cat))}


class WideDeepModel(WideDeepParams, Model):
    def __init__(self):
        super().__init__()
        self._params: Optional[Dict[str, Any]] = None
        self._vocab_sizes: Optional[Tuple[int, ...]] = None
        self._loss_log: List[float] = []

    @property
    def loss_log(self) -> List[float]:
        """Per-epoch mean training loss (the linear family's accessor)."""
        return list(self._loss_log)

    def _require_model(self):
        if self._params is None:
            raise RuntimeError("WideDeepModel has no model data")

    def transform_kernel(self, schema):
        """Chain TERMINAL: one fused sigmoid(forward) over the segment's
        device columns.  The categorical id range check (host control
        flow) runs as the kernel's ``pre`` on the segment's entry
        columns, so the stage only chains while catFeatures passes
        through from the segment input untouched."""
        from ...api.chain import StageKernel, numeric_entry

        self._require_model()
        dcol, ccol = self.DENSE_FEATURES_COL, self.CAT_FEATURES_COL
        cat_entry = schema.get(ccol)
        if numeric_entry(schema, dcol) is None \
                or cat_entry is None or cat_entry[1].kind not in "iu" \
                or len(cat_entry[0]) != 1 \
                or cat_entry[0][0] != len(self._vocab_sizes):
            return None
        raw_col = self.get_raw_prediction_col()
        pred_col = self.get_prediction_col()
        score_col = f"__chain_scores__{pred_col}"
        vocab_sizes = self._vocab_sizes

        def pre(host):
            _validate_cat_ids(np.asarray(host[ccol]), vocab_sizes)

        def post(host):
            scores = host[score_col].astype(np.float64)
            return {raw_col: scores,
                    pred_col: (scores > 0.5).astype(np.int64)}

        return StageKernel(
            fn=_widedeep_chain_kernel, static=(dcol, ccol, score_col),
            params={"net": self._params,
                    "offsets": _field_offsets(vocab_sizes)},
            consumes=(dcol, ccol), produces=(score_col,),
            post=post, pre=pre, pre_cols=(ccol,))

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        # the kernel registry's shared dispatch surface (the chain
        # terminal's (fn, static) plan): offline transform, fused
        # pipelines, and serving share one compiled executable per
        # (schema, bucket); the in-kernel id offset is an exact int add,
        # the range check runs as the kernel's host pre exactly like
        # _validate_cat_ids
        from ...api.chain import apply_kernel_or_none

        kernel = self.transform_kernel(table.schema())
        cols = apply_kernel_or_none(kernel, table)
        if cols is not None:
            out = table
            for name in (n for n in cols if n not in kernel.produces):
                out = out.with_column(name, cols[name])
            return [out]
        dense = np.asarray(table[self.DENSE_FEATURES_COL],
                           np.float32)
        cat = np.asarray(table[self.CAT_FEATURES_COL], np.int32)
        cat = _validate_cat_ids(cat, self._vocab_sizes)
        # bucketed batch shape (utils/padding.py): one compiled forward per
        # power-of-two bucket serves every batch size; the per-row forward
        # makes zero-pad rows (id 0 is always a valid slot) inert
        from ...utils.padding import pad_rows_to_bucket

        (dense_p, cat_p), n = pad_rows_to_bucket((dense, cat))
        scores = np.asarray(_jit_scores(self._params, dense_p, cat_p),
                            np.float64)[:n]
        out = table.with_column(self.get_raw_prediction_col(), scores)
        out = out.with_column(self.get_prediction_col(),
                              (scores > 0.5).astype(np.int64))
        return [out]

    # -- persistence --------------------------------------------------------
    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(
            self, path, {"vocabSizes": list(self._vocab_sizes)})
        flat = {"wide_cat": self._params["wide_cat"],
                "wide_dense": self._params["wide_dense"],
                "wide_b": self._params["wide_b"],
                "emb": self._params["emb"]}
        for i, layer in enumerate(self._params["mlp"]):
            flat[f"mlp_{i}_w"] = layer["w"]
            flat[f"mlp_{i}_b"] = layer["b"]
        persist.save_model_arrays(path, "model", flat)

    @classmethod
    def load(cls, path: str) -> "WideDeepModel":
        model = persist.load_stage_param(path)
        meta = persist.load_metadata(path)
        data = persist.load_model_arrays(path, "model")
        n_layers = sum(1 for k in data if k.endswith("_w"))
        model._params = {
            "wide_cat": data["wide_cat"],
            "wide_dense": data["wide_dense"],
            "wide_b": data["wide_b"],
            "emb": data["emb"],
            "mlp": [{"w": data[f"mlp_{i}_w"], "b": data[f"mlp_{i}_b"]}
                    for i in range(n_layers)],
        }
        model._vocab_sizes = tuple(meta["vocabSizes"])
        return model


# embedding-shaped tables whose per-step gradient support is the batch's
# id set — the lazy optimizer updates only those rows
_LAZY_TABLE_KEYS = ("emb", "wide_cat")


def _make_train_ops(params, lr: float, lazy: bool, route=None,
                    b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8):
    """Build ``(batch_step, opt_state0)`` for the Wide&Deep training loop.

    ``lazy=False``: dense ``optax.adam`` over every parameter (the
    reference oracle semantics).

    ``route`` (an ``ops.emb_grad.EmbGradRoute``, dense-Adam only): the
    returned step takes four extra per-step route arrays
    (``order, sorted_ids, out_pos, out_ids`` — one step's slice) and
    computes the embedding/wide-table gradients with the statically-
    routed scatter instead of autodiff's random-RMW scatter-add; all
    other gradients and the Adam update are identical.  See the
    ``routedEmbeddingGrad`` param doc.

    ``lazy=True`` (LazyAdam, ``lazyEmbeddingOptimizer``): dense Adam
    touches every row of the ``(total_vocab, emb_dim)`` embedding and
    ``(total_vocab,)`` wide tables each step — m/v/param read+write
    streams over rows whose gradient is exactly zero (~1.6 GB/step at
    the 2^20-vocab bench shape).  The lazy step instead:

    1. takes the standard dense-shaped gradient (XLA's scatter-add from
       the gather's transpose — one zero-init + 213k-row scatter, the
       only full-table-shaped cost left),
    2. gathers the batch's ``ids = cat_ids.reshape(-1)`` rows of
       grad/m/v/param (duplicate ids read the SAME combined gradient
       row, so every duplicate computes identical values),
    3. applies exact Adam math at those rows and scatter-``set``s them
       back — duplicate writes are idempotent, so the result is
       deterministic.

    Rows a batch does not touch keep param AND optimizer state exactly
    (no momentum tail, no bias-correction drift): the standard LazyAdam
    semantic deviation from dense Adam.  A row touched by EVERY step has
    a bit-for-bit dense-Adam history — the oracle `tests/test_widedeep.py`
    asserts both properties.  The MLP/wide-dense/bias params always use
    dense ``optax.adam``; the shared step count drives bias correction
    for both halves.

    Measured reality (r4, one v5e chip, 2^20 total vocab, batch 8192):
    the DENSE step wins — 18.8 vs 42.5 ms — because XLA lowers the
    213k-row gather/scatter pair to serialized random HBM access while
    the full-table m/v/param update is three perfectly-streamed passes
    (the same asymmetry that motivated the static-routing ELL kernel
    for LR, ``ops/ell_scatter.py``).  Lazy is therefore an opt-in: use
    it for its freshness semantics, or when the vocabulary is so large
    that full-table streams dominate the step or the m/v tables cannot
    be afforded at all (2^22+ total vocab did not fit this chip's
    visible HBM to measure the crossover)."""
    opt = optax.adam(lr)
    grad_fn = jax.value_and_grad(bce_loss)

    def split(tree):
        tables = {k: tree[k] for k in _LAZY_TABLE_KEYS}
        rest = {k: v for k, v in tree.items() if k not in _LAZY_TABLE_KEYS}
        return tables, rest

    if route is not None:
        if lazy:
            raise ValueError(
                "routed table gradients are a dense-Adam path; disable "
                "lazyEmbeddingOptimizer or set routedEmbeddingGrad='off'")
        # registry op ``routed_table_grad``, resolved ONCE at step-build:
        # the fused Mosaic fold (ops/emb_grad_pallas.py) on TPU, the XLA
        # routed path elsewhere — the step body never branches on backend
        route_apply = route.resolve_apply()

        def batch_step(params, opt_state, dense, cat_ids, labels, mask,
                       *route_arrays):
            _, rest = split(params)
            emb_rows = params["emb"][cat_ids]
            wide_rows = params["wide_cat"][cat_ids]

            def loss_rows(rest, emb_rows, wide_rows):
                return logistic_loss(
                    forward_from_rows(rest, dense, wide_rows, emb_rows),
                    labels, mask)

            loss, (g_rest, g_emb, g_wide) = jax.value_and_grad(
                loss_rows, argnums=(0, 1, 2))(rest, emb_rows, wide_rows)
            emb_dim = emb_rows.shape[-1]
            grads = {
                **g_rest,
                "emb": route_apply(g_emb.reshape(-1, emb_dim),
                                   *route_arrays),
                "wide_cat": route_apply(g_wide.reshape(-1),
                                        *route_arrays),
            }
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        return batch_step, opt.init(params)
    if not lazy:
        def batch_step(params, opt_state, dense, cat_ids, labels, mask):
            loss, grads = grad_fn(params, dense, cat_ids, labels, mask)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        return batch_step, opt.init(params)

    tables0, rest0 = split(params)
    opt_state0 = {
        "rest": opt.init(rest0),
        "m": jax.tree_util.tree_map(jnp.zeros_like, tables0),
        "v": jax.tree_util.tree_map(jnp.zeros_like, tables0),
        "t": jnp.zeros((), jnp.int32),
    }

    def batch_step(params, opt_state, dense, cat_ids, labels, mask):
        loss, grads = grad_fn(params, dense, cat_ids, labels, mask)
        tables, rest = split(params)
        g_tab, g_rest = split(grads)
        rest_updates, rest_state = opt.update(g_rest, opt_state["rest"],
                                              rest)
        rest = optax.apply_updates(rest, rest_updates)
        t = opt_state["t"] + 1
        # optax.scale_by_adam's exact bias correction: 1 - decay**count
        bc1 = 1.0 - jnp.power(b1, t.astype(jnp.float32))
        bc2 = 1.0 - jnp.power(b2, t.astype(jnp.float32))
        # weight-0 rows (epoch padding carries cat id 0) must NOT count
        # as touched — id 0 would collect phantom momentum-tail updates.
        # Their ids go out of bounds so every scatter drops them; the
        # gathers use a clamped copy (the computed value is discarded).
        total = tables["emb"].shape[0]
        ids = jnp.where(mask[:, None] > 0, cat_ids, total).reshape(-1)
        gids = jnp.minimum(ids, total - 1)
        new_tab, new_m, new_v = {}, {}, {}
        for k in _LAZY_TABLE_KEYS:
            g_rows = g_tab[k][gids]
            m_rows = b1 * opt_state["m"][k][gids] + (1.0 - b1) * g_rows
            v_rows = (b2 * opt_state["v"][k][gids]
                      + (1.0 - b2) * jnp.square(g_rows))
            step_rows = lr * (m_rows / bc1) / (
                jnp.sqrt(v_rows / bc2) + eps)
            new_m[k] = opt_state["m"][k].at[ids].set(m_rows, mode="drop")
            new_v[k] = opt_state["v"][k].at[ids].set(v_rows, mode="drop")
            new_tab[k] = tables[k].at[ids].set(
                tables[k][gids] - step_rows, mode="drop")
        new_state = {"rest": rest_state, "m": new_m, "v": new_v, "t": t}
        return {**rest, **new_tab}, new_state, loss

    return batch_step, opt_state0


def build_reference_train_step(d_dense: int, vocab_sizes, emb_dim: int,
                               hidden, lr: float = 1e-2,
                               lazy_embeddings: bool = False,
                               route=None):
    """The unsharded single-device oracle for :func:`build_sharded_train_step`
    — SAME init seed (0), optimizer, and loss, no shardings anywhere.
    Returns (train_step, params, opt_state).  The dp x tp step must
    reproduce this one allclose on loss AND updated params (a wrong
    psum/axis placement still converges, so only exact equivalence catches
    it); asserted by tests/test_widedeep.py and __graft_entry__'s multichip
    dryrun.  ``lazy_embeddings`` swaps in the LazyAdam table update;
    ``route`` swaps in the statically-routed table gradients (see
    :func:`_make_train_ops` — the step then takes four extra per-step
    route arrays)."""
    params = jax.tree_util.tree_map(
        jnp.asarray,
        init_params(np.random.default_rng(0), d_dense, vocab_sizes, emb_dim,
                    hidden))
    batch_step, opt_state = _make_train_ops(params, lr, lazy_embeddings,
                                            route=route)
    return jax.jit(batch_step), params, opt_state


def assert_sharded_matches_reference(sharded_params, sharded_loss,
                                     ref_params, ref_loss) -> None:
    """Allclose on loss and every param leaf (f32 tolerances: cross-device
    reduction order differs from the single-device program)."""
    np.testing.assert_allclose(float(np.asarray(sharded_loss)),
                               float(np.asarray(ref_loss)),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(sharded_params)),
                    jax.tree_util.tree_leaves(jax.device_get(ref_params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def build_sharded_train_step(mesh, d_dense: int, vocab_sizes, emb_dim: int,
                             hidden, lr: float = 1e-2, grad_reduce=None):
    """A dp x tp training step for the multichip dry run: embeddings and MLP
    hidden dims sharded over 'model', batch over 'data'.  Returns
    (train_step, sharded_params, opt, sharded_opt_state, shard_batch_fn).

    ``grad_reduce``
    (:class:`~flink_ml_tpu.parallel.grad_reduce.GradReduceConfig`):
    ``None``/``mode="exact"`` keep the implicit-GSPMD step above
    unchanged.  A compressed mode routes the DENSE-tower gradients
    (``wide_dense``/``wide_b``/``mlp``) through
    :func:`~flink_ml_tpu.parallel.grad_reduce.reduce_gradients` — the
    data axis goes manual (``shard_map``) while the ``model`` axis stays
    under GSPMD auto partitioning, so Megatron-style tensor parallelism
    composes untouched.  The embedding/wide-table gradients stay EXACT:
    their per-step support is the batch's id set, i.e. they are already
    sparse by construction and top-k would only re-compress a scatter.
    The step then takes (and returns) the reducer state, and the builder
    returns a 6-tuple with its initial value appended:
    ``(train_step, params, opt, opt_state, shard_batch_fn, gr_state0)``
    with ``train_step(params, opt_state, gr_state, dense, cat_ids,
    labels, mask) -> (params, opt_state, gr_state, loss)``.

    ``grad_reduce.bucket_count`` / ``adaptive`` route the dense-tower
    reduce through the bucketed transport and the per-leaf density
    ladder; ``overlap=True`` makes the dense-tower grads one-step stale
    (the pending buffer rides ``gr_state``) while table grads stay
    fresh — callers that want the final pending applied run one extra
    step on a zero-mask batch."""
    rng = np.random.default_rng(0)
    params = init_params(rng, d_dense, vocab_sizes, emb_dim, hidden)

    def param_spec(path_params):
        specs = {
            "wide_cat": P(), "wide_dense": P(), "wide_b": P(),
            "emb": P(None, "model"),
        }
        mlp_specs = []
        n = len(path_params["mlp"])
        for i in range(n):
            # Megatron-style pairing: even layers column-parallel (outputs
            # sharded over 'model'), odd layers row-parallel (inputs sharded;
            # XLA inserts the psum that gathers activations back).
            if i % 2 == 0 and i + 1 < n:
                mlp_specs.append({"w": P(None, "model"), "b": P("model")})
            elif i % 2 == 1:
                mlp_specs.append({"w": P("model", None), "b": P()})
            else:  # final (or only) layer: replicated scalar head
                mlp_specs.append({"w": P(), "b": P()})
        return {**{k: specs[k] for k in specs}, "mlp": mlp_specs}

    specs = param_spec(params)
    sharded_params = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda x: isinstance(x, np.ndarray))

    opt = optax.adam(lr)
    opt_state = opt.init(sharded_params)
    grad_fn = jax.value_and_grad(bce_loss)

    if grad_reduce is not None and grad_reduce.mode != "exact":
        return _build_reduced_sharded_step(mesh, grad_reduce, sharded_params,
                                           opt, opt_state, grad_fn)

    @jax.jit
    def train_step(params, opt_state, dense, cat_ids, labels, mask):
        loss, grads = grad_fn(params, dense, cat_ids, labels, mask)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def shard_batch_fn(dense, cat_ids, labels, mask):
        return (
            jax.device_put(dense, NamedSharding(mesh, P("data", None))),
            jax.device_put(cat_ids, NamedSharding(mesh, P("data", None))),
            jax.device_put(labels, NamedSharding(mesh, P("data"))),
            jax.device_put(mask, NamedSharding(mesh, P("data"))),
        )

    return train_step, sharded_params, opt, opt_state, shard_batch_fn


def _build_reduced_sharded_step(mesh, gr, sharded_params, opt, opt_state,
                                grad_fn):
    """The compressed-reduction variant of :func:`build_sharded_train_step`
    (see its docstring for the contract): manual ``shard_map`` over the
    reduction axes, every OTHER mesh axis (``model``) left to GSPMD auto
    partitioning, dense-tower grads through ``reduce_gradients`` — on
    the recursive-halving/doubling wire protocol by default, so the
    reducer state here also carries the per-round fill-in/union
    accounting leaves — table grads exact."""
    from ...parallel import grad_reduce as GR
    from ...parallel.collectives import shard_map_fn

    axes, n_red, batch_axis = GR.mesh_layout(gr, mesh)
    auto_axes = frozenset(n for n in mesh.axis_names if n not in axes)

    def split(tree):
        tables = {k: tree[k] for k in _LAZY_TABLE_KEYS}
        rest = {k: v for k, v in tree.items() if k not in _LAZY_TABLE_KEYS}
        return tables, rest

    _, rest0 = split(sharded_params)
    gr_state0 = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P(batch_axis))),
        GR.init_state(gr, jax.tree_util.tree_map(np.asarray, rest0), n_red))

    # Stage 1 — per-device gradients, 'model' under GSPMD auto so the
    # Megatron sharding composes: table grads reduce EXACTLY here (their
    # support is the batch's id set — sparse by construction); the dense
    # tower comes back STACKED per participant for stage 2.
    def local_grads(params, dense, cat_ids, labels, mask):
        loss_l, grads = grad_fn(params, dense, cat_ids, labels, mask)
        # bce_loss is a mask-weighted LOCAL mean; renormalize to the
        # global denominator so loss and gradient equal the
        # single-program objective (the _mixed_update_sharded stance)
        denom_l = jnp.maximum(jnp.sum(mask), 1e-12)
        denom = jax.lax.psum(denom_l, axes)
        loss = jax.lax.psum(loss_l * denom_l, axes) / denom
        grads = jax.tree_util.tree_map(lambda g: g * (denom_l / denom),
                                       grads)
        g_tab, g_rest = split(grads)
        g_tab = jax.tree_util.tree_map(lambda g: jax.lax.psum(g, axes),
                                       g_tab)
        return loss, g_tab, jax.tree_util.tree_map(
            lambda g: g[None], g_rest)

    grads_fn = shard_map_fn(
        local_grads, mesh,
        in_specs=(P(), P(batch_axis, None), P(batch_axis, None),
                  P(batch_axis), P(batch_axis)),
        out_specs=(P(), P(), P(batch_axis)),
        auto=auto_axes)

    # Stage 2 — the compressed reduction runs FULLY manual (every mesh
    # axis bound): this XLA's partitioner aborts on lax.top_k inside a
    # manual-subgroup (auto) region, and the dense-tower leaves carry no
    # model sharding anyway, so model peers just replicate the reduce.
    # With overlap the PREVIOUS step's pending dense-tower grads are
    # reduced (their bucket collectives carry no dependence on this
    # step's forward/backward) and this step's land in the pending
    # buffer; table grads stay fresh — mixing a one-step-stale dense
    # tower with fresh tables is absorbed by the EF residual like the
    # sparsification itself.
    def reduce_local(g_stacked, gr_state):
        g_l = jax.tree_util.tree_map(lambda a: a[0], g_stacked)
        st = GR.squeeze_state(gr_state)
        if GR.wants_overlap(gr):
            red, new_state = GR.pipelined_reduce(g_l, st, gr)
        else:
            red, new_state = GR.reduce_gradients(g_l, st, gr)
        return red, GR.unsqueeze_state(new_state)

    reduce_fn = shard_map_fn(
        reduce_local, mesh,
        in_specs=(P(batch_axis), P(batch_axis)),
        out_specs=(P(), P(batch_axis)))

    @jax.jit
    def train_step(params, opt_state, gr_state, dense, cat_ids, labels,
                   mask):
        loss, g_tab, g_stacked = grads_fn(params, dense, cat_ids, labels,
                                          mask)
        g_rest, gr_state = reduce_fn(g_stacked, gr_state)
        grads = {**g_tab, **g_rest}
        updates, opt_state = opt.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state, gr_state,
                loss)

    def shard_batch_fn(dense, cat_ids, labels, mask):
        return (
            jax.device_put(dense, NamedSharding(mesh, P(batch_axis, None))),
            jax.device_put(cat_ids, NamedSharding(mesh, P(batch_axis, None))),
            jax.device_put(labels, NamedSharding(mesh, P(batch_axis))),
            jax.device_put(mask, NamedSharding(mesh, P(batch_axis))),
        )

    return (train_step, sharded_params, opt, opt_state, shard_batch_fn,
            gr_state0)


# ---------------------------------------------------------------------------
# kernel-registry entry: op ``widedeep_scores`` (stage convention) — the
# chain-terminal sigmoid(forward) plan shared by offline transform,
# fused pipelines, and the serving executor.
# ---------------------------------------------------------------------------

def _register_widedeep_kernels() -> None:
    from ...kernels.registry import register_kernel

    register_kernel("widedeep_scores", "xla", _widedeep_chain_kernel,
                    convention="stage")


_register_widedeep_kernels()
