"""Wide&Deep two-tower recommender (BASELINE.json stretch config 5).

Not present in the reference (its iteration runtime was never stretched to
DNNs — that's the point of this config): a wide linear tower over
categorical ids + dense features, and a deep tower of embeddings + MLP,
trained jointly with Adam on binary cross-entropy.

TPU-native design:
- one stacked embedding table ``(total_vocab, emb_dim)`` — lookups are a
  single gather, MXU-friendly; per-field vocabularies are offset into it
- the whole multi-epoch training loop is fused (``iterate`` + inner
  ``lax.scan`` over mini-batches), parameters and optimizer state live in
  HBM between epochs
- sharding: batch over the mesh's ``data`` axis; with a ``model`` axis the
  embedding dim and MLP hidden dims shard over it (tensor parallelism) —
  see ``build_sharded_train_step`` which __graft_entry__ dry-runs multichip
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...api.stage import Estimator, Model
from ...data.table import Table
from ...iteration import IterationBodyResult, IterationConfig, iterate
from ...params.param import (
    FloatParam,
    IntArrayParam,
    IntParam,
    ParamValidators,
    StringParam,
)
from ...params.shared import (
    HasGlobalBatchSize,
    HasLabelCol,
    HasMaxIter,
    HasPredictionCol,
    HasRawPredictionCol,
    HasSeed,
)
from ...parallel.mesh import default_mesh, replicate
from ...utils import persist
from ..common.losses import logistic_loss
from ..common.sgd import (
    DEFAULT_GLOBAL_BATCH,
    plan_epoch_layout,
    prepare_epoch_tensor,
)

__all__ = ["WideDeep", "WideDeepModel", "WideDeepParams"]


class WideDeepParams(HasLabelCol, HasPredictionCol, HasRawPredictionCol,
                     HasMaxIter, HasGlobalBatchSize, HasSeed):
    DENSE_FEATURES_COL = StringParam(
        "denseFeaturesCol", "Dense feature matrix column.",
        default="denseFeatures")
    CAT_FEATURES_COL = StringParam(
        "catFeaturesCol", "Categorical id matrix column (int).",
        default="catFeatures")
    VOCAB_SIZES = IntArrayParam(
        "vocabSizes", "Vocabulary size per categorical field.",
        default=None, validator=lambda v: v is None or (len(v) > 0 and
                                                        all(s > 0 for s in v)))
    EMBEDDING_DIM = IntParam("embeddingDim", "Embedding width per field.",
                             default=8, validator=ParamValidators.gt(0))
    HIDDEN_UNITS = IntArrayParam("hiddenUnits", "Deep-tower MLP widths.",
                                 default=(64, 32))
    LEARNING_RATE = FloatParam("learningRate", "Adam learning rate.",
                               default=1e-2, validator=ParamValidators.gt(0))

    def get_vocab_sizes(self):
        return self.get(WideDeepParams.VOCAB_SIZES)

    def set_vocab_sizes(self, v):
        return self.set(WideDeepParams.VOCAB_SIZES, v)


def _field_offsets(vocab_sizes) -> np.ndarray:
    return np.concatenate([[0], np.cumsum(vocab_sizes)[:-1]]).astype(np.int32)


def init_params(rng: np.random.Generator, d_dense: int, vocab_sizes,
                emb_dim: int, hidden) -> Dict[str, Any]:
    total_vocab = int(np.sum(vocab_sizes))
    n_fields = len(vocab_sizes)
    deep_in = d_dense + n_fields * emb_dim
    layers = []
    fan_in = deep_in
    for h in list(hidden) + [1]:
        scale = np.sqrt(2.0 / fan_in)
        layers.append({
            "w": (rng.normal(size=(fan_in, h)) * scale).astype(np.float32),
            "b": np.zeros((h,), np.float32),
        })
        fan_in = h
    return {
        "wide_cat": np.zeros((total_vocab,), np.float32),
        "wide_dense": np.zeros((d_dense,), np.float32),
        "wide_b": np.zeros((), np.float32),
        "emb": (rng.normal(size=(total_vocab, emb_dim)) * 0.05
                ).astype(np.float32),
        "mlp": layers,
    }


def forward(params: Dict[str, Any], dense: jnp.ndarray,
            cat_ids: jnp.ndarray) -> jnp.ndarray:
    """Logits for a batch.  ``cat_ids`` are already offset into the stacked
    vocab (shape (batch, n_fields))."""
    wide = (dense @ params["wide_dense"]
            + jnp.sum(params["wide_cat"][cat_ids], axis=1)
            + params["wide_b"])
    emb = params["emb"][cat_ids]                      # (b, fields, emb)
    deep = jnp.concatenate(
        [dense, emb.reshape(emb.shape[0], -1)], axis=1)
    for i, layer in enumerate(params["mlp"]):
        deep = deep @ layer["w"] + layer["b"]
        if i + 1 < len(params["mlp"]):
            deep = jax.nn.relu(deep)
    return wide + deep[:, 0]


def bce_loss(params, dense, cat_ids, labels, mask):
    # Identical to the linear family's masked binary log-loss — one shared
    # implementation of the {0,1}->±1 softplus form and padding epsilon.
    return logistic_loss(forward(params, dense, cat_ids), labels, mask)


def _validate_cat_ids(cat: np.ndarray, vocab_sizes) -> np.ndarray:
    """Range-check raw per-field ids, then offset into the stacked vocab.
    Both fit() and transform() go through here: a jitted gather silently
    CLAMPS out-of-range indices, so serving an unseen id would otherwise
    return another field's embedding with no error."""
    if cat.shape[1] != len(vocab_sizes):
        raise ValueError(
            f"catFeatures has {cat.shape[1]} fields, vocabSizes has "
            f"{len(vocab_sizes)}")
    if np.any(cat < 0) or np.any(cat >= np.asarray(vocab_sizes)[None, :]):
        raise ValueError("categorical id out of vocab range")
    return cat + _field_offsets(vocab_sizes)[None, :]


class WideDeep(WideDeepParams, Estimator["WideDeepModel"]):
    """fit(table with denseFeatures (n,d) float, catFeatures (n,f) int,
    label (n,) {0,1})."""

    def fit(self, *inputs) -> "WideDeepModel":
        (table,) = inputs
        vocab_sizes = self.get_vocab_sizes()
        if vocab_sizes is None:
            raise ValueError("WideDeep requires vocabSizes to be set")
        mesh = default_mesh()
        n_dev = int(mesh.shape["data"])

        dense = np.asarray(table[self.DENSE_FEATURES_COL],
                           np.float32)
        cat = np.asarray(table[self.CAT_FEATURES_COL], np.int32)
        labels = np.asarray(table[self.get_label_col()], np.float32)
        cat = _validate_cat_ids(cat, vocab_sizes)

        n = dense.shape[0]
        steps, batch, perm = plan_epoch_layout(
            n, self.get_global_batch_size() or DEFAULT_GLOBAL_BATCH, n_dev,
            self.get_seed())

        def layout(arr):
            return prepare_epoch_tensor(arr, perm, steps, batch)

        mask = layout(np.ones((n,), np.float32))
        X = layout(dense)
        C = layout(cat)
        y = layout(labels)

        bsh = NamedSharding(mesh, P(None, "data"))
        X = jax.device_put(X, NamedSharding(mesh, P(None, "data", None)))
        C = jax.device_put(C, NamedSharding(mesh, P(None, "data", None)))
        y, mask = jax.device_put(y, bsh), jax.device_put(mask, bsh)

        rng = np.random.default_rng(self.get_seed() + 1)  # init-draw stream
        params = replicate(
            init_params(rng, dense.shape[1], vocab_sizes,
                        self.EMBEDDING_DIM,
                        self.HIDDEN_UNITS), mesh)
        opt = optax.adam(self.LEARNING_RATE)
        opt_state = replicate(opt.init(params), mesh)
        grad_fn = jax.value_and_grad(bce_loss)

        def epoch_body(state, epoch, data):
            Xd, Cd, yd, md = data
            params, opt_state, loss_log = state

            def batch_step(carry, i):
                params, opt_state = carry
                loss, grads = grad_fn(params, Xd[i], Cd[i], yd[i], md[i])
                updates, opt_state = opt.update(grads, opt_state, params)
                return (optax.apply_updates(params, updates), opt_state), loss

            (params, opt_state), losses = jax.lax.scan(
                batch_step, (params, opt_state),
                jnp.arange(steps, dtype=jnp.int32))
            loss_log = loss_log.at[epoch].set(jnp.mean(losses))
            return IterationBodyResult((params, opt_state, loss_log))

        max_epochs = self.get_max_iter()
        init_state = (params, opt_state,
                      jnp.full((max_epochs,), jnp.nan, jnp.float32))
        result = iterate(epoch_body, init_state, (X, C, y, mask),
                         max_epochs=max_epochs,
                         config=IterationConfig(mode="fused"))
        fitted, _, loss_buf = result.state

        model = WideDeepModel()
        model.copy_params_from(self)
        model._params = jax.device_get(fitted)
        model._vocab_sizes = tuple(int(v) for v in vocab_sizes)
        model._loss_log = list(np.asarray(jax.device_get(loss_buf)))
        return model

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)

    @classmethod
    def load(cls, path: str) -> "WideDeep":
        return persist.load_stage_param(path)


@jax.jit
def _jit_scores(params, dense, cat_ids):
    return jax.nn.sigmoid(forward(params, dense, cat_ids))


class WideDeepModel(WideDeepParams, Model):
    def __init__(self):
        super().__init__()
        self._params: Optional[Dict[str, Any]] = None
        self._vocab_sizes: Optional[Tuple[int, ...]] = None
        self._loss_log: List[float] = []

    def _require_model(self):
        if self._params is None:
            raise RuntimeError("WideDeepModel has no model data")

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        dense = np.asarray(table[self.DENSE_FEATURES_COL],
                           np.float32)
        cat = np.asarray(table[self.CAT_FEATURES_COL], np.int32)
        cat = _validate_cat_ids(cat, self._vocab_sizes)
        scores = np.asarray(_jit_scores(self._params, dense, cat), np.float64)
        out = table.with_column(self.get_raw_prediction_col(), scores)
        out = out.with_column(self.get_prediction_col(),
                              (scores > 0.5).astype(np.int64))
        return [out]

    # -- persistence --------------------------------------------------------
    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(
            self, path, {"vocabSizes": list(self._vocab_sizes)})
        flat = {"wide_cat": self._params["wide_cat"],
                "wide_dense": self._params["wide_dense"],
                "wide_b": self._params["wide_b"],
                "emb": self._params["emb"]}
        for i, layer in enumerate(self._params["mlp"]):
            flat[f"mlp_{i}_w"] = layer["w"]
            flat[f"mlp_{i}_b"] = layer["b"]
        persist.save_model_arrays(path, "model", flat)

    @classmethod
    def load(cls, path: str) -> "WideDeepModel":
        model = persist.load_stage_param(path)
        meta = persist.load_metadata(path)
        data = persist.load_model_arrays(path, "model")
        n_layers = sum(1 for k in data if k.endswith("_w"))
        model._params = {
            "wide_cat": data["wide_cat"],
            "wide_dense": data["wide_dense"],
            "wide_b": data["wide_b"],
            "emb": data["emb"],
            "mlp": [{"w": data[f"mlp_{i}_w"], "b": data[f"mlp_{i}_b"]}
                    for i in range(n_layers)],
        }
        model._vocab_sizes = tuple(meta["vocabSizes"])
        return model


def build_reference_train_step(d_dense: int, vocab_sizes, emb_dim: int,
                               hidden, lr: float = 1e-2):
    """The unsharded single-device oracle for :func:`build_sharded_train_step`
    — SAME init seed (0), optimizer, and loss, no shardings anywhere.
    Returns (train_step, params, opt_state).  The dp x tp step must
    reproduce this one allclose on loss AND updated params (a wrong
    psum/axis placement still converges, so only exact equivalence catches
    it); asserted by tests/test_widedeep.py and __graft_entry__'s multichip
    dryrun."""
    params = jax.tree_util.tree_map(
        jnp.asarray,
        init_params(np.random.default_rng(0), d_dense, vocab_sizes, emb_dim,
                    hidden))
    opt = optax.adam(lr)
    opt_state = opt.init(params)
    grad_fn = jax.value_and_grad(bce_loss)

    @jax.jit
    def train_step(params, opt_state, dense, cat_ids, labels, mask):
        loss, grads = grad_fn(params, dense, cat_ids, labels, mask)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return train_step, params, opt_state


def assert_sharded_matches_reference(sharded_params, sharded_loss,
                                     ref_params, ref_loss) -> None:
    """Allclose on loss and every param leaf (f32 tolerances: cross-device
    reduction order differs from the single-device program)."""
    np.testing.assert_allclose(float(np.asarray(sharded_loss)),
                               float(np.asarray(ref_loss)),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(sharded_params)),
                    jax.tree_util.tree_leaves(jax.device_get(ref_params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def build_sharded_train_step(mesh, d_dense: int, vocab_sizes, emb_dim: int,
                             hidden, lr: float = 1e-2):
    """A dp x tp training step for the multichip dry run: embeddings and MLP
    hidden dims sharded over 'model', batch over 'data'.  Returns
    (train_step, sharded_params, opt, sharded_opt_state, shard_batch_fn)."""
    rng = np.random.default_rng(0)
    params = init_params(rng, d_dense, vocab_sizes, emb_dim, hidden)

    def param_spec(path_params):
        specs = {
            "wide_cat": P(), "wide_dense": P(), "wide_b": P(),
            "emb": P(None, "model"),
        }
        mlp_specs = []
        n = len(path_params["mlp"])
        for i in range(n):
            # Megatron-style pairing: even layers column-parallel (outputs
            # sharded over 'model'), odd layers row-parallel (inputs sharded;
            # XLA inserts the psum that gathers activations back).
            if i % 2 == 0 and i + 1 < n:
                mlp_specs.append({"w": P(None, "model"), "b": P("model")})
            elif i % 2 == 1:
                mlp_specs.append({"w": P("model", None), "b": P()})
            else:  # final (or only) layer: replicated scalar head
                mlp_specs.append({"w": P(), "b": P()})
        return {**{k: specs[k] for k in specs}, "mlp": mlp_specs}

    specs = param_spec(params)
    sharded_params = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda x: isinstance(x, np.ndarray))

    opt = optax.adam(lr)
    opt_state = opt.init(sharded_params)
    grad_fn = jax.value_and_grad(bce_loss)

    @jax.jit
    def train_step(params, opt_state, dense, cat_ids, labels, mask):
        loss, grads = grad_fn(params, dense, cat_ids, labels, mask)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def shard_batch_fn(dense, cat_ids, labels, mask):
        return (
            jax.device_put(dense, NamedSharding(mesh, P("data", None))),
            jax.device_put(cat_ids, NamedSharding(mesh, P("data", None))),
            jax.device_put(labels, NamedSharding(mesh, P("data"))),
            jax.device_put(mask, NamedSharding(mesh, P("data"))),
        )

    return train_step, sharded_params, opt, opt_state, shard_batch_fn
