"""Algorithm library — estimators, models, feature stages, evaluators."""

from .classification import (  # noqa: F401
    GBTClassifier,
    GBTClassifierModel,
    KNNClassifier,
    KNNClassifierModel,
    LinearSVC,
    LinearSVCModel,
    LogisticRegression,
    LogisticRegressionModel,
    NaiveBayes,
    NaiveBayesModel,
    OnlineLogisticRegression,
    OnlineLogisticRegressionModel,
    SoftmaxRegression,
    SoftmaxRegressionModel,
)
from .clustering import (  # noqa: F401
    AgglomerativeClustering,
    KMeans,
    KMeansModel,
    OnlineKMeans,
    OnlineKMeansModel,
)
from .evaluation import (  # noqa: F401
    BinaryClassificationEvaluator,
    MulticlassClassificationEvaluator,
)
from .feature import (  # noqa: F401
    Binarizer,
    Bucketizer,
    Imputer,
    ImputerModel,
    MaxAbsScaler,
    MaxAbsScalerModel,
    MinMaxScaler,
    MinMaxScalerModel,
    Normalizer,
    OneHotEncoder,
    OneHotEncoderModel,
    OnlineStandardScaler,
    OnlineStandardScalerModel,
    PolynomialExpansion,
    RobustScaler,
    RobustScalerModel,
    StandardScaler,
    StandardScalerModel,
    StringIndexer,
    StringIndexerModel,
    VectorAssembler,
)
from .recommendation import ALS, ALSModel, WideDeep, WideDeepModel  # noqa: F401
from .stats import ChiSqTest  # noqa: F401
from .regression import (  # noqa: F401
    GBTRegressor,
    GBTRegressorModel,
    LinearRegression,
    LinearRegressionModel,
)
