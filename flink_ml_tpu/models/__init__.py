"""Algorithm library — estimators, models, feature stages, evaluators."""

from .classification import (  # noqa: F401
    LinearSVC,
    LinearSVCModel,
    LogisticRegression,
    LogisticRegressionModel,
    NaiveBayes,
    NaiveBayesModel,
    OnlineLogisticRegression,
    OnlineLogisticRegressionModel,
)
from .clustering import (  # noqa: F401
    KMeans,
    KMeansModel,
    OnlineKMeans,
    OnlineKMeansModel,
)
from .evaluation import BinaryClassificationEvaluator  # noqa: F401
from .feature import (  # noqa: F401
    MinMaxScaler,
    MinMaxScalerModel,
    OneHotEncoder,
    OneHotEncoderModel,
    StandardScaler,
    StandardScalerModel,
    StringIndexer,
    StringIndexerModel,
    VectorAssembler,
)
from .recommendation import WideDeep, WideDeepModel  # noqa: F401
from .regression import LinearRegression, LinearRegressionModel  # noqa: F401
