"""Vector-shaping transformers: VectorSlicer, ElementwiseProduct,
Interaction, DCT, plus the fitted KBinsDiscretizer and VectorIndexer.

All are members of the Flink ML 2.x feature-engineering surface (the
reference snapshot's lib module is KMeans-only — SURVEY §2.8 — but the
library line these mirror ships them).  The dense row-wise math (DCT
matmul, interaction outer products, elementwise scaling) runs as jitted
XLA ops so batches land on the MXU; the index-learning estimators
(KBinsDiscretizer, VectorIndexer) compute their per-column statistics on
host in float64 where exact comparisons matter.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator, Model, Transformer
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.param import (
    BoolParam,
    DoubleArrayParam,
    IntArrayParam,
    IntParam,
    ParamValidators,
    StringParam,
)
from ...params.shared import HasInputCols, HasOutputCol, HasSeed
from ...utils import persist
from .transforms import _InOutParams, _SimpleTransformer

__all__ = [
    "DCT",
    "ElementwiseProduct",
    "Interaction",
    "KBinsDiscretizer",
    "KBinsDiscretizerModel",
    "VectorIndexer",
    "VectorIndexerModel",
    "VectorSlicer",
]


class VectorSlicer(_SimpleTransformer):
    """Select a sub-vector of the input by index list (order-preserving,
    duplicates allowed — the Flink ML VectorSlicer contract requires
    non-negative indices within bounds)."""

    INDICES = IntArrayParam(
        "indices", "Indices of the features to keep (non-negative).",
        default=None, validator=ParamValidators.not_null())

    def get_indices(self):
        return self.get(VectorSlicer.INDICES)

    def set_indices(self, *values: int):
        vals = values[0] if len(values) == 1 and not np.isscalar(values[0]) \
            else values
        return self.set(VectorSlicer.INDICES, tuple(int(v) for v in vals))

    def _apply(self, X: np.ndarray) -> np.ndarray:
        idx = np.asarray(self.get_indices(), np.int64)
        if idx.size == 0:
            raise ValueError("VectorSlicer needs at least one index")
        if np.any(idx < 0) or np.any(idx >= X.shape[1]):
            raise ValueError(
                f"VectorSlicer index out of range for dim {X.shape[1]}: "
                f"{idx[(idx < 0) | (idx >= X.shape[1])][0]}")
        return X[:, idx]


class ElementwiseProduct(_SimpleTransformer):
    """Hadamard product of each row with a fixed scaling vector."""

    SCALING_VEC = DoubleArrayParam(
        "scalingVec", "The vector to multiply with.", default=None,
        validator=ParamValidators.not_null())

    def get_scaling_vec(self):
        return self.get(ElementwiseProduct.SCALING_VEC)

    def set_scaling_vec(self, *values: float):
        vals = values[0] if len(values) == 1 and not np.isscalar(values[0]) \
            else values
        return self.set(ElementwiseProduct.SCALING_VEC,
                        tuple(float(v) for v in vals))

    def _apply(self, X: np.ndarray) -> np.ndarray:
        scale = np.asarray(self.get_scaling_vec(), np.float64)
        if scale.shape[0] != X.shape[1]:
            raise ValueError(
                f"scalingVec has dim {scale.shape[0]}, input rows have "
                f"dim {X.shape[1]}")
        return X * scale[None, :]


class Interaction(HasInputCols, HasOutputCol, Transformer):
    """Row-wise tensor (outer) product of the input columns, flattened.

    For input vectors ``a (da,), b (db,), c (dc,)`` the output row is the
    flattened ``da*db*dc`` product tensor with the LAST input varying
    fastest — the nested-loop order of the Flink ML / Spark Interaction.
    Scalar (1-D) columns are treated as length-1 vectors.  The whole batch
    is one jitted chain of broadcasted multiplies.
    """

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        cols = self.get_input_cols()
        if not cols or len(cols) < 2:
            raise ValueError("Interaction needs >= 2 input columns")
        mats = []
        for name in cols:
            arr = np.asarray(table[name], np.float64)
            if arr.ndim == 1:
                arr = arr[:, None]
            mats.append(jnp.asarray(arr, jnp.float32))
        out = np.asarray(_interact(tuple(mats)))
        return [table.with_column(self.get_output_col(), out)]


@jax.jit
def _interact(mats):
    acc = mats[0]                                   # (n, d0)
    for m in mats[1:]:
        # (n, da, 1) * (n, 1, db) -> (n, da, db) -> (n, da*db)
        acc = (acc[:, :, None] * m[:, None, :]).reshape(acc.shape[0], -1)
    return acc


class DCT(_SimpleTransformer):
    """Orthonormal 1-D DCT-II of each row (``inverse=True`` applies the
    DCT-III inverse).  Implemented as one (n, d) @ (d, d) matmul so the
    whole batch rides the MXU — for feature-sized d the cosine matrix is
    tiny and XLA keeps it resident."""

    INVERSE = BoolParam("inverse", "Apply the inverse transform (DCT-III).",
                        default=False)

    def get_inverse(self) -> bool:
        return self.get(DCT.INVERSE)

    def set_inverse(self, value: bool):
        return self.set(DCT.INVERSE, bool(value))

    @staticmethod
    def _matrix(d: int) -> np.ndarray:
        # C[k, n] = s_k * sqrt(2/d) * cos(pi * (2n + 1) * k / (2d)),
        # s_0 = 1/sqrt(2): the orthonormal DCT-II basis (C @ C.T = I).
        n = np.arange(d)
        k = np.arange(d)[:, None]
        C = np.sqrt(2.0 / d) * np.cos(np.pi * (2 * n[None, :] + 1) * k
                                      / (2.0 * d))
        C[0] /= np.sqrt(2.0)
        return C

    def _apply(self, X: np.ndarray) -> np.ndarray:
        C = self._matrix(X.shape[1])
        return np.asarray(_dct_apply(jnp.asarray(X, jnp.float32),
                                     jnp.asarray(C, jnp.float32),
                                     self.get_inverse()))


@partial(jax.jit, static_argnums=(2,))
def _dct_apply(X, C, inverse):
    # orthonormal => inverse is the transpose
    return X @ (C if inverse else C.T)


# ---------------------------------------------------------------------------
# KBinsDiscretizer
# ---------------------------------------------------------------------------

class KBinsDiscretizerParams(_InOutParams, HasSeed):
    NUM_BINS = IntParam("numBins", "Number of bins per column.", default=5,
                        validator=ParamValidators.gt_eq(2))
    STRATEGY = StringParam(
        "strategy", "Bin-edge strategy: uniform | quantile | kmeans.",
        default="quantile",
        validator=ParamValidators.in_array(["uniform", "quantile", "kmeans"]))
    SUB_SAMPLES = IntParam(
        "subSamples", "Max rows sampled for edge fitting (<=0: use all).",
        default=200_000)

    def get_num_bins(self) -> int:
        return self.get(KBinsDiscretizerParams.NUM_BINS)

    def set_num_bins(self, value: int):
        return self.set(KBinsDiscretizerParams.NUM_BINS, value)

    def get_strategy(self) -> str:
        return self.get(KBinsDiscretizerParams.STRATEGY)

    def set_strategy(self, value: str):
        return self.set(KBinsDiscretizerParams.STRATEGY, value)

    def get_sub_samples(self) -> int:
        return self.get(KBinsDiscretizerParams.SUB_SAMPLES)

    def set_sub_samples(self, value: int):
        return self.set(KBinsDiscretizerParams.SUB_SAMPLES, value)


def _kmeans_1d_edges(col: np.ndarray, k: int, iters: int = 25) -> np.ndarray:
    """1-D Lloyd's on a sorted column; edges are midpoints between adjacent
    final centroids (the KBinsDiscretizer 'kmeans' strategy)."""
    uniq = np.unique(col)
    if len(uniq) <= k:
        # one bin per distinct value: edges at midpoints
        mids = (uniq[1:] + uniq[:-1]) / 2.0
        return np.concatenate([[col.min()], mids, [col.max()]])
    centers = np.quantile(col, (np.arange(k) + 0.5) / k)
    for _ in range(iters):
        # 1-D assignment = searchsorted against boundary midpoints
        bounds = (centers[1:] + centers[:-1]) / 2.0
        assign = np.searchsorted(bounds, col)
        sums = np.bincount(assign, weights=col, minlength=k)
        counts = np.bincount(assign, minlength=k)
        nonempty = counts > 0
        new = centers.copy()
        new[nonempty] = sums[nonempty] / counts[nonempty]
        if np.allclose(new, centers):
            centers = new
            break
        centers = new
    mids = (np.sort(centers)[1:] + np.sort(centers)[:-1]) / 2.0
    return np.concatenate([[col.min()], mids, [col.max()]])


class KBinsDiscretizerModel(KBinsDiscretizerParams, Model):
    """Buckets each column by its learned edges; out-of-range values clamp
    into the first/last bin (the Flink ML KBinsDiscretizerModel behavior)."""

    def __init__(self):
        super().__init__()
        self._edges: Optional[np.ndarray] = None   # (d, max_edges) +inf pad
        self._n_edges: Optional[np.ndarray] = None  # (d,) valid counts

    def set_model_data(self, *inputs) -> "KBinsDiscretizerModel":
        (t,) = inputs
        self._edges = np.asarray(t["edges"], np.float64)
        self._n_edges = np.asarray(t["n_edges"], np.int64)
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"edges": self._edges, "n_edges": self._n_edges})]

    def _require_model(self) -> None:
        if self._edges is None:
            raise RuntimeError("KBinsDiscretizerModel has no model data")

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        out = np.empty_like(X)
        for j in range(X.shape[1]):
            edges = self._edges[j, : self._n_edges[j]]
            # interior edges only: clamping outer values into first/last bin
            idx = np.searchsorted(edges[1:-1], X[:, j], side="right")
            out[:, j] = idx
        return [table.with_column(self.get_output_col(), out)]

    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {
            "edges": self._edges, "n_edges": self._n_edges})

    @classmethod
    def load(cls, path: str) -> "KBinsDiscretizerModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._edges = data["edges"].astype(np.float64)
        model._n_edges = data["n_edges"].astype(np.int64)
        return model


class KBinsDiscretizer(KBinsDiscretizerParams,
                       Estimator[KBinsDiscretizerModel]):
    """Learns per-column bin edges.  ``quantile`` collapses duplicate
    quantile edges (fewer effective bins on skewed data, same as the Flink
    ML implementation); ``uniform`` spaces bins over [min, max]; ``kmeans``
    runs 1-D Lloyd's per column and cuts at centroid midpoints."""

    def fit(self, *inputs) -> KBinsDiscretizerModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        sub = self.get_sub_samples()
        if 0 < sub < X.shape[0]:
            sel = np.random.default_rng(self.get_seed()).choice(
                X.shape[0], sub, replace=False)
            X = X[sel]
        k = self.get_num_bins()
        strategy = self.get_strategy()
        per_col: List[np.ndarray] = []
        for j in range(X.shape[1]):
            col = X[:, j]
            if col.min() == col.max():
                # constant column: one [min, min+1) bin for EVERY strategy
                # (uniform's linspace would yield k+1 identical edges and
                # searchsorted would bucket everything into bin k-1)
                edges = np.array([col.min(), col.max() + 1.0])
            elif strategy == "uniform":
                edges = np.linspace(col.min(), col.max(), k + 1)
            elif strategy == "quantile":
                edges = np.unique(np.quantile(col, np.linspace(0, 1, k + 1)))
            else:
                edges = _kmeans_1d_edges(col, k)
            per_col.append(edges)

        max_e = max(len(e) for e in per_col)
        edges = np.full((X.shape[1], max_e), np.inf)
        n_edges = np.zeros(X.shape[1], np.int64)
        for j, e in enumerate(per_col):
            edges[j, : len(e)] = e
            n_edges[j] = len(e)

        model = KBinsDiscretizerModel()
        model.copy_params_from(self)
        model._edges = edges
        model._n_edges = n_edges
        return model


# ---------------------------------------------------------------------------
# VectorIndexer
# ---------------------------------------------------------------------------

class VectorIndexerParams(_InOutParams):
    MAX_CATEGORIES = IntParam(
        "maxCategories",
        "Columns with more distinct values than this stay continuous.",
        default=20, validator=ParamValidators.gt_eq(2))
    HANDLE_INVALID = StringParam(
        "handleInvalid", "Unseen categorical values: error | skip | keep.",
        default="error",
        validator=ParamValidators.in_array(["error", "skip", "keep"]))

    def get_max_categories(self) -> int:
        return self.get(VectorIndexerParams.MAX_CATEGORIES)

    def set_max_categories(self, value: int):
        return self.set(VectorIndexerParams.MAX_CATEGORIES, value)

    def get_handle_invalid(self) -> str:
        return self.get(VectorIndexerParams.HANDLE_INVALID)

    def set_handle_invalid(self, value: str):
        return self.set(VectorIndexerParams.HANDLE_INVALID, value)


class VectorIndexerModel(VectorIndexerParams, Model):
    """Maps each categorical column's values to indices in ascending value
    order; columns whose distinct count exceeded ``maxCategories`` at fit
    time pass through unchanged.  Unseen values at transform time follow
    ``handleInvalid``: error raises, skip drops the row, keep maps to the
    extra index ``numCategories``."""

    def __init__(self):
        super().__init__()
        self._values: Optional[np.ndarray] = None   # (d, max_vals) NaN pad
        self._n_values: Optional[np.ndarray] = None  # (d,) -1 => continuous

    def set_model_data(self, *inputs) -> "VectorIndexerModel":
        (t,) = inputs
        self._values = np.asarray(t["values"], np.float64)
        self._n_values = np.asarray(t["n_values"], np.int64)
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"values": self._values, "n_values": self._n_values})]

    def _require_model(self) -> None:
        if self._values is None:
            raise RuntimeError("VectorIndexerModel has no model data")

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        out = X.copy()
        invalid_rows = np.zeros(X.shape[0], bool)
        policy = self.get_handle_invalid()
        for j in range(X.shape[1]):
            n = self._n_values[j]
            if n < 0:           # continuous column: passthrough
                continue
            vals = self._values[j, :n]
            pos = np.searchsorted(vals, X[:, j])
            pos_c = np.clip(pos, 0, n - 1)
            hit = vals[pos_c] == X[:, j]
            if not np.all(hit):
                if policy == "error":
                    bad = X[:, j][~hit][0]
                    raise ValueError(
                        f"VectorIndexer saw unseen value {bad} in column {j}"
                        "; set handleInvalid to 'keep' or 'skip'")
                invalid_rows |= ~hit
            out[:, j] = np.where(hit, pos_c, float(n))
        result = table.with_column(self.get_output_col(), out)
        if policy == "skip" and np.any(invalid_rows):
            result = result.select_rows(np.flatnonzero(~invalid_rows))
        return [result]

    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {
            "values": self._values, "n_values": self._n_values})

    @classmethod
    def load(cls, path: str) -> "VectorIndexerModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._values = data["values"].astype(np.float64)
        model._n_values = data["n_values"].astype(np.int64)
        return model


class VectorIndexer(VectorIndexerParams, Estimator[VectorIndexerModel]):
    def fit(self, *inputs) -> VectorIndexerModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        max_cat = self.get_max_categories()
        per_col: List[Optional[np.ndarray]] = []
        for j in range(X.shape[1]):
            uniq = np.unique(X[:, j])
            per_col.append(uniq if len(uniq) <= max_cat else None)

        max_v = max((len(v) for v in per_col if v is not None), default=1)
        values = np.full((X.shape[1], max_v), np.nan)
        n_values = np.full(X.shape[1], -1, np.int64)
        for j, v in enumerate(per_col):
            if v is not None:
                values[j, : len(v)] = v
                n_values[j] = len(v)

        model = VectorIndexerModel()
        model.copy_params_from(self)
        model._values = values
        model._n_values = n_values
        return model
