"""Vector-shaping transformers: VectorSlicer, ElementwiseProduct,
Interaction, DCT, plus the fitted KBinsDiscretizer and VectorIndexer.

All are members of the Flink ML 2.x feature-engineering surface (the
reference snapshot's lib module is KMeans-only — SURVEY §2.8 — but the
library line these mirror ships them).  The dense row-wise math (DCT
matmul, interaction outer products, elementwise scaling) runs as jitted
XLA ops so batches land on the MXU; the index-learning estimators
(KBinsDiscretizer, VectorIndexer) compute their per-column statistics on
host in float64 where exact comparisons matter.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.chain import (StageKernel, as_matrix as _mat, f32_ceil,
                          numeric_entry)
from ...api.stage import Estimator, Model, Transformer
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.param import (
    BoolParam,
    DoubleArrayParam,
    IntArrayParam,
    IntParam,
    ParamValidators,
    StringParam,
)
from ...params.shared import HasInputCols, HasOutputCol, HasSeed
from ...utils import persist
from .transforms import _InOutParams, _SimpleTransformer

__all__ = [
    "DCT",
    "ElementwiseProduct",
    "Interaction",
    "KBinsDiscretizer",
    "KBinsDiscretizerModel",
    "VectorIndexer",
    "VectorIndexerModel",
    "VectorSlicer",
]


class VectorSlicer(_SimpleTransformer):
    """Select a sub-vector of the input by index list (order-preserving,
    duplicates allowed — the Flink ML VectorSlicer contract requires
    non-negative indices within bounds)."""

    INDICES = IntArrayParam(
        "indices", "Indices of the features to keep (non-negative).",
        default=None, validator=ParamValidators.not_null())

    def get_indices(self):
        return self.get(VectorSlicer.INDICES)

    def set_indices(self, *values: int):
        vals = values[0] if len(values) == 1 and not np.isscalar(values[0]) \
            else values
        return self.set(VectorSlicer.INDICES, tuple(int(v) for v in vals))

    def _apply(self, X: np.ndarray) -> np.ndarray:
        idx = np.asarray(self.get_indices(), np.int64)
        if idx.size == 0:
            raise ValueError("VectorSlicer needs at least one index")
        if np.any(idx < 0) or np.any(idx >= X.shape[1]):
            raise ValueError(
                f"VectorSlicer index out of range for dim {X.shape[1]}: "
                f"{idx[(idx < 0) | (idx >= X.shape[1])][0]}")
        return X[:, idx]

    def transform_kernel(self, schema):
        entry = numeric_entry(schema, self.get_features_col())
        if entry is None or not entry[0]:
            return None
        idx = np.asarray(self.get_indices() or (), np.int64)
        if idx.size == 0 or np.any(idx < 0) or np.any(idx >= entry[0][0]):
            return None      # stagewise raises the diagnostic error
        return StageKernel(
            fn=_gather_cols_kernel,
            static=(self.get_features_col(), self.get_output_col()),
            params={"idx": idx.astype(np.int32)},
            consumes=(self.get_features_col(),),
            produces=(self.get_output_col(),))


class ElementwiseProduct(_SimpleTransformer):
    """Hadamard product of each row with a fixed scaling vector."""

    SCALING_VEC = DoubleArrayParam(
        "scalingVec", "The vector to multiply with.", default=None,
        validator=ParamValidators.not_null())

    def get_scaling_vec(self):
        return self.get(ElementwiseProduct.SCALING_VEC)

    def set_scaling_vec(self, *values: float):
        vals = values[0] if len(values) == 1 and not np.isscalar(values[0]) \
            else values
        return self.set(ElementwiseProduct.SCALING_VEC,
                        tuple(float(v) for v in vals))

    def _apply(self, X: np.ndarray) -> np.ndarray:
        scale = np.asarray(self.get_scaling_vec(), np.float64)
        if scale.shape[0] != X.shape[1]:
            raise ValueError(
                f"scalingVec has dim {scale.shape[0]}, input rows have "
                f"dim {X.shape[1]}")
        return X * scale[None, :]

    def transform_kernel(self, schema):
        entry = numeric_entry(schema, self.get_features_col())
        if entry is None:
            return None
        scale = np.asarray(self.get_scaling_vec() or (), np.float64)
        d = int(entry[0][0]) if entry[0] else 1
        if scale.shape[0] != d:
            return None      # stagewise raises the diagnostic error
        return StageKernel(
            fn=_elementwise_product_kernel,
            static=(self.get_features_col(), self.get_output_col()),
            params={"scale": scale.astype(np.float32)},
            consumes=(self.get_features_col(),),
            produces=(self.get_output_col(),))

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        from ...api.chain import apply_kernel_or_none

        fetched = apply_kernel_or_none(
            self.transform_kernel(table.schema()), table)
        if fetched is None:     # object/mismatched/f32-unsafe: host path
            return super().transform(*inputs)
        out = fetched[self.get_output_col()]
        return [table.with_column(self.get_output_col(), out)]


def _gather_cols_kernel(static, params, cols):
    (fcol, ocol) = static
    return {ocol: _mat(cols[fcol])[:, params["idx"]]}


def _elementwise_product_kernel(static, params, cols):
    (fcol, ocol) = static
    return {ocol: _mat(cols[fcol]) * params["scale"][None, :]}


class Interaction(HasInputCols, HasOutputCol, Transformer):
    """Row-wise tensor (outer) product of the input columns, flattened.

    For input vectors ``a (da,), b (db,), c (dc,)`` the output row is the
    flattened ``da*db*dc`` product tensor with the LAST input varying
    fastest — the nested-loop order of the Flink ML / Spark Interaction.
    Scalar (1-D) columns are treated as length-1 vectors.  The whole batch
    is one jitted chain of broadcasted multiplies.
    """

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        cols = self.get_input_cols()
        if not cols or len(cols) < 2:
            raise ValueError("Interaction needs >= 2 input columns")
        mats = []
        for name in cols:
            arr = np.asarray(table[name], np.float64)
            if arr.ndim == 1:
                arr = arr[:, None]
            mats.append(jnp.asarray(arr, jnp.float32))
        out = np.asarray(_interact(tuple(mats)))
        return [table.with_column(self.get_output_col(), out)]

    def transform_kernel(self, schema):
        in_cols = self.get_input_cols()
        if not in_cols or len(in_cols) < 2:
            return None      # stagewise raises the diagnostic error
        for name in in_cols:
            if numeric_entry(schema, name) is None:
                return None
        return StageKernel(
            fn=_interaction_kernel,
            static=(tuple(in_cols), self.get_output_col()),
            params={},
            consumes=tuple(in_cols),
            produces=(self.get_output_col(),))


@jax.jit
def _interact(mats):
    acc = mats[0]                                   # (n, d0)
    for m in mats[1:]:
        # (n, da, 1) * (n, 1, db) -> (n, da, db) -> (n, da*db)
        acc = (acc[:, :, None] * m[:, None, :]).reshape(acc.shape[0], -1)
    return acc


def _interaction_kernel(static, params, cols):
    in_cols, ocol = static
    acc = _mat(cols[in_cols[0]]).astype(jnp.float32)
    for name in in_cols[1:]:
        m = _mat(cols[name]).astype(jnp.float32)
        acc = (acc[:, :, None] * m[:, None, :]).reshape(acc.shape[0], -1)
    return {ocol: acc}


class DCT(_SimpleTransformer):
    """Orthonormal 1-D DCT-II of each row (``inverse=True`` applies the
    DCT-III inverse).  Implemented as one (n, d) @ (d, d) matmul so the
    whole batch rides the MXU — for feature-sized d the cosine matrix is
    tiny and XLA keeps it resident."""

    INVERSE = BoolParam("inverse", "Apply the inverse transform (DCT-III).",
                        default=False)

    def get_inverse(self) -> bool:
        return self.get(DCT.INVERSE)

    def set_inverse(self, value: bool):
        return self.set(DCT.INVERSE, bool(value))

    @staticmethod
    def _matrix(d: int) -> np.ndarray:
        # C[k, n] = s_k * sqrt(2/d) * cos(pi * (2n + 1) * k / (2d)),
        # s_0 = 1/sqrt(2): the orthonormal DCT-II basis (C @ C.T = I).
        n = np.arange(d)
        k = np.arange(d)[:, None]
        C = np.sqrt(2.0 / d) * np.cos(np.pi * (2 * n[None, :] + 1) * k
                                      / (2.0 * d))
        C[0] /= np.sqrt(2.0)
        return C

    def _apply(self, X: np.ndarray) -> np.ndarray:
        C = self._matrix(X.shape[1])
        return np.asarray(_dct_apply(jnp.asarray(X, jnp.float32),
                                     jnp.asarray(C, jnp.float32),
                                     self.get_inverse()))

    def transform_kernel(self, schema):
        entry = numeric_entry(schema, self.get_features_col())
        if entry is None:
            return None
        d = int(entry[0][0]) if entry[0] else 1
        C = self._matrix(d).astype(np.float32)
        return StageKernel(
            fn=_dct_chain_kernel,
            static=(self.get_features_col(), self.get_output_col(),
                    bool(self.get_inverse())),
            params={"C": C},
            consumes=(self.get_features_col(),),
            produces=(self.get_output_col(),))


def _dct_chain_kernel(static, params, cols):
    (fcol, ocol, inverse) = static
    X = _mat(cols[fcol]).astype(jnp.float32)
    C = params["C"]
    return {ocol: X @ (C if inverse else C.T)}


@partial(jax.jit, static_argnums=(2,))
def _dct_apply(X, C, inverse):
    # orthonormal => inverse is the transpose
    return X @ (C if inverse else C.T)


# ---------------------------------------------------------------------------
# KBinsDiscretizer
# ---------------------------------------------------------------------------

class KBinsDiscretizerParams(_InOutParams, HasSeed):
    NUM_BINS = IntParam("numBins", "Number of bins per column.", default=5,
                        validator=ParamValidators.gt_eq(2))
    STRATEGY = StringParam(
        "strategy", "Bin-edge strategy: uniform | quantile | kmeans.",
        default="quantile",
        validator=ParamValidators.in_array(["uniform", "quantile", "kmeans"]))
    SUB_SAMPLES = IntParam(
        "subSamples", "Max rows sampled for edge fitting (<=0: use all).",
        default=200_000)

    def get_num_bins(self) -> int:
        return self.get(KBinsDiscretizerParams.NUM_BINS)

    def set_num_bins(self, value: int):
        return self.set(KBinsDiscretizerParams.NUM_BINS, value)

    def get_strategy(self) -> str:
        return self.get(KBinsDiscretizerParams.STRATEGY)

    def set_strategy(self, value: str):
        return self.set(KBinsDiscretizerParams.STRATEGY, value)

    def get_sub_samples(self) -> int:
        return self.get(KBinsDiscretizerParams.SUB_SAMPLES)

    def set_sub_samples(self, value: int):
        return self.set(KBinsDiscretizerParams.SUB_SAMPLES, value)


def _kmeans_1d_edges(col: np.ndarray, k: int, iters: int = 25) -> np.ndarray:
    """1-D Lloyd's on a sorted column; edges are midpoints between adjacent
    final centroids (the KBinsDiscretizer 'kmeans' strategy)."""
    uniq = np.unique(col)
    if len(uniq) <= k:
        # one bin per distinct value: edges at midpoints
        mids = (uniq[1:] + uniq[:-1]) / 2.0
        return np.concatenate([[col.min()], mids, [col.max()]])
    centers = np.quantile(col, (np.arange(k) + 0.5) / k)
    for _ in range(iters):
        # 1-D assignment = searchsorted against boundary midpoints
        bounds = (centers[1:] + centers[:-1]) / 2.0
        assign = np.searchsorted(bounds, col)
        sums = np.bincount(assign, weights=col, minlength=k)
        counts = np.bincount(assign, minlength=k)
        nonempty = counts > 0
        new = centers.copy()
        new[nonempty] = sums[nonempty] / counts[nonempty]
        if np.allclose(new, centers):
            centers = new
            break
        centers = new
    mids = (np.sort(centers)[1:] + np.sort(centers)[:-1]) / 2.0
    return np.concatenate([[col.min()], mids, [col.max()]])


class KBinsDiscretizerModel(KBinsDiscretizerParams, Model):
    """Buckets each column by its learned edges; out-of-range values clamp
    into the first/last bin (the Flink ML KBinsDiscretizerModel behavior)."""

    def __init__(self):
        super().__init__()
        self._edges: Optional[np.ndarray] = None   # (d, max_edges) +inf pad
        self._n_edges: Optional[np.ndarray] = None  # (d,) valid counts

    def set_model_data(self, *inputs) -> "KBinsDiscretizerModel":
        (t,) = inputs
        self._edges = np.asarray(t["edges"], np.float64)
        self._n_edges = np.asarray(t["n_edges"], np.int64)
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"edges": self._edges, "n_edges": self._n_edges})]

    def _require_model(self) -> None:
        if self._edges is None:
            raise RuntimeError("KBinsDiscretizerModel has no model data")

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        out = np.empty_like(X)
        for j in range(X.shape[1]):
            edges = self._edges[j, : self._n_edges[j]]
            # interior edges only: clamping outer values into first/last bin
            idx = np.searchsorted(edges[1:-1], X[:, j], side="right")
            out[:, j] = idx
        return [table.with_column(self.get_output_col(), out)]

    def transform_kernel(self, schema):
        """Learned edges are arbitrary f64 quantiles, so the kernel binning
        uses f32_ceil surrogates per interior edge: ``#{e <= v}`` counted
        against the surrogates is bit-exact with the host-f64 searchsorted
        for every f32 value ``v`` — which is why f64 columns decline
        (``exact_compare``): segment-entry rounding could carry a value
        across an edge the host-f64 compare respects."""
        self._require_model()
        entry = numeric_entry(schema, self.get_features_col(),
                              exact_compare=True)
        if entry is None:
            return None
        d = int(entry[0][0]) if entry[0] else 1
        if d != self._edges.shape[0]:
            return None
        width = max(int(self._n_edges.max()) - 2, 1)
        ceil_edges = np.full((d, width), np.inf, np.float32)
        for j in range(d):
            interior = self._edges[j, 1: self._n_edges[j] - 1]
            ceil_edges[j, : len(interior)] = f32_ceil(interior)
        n_interior = np.maximum(self._n_edges - 2, 0).astype(np.int32)
        return StageKernel(
            fn=_kbins_kernel,
            static=(self.get_features_col(), self.get_output_col()),
            params={"ceil_edges": ceil_edges, "n_interior": n_interior},
            consumes=(self.get_features_col(),),
            produces=(self.get_output_col(),))

    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {
            "edges": self._edges, "n_edges": self._n_edges})

    @classmethod
    def load(cls, path: str) -> "KBinsDiscretizerModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._edges = data["edges"].astype(np.float64)
        model._n_edges = data["n_edges"].astype(np.int64)
        return model


def _kbins_kernel(static, params, cols):
    (fcol, ocol) = static
    X = _mat(cols[fcol])
    # searchsorted(interior, x, "right") == #{e: e <= x}; +inf pads never hit
    idx = jnp.sum(X[:, :, None] >= params["ceil_edges"][None, :, :], axis=-1)
    # NaN compares false against every edge (bin 0 here), but the host
    # searchsorted sorts NaN AFTER everything -> last bin
    idx = jnp.where(jnp.isnan(X), params["n_interior"][None, :], idx)
    return {ocol: idx.astype(jnp.float32)}


class KBinsDiscretizer(KBinsDiscretizerParams,
                       Estimator[KBinsDiscretizerModel]):
    """Learns per-column bin edges.  ``quantile`` collapses duplicate
    quantile edges (fewer effective bins on skewed data, same as the Flink
    ML implementation); ``uniform`` spaces bins over [min, max]; ``kmeans``
    runs 1-D Lloyd's per column and cuts at centroid midpoints."""

    def fit(self, *inputs) -> KBinsDiscretizerModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        sub = self.get_sub_samples()
        if 0 < sub < X.shape[0]:
            sel = np.random.default_rng(self.get_seed()).choice(
                X.shape[0], sub, replace=False)
            X = X[sel]
        k = self.get_num_bins()
        strategy = self.get_strategy()
        per_col: List[np.ndarray] = []
        for j in range(X.shape[1]):
            col = X[:, j]
            if col.min() == col.max():
                # constant column: one [min, min+1) bin for EVERY strategy
                # (uniform's linspace would yield k+1 identical edges and
                # searchsorted would bucket everything into bin k-1)
                edges = np.array([col.min(), col.max() + 1.0])
            elif strategy == "uniform":
                edges = np.linspace(col.min(), col.max(), k + 1)
            elif strategy == "quantile":
                edges = np.unique(np.quantile(col, np.linspace(0, 1, k + 1)))
            else:
                edges = _kmeans_1d_edges(col, k)
            per_col.append(edges)

        max_e = max(len(e) for e in per_col)
        edges = np.full((X.shape[1], max_e), np.inf)
        n_edges = np.zeros(X.shape[1], np.int64)
        for j, e in enumerate(per_col):
            edges[j, : len(e)] = e
            n_edges[j] = len(e)

        model = KBinsDiscretizerModel()
        model.copy_params_from(self)
        model._edges = edges
        model._n_edges = n_edges
        return model


# ---------------------------------------------------------------------------
# VectorIndexer
# ---------------------------------------------------------------------------

class VectorIndexerParams(_InOutParams):
    MAX_CATEGORIES = IntParam(
        "maxCategories",
        "Columns with more distinct values than this stay continuous.",
        default=20, validator=ParamValidators.gt_eq(2))
    HANDLE_INVALID = StringParam(
        "handleInvalid", "Unseen categorical values: error | skip | keep.",
        default="error",
        validator=ParamValidators.in_array(["error", "skip", "keep"]))

    def get_max_categories(self) -> int:
        return self.get(VectorIndexerParams.MAX_CATEGORIES)

    def set_max_categories(self, value: int):
        return self.set(VectorIndexerParams.MAX_CATEGORIES, value)

    def get_handle_invalid(self) -> str:
        return self.get(VectorIndexerParams.HANDLE_INVALID)

    def set_handle_invalid(self, value: str):
        return self.set(VectorIndexerParams.HANDLE_INVALID, value)


class VectorIndexerModel(VectorIndexerParams, Model):
    """Maps each categorical column's values to indices in ascending value
    order; columns whose distinct count exceeded ``maxCategories`` at fit
    time pass through unchanged.  Unseen values at transform time follow
    ``handleInvalid``: error raises, skip drops the row, keep maps to the
    extra index ``numCategories``."""

    def __init__(self):
        super().__init__()
        self._values: Optional[np.ndarray] = None   # (d, max_vals) NaN pad
        self._n_values: Optional[np.ndarray] = None  # (d,) -1 => continuous

    def set_model_data(self, *inputs) -> "VectorIndexerModel":
        (t,) = inputs
        self._values = np.asarray(t["values"], np.float64)
        self._n_values = np.asarray(t["n_values"], np.int64)
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"values": self._values, "n_values": self._n_values})]

    def _require_model(self) -> None:
        if self._values is None:
            raise RuntimeError("VectorIndexerModel has no model data")

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        out = X.copy()
        invalid_rows = np.zeros(X.shape[0], bool)
        policy = self.get_handle_invalid()
        for j in range(X.shape[1]):
            n = self._n_values[j]
            if n < 0:           # continuous column: passthrough
                continue
            vals = self._values[j, :n]
            pos = np.searchsorted(vals, X[:, j])
            pos_c = np.clip(pos, 0, n - 1)
            hit = vals[pos_c] == X[:, j]
            if not np.all(hit):
                if policy == "error":
                    bad = X[:, j][~hit][0]
                    raise ValueError(
                        f"VectorIndexer saw unseen value {bad} in column {j}"
                        "; set handleInvalid to 'keep' or 'skip'")
                invalid_rows |= ~hit
            out[:, j] = np.where(hit, pos_c, float(n))
        result = table.with_column(self.get_output_col(), out)
        if policy == "skip" and np.any(invalid_rows):
            result = result.select_rows(np.flatnonzero(~invalid_rows))
        return [result]

    def transform_kernel(self, schema):
        """Chainable only under ``handleInvalid="keep"`` (error raises,
        skip drops rows — both host control flow).  Vocab values carry
        their f32 casts plus an exactness mask: a fitted value that is
        not f32-representable can never equal an f32 column value, so it
        is simply unmatchable (bit-exact with the host-f64 compare on
        f32 columns); two values colliding in f32 make the lookup
        ambiguous, and the stage falls back stagewise.  f64 columns
        decline (``exact_compare``): entry rounding could land an unseen
        f64 value exactly on a vocab entry the host-f64 compare rejects."""
        self._require_model()
        if self.get_handle_invalid() != "keep":
            return None
        entry = numeric_entry(schema, self.get_features_col(),
                              exact_compare=True)
        if entry is None:
            return None
        d = int(entry[0][0]) if entry[0] else 1
        if d != self._values.shape[0]:
            return None
        m = max(int(self._n_values.max()), 1)
        vals32 = np.full((d, m), np.inf, np.float32)
        exact = np.zeros((d, m), np.float32)
        for j in range(d):
            n = self._n_values[j]
            if n < 0:
                continue
            v = self._values[j, :n]
            v32 = v.astype(np.float32)
            if np.any(np.diff(v32) <= 0):
                return None       # f32 collision: lookup would be ambiguous
            vals32[j, :n] = v32
            exact[j, :n] = (v32.astype(np.float64) == v)
        return StageKernel(
            fn=_vector_indexer_kernel,
            static=(self.get_features_col(), self.get_output_col()),
            params={"vals": vals32, "exact": exact,
                    "unseen": self._n_values.astype(np.float32),
                    "is_cat": (self._n_values >= 0).astype(np.float32)},
            consumes=(self.get_features_col(),),
            produces=(self.get_output_col(),))

    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {
            "values": self._values, "n_values": self._n_values})

    @classmethod
    def load(cls, path: str) -> "VectorIndexerModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._values = data["values"].astype(np.float64)
        model._n_values = data["n_values"].astype(np.int64)
        return model


def _vector_indexer_kernel(static, params, cols):
    (fcol, ocol) = static
    X = _mat(cols[fcol]).astype(jnp.float32)
    vals = params["vals"]                               # (d, m), +inf pad
    d = vals.shape[0]
    col_ids = jnp.arange(d)[None, :]
    # last index with vals <= x (unique vocab => same index searchsorted
    # side="left" lands on when x matches)
    pos = jnp.sum(X[:, :, None] >= vals[None, :, :], axis=-1) - 1
    pos_c = jnp.clip(pos, 0, vals.shape[1] - 1)
    hit = (vals[col_ids, pos_c] == X) & (params["exact"][col_ids, pos_c] > 0)
    out_cat = jnp.where(hit, pos_c.astype(jnp.float32),
                        params["unseen"][None, :])
    return {ocol: jnp.where(params["is_cat"][None, :] > 0, out_cat, X)}


class VectorIndexer(VectorIndexerParams, Estimator[VectorIndexerModel]):
    def fit(self, *inputs) -> VectorIndexerModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        max_cat = self.get_max_categories()
        per_col: List[Optional[np.ndarray]] = []
        for j in range(X.shape[1]):
            uniq = np.unique(X[:, j])
            per_col.append(uniq if len(uniq) <= max_cat else None)

        max_v = max((len(v) for v in per_col if v is not None), default=1)
        values = np.full((X.shape[1], max_v), np.nan)
        n_values = np.full(X.shape[1], -1, np.int64)
        for j, v in enumerate(per_col):
            if v is not None:
                values[j, : len(v)] = v
                n_values[j] = len(v)

        model = VectorIndexerModel()
        model.copy_params_from(self)
        model._values = values
        model._n_values = n_values
        return model
