"""Categorical encoders + column assembly: StringIndexer, OneHotEncoder,
VectorAssembler — the feature-prep stages that feed the linear family and
Wide&Deep (string -> index -> one-hot / stacked cat ids)."""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ...api.chain import StageKernel, numeric_entry
from ...api.stage import Estimator, Model, Transformer
from ...data.table import Table
from ...params.param import BoolParam, StringParam
from ...params.shared import HasFeaturesCol, HasInputCols, HasOutputCols
from ...utils import persist

__all__ = ["StringIndexer", "StringIndexerModel", "OneHotEncoder",
           "OneHotEncoderModel", "VectorAssembler"]


class _ColsParams(HasInputCols, HasOutputCols):
    """Both-columns mixin shared by the multi-column feature stages."""


def _check_cols(stage) -> tuple:
    in_cols, out_cols = stage.get_input_cols(), stage.get_output_cols()
    if not in_cols:
        raise ValueError(f"{type(stage).__name__} requires inputCols")
    out_cols = out_cols or tuple(f"{c}_out" for c in in_cols)
    if len(out_cols) != len(in_cols):
        raise ValueError("inputCols and outputCols lengths differ")
    return in_cols, out_cols


class StringIndexerModel(_ColsParams, Model):
    """Maps string/any values to dense int ids by fitted vocabulary;
    unseen values -> len(vocab) (the "keep" policy) or error."""

    HANDLE_INVALID = StringParam(
        "handleInvalid", "Unseen-value policy.", default="keep",
        validator=lambda v: v in ("keep", "error"))

    def __init__(self):
        super().__init__()
        self._vocab: Dict[str, List] = {}

    def set_model_data(self, *inputs) -> "StringIndexerModel":
        (t,) = inputs
        self._vocab = {name: list(t[name]) for name in t.column_names}
        return self

    def get_model_data(self) -> List[Table]:
        return [Table({k: np.asarray(v) for k, v in self._vocab.items()})]

    def vocab_sizes(self) -> List[int]:
        in_cols, _ = _check_cols(self)
        return [len(self._vocab[c]) for c in in_cols]

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        in_cols, out_cols = _check_cols(self)
        policy = self.get(StringIndexerModel.HANDLE_INVALID)
        out = table
        for ic, oc in zip(in_cols, out_cols):
            vocab_arr = np.asarray(self._vocab[ic])
            column = np.asarray(table[ic])
            # promote BOTH sides to the wider dtype — casting the column to
            # the vocab's fixed-width string dtype would silently truncate
            # longer unseen values onto vocab prefixes
            joint = np.promote_types(vocab_arr.dtype, column.dtype)
            vocab_arr = vocab_arr.astype(joint, copy=False)
            column = column.astype(joint, copy=False)
            # vectorized lookup: searchsorted over the sorted vocab, mapped
            # back to fitted (frequency-ordered) ids
            order = np.argsort(vocab_arr, kind="stable")
            sorted_vocab = vocab_arr[order]
            pos = np.searchsorted(sorted_vocab, column)
            pos_clipped = np.minimum(pos, len(vocab_arr) - 1)
            found = sorted_vocab[pos_clipped] == column
            if policy == "error" and not found.all():
                missing = column[~found][0]
                raise ValueError(f"Unseen value {missing!r} in column {ic!r}")
            ids = np.where(found, order[pos_clipped], len(vocab_arr)
                           ).astype(np.int64)
            out = out.with_column(oc, ids)
        return [out]

    def transform_kernel(self, schema):
        """Chain kernel for NUMERIC vocabularies (the post-discretization
        re-indexing case): the sorted-vocab searchsorted lookup runs
        in-device with the fitted-order id mapping precomputed at chain
        build.  String/object domains and the ``error`` policy stay
        stagewise (string columns cannot live on device; the raise is
        host control flow).  f64 columns decline (``exact_compare``):
        the lookup is a vocabulary-equality decision, and segment-entry
        rounding could land an unseen f64 value exactly on a vocab entry
        the host-f64 compare rejects."""
        if self.get(StringIndexerModel.HANDLE_INVALID) != "keep":
            return None
        in_cols, out_cols = _check_cols(self)
        vals_list, fid_list, exact_list, unseen = [], [], [], []
        for ic in in_cols:
            entry = numeric_entry(schema, ic, exact_compare=True)
            if entry is None or entry[0]:
                return None          # non-numeric/f64 or non-scalar column
            vocab = np.asarray(self._vocab[ic])
            if vocab.dtype.kind not in "fiub":
                return None          # string-domain vocabulary
            vocab = vocab.astype(np.float64)
            order = np.argsort(vocab, kind="stable")
            sorted_vals = vocab[order]
            v32 = sorted_vals.astype(np.float32)
            if len(v32) > 1 and np.any(np.diff(v32) <= 0):
                return None          # f32 collision: ambiguous lookup
            vals_list.append(v32)
            fid_list.append(order.astype(np.int32))
            exact_list.append(
                (v32.astype(np.float64) == sorted_vals).astype(np.float32))
            unseen.append(np.int32(len(vocab)))
        return StageKernel(
            fn=_string_indexer_kernel,
            static=(tuple(zip(in_cols, out_cols)),),
            params={"vals": vals_list, "fid": fid_list,
                    "exact": exact_list, "unseen": unseen},
            consumes=tuple(in_cols), produces=tuple(out_cols))

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)
        persist.save_model_arrays(
            path, "model", {k: np.asarray(v) for k, v in self._vocab.items()})

    @classmethod
    def load(cls, path: str) -> "StringIndexerModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._vocab = {k: list(v) for k, v in data.items()}
        return model


def _string_indexer_kernel(static, params, cols):
    (pairs,) = static
    out = {}
    for i, (ic, oc) in enumerate(pairs):
        x = cols[ic].astype(jnp.float32)
        vals, fid = params["vals"][i], params["fid"][i]
        pos = jnp.sum(x[:, None] >= vals[None, :], axis=-1) - 1
        pos_c = jnp.clip(pos, 0, vals.shape[0] - 1)
        hit = (vals[pos_c] == x) & (params["exact"][i][pos_c] > 0)
        out[oc] = jnp.where(hit, fid[pos_c], params["unseen"][i]
                            ).astype(jnp.int32)
    return out


class StringIndexer(_ColsParams, Estimator[StringIndexerModel]):
    """Vocabulary ordering follows ``stringOrderType`` (the Flink ML
    StringIndexer param): frequencyDesc (default; ties by value
    ascending), frequencyAsc, alphabetAsc, alphabetDesc."""

    STRING_ORDER_TYPE = StringParam(
        "stringOrderType",
        "frequencyDesc | frequencyAsc | alphabetAsc | alphabetDesc.",
        default="frequencyDesc",
        validator=lambda v: v in ("frequencyDesc", "frequencyAsc",
                                  "alphabetAsc", "alphabetDesc"))

    def get_string_order_type(self) -> str:
        return self.get(StringIndexer.STRING_ORDER_TYPE)

    def set_string_order_type(self, value: str):
        return self.set(StringIndexer.STRING_ORDER_TYPE, value)

    def fit(self, *inputs) -> StringIndexerModel:
        (table,) = inputs
        in_cols, _ = _check_cols(self)
        order_type = self.get_string_order_type()
        model = StringIndexerModel()
        model.copy_params_from(self)
        for col in in_cols:
            # np.unique returns values already ascending-sorted, so the
            # alphabet orders are identity / reverse
            values, counts = np.unique(table[col], return_counts=True)
            if order_type == "frequencyDesc":
                order = np.lexsort((values, -counts))
            elif order_type == "frequencyAsc":
                order = np.lexsort((values, counts))
            elif order_type == "alphabetAsc":
                order = np.arange(len(values))
            else:                                   # alphabetDesc
                order = np.arange(len(values))[::-1]
            model._vocab[col] = [values[i].item() if hasattr(values[i], "item")
                                 else values[i] for i in order]
        return model


class OneHotEncoderParams(_ColsParams):
    DROP_LAST = BoolParam("dropLast", "Drop the last category column.",
                          default=True)
    HANDLE_INVALID = StringParam(
        "handleInvalid", "Out-of-range id policy: 'error' raises, 'keep' "
        "emits an all-zeros row (matches StringIndexer's unseen->len(vocab) "
        "ids).", default="error",
        validator=lambda v: v in ("keep", "error"))


class OneHotEncoderModel(OneHotEncoderParams, Model):
    def __init__(self):
        super().__init__()
        self._sizes: Dict[str, int] = {}

    def set_model_data(self, *inputs) -> "OneHotEncoderModel":
        (t,) = inputs
        self._sizes = {name: int(t[name][0]) for name in t.column_names}
        return self

    def get_model_data(self) -> List[Table]:
        return [Table({k: np.asarray([v]) for k, v in self._sizes.items()})]

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        in_cols, out_cols = _check_cols(self)
        drop = self.get(OneHotEncoderParams.DROP_LAST)
        out = table
        keep = self.get(OneHotEncoderParams.HANDLE_INVALID) == "keep"
        for ic, oc in zip(in_cols, out_cols):
            size = self._sizes[ic]
            ids = np.asarray(table[ic], np.int64)
            if np.any(ids < 0) or (not keep and np.any(ids >= size)):
                raise ValueError(f"id out of range [0, {size}) in {ic!r}")
            width = size - 1 if drop else size
            hot = np.zeros((len(ids), width), np.float64)
            in_range = ids < width  # dropped-last and invalid ids -> zeros
            hot[np.nonzero(in_range)[0], ids[in_range]] = 1.0
            out = out.with_column(oc, hot)
        return [out]

    def transform_kernel(self, schema):
        """Chainable under ``handleInvalid="keep"``: too-LARGE ids one-hot
        to all-zero rows in-device, exactly the stagewise keep semantics.
        Negative ids raise on host even under keep, so a ``pre`` hook
        carries that check into the segment (the ``error`` policy's
        any-out-of-range raise stays host control flow — non-chainable)."""
        if self.get(OneHotEncoderParams.HANDLE_INVALID) != "keep":
            return None
        in_cols, out_cols = _check_cols(self)
        drop = self.get(OneHotEncoderParams.DROP_LAST)
        specs = []
        for ic, oc in zip(in_cols, out_cols):
            entry = schema.get(ic)
            if entry is None or entry[1].kind not in "iub" or entry[0]:
                return None          # ids must be scalar integer columns
            size = self._sizes[ic]
            specs.append((ic, oc, size - 1 if drop else size))
        sizes = tuple((ic, self._sizes[ic]) for ic in in_cols)
        return StageKernel(
            fn=_onehot_kernel, static=(tuple(specs),), params={},
            consumes=tuple(in_cols), produces=tuple(out_cols),
            pre=partial(_onehot_pre, sizes), pre_cols=tuple(in_cols))

    def save(self, path: str) -> None:
        persist.save_metadata(self, path, {"sizes": self._sizes})

    @classmethod
    def load(cls, path: str) -> "OneHotEncoderModel":
        model = persist.load_stage_param(path)
        meta = persist.load_metadata(path)
        model._sizes = {k: int(v) for k, v in meta["sizes"].items()}
        return model


def _onehot_pre(col_sizes, host):
    """Host entry validation: the stagewise keep path still raises on a
    NEGATIVE id (only too-large ids zero out) — the fused path must too,
    not silently emit a zero row."""
    for ic, size in col_sizes:
        ids = host[ic]
        if ids.size and int(ids.min()) < 0:
            raise ValueError(f"id out of range [0, {size}) in {ic!r}")


def _onehot_kernel(static, params, cols):
    (specs,) = static
    out = {}
    for ic, oc, width in specs:
        ids = cols[ic]
        out[oc] = (ids[:, None] == jnp.arange(width)[None, :]
                   ).astype(jnp.float32)
    return out


def _assembler_kernel(static, params, cols):
    in_cols, ocol = static
    parts = []
    for name in in_cols:
        arr = cols[name].astype(jnp.float32)
        parts.append(arr[:, None] if arr.ndim == 1 else arr)
    return {ocol: jnp.concatenate(parts, axis=1)}


class OneHotEncoder(OneHotEncoderParams, Estimator[OneHotEncoderModel]):
    """Category count per column = max id + 1 over the fit data."""

    def fit(self, *inputs) -> OneHotEncoderModel:
        (table,) = inputs
        in_cols, _ = _check_cols(self)
        model = OneHotEncoderModel()
        model.copy_params_from(self)
        for col in in_cols:
            ids = np.asarray(table[col], np.int64)
            if ids.min() < 0:
                raise ValueError(f"negative ids in column {col!r}")
            model._sizes[col] = int(ids.max()) + 1
        return model


class VectorAssembler(_ColsParams, HasFeaturesCol, Transformer):
    """Concatenate scalar/vector columns into one dense feature matrix
    (output column = featuresCol)."""

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        in_cols = self.get_input_cols()
        if not in_cols:
            raise ValueError("VectorAssembler requires inputCols")
        parts = []
        for col in in_cols:
            arr = np.asarray(table[col], np.float64)
            parts.append(arr[:, None] if arr.ndim == 1 else arr)
        stacked = np.concatenate(parts, axis=1)
        return [table.with_column(self.get_features_col(), stacked)]

    def transform_kernel(self, schema):
        """Chain kernel: concatenation is value-exact at f32 for every
        f32-exact input, so the fused path matches stagewise bit-exactly."""
        in_cols = self.get_input_cols()
        if not in_cols:
            return None      # stagewise raises the diagnostic error
        for name in in_cols:
            if numeric_entry(schema, name) is None:
                return None
        return StageKernel(
            fn=_assembler_kernel,
            static=(tuple(in_cols), self.get_features_col()),
            params={},
            consumes=tuple(in_cols),
            produces=(self.get_features_col(),))
