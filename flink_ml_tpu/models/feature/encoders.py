"""Categorical encoders + column assembly: StringIndexer, OneHotEncoder,
VectorAssembler — the feature-prep stages that feed the linear family and
Wide&Deep (string -> index -> one-hot / stacked cat ids)."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ...api.stage import Estimator, Model, Transformer
from ...data.table import Table
from ...params.param import BoolParam, StringParam
from ...params.shared import HasFeaturesCol, HasInputCols, HasOutputCols
from ...utils import persist

__all__ = ["StringIndexer", "StringIndexerModel", "OneHotEncoder",
           "OneHotEncoderModel", "VectorAssembler"]


class _ColsParams(HasInputCols, HasOutputCols):
    """Both-columns mixin shared by the multi-column feature stages."""


def _check_cols(stage) -> tuple:
    in_cols, out_cols = stage.get_input_cols(), stage.get_output_cols()
    if not in_cols:
        raise ValueError(f"{type(stage).__name__} requires inputCols")
    out_cols = out_cols or tuple(f"{c}_out" for c in in_cols)
    if len(out_cols) != len(in_cols):
        raise ValueError("inputCols and outputCols lengths differ")
    return in_cols, out_cols


class StringIndexerModel(_ColsParams, Model):
    """Maps string/any values to dense int ids by fitted vocabulary;
    unseen values -> len(vocab) (the "keep" policy) or error."""

    HANDLE_INVALID = StringParam(
        "handleInvalid", "Unseen-value policy.", default="keep",
        validator=lambda v: v in ("keep", "error"))

    def __init__(self):
        super().__init__()
        self._vocab: Dict[str, List] = {}

    def set_model_data(self, *inputs) -> "StringIndexerModel":
        (t,) = inputs
        self._vocab = {name: list(t[name]) for name in t.column_names}
        return self

    def get_model_data(self) -> List[Table]:
        return [Table({k: np.asarray(v) for k, v in self._vocab.items()})]

    def vocab_sizes(self) -> List[int]:
        in_cols, _ = _check_cols(self)
        return [len(self._vocab[c]) for c in in_cols]

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        in_cols, out_cols = _check_cols(self)
        policy = self.get(StringIndexerModel.HANDLE_INVALID)
        out = table
        for ic, oc in zip(in_cols, out_cols):
            vocab_arr = np.asarray(self._vocab[ic])
            column = np.asarray(table[ic])
            # promote BOTH sides to the wider dtype — casting the column to
            # the vocab's fixed-width string dtype would silently truncate
            # longer unseen values onto vocab prefixes
            joint = np.promote_types(vocab_arr.dtype, column.dtype)
            vocab_arr = vocab_arr.astype(joint, copy=False)
            column = column.astype(joint, copy=False)
            # vectorized lookup: searchsorted over the sorted vocab, mapped
            # back to fitted (frequency-ordered) ids
            order = np.argsort(vocab_arr, kind="stable")
            sorted_vocab = vocab_arr[order]
            pos = np.searchsorted(sorted_vocab, column)
            pos_clipped = np.minimum(pos, len(vocab_arr) - 1)
            found = sorted_vocab[pos_clipped] == column
            if policy == "error" and not found.all():
                missing = column[~found][0]
                raise ValueError(f"Unseen value {missing!r} in column {ic!r}")
            ids = np.where(found, order[pos_clipped], len(vocab_arr)
                           ).astype(np.int64)
            out = out.with_column(oc, ids)
        return [out]

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)
        persist.save_model_arrays(
            path, "model", {k: np.asarray(v) for k, v in self._vocab.items()})

    @classmethod
    def load(cls, path: str) -> "StringIndexerModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._vocab = {k: list(v) for k, v in data.items()}
        return model


class StringIndexer(_ColsParams, Estimator[StringIndexerModel]):
    """Vocabulary ordering follows ``stringOrderType`` (the Flink ML
    StringIndexer param): frequencyDesc (default; ties by value
    ascending), frequencyAsc, alphabetAsc, alphabetDesc."""

    STRING_ORDER_TYPE = StringParam(
        "stringOrderType",
        "frequencyDesc | frequencyAsc | alphabetAsc | alphabetDesc.",
        default="frequencyDesc",
        validator=lambda v: v in ("frequencyDesc", "frequencyAsc",
                                  "alphabetAsc", "alphabetDesc"))

    def get_string_order_type(self) -> str:
        return self.get(StringIndexer.STRING_ORDER_TYPE)

    def set_string_order_type(self, value: str):
        return self.set(StringIndexer.STRING_ORDER_TYPE, value)

    def fit(self, *inputs) -> StringIndexerModel:
        (table,) = inputs
        in_cols, _ = _check_cols(self)
        order_type = self.get_string_order_type()
        model = StringIndexerModel()
        model.copy_params_from(self)
        for col in in_cols:
            # np.unique returns values already ascending-sorted, so the
            # alphabet orders are identity / reverse
            values, counts = np.unique(table[col], return_counts=True)
            if order_type == "frequencyDesc":
                order = np.lexsort((values, -counts))
            elif order_type == "frequencyAsc":
                order = np.lexsort((values, counts))
            elif order_type == "alphabetAsc":
                order = np.arange(len(values))
            else:                                   # alphabetDesc
                order = np.arange(len(values))[::-1]
            model._vocab[col] = [values[i].item() if hasattr(values[i], "item")
                                 else values[i] for i in order]
        return model


class OneHotEncoderParams(_ColsParams):
    DROP_LAST = BoolParam("dropLast", "Drop the last category column.",
                          default=True)
    HANDLE_INVALID = StringParam(
        "handleInvalid", "Out-of-range id policy: 'error' raises, 'keep' "
        "emits an all-zeros row (matches StringIndexer's unseen->len(vocab) "
        "ids).", default="error",
        validator=lambda v: v in ("keep", "error"))


class OneHotEncoderModel(OneHotEncoderParams, Model):
    def __init__(self):
        super().__init__()
        self._sizes: Dict[str, int] = {}

    def set_model_data(self, *inputs) -> "OneHotEncoderModel":
        (t,) = inputs
        self._sizes = {name: int(t[name][0]) for name in t.column_names}
        return self

    def get_model_data(self) -> List[Table]:
        return [Table({k: np.asarray([v]) for k, v in self._sizes.items()})]

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        in_cols, out_cols = _check_cols(self)
        drop = self.get(OneHotEncoderParams.DROP_LAST)
        out = table
        keep = self.get(OneHotEncoderParams.HANDLE_INVALID) == "keep"
        for ic, oc in zip(in_cols, out_cols):
            size = self._sizes[ic]
            ids = np.asarray(table[ic], np.int64)
            if np.any(ids < 0) or (not keep and np.any(ids >= size)):
                raise ValueError(f"id out of range [0, {size}) in {ic!r}")
            width = size - 1 if drop else size
            hot = np.zeros((len(ids), width), np.float64)
            in_range = ids < width  # dropped-last and invalid ids -> zeros
            hot[np.nonzero(in_range)[0], ids[in_range]] = 1.0
            out = out.with_column(oc, hot)
        return [out]

    def save(self, path: str) -> None:
        persist.save_metadata(self, path, {"sizes": self._sizes})

    @classmethod
    def load(cls, path: str) -> "OneHotEncoderModel":
        model = persist.load_stage_param(path)
        meta = persist.load_metadata(path)
        model._sizes = {k: int(v) for k, v in meta["sizes"].items()}
        return model


class OneHotEncoder(OneHotEncoderParams, Estimator[OneHotEncoderModel]):
    """Category count per column = max id + 1 over the fit data."""

    def fit(self, *inputs) -> OneHotEncoderModel:
        (table,) = inputs
        in_cols, _ = _check_cols(self)
        model = OneHotEncoderModel()
        model.copy_params_from(self)
        for col in in_cols:
            ids = np.asarray(table[col], np.int64)
            if ids.min() < 0:
                raise ValueError(f"negative ids in column {col!r}")
            model._sizes[col] = int(ids.max()) + 1
        return model


class VectorAssembler(_ColsParams, HasFeaturesCol, Transformer):
    """Concatenate scalar/vector columns into one dense feature matrix
    (output column = featuresCol)."""

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        in_cols = self.get_input_cols()
        if not in_cols:
            raise ValueError("VectorAssembler requires inputCols")
        parts = []
        for col in in_cols:
            arr = np.asarray(table[col], np.float64)
            parts.append(arr[:, None] if arr.ndim == 1 else arr)
        stacked = np.concatenate(parts, axis=1)
        return [table.with_column(self.get_features_col(), stacked)]
