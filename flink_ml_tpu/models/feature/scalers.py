"""Feature scalers: StandardScaler and MinMaxScaler.

The reference snapshot ships no feature transformers (its lib is KMeans
only), but Flink ML's library surface includes them; they're also what make
the Pipeline API practically usable.  Statistics are computed on device (one
reduction over the sharded batch), applied as a jitted broadcast op."""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator, Model
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.param import BoolParam, FloatParam
from ...params.shared import HasFeaturesCol, HasOutputCol
from ...utils import persist

__all__ = ["StandardScaler", "StandardScalerModel",
           "MinMaxScaler", "MinMaxScalerModel"]


class _HasOutputCol(HasFeaturesCol, HasOutputCol):
    """features-in / output-out mixin for the scalers."""


class StandardScalerParams(_HasOutputCol):
    WITH_MEAN = BoolParam("withMean", "Center to zero mean.", default=True)
    WITH_STD = BoolParam("withStd", "Scale to unit variance.", default=True)


@jax.jit
def _standardize(X, mean, scale):
    return (X - mean) * scale


class StandardScalerModel(StandardScalerParams, Model):
    def __init__(self):
        super().__init__()
        self._mean: Optional[np.ndarray] = None
        self._std: Optional[np.ndarray] = None

    def set_model_data(self, *inputs) -> "StandardScalerModel":
        (t,) = inputs
        self._mean = np.asarray(t["mean"][0], np.float64)
        self._std = np.asarray(t["std"][0], np.float64)
        return self

    def get_model_data(self) -> List[Table]:
        return [Table({"mean": self._mean[None], "std": self._std[None]})]

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float32)
        mean = (self._mean if self.get(StandardScalerParams.WITH_MEAN)
                else np.zeros_like(self._mean))
        scale = (1.0 / np.maximum(self._std, 1e-12)
                 if self.get(StandardScalerParams.WITH_STD)
                 else np.ones_like(self._std))
        out = np.asarray(_standardize(X, jnp.asarray(mean, jnp.float32),
                                      jnp.asarray(scale, jnp.float32)))
        return [table.with_column(self.get_output_col(), out)]

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model",
                                  {"mean": self._mean, "std": self._std})

    @classmethod
    def load(cls, path: str) -> "StandardScalerModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._mean, model._std = (data["mean"].astype(np.float64),
                                   data["std"].astype(np.float64))
        return model


class StandardScaler(StandardScalerParams, Estimator[StandardScalerModel]):
    def fit(self, *inputs) -> StandardScalerModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()])
        model = StandardScalerModel()
        model.copy_params_from(self)
        model._mean = X.mean(axis=0)
        model._std = X.std(axis=0)
        return model


class MinMaxScalerParams(_HasOutputCol):
    MIN = FloatParam("min", "Lower bound of the output range.", default=0.0)
    MAX = FloatParam("max", "Upper bound of the output range.", default=1.0)


class MinMaxScalerModel(MinMaxScalerParams, Model):
    def __init__(self):
        super().__init__()
        self._data_min: Optional[np.ndarray] = None
        self._data_max: Optional[np.ndarray] = None

    def set_model_data(self, *inputs) -> "MinMaxScalerModel":
        (t,) = inputs
        self._data_min = np.asarray(t["min"][0], np.float64)
        self._data_max = np.asarray(t["max"][0], np.float64)
        return self

    def get_model_data(self) -> List[Table]:
        return [Table({"min": self._data_min[None],
                       "max": self._data_max[None]})]

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        lo, hi = self.get(MinMaxScalerParams.MIN), self.get(MinMaxScalerParams.MAX)
        if hi <= lo:
            raise ValueError(f"min {lo} must be < max {hi}")
        X = stack_vectors(table[self.get_features_col()])
        span = np.maximum(self._data_max - self._data_min, 1e-12)
        out = (X - self._data_min) / span * (hi - lo) + lo
        return [table.with_column(self.get_output_col(), out)]

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {"min": self._data_min,
                                                  "max": self._data_max})

    @classmethod
    def load(cls, path: str) -> "MinMaxScalerModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._data_min = data["min"].astype(np.float64)
        model._data_max = data["max"].astype(np.float64)
        return model


class MinMaxScaler(MinMaxScalerParams, Estimator[MinMaxScalerModel]):
    def fit(self, *inputs) -> MinMaxScalerModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()])
        model = MinMaxScalerModel()
        model.copy_params_from(self)
        model._data_min = X.min(axis=0)
        model._data_max = X.max(axis=0)
        return model
