"""Feature scalers: StandardScaler and MinMaxScaler.

The reference snapshot ships no feature transformers (its lib is KMeans
only), but Flink ML's library surface includes them; they're also what make
the Pipeline API practically usable.  Statistics are computed on device (one
reduction over the sharded batch), applied as a jitted broadcast op."""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.chain import StageKernel, as_matrix as _as_matrix, numeric_entry
from ...api.stage import Estimator, Model
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.param import BoolParam, FloatParam
from ...params.shared import HasFeaturesCol, HasOutputCol
from ...utils import persist

__all__ = ["StandardScaler", "StandardScalerModel",
           "MinMaxScaler", "MinMaxScalerModel",
           "MaxAbsScaler", "MaxAbsScalerModel",
           "RobustScaler", "RobustScalerModel"]


class _HasOutputCol(HasFeaturesCol, HasOutputCol):
    """features-in / output-out mixin for the scalers."""


def _numeric_feature(schema, col: str) -> bool:
    """Chainable only when the features column is a plain numeric array
    (object/string columns — DenseVector lists etc. — stay stagewise)."""
    return numeric_entry(schema, col) is not None


def _affine_kernel(static, params, cols):
    (fcol, ocol) = static
    X = _as_matrix(cols[fcol])
    return {ocol: (X - params["shift"]) * params["scale"]}


def _div_affine_kernel(static, params, cols):
    """Division-form affine: mirrors the stagewise ``(X - lo) / span``
    expression ORDER so range boundaries stay exact (x/x == 1.0; a
    reciprocal-multiply would round)."""
    (fcol, ocol) = static
    X = _as_matrix(cols[fcol])
    return {ocol: (X - params["shift"]) / params["div"] * params["mul"]
            + params["add"]}


class _ScalerChainMixin:
    """Shared ``transform_kernel`` plumbing: subclasses provide
    ``_kernel_fn`` + ``_kernel_params`` (f32 arrays precomputed from the
    fitted state — the WITH_* flags fold into the params, so one shared
    fn serves every configuration and CrossValidator folds share its
    compile)."""

    _kernel_fn = staticmethod(_affine_kernel)

    def transform_kernel(self, schema):
        fcol, ocol = self.get_features_col(), self.get_output_col()
        if not _numeric_feature(schema, fcol):
            return None
        return StageKernel(
            fn=self._kernel_fn, static=(fcol, ocol),
            params=self._kernel_params(),
            consumes=(fcol,), produces=(ocol,))


class StandardScalerParams(_HasOutputCol):
    WITH_MEAN = BoolParam("withMean", "Center to zero mean.", default=True)
    WITH_STD = BoolParam("withStd", "Scale to unit variance.", default=True)


@jax.jit
def _standardize(X, mean, scale):
    return (X - mean) * scale


class StandardScalerModel(StandardScalerParams, _ScalerChainMixin, Model):
    def __init__(self):
        super().__init__()
        self._mean: Optional[np.ndarray] = None
        self._std: Optional[np.ndarray] = None

    def _kernel_params(self):
        # identical precompute to transform(): f64 statistics, cast f32
        mean = (self._mean if self.get(StandardScalerParams.WITH_MEAN)
                else np.zeros_like(self._mean))
        scale = (1.0 / np.maximum(self._std, 1e-12)
                 if self.get(StandardScalerParams.WITH_STD)
                 else np.ones_like(self._std))
        return {"shift": np.asarray(mean, np.float32),
                "scale": np.asarray(scale, np.float32)}

    def set_model_data(self, *inputs) -> "StandardScalerModel":
        (t,) = inputs
        self._mean = np.asarray(t["mean"][0], np.float64)
        self._std = np.asarray(t["std"][0], np.float64)
        return self

    def get_model_data(self) -> List[Table]:
        return [Table({"mean": self._mean[None], "std": self._std[None]})]

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float32)
        mean = (self._mean if self.get(StandardScalerParams.WITH_MEAN)
                else np.zeros_like(self._mean))
        scale = (1.0 / np.maximum(self._std, 1e-12)
                 if self.get(StandardScalerParams.WITH_STD)
                 else np.ones_like(self._std))
        out = np.asarray(_standardize(X, jnp.asarray(mean, jnp.float32),
                                      jnp.asarray(scale, jnp.float32)))
        return [table.with_column(self.get_output_col(), out)]

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model",
                                  {"mean": self._mean, "std": self._std})

    @classmethod
    def load(cls, path: str) -> "StandardScalerModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._mean, model._std = (data["mean"].astype(np.float64),
                                   data["std"].astype(np.float64))
        return model


class StandardScaler(StandardScalerParams, Estimator[StandardScalerModel]):
    def fit(self, *inputs) -> StandardScalerModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()])
        model = StandardScalerModel()
        model.copy_params_from(self)
        model._mean = X.mean(axis=0)
        model._std = X.std(axis=0)
        return model


class MinMaxScalerParams(_HasOutputCol):
    MIN = FloatParam("min", "Lower bound of the output range.", default=0.0)
    MAX = FloatParam("max", "Upper bound of the output range.", default=1.0)


class MinMaxScalerModel(MinMaxScalerParams, _ScalerChainMixin, Model):
    _kernel_fn = staticmethod(_div_affine_kernel)

    def __init__(self):
        super().__init__()
        self._data_min: Optional[np.ndarray] = None
        self._data_max: Optional[np.ndarray] = None

    def _kernel_params(self):
        lo = self.get(MinMaxScalerParams.MIN)
        hi = self.get(MinMaxScalerParams.MAX)
        if hi <= lo:
            raise ValueError(f"min {lo} must be < max {hi}")
        span = np.maximum(self._data_max - self._data_min, 1e-12)
        return {"shift": np.asarray(self._data_min, np.float32),
                "div": np.asarray(span, np.float32),
                "mul": np.float32(hi - lo), "add": np.float32(lo)}

    def set_model_data(self, *inputs) -> "MinMaxScalerModel":
        (t,) = inputs
        self._data_min = np.asarray(t["min"][0], np.float64)
        self._data_max = np.asarray(t["max"][0], np.float64)
        return self

    def get_model_data(self) -> List[Table]:
        return [Table({"min": self._data_min[None],
                       "max": self._data_max[None]})]

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        from ...api.chain import apply_kernel_or_none

        kernel = self.transform_kernel(table.schema())
        fetched = apply_kernel_or_none(kernel, table)
        if fetched is None:     # object dtype / f32-unsafe ints: host path
            lo = self.get(MinMaxScalerParams.MIN)
            hi = self.get(MinMaxScalerParams.MAX)
            if hi <= lo:
                raise ValueError(f"min {lo} must be < max {hi}")
            X = stack_vectors(table[self.get_features_col()])
            span = np.maximum(self._data_max - self._data_min, 1e-12)
            out = (X - self._data_min) / span * (hi - lo) + lo
        else:                   # device kernel: shared with the fused chain
            out = fetched[self.get_output_col()]
        return [table.with_column(self.get_output_col(), out)]

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {"min": self._data_min,
                                                  "max": self._data_max})

    @classmethod
    def load(cls, path: str) -> "MinMaxScalerModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._data_min = data["min"].astype(np.float64)
        model._data_max = data["max"].astype(np.float64)
        return model


class MinMaxScaler(MinMaxScalerParams, Estimator[MinMaxScalerModel]):
    def fit(self, *inputs) -> MinMaxScalerModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()])
        model = MinMaxScalerModel()
        model.copy_params_from(self)
        model._data_min = X.min(axis=0)
        model._data_max = X.max(axis=0)
        return model


class MaxAbsScalerModel(_HasOutputCol, _ScalerChainMixin, Model):
    """Scale columns into [-1, 1] by the per-column max absolute value
    (preserves sparsity/sign; Flink ML 2.x feature surface)."""

    _kernel_fn = staticmethod(_div_affine_kernel)

    def __init__(self):
        super().__init__()
        self._max_abs: Optional[np.ndarray] = None

    def _kernel_params(self):
        return {"shift": np.float32(0.0),
                "div": np.asarray(np.maximum(self._max_abs, 1e-12),
                                  np.float32),
                "mul": np.float32(1.0), "add": np.float32(0.0)}

    def set_model_data(self, *inputs) -> "MaxAbsScalerModel":
        (t,) = inputs
        self._max_abs = np.asarray(t["maxAbs"][0], np.float64)
        return self

    def get_model_data(self) -> List[Table]:
        return [Table({"maxAbs": self._max_abs[None]})]

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        from ...api.chain import apply_kernel_or_none

        fetched = apply_kernel_or_none(
            self.transform_kernel(table.schema()), table)
        if fetched is None:     # object dtype / f32-unsafe ints: host path
            X = stack_vectors(table[self.get_features_col()])
            out = X / np.maximum(self._max_abs, 1e-12)
        else:                   # device kernel: shared with the fused chain
            out = fetched[self.get_output_col()]
        return [table.with_column(self.get_output_col(), out)]

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {"maxAbs": self._max_abs})

    @classmethod
    def load(cls, path: str) -> "MaxAbsScalerModel":
        model = persist.load_stage_param(path)
        model._max_abs = persist.load_model_arrays(
            path, "model")["maxAbs"].astype(np.float64)
        return model


class MaxAbsScaler(_HasOutputCol, Estimator[MaxAbsScalerModel]):
    def fit(self, *inputs) -> MaxAbsScalerModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()])
        model = MaxAbsScalerModel()
        model.copy_params_from(self)
        model._max_abs = np.abs(X).max(axis=0)
        return model


class RobustScalerParams(_HasOutputCol):
    LOWER = FloatParam("lower", "Lower quantile of the scaling range.",
                       default=25.0)
    UPPER = FloatParam("upper", "Upper quantile of the scaling range.",
                       default=75.0)
    WITH_CENTERING = BoolParam("withCentering", "Subtract the median.",
                               default=True)
    WITH_SCALING = BoolParam("withScaling", "Divide by the quantile range.",
                             default=True)


class RobustScalerModel(RobustScalerParams, _ScalerChainMixin, Model):
    """Median/IQR scaling — outlier-robust standardization."""

    _kernel_fn = staticmethod(_div_affine_kernel)

    def __init__(self):
        super().__init__()
        self._median: Optional[np.ndarray] = None
        self._range: Optional[np.ndarray] = None

    def _kernel_params(self):
        center = (self._median
                  if self.get(RobustScalerParams.WITH_CENTERING)
                  else np.zeros_like(self._median))
        div = (np.maximum(self._range, 1e-12)
               if self.get(RobustScalerParams.WITH_SCALING)
               else np.ones_like(self._range))
        return {"shift": np.asarray(center, np.float32),
                "div": np.asarray(div, np.float32),
                "mul": np.float32(1.0), "add": np.float32(0.0)}

    def set_model_data(self, *inputs) -> "RobustScalerModel":
        (t,) = inputs
        self._median = np.asarray(t["median"][0], np.float64)
        self._range = np.asarray(t["range"][0], np.float64)
        return self

    def get_model_data(self) -> List[Table]:
        return [Table({"median": self._median[None],
                       "range": self._range[None]})]

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        from ...api.chain import apply_kernel_or_none

        fetched = apply_kernel_or_none(
            self.transform_kernel(table.schema()), table)
        if fetched is None:     # object dtype / f32-unsafe ints: host path
            X = stack_vectors(
                table[self.get_features_col()]).astype(np.float64)
            if self.get(RobustScalerParams.WITH_CENTERING):
                X = X - self._median
            if self.get(RobustScalerParams.WITH_SCALING):
                X = X / np.maximum(self._range, 1e-12)
            out = X
        else:                   # device kernel: shared with the fused chain
            out = fetched[self.get_output_col()]
        return [table.with_column(self.get_output_col(), out)]

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {"median": self._median,
                                                  "range": self._range})

    @classmethod
    def load(cls, path: str) -> "RobustScalerModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._median = data["median"].astype(np.float64)
        model._range = data["range"].astype(np.float64)
        return model


class RobustScaler(RobustScalerParams, Estimator[RobustScalerModel]):
    def fit(self, *inputs) -> RobustScalerModel:
        (table,) = inputs
        lo = self.get(RobustScalerParams.LOWER)
        hi = self.get(RobustScalerParams.UPPER)
        if not 0.0 <= lo < hi <= 100.0:
            raise ValueError(f"need 0 <= lower < upper <= 100, "
                             f"got ({lo}, {hi})")
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        model = RobustScalerModel()
        model.copy_params_from(self)
        model._median = np.median(X, axis=0)
        q_lo, q_hi = np.percentile(X, [lo, hi], axis=0)
        model._range = q_hi - q_lo
        return model
