"""Stateless-ish feature transformers: Bucketizer, Binarizer, Normalizer,
PolynomialExpansion, and the fitted Imputer.

All are members of the Flink ML 2.x feature-engineering surface (the
reference snapshot ships no feature transformers — its lib is KMeans only —
but the library line includes them; SURVEY §2.8 frames the lib module as
"the algorithm library").  Pure AlgoOperator-style Transformers do their
work in one jitted vector op; Imputer is an Estimator (it learns the fill
statistics).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.chain import (StageKernel, as_matrix as _as_mat,
                          f32_ceil, f32_floor, numeric_entry)
from ...api.stage import Estimator, Model, Transformer
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.param import (
    DoubleArrayParam,
    FloatParam,
    IntParam,
    ParamValidators,
    StringParam,
)
from ...params.shared import HasFeaturesCol, HasOutputCol
from ...utils import persist

__all__ = [
    "Binarizer",
    "Bucketizer",
    "Imputer",
    "ImputerModel",
    "Normalizer",
    "PolynomialExpansion",
]


class _InOutParams(HasFeaturesCol, HasOutputCol):
    pass


class _SimpleTransformer(_InOutParams, Transformer):
    """Shared column plumbing for the stateless transformers (save/load come
    from the Stage defaults — params-only persistence).  ``_apply`` receives
    the raw float64 batch: the host-side index transforms (Bucketizer,
    Binarizer) must compare at full precision; the jitted ones cast to f32
    themselves."""

    def _apply(self, X: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    #: exact-compare transforms (threshold / bucket index outputs) set
    #: this True: their kernels decline f64 columns (chain.numeric_entry)
    _exact_compare = False

    def _numeric_feature(self, schema) -> bool:
        return numeric_entry(schema, self.get_features_col(),
                             exact_compare=self._exact_compare) is not None

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        return [table.with_column(self.get_output_col(), self._apply(X))]


class Binarizer(_SimpleTransformer):
    """x -> 1.0 if x > threshold else 0.0, elementwise."""

    _exact_compare = True

    THRESHOLD = FloatParam("threshold", "Binarization threshold.",
                           default=0.0)

    def get_threshold(self) -> float:
        return self.get(Binarizer.THRESHOLD)

    def set_threshold(self, value: float):
        return self.set(Binarizer.THRESHOLD, value)

    def _apply(self, X: np.ndarray) -> np.ndarray:
        # pure host comparison: full float64 precision for the threshold
        return (X > self.get_threshold()).astype(np.float64)

    def transform_kernel(self, schema):
        """Chain kernel with the f32_floor SURROGATE threshold: for any
        f32 value ``v``, ``v > t ⟺ v > f32_floor(t)`` — the in-segment
        compare is bit-exact with the host-f64 stagewise compare on the
        segment's f32 columns."""
        if not self._numeric_feature(schema):
            return None
        thr = f32_floor(np.asarray([self.get_threshold()]))[0]
        return StageKernel(
            fn=_binarizer_kernel,
            static=(self.get_features_col(), self.get_output_col()),
            params={"threshold": np.float32(thr)},
            consumes=(self.get_features_col(),),
            produces=(self.get_output_col(),))


def _binarizer_kernel(static, params, cols):
    (fcol, ocol) = static
    X = _as_mat(cols[fcol])
    return {ocol: (X > params["threshold"]).astype(jnp.float32)}


class Bucketizer(_SimpleTransformer):
    """Map each value to the index of its half-open split interval
    ``[splits[i], splits[i+1])``.  Values outside the outer splits are
    *invalid* (as is NaN) and routed by ``handleInvalid`` (the Flink ML
    Bucketizer contract): ``"error"`` (default) raises, ``"keep"`` maps them
    into a dedicated extra bucket ``len(splits) - 1``, ``"clip"`` clamps
    into the first/last regular bucket (NaN still errors — it has no nearest
    bucket).  One ``searchsorted`` per column batch."""

    _exact_compare = True

    SPLITS = DoubleArrayParam(
        "splits", "Strictly increasing bucket boundaries (>= 3 values).",
        default=None, validator=ParamValidators.not_null())
    HANDLE_INVALID = StringParam(
        "handleInvalid",
        "Values outside the outer splits: error | keep | clip.",
        default="error",
        validator=ParamValidators.in_array(["error", "keep", "clip"]))

    def get_splits(self):
        return self.get(Bucketizer.SPLITS)

    def set_splits(self, *values: float):
        vals = values[0] if len(values) == 1 and not np.isscalar(values[0]) \
            else values
        return self.set(Bucketizer.SPLITS, tuple(float(v) for v in vals))

    def get_handle_invalid(self) -> str:
        return self.get(Bucketizer.HANDLE_INVALID)

    def set_handle_invalid(self, value: str):
        return self.set(Bucketizer.HANDLE_INVALID, value)

    def _apply(self, X: np.ndarray) -> np.ndarray:
        splits = np.asarray(self.get_splits(), np.float64)
        if len(splits) < 3:
            raise ValueError("Bucketizer needs >= 3 split values "
                             f"(got {len(splits)})")
        if not np.all(np.diff(splits) > 0):
            raise ValueError("Bucketizer splits must be strictly increasing")
        n_buckets = len(splits) - 1  # last regular bucket is closed on top
        nan = np.isnan(X)
        invalid = nan | (X < splits[0]) | (X > splits[-1])
        policy = self.get_handle_invalid()
        if np.any(invalid) and (policy == "error"
                                or (policy == "clip" and np.any(nan))):
            bad = X[invalid if policy == "error" else nan].ravel()[0]
            raise ValueError(
                f"Bucketizer got invalid value {bad} for splits "
                f"[{splits[0]}, {splits[-1]}]; set handleInvalid to 'keep' "
                "to accept it")
        idx = np.searchsorted(splits, X, side="right") - 1
        idx = np.clip(idx, 0, n_buckets - 1)  # top edge + 'clip' policy
        if policy == "keep":
            idx = np.where(invalid, n_buckets, idx)
        return idx.astype(np.float64)

    def transform_kernel(self, schema):
        """Chainable only under ``handleInvalid="keep"`` — the other
        policies raise on data the kernel would have to detect in-device.
        The splits carry f32_ceil/f32_floor surrogates so the searchsorted
        semantics (``#{splits[j] <= v}``) are bit-exact on f32 columns."""
        if self.get_handle_invalid() != "keep" \
                or not self._numeric_feature(schema):
            return None
        splits = np.asarray(self.get_splits(), np.float64)
        if len(splits) < 3 or not np.all(np.diff(splits) > 0):
            return None      # stagewise raises the diagnostic error
        return StageKernel(
            fn=_bucketizer_kernel,
            static=(self.get_features_col(), self.get_output_col()),
            params={"ceil_splits": f32_ceil(splits),
                    "lower": np.float32(f32_ceil(splits[:1])[0]),
                    "upper": np.float32(f32_floor(splits[-1:])[0]),
                    "n_buckets": np.int32(len(splits) - 1)},
            consumes=(self.get_features_col(),),
            produces=(self.get_output_col(),))


def _bucketizer_kernel(static, params, cols):
    (fcol, ocol) = static
    X = _as_mat(cols[fcol])
    nb = params["n_buckets"]
    # searchsorted(splits, X, "right") == #{j: splits[j] <= X}
    idx = jnp.sum(X[..., None] >= params["ceil_splits"], axis=-1) - 1
    idx = jnp.clip(idx, 0, nb - 1)
    invalid = jnp.isnan(X) | (X < params["lower"]) | (X > params["upper"])
    return {ocol: jnp.where(invalid, nb, idx).astype(jnp.float32)}


class Normalizer(_SimpleTransformer):
    """Scale each row to unit p-norm."""

    P = FloatParam("p", "Norm order.", default=2.0,
                   validator=ParamValidators.gt_eq(1.0))

    def get_p(self) -> float:
        return self.get(Normalizer.P)

    def set_p(self, value: float):
        return self.set(Normalizer.P, value)

    def _apply(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(_normalize(jnp.asarray(X, jnp.float32),
                                     self.get_p()))

    def transform_kernel(self, schema):
        if not self._numeric_feature(schema):
            return None
        return StageKernel(
            fn=_normalizer_kernel,
            static=(self.get_features_col(), self.get_output_col(),
                    float(self.get_p())),
            params={},
            consumes=(self.get_features_col(),),
            produces=(self.get_output_col(),))


def _normalizer_kernel(static, params, cols):
    (fcol, ocol, p) = static
    X = _as_mat(cols[fcol])
    # expression-identical to _normalize (p is plan-static)
    if np.isinf(p):
        norm = jnp.max(jnp.abs(X), axis=-1, keepdims=True)
    else:
        norm = jnp.sum(jnp.abs(X) ** p, axis=-1, keepdims=True) ** (1.0 / p)
    return {ocol: X / jnp.maximum(norm, 1e-12)}


@partial(jax.jit, static_argnums=(1,))
def _normalize(X, p):
    # |x|**inf over/underflows into a constant 1.0 norm, so inf-norm needs
    # its own branch (p is a static python float here).
    if np.isinf(p):
        norm = jnp.max(jnp.abs(X), axis=-1, keepdims=True)
    else:
        norm = jnp.sum(jnp.abs(X) ** p, axis=-1, keepdims=True) ** (1.0 / p)
    return X / jnp.maximum(norm, 1e-12)


def _poly_exponents(d: int, degree: int) -> np.ndarray:
    """(n_terms, d) monomial exponent rows, in the expansion order BOTH
    the stagewise and fused paths share — the ordering is the
    bit-exactness contract between them, so it lives in one place."""
    exponents: List[np.ndarray] = []

    def expand(prefix, remaining, start):
        for j in range(start, d):
            e = prefix.copy()
            e[j] += 1
            exponents.append(e.copy())
            if remaining > 1:
                expand(e, remaining - 1, j)

    expand(np.zeros(d, np.int64), degree, 0)
    return np.stack(exponents)


class PolynomialExpansion(_SimpleTransformer):
    """Expand features into all monomials up to ``degree`` (without the
    constant term), depth-first by variable index: for (x, y), degree 2 ->
    [x, x^2, xy, y, y^2]."""

    DEGREE = IntParam("degree", "Polynomial degree.", default=2,
                      validator=ParamValidators.gt_eq(1))

    def get_degree(self) -> int:
        return self.get(PolynomialExpansion.DEGREE)

    def set_degree(self, value: int):
        return self.set(PolynomialExpansion.DEGREE, value)

    def _apply(self, X: np.ndarray) -> np.ndarray:
        expo = _poly_exponents(X.shape[1], self.get_degree())
        return np.asarray(_poly_apply(jnp.asarray(X, jnp.float32),
                                      jnp.asarray(expo, jnp.float32)))

    def transform_kernel(self, schema):
        entry = numeric_entry(schema, self.get_features_col())
        if entry is None:
            return None
        shape = entry[0]
        d = int(shape[0]) if shape else 1
        expo = _poly_exponents(d, self.get_degree())
        return StageKernel(
            fn=_poly_chain_kernel,
            static=(self.get_features_col(), self.get_output_col()),
            params={"expo": expo.astype(np.float32)},
            consumes=(self.get_features_col(),),
            produces=(self.get_output_col(),))


def _poly_chain_kernel(static, params, cols):
    (fcol, ocol) = static
    X = _as_mat(cols[fcol])
    expo = params["expo"]
    return {ocol: jnp.prod(X[:, None, :] ** expo[None, :, :], axis=-1)}


@jax.jit
def _poly_apply(X, expo):
    # (n, 1, d) ** (terms, d) -> product over d: one fused power/reduce
    return jnp.prod(X[:, None, :] ** expo[None, :, :], axis=-1)


class ImputerParams(_InOutParams):
    STRATEGY = StringParam(
        "strategy", "Fill statistic.", default="mean",
        validator=ParamValidators.in_array(["mean", "median", "most_frequent"]))
    MISSING_VALUE = FloatParam(
        "missingValue", "Placeholder for missing entries (NaN always counts "
        "as missing).", default=float("nan"))

    def get_strategy(self) -> str:
        return self.get(ImputerParams.STRATEGY)

    def set_strategy(self, value: str):
        return self.set(ImputerParams.STRATEGY, value)

    def get_missing_value(self) -> float:
        return self.get(ImputerParams.MISSING_VALUE)

    def set_missing_value(self, value: float):
        return self.set(ImputerParams.MISSING_VALUE, value)


def _missing_mask(X: np.ndarray, missing: float) -> np.ndarray:
    mask = np.isnan(X)
    if not np.isnan(missing):
        mask |= X == missing
    return mask


class ImputerModel(ImputerParams, Model):
    def __init__(self):
        super().__init__()
        self._fill: Optional[np.ndarray] = None

    def set_model_data(self, *inputs) -> "ImputerModel":
        (t,) = inputs
        self._fill = np.asarray(t["fill"][0], np.float64)
        return self

    def _require_model(self) -> None:
        if self._fill is None:
            raise RuntimeError("ImputerModel has no model data; call "
                               "set_model_data() or fit an Imputer first")

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"fill": self._fill[None]})]

    def transform_kernel(self, schema):
        self._require_model()
        missing = self.get_missing_value()
        # equality only fires for f32-exact placeholders (+-inf included:
        # both are exact in f32): a non-exact placeholder can never equal
        # an f32 column value (the host path widens f32 exactly), so the
        # kernel drops the compare instead of matching the ROUNDED
        # placeholder against real values
        use_eq = (not np.isnan(missing)
                  and float(np.float32(missing)) == float(missing))
        # ANY non-NaN placeholder is an exact decision over the column
        # values, so f64 columns decline even when use_eq is False: f64
        # data can carry the placeholder exactly (host path fills it)
        # while entry rounding makes it unmatchable — only the NaN
        # placeholder survives rounding unchanged
        if numeric_entry(schema, self.get_features_col(),
                         exact_compare=not np.isnan(missing)) is None:
            return None
        return StageKernel(
            fn=_imputer_kernel,
            static=(self.get_features_col(), self.get_output_col(),
                    float(np.float32(missing)) if use_eq else None),
            params={"fill": np.asarray(self._fill, np.float32)},
            consumes=(self.get_features_col(),),
            produces=(self.get_output_col(),))

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        from ...api.chain import apply_kernel_or_none

        fetched = apply_kernel_or_none(
            self.transform_kernel(table.schema()), table)
        if fetched is None:     # object dtype / f32-unsafe ints: host path
            X = stack_vectors(
                table[self.get_features_col()]).astype(np.float64)
            mask = _missing_mask(X, self.get_missing_value())
            out = np.where(mask, self._fill[None, :], X)
        else:                   # device kernel: shared with the fused chain
            out = fetched[self.get_output_col()]
        return [table.with_column(self.get_output_col(), out)]

    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {"fill": self._fill})

    @classmethod
    def load(cls, path: str) -> "ImputerModel":
        model = persist.load_stage_param(path)
        model._fill = persist.load_model_arrays(
            path, "model")["fill"].astype(np.float64)
        return model


def _imputer_kernel(static, params, cols):
    (fcol, ocol, missing) = static
    X = _as_mat(cols[fcol])
    mask = jnp.isnan(X)
    if missing is not None:
        mask = mask | (X == missing)
    return {ocol: jnp.where(mask, params["fill"][None, :], X)}


class Imputer(ImputerParams, Estimator[ImputerModel]):
    """save/load come from the Stage defaults (params-only persistence)."""

    def fit(self, *inputs) -> ImputerModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        mask = _missing_mask(X, self.get_missing_value())
        masked = np.ma.masked_array(X, mask)
        strategy = self.get_strategy()
        if strategy == "mean":
            fill = masked.mean(axis=0)
        elif strategy == "median":
            fill = np.ma.median(masked, axis=0)
        else:  # most_frequent
            fill = np.empty(X.shape[1])
            for j in range(X.shape[1]):
                col = X[~mask[:, j], j]
                if len(col) == 0:
                    fill[j] = 0.0
                    continue
                vals, counts = np.unique(col, return_counts=True)
                fill[j] = vals[np.argmax(counts)]
        fill = np.asarray(np.ma.filled(fill, 0.0), np.float64)

        model = ImputerModel()
        model.copy_params_from(self)
        model._fill = fill
        return model
