"""Stateless-ish feature transformers: Bucketizer, Binarizer, Normalizer,
PolynomialExpansion, and the fitted Imputer.

All are members of the Flink ML 2.x feature-engineering surface (the
reference snapshot ships no feature transformers — its lib is KMeans only —
but the library line includes them; SURVEY §2.8 frames the lib module as
"the algorithm library").  Pure AlgoOperator-style Transformers do their
work in one jitted vector op; Imputer is an Estimator (it learns the fill
statistics).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator, Model, Transformer
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.param import (
    DoubleArrayParam,
    FloatParam,
    IntParam,
    ParamValidators,
    StringParam,
)
from ...params.shared import HasFeaturesCol, HasOutputCol
from ...utils import persist

__all__ = [
    "Binarizer",
    "Bucketizer",
    "Imputer",
    "ImputerModel",
    "Normalizer",
    "PolynomialExpansion",
]


class _InOutParams(HasFeaturesCol, HasOutputCol):
    pass


class _SimpleTransformer(_InOutParams, Transformer):
    """Shared column plumbing for the stateless transformers (save/load come
    from the Stage defaults — params-only persistence).  ``_apply`` receives
    the raw float64 batch: the host-side index transforms (Bucketizer,
    Binarizer) must compare at full precision; the jitted ones cast to f32
    themselves."""

    def _apply(self, X: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        return [table.with_column(self.get_output_col(), self._apply(X))]


class Binarizer(_SimpleTransformer):
    """x -> 1.0 if x > threshold else 0.0, elementwise."""

    THRESHOLD = FloatParam("threshold", "Binarization threshold.",
                           default=0.0)

    def get_threshold(self) -> float:
        return self.get(Binarizer.THRESHOLD)

    def set_threshold(self, value: float):
        return self.set(Binarizer.THRESHOLD, value)

    def _apply(self, X: np.ndarray) -> np.ndarray:
        # pure host comparison: full float64 precision for the threshold
        return (X > self.get_threshold()).astype(np.float64)


class Bucketizer(_SimpleTransformer):
    """Map each value to the index of its half-open split interval
    ``[splits[i], splits[i+1])``.  Values outside the outer splits are
    *invalid* (as is NaN) and routed by ``handleInvalid`` (the Flink ML
    Bucketizer contract): ``"error"`` (default) raises, ``"keep"`` maps them
    into a dedicated extra bucket ``len(splits) - 1``, ``"clip"`` clamps
    into the first/last regular bucket (NaN still errors — it has no nearest
    bucket).  One ``searchsorted`` per column batch."""

    SPLITS = DoubleArrayParam(
        "splits", "Strictly increasing bucket boundaries (>= 3 values).",
        default=None, validator=ParamValidators.not_null())
    HANDLE_INVALID = StringParam(
        "handleInvalid",
        "Values outside the outer splits: error | keep | clip.",
        default="error",
        validator=ParamValidators.in_array(["error", "keep", "clip"]))

    def get_splits(self):
        return self.get(Bucketizer.SPLITS)

    def set_splits(self, *values: float):
        vals = values[0] if len(values) == 1 and not np.isscalar(values[0]) \
            else values
        return self.set(Bucketizer.SPLITS, tuple(float(v) for v in vals))

    def get_handle_invalid(self) -> str:
        return self.get(Bucketizer.HANDLE_INVALID)

    def set_handle_invalid(self, value: str):
        return self.set(Bucketizer.HANDLE_INVALID, value)

    def _apply(self, X: np.ndarray) -> np.ndarray:
        splits = np.asarray(self.get_splits(), np.float64)
        if len(splits) < 3:
            raise ValueError("Bucketizer needs >= 3 split values "
                             f"(got {len(splits)})")
        if not np.all(np.diff(splits) > 0):
            raise ValueError("Bucketizer splits must be strictly increasing")
        n_buckets = len(splits) - 1  # last regular bucket is closed on top
        nan = np.isnan(X)
        invalid = nan | (X < splits[0]) | (X > splits[-1])
        policy = self.get_handle_invalid()
        if np.any(invalid) and (policy == "error"
                                or (policy == "clip" and np.any(nan))):
            bad = X[invalid if policy == "error" else nan].ravel()[0]
            raise ValueError(
                f"Bucketizer got invalid value {bad} for splits "
                f"[{splits[0]}, {splits[-1]}]; set handleInvalid to 'keep' "
                "to accept it")
        idx = np.searchsorted(splits, X, side="right") - 1
        idx = np.clip(idx, 0, n_buckets - 1)  # top edge + 'clip' policy
        if policy == "keep":
            idx = np.where(invalid, n_buckets, idx)
        return idx.astype(np.float64)


class Normalizer(_SimpleTransformer):
    """Scale each row to unit p-norm."""

    P = FloatParam("p", "Norm order.", default=2.0,
                   validator=ParamValidators.gt_eq(1.0))

    def get_p(self) -> float:
        return self.get(Normalizer.P)

    def set_p(self, value: float):
        return self.set(Normalizer.P, value)

    def _apply(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(_normalize(jnp.asarray(X, jnp.float32),
                                     self.get_p()))


@partial(jax.jit, static_argnums=(1,))
def _normalize(X, p):
    # |x|**inf over/underflows into a constant 1.0 norm, so inf-norm needs
    # its own branch (p is a static python float here).
    if np.isinf(p):
        norm = jnp.max(jnp.abs(X), axis=-1, keepdims=True)
    else:
        norm = jnp.sum(jnp.abs(X) ** p, axis=-1, keepdims=True) ** (1.0 / p)
    return X / jnp.maximum(norm, 1e-12)


class PolynomialExpansion(_SimpleTransformer):
    """Expand features into all monomials up to ``degree`` (without the
    constant term), depth-first by variable index: for (x, y), degree 2 ->
    [x, x^2, xy, y, y^2]."""

    DEGREE = IntParam("degree", "Polynomial degree.", default=2,
                      validator=ParamValidators.gt_eq(1))

    def get_degree(self) -> int:
        return self.get(PolynomialExpansion.DEGREE)

    def set_degree(self, value: int):
        return self.set(PolynomialExpansion.DEGREE, value)

    def _apply(self, X: np.ndarray) -> np.ndarray:
        degree = self.get_degree()
        d = X.shape[1]
        exponents: List[np.ndarray] = []

        def expand(prefix, remaining, start):
            for j in range(start, d):
                e = prefix.copy()
                e[j] += 1
                exponents.append(e.copy())
                if remaining > 1:
                    expand(e, remaining - 1, j)

        expand(np.zeros(d, np.int64), degree, 0)
        expo = np.stack(exponents)                      # (n_terms, d)
        return np.asarray(_poly_apply(jnp.asarray(X, jnp.float32),
                                      jnp.asarray(expo, jnp.float32)))


@jax.jit
def _poly_apply(X, expo):
    # (n, 1, d) ** (terms, d) -> product over d: one fused power/reduce
    return jnp.prod(X[:, None, :] ** expo[None, :, :], axis=-1)


class ImputerParams(_InOutParams):
    STRATEGY = StringParam(
        "strategy", "Fill statistic.", default="mean",
        validator=ParamValidators.in_array(["mean", "median", "most_frequent"]))
    MISSING_VALUE = FloatParam(
        "missingValue", "Placeholder for missing entries (NaN always counts "
        "as missing).", default=float("nan"))

    def get_strategy(self) -> str:
        return self.get(ImputerParams.STRATEGY)

    def set_strategy(self, value: str):
        return self.set(ImputerParams.STRATEGY, value)

    def get_missing_value(self) -> float:
        return self.get(ImputerParams.MISSING_VALUE)

    def set_missing_value(self, value: float):
        return self.set(ImputerParams.MISSING_VALUE, value)


def _missing_mask(X: np.ndarray, missing: float) -> np.ndarray:
    mask = np.isnan(X)
    if not np.isnan(missing):
        mask |= X == missing
    return mask


class ImputerModel(ImputerParams, Model):
    def __init__(self):
        super().__init__()
        self._fill: Optional[np.ndarray] = None

    def set_model_data(self, *inputs) -> "ImputerModel":
        (t,) = inputs
        self._fill = np.asarray(t["fill"][0], np.float64)
        return self

    def _require_model(self) -> None:
        if self._fill is None:
            raise RuntimeError("ImputerModel has no model data; call "
                               "set_model_data() or fit an Imputer first")

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"fill": self._fill[None]})]

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        mask = _missing_mask(X, self.get_missing_value())
        out = np.where(mask, self._fill[None, :], X)
        return [table.with_column(self.get_output_col(), out)]

    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {"fill": self._fill})

    @classmethod
    def load(cls, path: str) -> "ImputerModel":
        model = persist.load_stage_param(path)
        model._fill = persist.load_model_arrays(
            path, "model")["fill"].astype(np.float64)
        return model


class Imputer(ImputerParams, Estimator[ImputerModel]):
    """save/load come from the Stage defaults (params-only persistence)."""

    def fit(self, *inputs) -> ImputerModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        mask = _missing_mask(X, self.get_missing_value())
        masked = np.ma.masked_array(X, mask)
        strategy = self.get_strategy()
        if strategy == "mean":
            fill = masked.mean(axis=0)
        elif strategy == "median":
            fill = np.ma.median(masked, axis=0)
        else:  # most_frequent
            fill = np.empty(X.shape[1])
            for j in range(X.shape[1]):
                col = X[~mask[:, j], j]
                if len(col) == 0:
                    fill[j] = 0.0
                    continue
                vals, counts = np.unique(col, return_counts=True)
                fill[j] = vals[np.argmax(counts)]
        fill = np.asarray(np.ma.filled(fill, 0.0), np.float64)

        model = ImputerModel()
        model.copy_params_from(self)
        model._fill = fill
        return model
