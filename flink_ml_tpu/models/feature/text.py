"""Text/hashing feature stages: HashingTF, IDF, FeatureHasher, and
IndexToString (the StringIndexer inverse).

Members of the Flink ML 2.x feature surface.  Hashing uses a deterministic
FNV-1a over the value's string form (stable across runs and machines — a
requirement the reference family inherits from save/load).  The TF/IDF
scoring itself is device work: one elementwise log-scale op over the
document-term matrix.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator, Model, Transformer
from ...data.table import Table
from ...params.param import BoolParam, IntParam, ParamValidators
from ...params.shared import (
    HasFeaturesCol,
    HasInputCols,
    HasOutputCol,
)
from ...utils import native_text, persist

__all__ = ["HashingTF", "IDF", "IDFModel", "FeatureHasher", "IndexToString"]

_FNV_OFFSET = 14695981039346656037
_FNV_PRIME = 1099511628211
_FNV_MASK = (1 << 64) - 1


def _fnv1a(value) -> int:
    # Python-int arithmetic masked to 64 bits: identical wrap-around values
    # to uint64 hardware arithmetic, without numpy overflow warnings.
    h = _FNV_OFFSET
    for b in str(value).encode("utf-8"):
        h = ((h ^ b) * _FNV_PRIME) & _FNV_MASK
    return h


class HashingTF(HasOutputCol, HasFeaturesCol, Transformer):
    """Token sequences -> fixed-size term-frequency vectors by hashing.
    Input column: one list/array of tokens per row."""

    NUM_FEATURES = IntParam("numFeatures", "Hash-space size.", default=256,
                            validator=ParamValidators.gt(0))
    BINARY = BoolParam("binary", "1/0 presence instead of counts.",
                       default=False)

    def get_num_features(self) -> int:
        return self.get(HashingTF.NUM_FEATURES)

    def set_num_features(self, value: int):
        return self.set(HashingTF.NUM_FEATURES, value)

    def set_binary(self, value: bool):
        return self.set(HashingTF.BINARY, value)

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        docs = table[self.get_features_col()]
        m = self.get_num_features()
        binary = self.get(HashingTF.BINARY)
        # native batch fill (bit-identical hashes); per-byte Python loop
        # only as the no-toolchain fallback
        out = native_text.hashing_tf(docs, m, binary)
        if out is None:
            out = np.zeros((len(docs), m), np.float64)
            for i, doc in enumerate(docs):
                for token in np.ravel(np.asarray(doc, dtype=object)):
                    out[i, _fnv1a(token) % m] += 1.0
            if binary:
                out = (out > 0).astype(np.float64)
        return [table.with_column(self.get_output_col(), out)]

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)

    @classmethod
    def load(cls, path: str) -> "HashingTF":
        return persist.load_stage_param(path)


@jax.jit
def _idf_scale(tf, idf):
    return tf * idf[None, :]


class IDFModel(HasOutputCol, HasFeaturesCol, Model):
    def __init__(self):
        super().__init__()
        self._idf: Optional[np.ndarray] = None

    def set_model_data(self, *inputs) -> "IDFModel":
        (t,) = inputs
        self._idf = np.asarray(t["idf"][0], np.float64)
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"idf": self._idf[None]})]

    def _require_model(self) -> None:
        if self._idf is None:
            raise RuntimeError("IDFModel has no model data; call "
                               "set_model_data() or fit an IDF first")

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        tf = np.asarray(table[self.get_features_col()], np.float64)
        out = np.asarray(_idf_scale(jnp.asarray(tf, jnp.float32),
                                    jnp.asarray(self._idf, jnp.float32)),
                         np.float64)
        return [table.with_column(self.get_output_col(), out)]

    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {"idf": self._idf})

    @classmethod
    def load(cls, path: str) -> "IDFModel":
        model = persist.load_stage_param(path)
        model._idf = persist.load_model_arrays(
            path, "model")["idf"].astype(np.float64)
        return model


class IDF(HasOutputCol, HasFeaturesCol, Estimator[IDFModel]):
    """Learns ``log((n_docs + 1) / (df + 1))`` per term column."""

    MIN_DOC_FREQ = IntParam("minDocFreq",
                            "Terms below this document frequency get idf 0.",
                            default=0, validator=ParamValidators.gt_eq(0))

    def set_min_doc_freq(self, value: int):
        return self.set(IDF.MIN_DOC_FREQ, value)

    def fit(self, *inputs) -> IDFModel:
        (table,) = inputs
        tf = np.asarray(table[self.get_features_col()], np.float64)
        df = (tf > 0).sum(axis=0)
        idf = np.log((len(tf) + 1.0) / (df + 1.0))
        idf[df < self.get(IDF.MIN_DOC_FREQ)] = 0.0
        model = IDFModel()
        model.copy_params_from(self)
        model._idf = idf
        return model

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)

    @classmethod
    def load(cls, path: str) -> "IDF":
        return persist.load_stage_param(path)


class FeatureHasher(HasOutputCol, HasInputCols, Transformer):
    """Hash arbitrary columns into one fixed-size vector: numeric columns
    add their value at ``hash(colName)``, categorical/string columns add 1
    at ``hash(colName=value)`` (the classic hashing trick).

    With ``set_sparse_output(True)`` the transform never densifies: it emits
    the hashed PAIR columns ``{outputCol}_indices (n, n_cols) int32`` and
    ``{outputCol}_values (n, n_cols) float32`` — one active slot per input
    column — which the linear family scores directly against a dense weight
    (``models/common/linear.py::resolve_features``).  This is what makes
    2^20+ hash spaces (the Criteo shape) usable: the dense form would be an
    ``(n, 2^20)`` matrix.  Within-row slot collisions stay as separate pair
    entries; gather/scatter sums them, matching the dense semantics."""

    NUM_FEATURES = IntParam("numFeatures", "Hash-space size.", default=256,
                            validator=ParamValidators.gt(0))
    SPARSE_OUTPUT = BoolParam(
        "sparseOutput",
        "Emit {outputCol}_indices/{outputCol}_values pair columns instead "
        "of a dense matrix.", default=False)

    def get_num_features(self) -> int:
        return self.get(FeatureHasher.NUM_FEATURES)

    def set_num_features(self, value: int):
        return self.set(FeatureHasher.NUM_FEATURES, value)

    def set_sparse_output(self, value: bool):
        return self.set(FeatureHasher.SPARSE_OUTPUT, value)

    def _hash_columns(self, table: Table, in_cols, m: int):
        """Per input column: (slot indices (n,), float64 values (n,)).
        Categorical columns hash each distinct value once (np.unique +
        inverse) instead of per row.  Values stay float64 here; only the
        device-facing sparse pair output downcasts to f32."""
        n = table.num_rows
        idx_cols, val_cols = [], []
        for col in in_cols:
            values = np.asarray(table[col])
            if np.issubdtype(values.dtype, np.number):
                idx_cols.append(np.full((n,), _fnv1a(col) % m, np.int32))
                val_cols.append(values.astype(np.float64))
            else:
                uniq, inverse = np.unique(values, return_inverse=True)
                keys = [f"{col}={u}" for u in uniq]
                hashes = native_text.fnv1a_batch(keys)
                if hashes is None:
                    hashes = np.asarray([_fnv1a(k) for k in keys], np.uint64)
                slots = (hashes % np.uint64(m)).astype(np.int32)
                idx_cols.append(slots[inverse])
                val_cols.append(np.ones((n,), np.float64))
        return idx_cols, val_cols

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        in_cols = self.get_input_cols()
        if not in_cols:
            raise ValueError("FeatureHasher requires inputCols")
        m = self.get_num_features()
        idx_cols, val_cols = self._hash_columns(table, in_cols, m)
        out_col = self.get_output_col()
        if self.get(FeatureHasher.SPARSE_OUTPUT):
            return [table
                    .with_column(f"{out_col}_indices",
                                 np.stack(idx_cols, axis=1))
                    .with_column(f"{out_col}_values",
                                 np.stack(val_cols, axis=1)
                                 .astype(np.float32))]
        out = np.zeros((table.num_rows, m), np.float64)
        rows = np.arange(table.num_rows)
        for idx, vals in zip(idx_cols, val_cols):
            np.add.at(out, (rows, idx), vals)
        return [table.with_column(out_col, out)]

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)

    @classmethod
    def load(cls, path: str) -> "FeatureHasher":
        return persist.load_stage_param(path)


class IndexToString(HasOutputCol, HasFeaturesCol, Transformer):
    """Inverse of StringIndexer: dense ids -> original label values, using
    the labels array set via ``set_labels`` (or taken from a fitted
    StringIndexerModel's vocabulary)."""

    def __init__(self):
        super().__init__()
        self._labels: Optional[np.ndarray] = None

    def set_labels(self, labels) -> "IndexToString":
        self._labels = np.asarray(labels)
        return self

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        if self._labels is None:
            raise RuntimeError("IndexToString needs set_labels(...) first")
        idx = np.asarray(table[self.get_features_col()], np.int64)
        if idx.size and (idx.min() < 0 or idx.max() >= len(self._labels)):
            raise ValueError(f"index out of range for {len(self._labels)} "
                             "labels")
        return [table.with_column(self.get_output_col(), self._labels[idx])]

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {"labels": self._labels
                                                  if self._labels is not None
                                                  else np.zeros(0)})

    @classmethod
    def load(cls, path: str) -> "IndexToString":
        stage = persist.load_stage_param(path)
        labels = persist.load_model_arrays(path, "model")["labels"]
        stage._labels = labels if len(labels) else None
        return stage
