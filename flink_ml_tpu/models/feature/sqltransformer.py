"""SQLTransformer — SQL-style SELECT over a Table.

Member of the Flink ML 2.x feature surface (``feature/sqltransformer``;
the reference snapshot ships none — SURVEY §2.8).  The reference family
hands the statement to the host SQL engine with ``__THIS__`` standing for
the input table; this build has no SQL engine (and needs none: the Table
substrate is columnar numpy), so the statement is parsed into columnar
numpy expressions instead:

    SELECT <expr> [AS <name>], ... FROM __THIS__ [WHERE <cond>]

Supported in expressions: column names, literals, ``* `` for all columns,
arithmetic (+ - * / % **), comparisons, AND/OR/NOT, parentheses, and the
functions ABS, SQRT, EXP, LOG, LOG1P, SIN, COS, FLOOR, CEIL, ROUND, MIN,
MAX, POW, PLUS aggregate-free whole-column semantics (everything is
vectorized over rows).  Expressions are compiled through Python's ``ast``
with a strict whitelist — no attribute access, no calls outside the
function table, no names outside the column set — so a statement can
compute, it cannot reach into the process.
"""

from __future__ import annotations

import ast
import re
from typing import Any, Dict, List

import numpy as np

from ...api.stage import Transformer
from ...data.table import Table
from ...params.param import ParamValidators, StringParam

__all__ = ["SQLTransformer"]

_FUNCTIONS = {
    "abs": np.abs, "sqrt": np.sqrt, "exp": np.exp, "log": np.log,
    "log1p": np.log1p, "sin": np.sin, "cos": np.cos, "floor": np.floor,
    "ceil": np.ceil, "round": np.round, "min": np.minimum,
    "max": np.maximum, "pow": np.power,
}

_STATEMENT_RE = re.compile(
    r"^\s*select\s+(?P<select>.+?)\s+from\s+__THIS__\s*"
    r"(?:where\s+(?P<where>.+?)\s*)?$",
    re.IGNORECASE | re.DOTALL)

# SQL-isms normalised before ast-parsing as a Python expression.  All
# rewrites and the comma splitter run on a LITERAL-MASKED statement (see
# _mask_literals) so quoted strings are never corrupted.
_SQL_TO_PY = [
    (re.compile(r"(?<![<>!=])=(?!=)"), "=="),   # single = is equality
    (re.compile(r"<>"), "!="),
    (re.compile(r"\bAND\b", re.IGNORECASE), " and "),
    (re.compile(r"\bOR\b", re.IGNORECASE), " or "),
    (re.compile(r"\bNOT\b", re.IGNORECASE), " not "),
]

_LITERAL_RE = re.compile(r"'[^']*'")


def _mask_literals(statement: str):
    """Replace single-quoted literals with digit-only placeholders so the
    keyword/operator rewrites and the comma splitter cannot touch their
    contents; returns (masked, unmask_fn)."""
    literals: List[str] = []

    def stash(match):
        literals.append(match.group(0))
        return f"\x00{len(literals) - 1}\x00"

    masked = _LITERAL_RE.sub(stash, statement)

    def unmask(text: str) -> str:
        return re.sub(r"\x00(\d+)\x00",
                      lambda m: literals[int(m.group(1))], text)

    return masked, unmask

_ALLOWED_NODES = (
    ast.Expression, ast.BinOp, ast.UnaryOp, ast.BoolOp, ast.Compare,
    ast.Call, ast.Name, ast.Constant, ast.Load,
    ast.Add, ast.Sub, ast.Mult, ast.Div, ast.Mod, ast.Pow,
    ast.USub, ast.UAdd, ast.Not, ast.And, ast.Or,
    ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
)


def _check_ast(tree: ast.AST, columns) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise ValueError(
                f"unsupported syntax in SQLTransformer statement: "
                f"{type(node).__name__}")
        if isinstance(node, ast.Call):
            if not isinstance(node.func, ast.Name) \
                    or node.func.id.lower() not in _FUNCTIONS:
                raise ValueError(
                    "unknown function in SQLTransformer statement"
                    + (f": {node.func.id!r}"
                       if isinstance(node.func, ast.Name) else ""))
            if node.keywords:
                raise ValueError("keyword arguments are not supported")
        elif isinstance(node, ast.Name):
            if node.id not in columns \
                    and node.id.lower() not in _FUNCTIONS:
                raise ValueError(
                    f"unknown column {node.id!r}; available: "
                    f"{sorted(columns)}")


class _Evaluator(ast.NodeVisitor):
    def __init__(self, columns: Dict[str, np.ndarray]):
        self.columns = columns

    def visit_Expression(self, node):
        return self.visit(node.body)

    def visit_Constant(self, node):
        return node.value

    def visit_Name(self, node):
        if node.id in self.columns:
            return self.columns[node.id]
        return _FUNCTIONS[node.id.lower()]

    def visit_Call(self, node):
        fn = _FUNCTIONS[node.func.id.lower()]
        return fn(*[self.visit(a) for a in node.args])

    def visit_BinOp(self, node):
        left, right = self.visit(node.left), self.visit(node.right)
        op = type(node.op)
        if op is ast.Add:
            return left + right
        if op is ast.Sub:
            return left - right
        if op is ast.Mult:
            return left * right
        if op is ast.Div:
            return left / right
        if op is ast.Mod:
            return left % right
        return left ** right          # ast.Pow (whitelist-bounded)

    def visit_UnaryOp(self, node):
        val = self.visit(node.operand)
        if isinstance(node.op, ast.USub):
            return -val
        if isinstance(node.op, ast.Not):
            return np.logical_not(val)
        return val                     # UAdd

    def visit_BoolOp(self, node):
        vals = [np.asarray(self.visit(v), bool) for v in node.values]
        out = vals[0]
        for v in vals[1:]:
            out = (out & v) if isinstance(node.op, ast.And) else (out | v)
        return out

    def visit_Compare(self, node):
        left = self.visit(node.left)
        out = None
        for op, comp in zip(node.ops, node.comparators):
            right = self.visit(comp)
            op_t = type(op)
            if op_t is ast.Eq:
                res = left == right
            elif op_t is ast.NotEq:
                res = left != right
            elif op_t is ast.Lt:
                res = left < right
            elif op_t is ast.LtE:
                res = left <= right
            elif op_t is ast.Gt:
                res = left > right
            else:
                res = left >= right
            out = res if out is None else (out & res)
            left = right
        return out


def _split_select_list(select: str) -> List[str]:
    """Split on top-level commas (not inside parentheses)."""
    parts, depth, start = [], 0, 0
    for i, ch in enumerate(select):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(select[start:i].strip())
            start = i + 1
    parts.append(select[start:].strip())
    return [p for p in parts if p]


_AS_RE = re.compile(r"^(?P<expr>.+?)\s+as\s+(?P<name>[A-Za-z_]\w*)\s*$",
                    re.IGNORECASE | re.DOTALL)


class SQLTransformer(Transformer):
    STATEMENT = StringParam(
        "statement",
        "SELECT <expr> [AS <name>], ... FROM __THIS__ [WHERE <cond>].",
        default=None, validator=ParamValidators.not_null())

    def get_statement(self) -> str:
        return self.get(SQLTransformer.STATEMENT)

    def set_statement(self, value: str):
        return self.set(SQLTransformer.STATEMENT, value)

    @staticmethod
    def _eval(expr: str, columns: Dict[str, np.ndarray],
              unmask=None) -> Any:
        for pattern, repl in _SQL_TO_PY:
            expr = pattern.sub(repl, expr)
        if unmask is not None:
            expr = unmask(expr)
        try:
            tree = ast.parse(expr.strip(), mode="eval")
        except SyntaxError as exc:
            raise ValueError(
                f"SQLTransformer could not parse expression {expr!r}: "
                f"{exc.msg}") from exc
        _check_ast(tree, columns.keys())
        return _Evaluator(columns).visit(tree)

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        masked, unmask = _mask_literals(self.get_statement())
        match = _STATEMENT_RE.match(masked)
        if not match:
            raise ValueError(
                "SQLTransformer statement must be of the form "
                "'SELECT ... FROM __THIS__ [WHERE ...]' "
                f"(got {self.get_statement()!r})")
        columns = table.to_dict()

        where = match.group("where")
        if where:
            mask = np.asarray(self._eval(where, columns, unmask), bool)
            if mask.ndim != 1 or mask.shape[0] != table.num_rows:
                raise ValueError("WHERE clause must produce one boolean "
                                 "per row")
            columns = {n: c[mask] for n, c in columns.items()}

        out: Dict[str, np.ndarray] = {}
        n_rows = next(iter(columns.values())).shape[0] if columns else 0
        for i, item in enumerate(_split_select_list(match.group("select"))):
            if item == "*":
                out.update(columns)
                continue
            as_match = _AS_RE.match(item)
            expr = as_match.group("expr") if as_match else item
            name = (as_match.group("name") if as_match
                    else (expr if re.fullmatch(r"[A-Za-z_]\w*", expr)
                          else f"col{i}"))
            value = self._eval(expr, columns, unmask)
            value = np.asarray(value)
            if value.ndim == 0:        # scalar literal: broadcast
                value = np.full((n_rows,), value)
            out[name] = value
        return [Table(out)]
