"""Token-stream stages: Tokenizer, RegexTokenizer, NGram,
StopWordsRemover, and the fitted CountVectorizer.

Members of the Flink ML 2.x feature surface (the reference snapshot's lib
is KMeans-only — SURVEY §2.8).  Tokenization is inherently host string
work; the vocabulary counting of CountVectorizer and its transform-time
document-term matrix are built with integer ``np.bincount`` passes so the
resulting dense (rows, vocab) matrix lands device-ready for the TF/IDF
device ops downstream (``text.IDF``).

Token columns are numpy object arrays (one token list per row) — the same
convention ``HashingTF`` consumes.
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence

import numpy as np

from ...api.stage import Estimator, Model, Transformer
from ...data.table import Table
from ...params.param import (
    BoolParam,
    FloatParam,
    IntParam,
    ParamValidators,
    StringParam,
    StringArrayParam,
)
from ...params.shared import HasFeaturesCol, HasOutputCol
from ...utils import persist

__all__ = [
    "CountVectorizer",
    "CountVectorizerModel",
    "NGram",
    "RegexTokenizer",
    "StopWordsRemover",
    "Tokenizer",
]

# The Glasgow/Snowball English list the Flink ML / Spark
# StopWordsRemover.loadDefaultStopWords("english") family ships.
_ENGLISH_STOP_WORDS = (
    "a about above after again against all am an and any are aren't as at "
    "be because been before being below between both but by can't cannot "
    "could couldn't did didn't do does doesn't doing don't down during "
    "each few for from further had hadn't has hasn't have haven't having "
    "he he'd he'll he's her here here's hers herself him himself his how "
    "how's i i'd i'll i'm i've if in into is isn't it it's its itself "
    "let's me more most mustn't my myself no nor not of off on once only "
    "or other ought our ours ourselves out over own same shan't she she'd "
    "she'll she's should shouldn't so some such than that that's the their "
    "theirs them themselves then there there's these they they'd they'll "
    "they're they've this those through to too under until up very was "
    "wasn't we we'd we'll we're we've were weren't what what's when when's "
    "where where's which while who who's whom why why's with won't would "
    "wouldn't you you'd you'll you're you've your yours yourself yourselves"
).split()


def _tokens_array(rows: Sequence[List[str]]) -> np.ndarray:
    out = np.empty((len(rows),), object)
    for i, r in enumerate(rows):
        out[i] = list(r)
    return out


def _doc_tokens(doc) -> List[str]:
    """Canonical token-list view of one row of a token column."""
    return [str(t) for t in np.ravel(np.asarray(doc, dtype=object))]


def _iter_docs(col: np.ndarray):
    for doc in col:
        yield _doc_tokens(doc)


class _TokenTransformer(HasFeaturesCol, HasOutputCol, Transformer):
    """Shared plumbing: string/token column in, token column out.
    ``_row_fn`` is built once per transform call so per-row work reads no
    params and compiles no regexes."""

    def _row_fn(self):
        raise NotImplementedError

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        col = table[self.get_features_col()]
        fn = self._row_fn()
        rows = [fn(doc) for doc in col]
        return [table.with_column(self.get_output_col(),
                                  _tokens_array(rows))]


_SINGLE_WS = re.compile(r"\s")


class Tokenizer(_TokenTransformer):
    """Lowercase, then split on every single whitespace character — the
    Flink ML / Spark Tokenizer rule (Java ``split("\\s")``): consecutive
    whitespace yields empty interior tokens, trailing empties drop."""

    def _row_fn(self):
        def apply(doc):
            tokens = _SINGLE_WS.split(str(doc).lower())
            while tokens and tokens[-1] == "":
                tokens.pop()
            return tokens
        return apply


class RegexTokenizer(_TokenTransformer):
    """Regex-driven tokenization: ``gaps=True`` splits on matches of
    ``pattern``; ``gaps=False`` emits the matches themselves.  Tokens
    shorter than ``minTokenLength`` are dropped."""

    PATTERN = StringParam("pattern", "Split/match regex.", default=r"\s+")
    GAPS = BoolParam("gaps", "Pattern matches gaps (split) vs tokens.",
                     default=True)
    MIN_TOKEN_LENGTH = IntParam("minTokenLength", "Drop shorter tokens.",
                                default=1,
                                validator=ParamValidators.gt_eq(0))
    TO_LOWERCASE = BoolParam("toLowercase", "Lowercase before tokenizing.",
                             default=True)

    def get_pattern(self) -> str:
        return self.get(RegexTokenizer.PATTERN)

    def set_pattern(self, value: str):
        return self.set(RegexTokenizer.PATTERN, value)

    def set_gaps(self, value: bool):
        return self.set(RegexTokenizer.GAPS, bool(value))

    def set_min_token_length(self, value: int):
        return self.set(RegexTokenizer.MIN_TOKEN_LENGTH, value)

    def set_to_lowercase(self, value: bool):
        return self.set(RegexTokenizer.TO_LOWERCASE, bool(value))

    def _row_fn(self):
        lower = self.get(RegexTokenizer.TO_LOWERCASE)
        pattern = re.compile(self.get_pattern())
        gaps = self.get(RegexTokenizer.GAPS)
        min_len = self.get(RegexTokenizer.MIN_TOKEN_LENGTH)

        def apply(doc):
            text = str(doc).lower() if lower else str(doc)
            tokens = pattern.split(text) if gaps else pattern.findall(text)
            return [t for t in tokens if len(t) >= min_len]
        return apply


class NGram(_TokenTransformer):
    """Token list -> space-joined n-grams (rows shorter than ``n`` yield an
    empty list, the Flink ML NGram contract)."""

    N = IntParam("n", "Gram length.", default=2,
                 validator=ParamValidators.gt_eq(1))

    def get_n(self) -> int:
        return self.get(NGram.N)

    def set_n(self, value: int):
        return self.set(NGram.N, value)

    def _row_fn(self):
        n = self.get_n()

        def apply(doc):
            tokens = _doc_tokens(doc)
            return [" ".join(tokens[i:i + n])
                    for i in range(len(tokens) - n + 1)]
        return apply


class StopWordsRemover(_TokenTransformer):
    """Filter stop words out of a token list.  Defaults to the English
    list; ``caseSensitive=False`` (default) compares casefolded."""

    STOP_WORDS = StringArrayParam(
        "stopWords", "Words to remove.",
        default=tuple(_ENGLISH_STOP_WORDS))
    CASE_SENSITIVE = BoolParam("caseSensitive", "Exact-case comparison.",
                               default=False)

    def get_stop_words(self):
        return self.get(StopWordsRemover.STOP_WORDS)

    def set_stop_words(self, *words: str):
        vals = words[0] if len(words) == 1 and not isinstance(words[0], str) \
            else words
        return self.set(StopWordsRemover.STOP_WORDS,
                        tuple(str(w) for w in vals))

    def set_case_sensitive(self, value: bool):
        return self.set(StopWordsRemover.CASE_SENSITIVE, bool(value))

    @staticmethod
    def load_default_stop_words(language: str = "english"):
        if language != "english":
            raise ValueError(
                f"no built-in stop words for language {language!r}")
        return tuple(_ENGLISH_STOP_WORDS)

    def _row_fn(self):
        if self.get(StopWordsRemover.CASE_SENSITIVE):
            stop = set(self.get_stop_words())
            return lambda doc: [t for t in _doc_tokens(doc) if t not in stop]
        stop = {w.casefold() for w in self.get_stop_words()}
        return lambda doc: [t for t in _doc_tokens(doc)
                            if t.casefold() not in stop]


# ---------------------------------------------------------------------------
# CountVectorizer
# ---------------------------------------------------------------------------

class CountVectorizerParams(HasFeaturesCol, HasOutputCol):
    VOCABULARY_SIZE = IntParam(
        "vocabularySize", "Max vocabulary size.", default=1 << 18,
        validator=ParamValidators.gt(0))
    MIN_DF = FloatParam(
        "minDF", "Min document frequency (fraction if < 1, else count).",
        default=1.0, validator=ParamValidators.gt_eq(0.0))
    MAX_DF = FloatParam(
        "maxDF", "Max document frequency (fraction if < 1, else count).",
        default=float(1 << 62), validator=ParamValidators.gt_eq(0.0))
    MIN_TF = FloatParam(
        "minTF", "Per-document min term frequency filter at transform "
        "(fraction of doc length if < 1, else count).", default=1.0,
        validator=ParamValidators.gt_eq(0.0))
    BINARY = BoolParam("binary", "1/0 presence instead of counts.",
                       default=False)

    def get_vocabulary_size(self) -> int:
        return self.get(CountVectorizerParams.VOCABULARY_SIZE)

    def set_vocabulary_size(self, value: int):
        return self.set(CountVectorizerParams.VOCABULARY_SIZE, value)

    def get_min_df(self) -> float:
        return self.get(CountVectorizerParams.MIN_DF)

    def set_min_df(self, value: float):
        return self.set(CountVectorizerParams.MIN_DF, value)

    def get_max_df(self) -> float:
        return self.get(CountVectorizerParams.MAX_DF)

    def set_max_df(self, value: float):
        return self.set(CountVectorizerParams.MAX_DF, value)

    def get_min_tf(self) -> float:
        return self.get(CountVectorizerParams.MIN_TF)

    def set_min_tf(self, value: float):
        return self.set(CountVectorizerParams.MIN_TF, value)

    def set_binary(self, value: bool):
        return self.set(CountVectorizerParams.BINARY, bool(value))


class CountVectorizerModel(CountVectorizerParams, Model):
    """Vocabulary-indexed term counting: transform emits the dense
    (rows, vocab) document-term matrix in vocabulary order."""

    def __init__(self):
        super().__init__()
        self._vocabulary: Optional[np.ndarray] = None
        self._index: Optional[dict] = None

    @property
    def vocabulary(self) -> List[str]:
        self._require_model()
        return [str(v) for v in self._vocabulary]

    def _set_vocabulary(self, vocab: np.ndarray) -> None:
        self._vocabulary = vocab
        self._index = {str(v): i for i, v in enumerate(vocab)}

    def set_model_data(self, *inputs) -> "CountVectorizerModel":
        (t,) = inputs
        self._set_vocabulary(np.asarray(t["vocabulary"], dtype=np.str_))
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"vocabulary": self._vocabulary})]

    def _require_model(self) -> None:
        if self._vocabulary is None:
            raise RuntimeError("CountVectorizerModel has no model data")

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        col = table[self.get_features_col()]
        index = self._index
        v = len(index)
        min_tf = self.get_min_tf()
        out = np.zeros((len(col), v), np.float64)
        for i, tokens in enumerate(_iter_docs(col)):
            ids = [index[t] for t in tokens if t in index]
            if not ids:
                continue
            counts = np.bincount(np.asarray(ids, np.int64), minlength=v)
            bound = min_tf * len(tokens) if min_tf < 1.0 else min_tf
            out[i] = np.where(counts >= bound, counts, 0)
        if self.get(CountVectorizerParams.BINARY):
            out = (out > 0).astype(np.float64)
        return [table.with_column(self.get_output_col(), out)]

    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(
            path, "model", {"vocabulary": np.asarray(self._vocabulary)})

    @classmethod
    def load(cls, path: str) -> "CountVectorizerModel":
        model = persist.load_stage_param(path)
        model._set_vocabulary(persist.load_model_arrays(
            path, "model")["vocabulary"].astype(np.str_))
        return model


class CountVectorizer(CountVectorizerParams,
                      Estimator[CountVectorizerModel]):
    """Learns the vocabulary: terms ranked by corpus frequency (ties
    broken lexically for determinism), filtered by document-frequency
    bounds, truncated to ``vocabularySize``."""

    def fit(self, *inputs) -> CountVectorizerModel:
        (table,) = inputs
        col = table[self.get_features_col()]
        n_docs = len(col)
        term_freq: dict = {}
        doc_freq: dict = {}
        for tokens in _iter_docs(col):
            seen = set()
            for t in tokens:
                term_freq[t] = term_freq.get(t, 0) + 1
                if t not in seen:
                    seen.add(t)
                    doc_freq[t] = doc_freq.get(t, 0) + 1

        min_df, max_df = self.get_min_df(), self.get_max_df()
        lo = min_df * n_docs if min_df < 1.0 else min_df
        hi = max_df * n_docs if max_df < 1.0 else max_df
        terms = [t for t, df in doc_freq.items() if lo <= df <= hi]
        terms.sort(key=lambda t: (-term_freq[t], t))
        terms = terms[: self.get_vocabulary_size()]

        model = CountVectorizerModel()
        model.copy_params_from(self)
        model._set_vocabulary(np.asarray(terms, dtype=np.str_))
        return model
