"""MinHashLSH — locality-sensitive hashing for Jaccard similarity.

Member of the Flink ML 2.x feature surface (``feature/lsh``; the
reference snapshot ships no LSH — SURVEY §2.8).  Vectors are treated as
binary sets (nonzero positions).  Each hash function is the classic
universal hash ``((1 + i) * a + b) mod P`` minimized over the active
indices; the model carries ``numHashTables`` tables of
``numHashFunctionsPerTable`` functions.

TPU split: the min-hash of a whole batch is one jitted reduce — the
(d, m) hash-value table is precomputed once, and each row takes a masked
min over its active indices (``where`` + ``min``), so the batch never
leaves the device.  Candidate bucketing for the approximate queries is
host-side set arithmetic over the tiny per-table signatures.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator, Model
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.param import IntParam, ParamValidators
from ...params.shared import HasSeed
from ...utils import persist
from .transforms import _InOutParams

__all__ = ["MinHashLSH", "MinHashLSHModel"]

_MINHASH_PRIME = 2038074743


class MinHashLSHParams(_InOutParams, HasSeed):
    NUM_HASH_TABLES = IntParam(
        "numHashTables", "Number of hash tables (OR-amplification).",
        default=1, validator=ParamValidators.gt(0))
    NUM_HASH_FUNCTIONS_PER_TABLE = IntParam(
        "numHashFunctionsPerTable",
        "Hash functions per table (AND-amplification).",
        default=1, validator=ParamValidators.gt(0))

    def get_num_hash_tables(self) -> int:
        return self.get(MinHashLSHParams.NUM_HASH_TABLES)

    def set_num_hash_tables(self, value: int):
        return self.set(MinHashLSHParams.NUM_HASH_TABLES, value)

    def get_num_hash_functions_per_table(self) -> int:
        return self.get(MinHashLSHParams.NUM_HASH_FUNCTIONS_PER_TABLE)

    def set_num_hash_functions_per_table(self, value: int):
        return self.set(
            MinHashLSHParams.NUM_HASH_FUNCTIONS_PER_TABLE, value)


@jax.jit
def _minhash_batch(X, hash_values):
    """(n, d) binary batch x (d, m) int32 hash table -> (n, m) signatures:
    min of each hash column over the row's active indices.  Integer math —
    hash values reach ~2^31 and must compare exactly (f32 would merge
    distinct buckets at 24-bit mantissa resolution)."""
    active = X[:, :, None] > 0                       # (n, d, 1)
    vals = jnp.where(active, hash_values[None, :, :],
                     jnp.int32(_MINHASH_PRIME + 1))
    return jnp.min(vals, axis=1)                     # (n, m)


def _jaccard_distance(a: np.ndarray, B: np.ndarray) -> np.ndarray:
    """1 - |A ∩ B| / |A ∪ B| between one binary row and a batch."""
    a = a > 0
    B = B > 0
    inter = (a[None, :] & B).sum(axis=1)
    union = (a[None, :] | B).sum(axis=1)
    return 1.0 - inter / np.maximum(union, 1)


class MinHashLSHModel(MinHashLSHParams, Model):
    def __init__(self):
        super().__init__()
        self._coeff: Optional[np.ndarray] = None     # (m, 2) [a, b]

    def set_model_data(self, *inputs) -> "MinHashLSHModel":
        (t,) = inputs
        self._coeff = np.asarray(t["coefficients"], np.int64)
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"coefficients": self._coeff})]

    def _require_model(self) -> None:
        if self._coeff is None:
            raise RuntimeError("MinHashLSHModel has no model data")

    # -- hashing ------------------------------------------------------------
    def _signatures(self, X: np.ndarray) -> np.ndarray:
        """(n, tables, fns) float64 signatures."""
        self._require_model()
        if np.any((X > 0).sum(axis=1) == 0):
            raise ValueError("MinHashLSH requires at least one nonzero "
                             "entry per vector")
        d = X.shape[1]
        idx = np.arange(1, d + 1, dtype=np.int64)[:, None]   # 1-based
        a, b = self._coeff[:, 0][None, :], self._coeff[:, 1][None, :]
        table = ((idx * a + b) % _MINHASH_PRIME).astype(np.int32)
        sig = np.asarray(_minhash_batch(
            jnp.asarray(X > 0, jnp.float32), jnp.asarray(table)), np.float64)
        return sig.reshape(X.shape[0], self.get_num_hash_tables(),
                           self.get_num_hash_functions_per_table())

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()])
        return [table.with_column(self.get_output_col(),
                                  self._signatures(X))]

    # -- approximate queries -------------------------------------------------
    def _bucket_sets(self, sig: np.ndarray) -> List[set]:
        """Per-row set of hashable per-table bucket ids."""
        return [{(t, tuple(sig[i, t])) for t in range(sig.shape[1])}
                for i in range(sig.shape[0])]

    def approx_nearest_neighbors(self, dataset: Table, key: np.ndarray,
                                 k: int, features_col: Optional[str] = None
                                 ) -> Table:
        """Rows of ``dataset`` sharing >= 1 hash bucket with ``key``,
        ranked by true Jaccard distance, top-k; appends a ``distCol``
        column (falls back to a full scan when no bucket collides, like
        the Flink ML implementation's single-probe behavior does not —
        documented deviation for usability)."""
        col = features_col or self.get_features_col()
        X = stack_vectors(dataset[col])
        key = np.asarray(key, np.float64).ravel()
        sig = self._signatures(X)
        key_sig = self._signatures(key[None, :])
        key_buckets = self._bucket_sets(key_sig)[0]
        rows = self._bucket_sets(sig)
        cand = np.asarray([bool(r & key_buckets) for r in rows])
        if not cand.any():
            cand = np.ones(len(rows), bool)
        idx = np.flatnonzero(cand)
        dist = _jaccard_distance(key, X[idx])
        order = np.argsort(dist, kind="stable")[:k]
        out = dataset.select_rows(idx[order])
        return out.with_column("distCol", dist[order])

    def approx_similarity_join(self, table_a: Table, table_b: Table,
                               threshold: float, id_col: str) -> Table:
        """(idA, idB, distCol) for cross pairs sharing >= 1 bucket with
        Jaccard distance < threshold."""
        Xa = stack_vectors(table_a[self.get_features_col()])
        Xb = stack_vectors(table_b[self.get_features_col()])
        buckets_a = self._bucket_sets(self._signatures(Xa))
        buckets_b = self._bucket_sets(self._signatures(Xb))
        by_bucket: dict = {}
        for j, bs in enumerate(buckets_b):
            for bucket in bs:
                by_bucket.setdefault(bucket, []).append(j)
        ids_a, ids_b, dists = [], [], []
        for i, bs in enumerate(buckets_a):
            cand = sorted({j for bucket in bs
                           for j in by_bucket.get(bucket, [])})
            if not cand:
                continue
            dist = _jaccard_distance(Xa[i], Xb[np.asarray(cand)])
            for j, dj in zip(cand, dist):
                if dj < threshold:
                    ids_a.append(table_a[id_col][i])
                    ids_b.append(table_b[id_col][j])
                    dists.append(dj)
        return Table({"idA": np.asarray(ids_a), "idB": np.asarray(ids_b),
                      "distCol": np.asarray(dists, np.float64)})

    # -- persistence ---------------------------------------------------------
    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model",
                                  {"coefficients": self._coeff})

    @classmethod
    def load(cls, path: str) -> "MinHashLSHModel":
        model = persist.load_stage_param(path)
        model._coeff = persist.load_model_arrays(
            path, "model")["coefficients"].astype(np.int64)
        return model


class MinHashLSH(MinHashLSHParams, Estimator[MinHashLSHModel]):
    """Draws the (a, b) coefficient pairs uniformly from [1, P) x [0, P)
    under ``seed`` — the model is data-independent (fit ignores row
    values, as in the Flink ML MinHashLSH)."""

    def fit(self, *inputs) -> MinHashLSHModel:
        rng = np.random.default_rng(self.get_seed())
        m = (self.get_num_hash_tables()
             * self.get_num_hash_functions_per_table())
        coeff = np.column_stack([
            rng.integers(1, _MINHASH_PRIME, size=m),
            rng.integers(0, _MINHASH_PRIME, size=m),
        ]).astype(np.int64)
        model = MinHashLSHModel()
        model.copy_params_from(self)
        model._coeff = coeff
        return model
