"""PCA — principal component analysis, TPU-native.

A universally expected member of the feature surface (the reference
family's broader ecosystem ships it; the snapshot's lib is KMeans-only —
SURVEY §2.8).  Estimator/Model pair: fit computes the covariance as ONE
``X^T X`` MXU matmul over the centered batch plus a (d, d) device
``eigh`` (symmetric eigendecomposition — d is feature count, small);
transform is one projection matmul.  Components carry a deterministic
sign (largest-|loading| coordinate positive) so refits and reloads score
identically.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator, Model
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.param import IntParam, ParamValidators
from ...utils import persist
from .transforms import _InOutParams

__all__ = ["PCA", "PCAModel"]


class PCAParams(_InOutParams):
    K = IntParam("k", "Number of principal components.", default=2,
                 validator=ParamValidators.gt(0))

    def get_k(self) -> int:
        return self.get(PCAParams.K)

    def set_k(self, value: int):
        return self.set(PCAParams.K, value)


@partial(jax.jit, static_argnums=(1,))
def _fit_pca(X, k):
    """Centered covariance -> top-k eigenvectors (descending variance)."""
    n = X.shape[0]
    mean = jnp.mean(X, axis=0)
    Xc = X - mean[None, :]
    cov = (Xc.T @ Xc) / jnp.maximum(n - 1, 1)          # (d, d) MXU
    eigvals, eigvecs = jnp.linalg.eigh(cov)            # ascending
    order = jnp.argsort(-eigvals)[:k]
    components = eigvecs[:, order].T                   # (k, d)
    variances = jnp.maximum(eigvals[order], 0.0)
    # deterministic sign: the largest-|loading| coordinate is positive
    pivot = jnp.argmax(jnp.abs(components), axis=1)
    signs = jnp.sign(jnp.take_along_axis(components, pivot[:, None],
                                         axis=1))
    components = components * jnp.where(signs == 0, 1.0, signs)
    total = jnp.maximum(jnp.sum(jnp.maximum(eigvals, 0.0)), 1e-30)
    return mean, components, variances, variances / total


@jax.jit
def _project(X, mean, components):
    return (X - mean[None, :]) @ components.T


def _pca_chain_kernel(static, params, cols):
    """Chain-fused projection — the same expression as ``_project`` (one
    centered matmul; per-row dot products are unaffected by the segment's
    row padding, so fused output is bit-exact with the stagewise call)."""
    from ...api.chain import as_matrix

    (fcol, ocol) = static
    X = as_matrix(cols[fcol])
    return {ocol: (X - params["mean"][None, :]) @ params["components"].T}


class PCAModel(PCAParams, Model):
    """Holds (mean, components (k, d), explained variance [ratio])."""

    def __init__(self):
        super().__init__()
        self._mean: Optional[np.ndarray] = None
        self._components: Optional[np.ndarray] = None
        self._variance: Optional[np.ndarray] = None
        self._variance_ratio: Optional[np.ndarray] = None

    def set_model_data(self, *inputs) -> "PCAModel":
        (t,) = inputs
        # single-row layout (each cell holds the whole array), matching
        # the KMeansModel convention — Table requires equal row counts
        self._mean = np.asarray(t["mean"][0], np.float64)
        self._components = np.asarray(t["components"][0], np.float64)
        self._variance = np.asarray(t["explainedVariance"][0], np.float64)
        self._variance_ratio = np.asarray(
            t["explainedVarianceRatio"][0], np.float64)
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({
            "mean": self._mean[None, :],
            "components": self._components[None, :, :],
            "explainedVariance": self._variance[None, :],
            "explainedVarianceRatio": self._variance_ratio[None, :],
        })]

    @property
    def explained_variance_ratio(self) -> np.ndarray:
        self._require_model()
        return self._variance_ratio.copy()

    def _require_model(self) -> None:
        if self._components is None:
            raise RuntimeError("PCAModel has no model data; fit a PCA or "
                               "call set_model_data first")

    def transform_kernel(self, schema):
        from ...api.chain import StageKernel, numeric_entry

        self._require_model()
        fcol = self.get_features_col()
        if numeric_entry(schema, fcol) is None:
            return None
        return StageKernel(
            fn=_pca_chain_kernel,
            static=(fcol, self.get_output_col()),
            params={"mean": np.asarray(self._mean, np.float32),
                    "components": np.asarray(self._components, np.float32)},
            consumes=(fcol,), produces=(self.get_output_col(),))

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        X = stack_vectors(table[self.get_features_col()])
        out = np.asarray(_project(
            jnp.asarray(X, jnp.float32),
            jnp.asarray(self._mean, jnp.float32),
            jnp.asarray(self._components, jnp.float32)), np.float64)
        return [table.with_column(self.get_output_col(), out)]

    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {
            "mean": self._mean, "components": self._components,
            "explainedVariance": self._variance,
            "explainedVarianceRatio": self._variance_ratio,
        })

    @classmethod
    def load(cls, path: str) -> "PCAModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._mean = data["mean"].astype(np.float64)
        model._components = data["components"].astype(np.float64)
        model._variance = data["explainedVariance"].astype(np.float64)
        model._variance_ratio = data["explainedVarianceRatio"].astype(
            np.float64)
        return model


class PCA(PCAParams, Estimator[PCAModel]):
    def fit(self, *inputs) -> PCAModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float32)
        k = self.get_k()
        if k > X.shape[1]:
            raise ValueError(
                f"k={k} exceeds the feature dimension {X.shape[1]}")
        mean, components, variance, ratio = _fit_pca(jnp.asarray(X), k)
        model = PCAModel()
        model.copy_params_from(self)
        model._mean = np.asarray(mean, np.float64)
        model._components = np.asarray(components, np.float64)
        model._variance = np.asarray(variance, np.float64)
        model._variance_ratio = np.asarray(ratio, np.float64)
        return model
