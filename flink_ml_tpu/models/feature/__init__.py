from .encoders import (  # noqa: F401
    OneHotEncoder,
    OneHotEncoderModel,
    StringIndexer,
    StringIndexerModel,
    VectorAssembler,
)
from .online_scaler import (  # noqa: F401
    OnlineStandardScaler,
    OnlineStandardScalerModel,
)
from .scalers import (  # noqa: F401
    MaxAbsScaler,
    MaxAbsScalerModel,
    MinMaxScaler,
    MinMaxScalerModel,
    RobustScaler,
    RobustScalerModel,
    StandardScaler,
    StandardScalerModel,
)
from .lsh import (  # noqa: F401
    MinHashLSH,
    MinHashLSHModel,
)
from .pca import PCA, PCAModel  # noqa: F401
from .randomsplitter import RandomSplitter  # noqa: F401
from .sqltransformer import SQLTransformer  # noqa: F401
from .selectors import (  # noqa: F401
    UnivariateFeatureSelector,
    UnivariateFeatureSelectorModel,
    VarianceThresholdSelector,
    VarianceThresholdSelectorModel,
)
from .tokenize import (  # noqa: F401
    CountVectorizer,
    CountVectorizerModel,
    NGram,
    RegexTokenizer,
    StopWordsRemover,
    Tokenizer,
)
from .text import (  # noqa: F401
    FeatureHasher,
    HashingTF,
    IDF,
    IDFModel,
    IndexToString,
)
from .vector_ops import (  # noqa: F401
    DCT,
    ElementwiseProduct,
    Interaction,
    KBinsDiscretizer,
    KBinsDiscretizerModel,
    VectorIndexer,
    VectorIndexerModel,
    VectorSlicer,
)
from .transforms import (  # noqa: F401
    Binarizer,
    Bucketizer,
    Imputer,
    ImputerModel,
    Normalizer,
    PolynomialExpansion,
)
