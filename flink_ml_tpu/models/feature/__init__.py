from .encoders import (  # noqa: F401
    OneHotEncoder,
    OneHotEncoderModel,
    StringIndexer,
    StringIndexerModel,
    VectorAssembler,
)
from .scalers import (  # noqa: F401
    MinMaxScaler,
    MinMaxScalerModel,
    StandardScaler,
    StandardScalerModel,
)
from .transforms import (  # noqa: F401
    Binarizer,
    Bucketizer,
    Imputer,
    ImputerModel,
    Normalizer,
    PolynomialExpansion,
)
