"""RandomSplitter — split one Table into N by weighted random assignment.

Member of the Flink ML 2.x feature surface (``feature/randomsplitter``;
the reference snapshot ships no splitters — SURVEY §2.8).  AlgoOperator
with a multi-table output: each row is routed to output ``k`` with
probability ``weights[k] / sum(weights)``, deterministically under
``seed``.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ...api.stage import AlgoOperator
from ...data.table import Table
from ...params.param import DoubleArrayParam
from ...params.shared import HasSeed

__all__ = ["RandomSplitter"]


def _valid_weights(vals) -> bool:
    """>= 2 strictly positive weights — enforced on the param itself so the
    generic set()/json-restore path validates too, not just set_weights."""
    return vals is not None and len(vals) >= 2 and all(w > 0 for w in vals)


class RandomSplitter(HasSeed, AlgoOperator):
    WEIGHTS = DoubleArrayParam(
        "weights", "Relative split weights (>= 2 values, all > 0).",
        default=(1.0, 1.0), validator=_valid_weights)

    def get_weights(self):
        return self.get(RandomSplitter.WEIGHTS)

    def set_weights(self, *values: float):
        vals = values[0] if len(values) == 1 and not np.isscalar(values[0]) \
            else values
        return self.set(RandomSplitter.WEIGHTS,
                        tuple(float(v) for v in vals))

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        weights = np.asarray(self.get_weights(), np.float64)
        probs = weights / weights.sum()
        rng = np.random.default_rng(self.get_seed())
        assign = rng.choice(len(probs), size=table.num_rows, p=probs)
        return [table.select_rows(np.flatnonzero(assign == k))
                for k in range(len(probs))]
