"""Feature selectors: VarianceThresholdSelector and
UnivariateFeatureSelector.

Members of the Flink ML 2.x feature surface (``feature/
variancethresholdselector``, ``feature/univariatefeatureselector`` in the
library line; the reference snapshot ships neither — SURVEY §2.8).  Both
are Estimator/Model pairs whose model data is the list of surviving
feature indices; transform is one gather.

Scoring reuses the stats machinery: chi-squared (categorical feature /
categorical label, ``stats.chisqtest``), one-way ANOVA F (continuous /
categorical, ``stats.anovatest`` — device one-hot matmuls), and the
F-regression test (continuous / continuous) whose correlation reduction
is a single jitted pass.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator, Model
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.param import FloatParam, ParamValidators, StringParam
from ...params.shared import HasLabelCol
from ...utils import persist
from ..stats.anovatest import anova_f_scores
from ..stats.chisqtest import _chi2_from_contingency, _p_values
from ..stats.fvaluetest import f_regression_scores
from .transforms import _InOutParams

__all__ = [
    "UnivariateFeatureSelector",
    "UnivariateFeatureSelectorModel",
    "VarianceThresholdSelector",
    "VarianceThresholdSelectorModel",
]


class _IndexSelectingModel(Model):
    """Shared Model body: keep the learned subset of feature columns."""

    def __init__(self):
        super().__init__()
        self._indices: Optional[np.ndarray] = None

    def set_model_data(self, *inputs):
        (t,) = inputs
        self._indices = np.asarray(t["indices"], np.int64)
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"indices": self._indices})]

    def _require_model(self) -> None:
        if self._indices is None:
            raise RuntimeError(
                f"{type(self).__name__} has no model data; call "
                "set_model_data() or fit first")

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        X = stack_vectors(table[self.get_features_col()])
        if self._indices.size and self._indices.max() >= X.shape[1]:
            raise ValueError(
                f"model selects index {self._indices.max()} but input has "
                f"only {X.shape[1]} features")
        return [table.with_column(self.get_output_col(),
                                  X[:, self._indices])]

    def transform_kernel(self, schema):
        """Chain kernel: the transform is one gather by fitted indices —
        value-exact at any dtype, so the fused path is bit-exact."""
        from ...api.chain import StageKernel, numeric_entry
        from .vector_ops import _gather_cols_kernel

        self._require_model()
        entry = numeric_entry(schema, self.get_features_col())
        if entry is None:
            return None
        d = int(entry[0][0]) if entry[0] else 1
        if self._indices.size and self._indices.max() >= d:
            return None      # stagewise raises the diagnostic error
        return StageKernel(
            fn=_gather_cols_kernel,
            static=(self.get_features_col(), self.get_output_col()),
            params={"idx": self._indices.astype(np.int32)},
            consumes=(self.get_features_col(),),
            produces=(self.get_output_col(),))

    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {"indices": self._indices})

    @classmethod
    def load(cls, path: str):
        model = persist.load_stage_param(path)
        model._indices = persist.load_model_arrays(
            path, "model")["indices"].astype(np.int64)
        return model


# ---------------------------------------------------------------------------
# VarianceThresholdSelector
# ---------------------------------------------------------------------------

class VarianceThresholdSelectorParams(_InOutParams):
    VARIANCE_THRESHOLD = FloatParam(
        "varianceThreshold",
        "Features with sample variance <= this are removed.", default=0.0,
        validator=ParamValidators.gt_eq(0.0))

    def get_variance_threshold(self) -> float:
        return self.get(
            VarianceThresholdSelectorParams.VARIANCE_THRESHOLD)

    def set_variance_threshold(self, value: float):
        return self.set(
            VarianceThresholdSelectorParams.VARIANCE_THRESHOLD, value)


class VarianceThresholdSelectorModel(VarianceThresholdSelectorParams,
                                     _IndexSelectingModel):
    pass


@jax.jit
def _sample_variances(X):
    n = X.shape[0]
    mean = jnp.mean(X, axis=0, keepdims=True)
    ss = jnp.sum((X - mean) ** 2, axis=0)
    return ss / jnp.maximum(n - 1, 1)


class VarianceThresholdSelector(VarianceThresholdSelectorParams,
                                Estimator[VarianceThresholdSelectorModel]):
    """Drops features whose *sample* variance (ddof=1) does not exceed the
    threshold — the Flink ML / sklearn VarianceThresholdSelector rule."""

    def fit(self, *inputs) -> VarianceThresholdSelectorModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()])
        var = np.asarray(_sample_variances(jnp.asarray(X, jnp.float32)),
                         np.float64)
        keep = np.flatnonzero(var > self.get_variance_threshold())
        model = VarianceThresholdSelectorModel()
        model.copy_params_from(self)
        model._indices = keep.astype(np.int64)
        return model


# ---------------------------------------------------------------------------
# UnivariateFeatureSelector
# ---------------------------------------------------------------------------

def _chi2_scores(X: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Per-feature chi-squared p-values (categorical X, categorical y)."""
    _, y_idx = np.unique(y, return_inverse=True)
    n_label = int(y_idx.max()) + 1 if len(y_idx) else 0
    stats, dofs = [], []
    for j in range(X.shape[1]):
        _, xj = np.unique(X[:, j], return_inverse=True)
        n_feat = int(xj.max()) + 1 if len(xj) else 0
        contingency = np.bincount(
            xj * n_label + y_idx, minlength=n_feat * n_label).reshape(
                n_feat, n_label).astype(np.float64)
        stat, dof = _chi2_from_contingency(contingency)
        stats.append(stat)
        dofs.append(dof)
    return _p_values(np.asarray(stats), np.asarray(dofs))


def _f_regression_scores(X: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Per-feature F-regression p-values — THE implementation lives in
    ``stats.fvaluetest`` (the FValueTest AlgoOperator); the selector only
    consumes the p-values."""
    _, p, _ = f_regression_scores(X, y)
    return p


_DEFAULT_THRESHOLDS = {"numTopFeatures": 50.0, "percentile": 0.1,
                       "fpr": 0.05, "fdr": 0.05, "fwe": 0.05}


class UnivariateFeatureSelectorParams(_InOutParams, HasLabelCol):
    FEATURE_TYPE = StringParam(
        "featureType", "categorical | continuous.", default=None,
        validator=ParamValidators.in_array(["categorical", "continuous"]))
    LABEL_TYPE = StringParam(
        "labelType", "categorical | continuous.", default=None,
        validator=ParamValidators.in_array(["categorical", "continuous"]))
    SELECTION_MODE = StringParam(
        "selectionMode",
        "numTopFeatures | percentile | fpr | fdr | fwe.",
        default="numTopFeatures",
        validator=ParamValidators.in_array(
            ["numTopFeatures", "percentile", "fpr", "fdr", "fwe"]))
    SELECTION_THRESHOLD = FloatParam(
        "selectionThreshold",
        "Meaning depends on mode: top-k count, percentile fraction, or "
        "p-value bound.  Defaults per mode when unset.", default=None)

    def get_feature_type(self) -> str:
        return self.get(UnivariateFeatureSelectorParams.FEATURE_TYPE)

    def set_feature_type(self, value: str):
        return self.set(UnivariateFeatureSelectorParams.FEATURE_TYPE, value)

    def get_label_type(self) -> str:
        return self.get(UnivariateFeatureSelectorParams.LABEL_TYPE)

    def set_label_type(self, value: str):
        return self.set(UnivariateFeatureSelectorParams.LABEL_TYPE, value)

    def get_selection_mode(self) -> str:
        return self.get(UnivariateFeatureSelectorParams.SELECTION_MODE)

    def set_selection_mode(self, value: str):
        return self.set(UnivariateFeatureSelectorParams.SELECTION_MODE,
                        value)

    def get_selection_threshold(self) -> float:
        value = self.get(UnivariateFeatureSelectorParams.SELECTION_THRESHOLD)
        if value is None:
            return _DEFAULT_THRESHOLDS[self.get_selection_mode()]
        return value

    def set_selection_threshold(self, value: float):
        return self.set(
            UnivariateFeatureSelectorParams.SELECTION_THRESHOLD, value)


class UnivariateFeatureSelectorModel(UnivariateFeatureSelectorParams,
                                     _IndexSelectingModel):
    pass


def _select_by_mode(p: np.ndarray, mode: str, threshold: float) -> np.ndarray:
    """Sorted indices of the selected features, per the Flink ML modes."""
    d = len(p)
    order = np.argsort(p, kind="stable")
    if mode == "numTopFeatures":
        return np.sort(order[: int(threshold)])
    if mode == "percentile":
        return np.sort(order[: int(d * threshold)])
    if mode == "fpr":
        return np.flatnonzero(p < threshold)
    if mode == "fdr":
        # Benjamini-Hochberg: largest m with p_(m) <= m/d * alpha
        ranked = p[order]
        below = np.flatnonzero(ranked <= (np.arange(1, d + 1) / d) * threshold)
        if below.size == 0:
            return np.zeros(0, np.int64)
        return np.sort(order[: below[-1] + 1])
    if mode == "fwe":
        return np.flatnonzero(p < threshold / d)
    raise ValueError(f"unknown selection mode {mode!r}")


class UnivariateFeatureSelector(UnivariateFeatureSelectorParams,
                                Estimator[UnivariateFeatureSelectorModel]):
    """Scores each feature against the label with the test implied by
    (featureType, labelType) — chi-squared for categorical/categorical,
    ANOVA F for continuous/categorical, F-regression for
    continuous/continuous (categorical features with a continuous label are
    unsupported, as in Flink ML) — then keeps features by ``selectionMode``
    over the p-values."""

    def fit(self, *inputs) -> UnivariateFeatureSelectorModel:
        (table,) = inputs
        # param-system null check raises here if the types were never set
        ftype, ltype = self.get_feature_type(), self.get_label_type()
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        y = np.asarray(table[self.get_label_col()])

        if ftype == "categorical" and ltype == "categorical":
            p = _chi2_scores(X, y)
        elif ftype == "continuous" and ltype == "categorical":
            _, p, _, _ = anova_f_scores(X, y)
        elif ftype == "continuous" and ltype == "continuous":
            p = _f_regression_scores(X, y.astype(np.float64))
        else:
            raise ValueError(
                "categorical features with a continuous label are not "
                "supported (no test defined); index the label instead")

        indices = _select_by_mode(np.asarray(p, np.float64),
                                  self.get_selection_mode(),
                                  self.get_selection_threshold())
        model = UnivariateFeatureSelectorModel()
        model.copy_params_from(self)
        model._indices = indices.astype(np.int64)
        return model
