"""OnlineStandardScaler — streaming mean/variance over table windows.

The online counterpart of StandardScaler (Flink ML 2.x pairs batch feature
estimators with online variants, the way OnlineKMeans pairs with KMeans).

Numerics: each window's centered statistics (count, mean, M2) are computed
on device in f32 — centering first keeps f32 adequate — and merged across
windows on the host in float64 with Chan's parallel-Welford update.  The
naive E[x^2] - E[x]^2 route in f32 catastrophically cancels for data with
large means (std 1 at mean 1e4 underflows to 0), which is exactly the
regime a streaming scaler exists for.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator
from ...data.table import Table
from ...linalg import stack_vectors
from ...utils import persist
from .scalers import StandardScalerModel, StandardScalerParams

__all__ = ["OnlineStandardScaler", "OnlineStandardScalerModel"]


@jax.jit
def _window_stats(X):
    """Per-window (count, mean, M2) with on-device centering."""
    mean = jnp.mean(X, axis=0)
    centered = X - mean
    return jnp.asarray(X.shape[0], jnp.float32), mean, \
        jnp.sum(centered * centered, axis=0)


def _merge(count, mean, m2, wc, wm, wm2):
    """Chan's parallel Welford merge, float64 on host."""
    total = count + wc
    delta = wm - mean
    new_mean = mean + delta * (wc / total)
    new_m2 = m2 + wm2 + delta * delta * (count * wc / total)
    return total, new_mean, new_m2


class OnlineStandardScalerModel(StandardScalerModel):
    """StandardScalerModel + the model version counter of the streaming
    fit (persisted, mirroring ``OnlineKMeansModel``)."""

    def __init__(self):
        super().__init__()
        self.model_version = 0

    def save(self, path: str) -> None:
        persist.save_metadata(self, path,
                              {"modelVersion": self.model_version})
        persist.save_model_arrays(path, "model",
                                  {"mean": self._mean, "std": self._std})

    @classmethod
    def load(cls, path: str) -> "OnlineStandardScalerModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._mean = data["mean"].astype(np.float64)
        model._std = data["std"].astype(np.float64)
        model.model_version = int(
            persist.load_metadata(path).get("modelVersion", 0))
        return model


class OnlineStandardScaler(StandardScalerParams,
                           Estimator[OnlineStandardScalerModel]):
    def fit(self, *inputs) -> OnlineStandardScalerModel:
        """``fit(stream)``: an iterable of Tables (windows), or one Table
        (consumed as batches).  Returns when the stream ends."""
        (source,) = inputs
        feat = self.get_features_col()
        batches = iter(source) if not isinstance(source, Table) else iter(
            source.batches(4096))

        count = 0.0
        mean = None
        m2 = None
        versions = 0
        for t in batches:
            X = stack_vectors(t[feat]).astype(np.float32)
            if len(X) == 0:
                continue
            wc, wm, wm2 = (np.asarray(v, np.float64)
                           for v in _window_stats(jnp.asarray(X)))
            if mean is None:
                count, mean, m2 = float(wc), wm, wm2
            else:
                count, mean, m2 = _merge(count, mean, m2, float(wc), wm, wm2)
            versions += 1
        if mean is None:
            raise ValueError("OnlineStandardScaler.fit got an empty stream")

        model = OnlineStandardScalerModel()
        model.copy_params_from(self)
        model.set_model_data(Table({
            "mean": mean[None],
            "std": np.sqrt(np.maximum(m2 / count, 0.0))[None]}))
        model.model_version = versions
        return model
