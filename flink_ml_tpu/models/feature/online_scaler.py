"""OnlineStandardScaler — streaming mean/variance over table windows.

The online counterpart of StandardScaler (Flink ML 2.x pairs batch feature
estimators with online variants, the way OnlineKMeans pairs with KMeans).

Numerics: per-window centered statistics (count, mean, M2) merge across
windows with Chan's parallel-Welford update, all in host float64.  The
naive E[x^2] - E[x]^2 route in f32 catastrophically cancels for data with
large means (std 1 at mean 1e4 underflows to 0), which is exactly the
regime a streaming scaler exists for.  The stats are pure host numpy: a
mean/M2 pass is PCIe-transfer-bound, and windows vary in length, so a
jitted version would recompile per distinct window size for no gain.
"""

from __future__ import annotations

import numpy as np

from ...api.stage import Estimator
from ...data.stream import (cursor_adapter,
                            ensure_cursor_source, windows_of)
from ...data.table import Table
from ...iteration import IterationBodyResult, IterationConfig, iterate
from ...linalg import stack_vectors
from ...utils import persist
from .scalers import StandardScalerModel, StandardScalerParams

__all__ = ["OnlineStandardScaler", "OnlineStandardScalerModel"]


def _window_stats(X: np.ndarray):
    """Per-window (count, mean, M2), centered, float64."""
    X = np.asarray(X, np.float64)
    mean = X.mean(axis=0)
    centered = X - mean
    return float(X.shape[0]), mean, (centered * centered).sum(axis=0)


def _merge(count, mean, m2, wc, wm, wm2):
    """Chan's parallel Welford merge, float64 on host."""
    total = count + wc
    delta = wm - mean
    new_mean = mean + delta * (wc / total)
    new_m2 = m2 + wm2 + delta * delta * (count * wc / total)
    return total, new_mean, new_m2


class OnlineStandardScalerModel(StandardScalerModel):
    """StandardScalerModel + the model version counter of the streaming
    fit (persisted, mirroring ``OnlineKMeansModel``)."""

    def __init__(self):
        super().__init__()
        self.model_version = 0

    def save(self, path: str) -> None:
        persist.save_metadata(self, path,
                              {"modelVersion": self.model_version})
        persist.save_model_arrays(path, "model",
                                  {"mean": self._mean, "std": self._std})

    @classmethod
    def load(cls, path: str) -> "OnlineStandardScalerModel":
        # array restore delegates to the parent (one source of truth for the
        # on-disk layout); only the version counter is ours
        model = super().load(path)
        model.model_version = int(
            persist.load_metadata(path).get("modelVersion", 0))
        return model


class OnlineStandardScaler(StandardScalerParams,
                           Estimator[OnlineStandardScalerModel]):
    WINDOW_ROWS = 4096   # Table windowing granularity

    def fit(self, *inputs, checkpoint=None,
            resume: bool = False) -> OnlineStandardScalerModel:
        """``fit(stream)``: an iterable of Tables (windows), or one Table
        (consumed as batches).  Returns when the stream ends.

        ``checkpoint``/``resume`` follow the online-estimator contract
        (OnlineLogisticRegression/OnlineKMeans): the (count, mean, M2)
        statistics and the source cursor cut together; wrap live feeds
        in ``data.wal.WindowLog``.  No warm-start requirement — the
        zero-count state is a clean merge identity, so nothing needs
        sniffing before the cursor restores."""
        (source,) = inputs
        feat = self.get_features_col()
        if checkpoint is not None:
            source = ensure_cursor_source(source, self.WINDOW_ROWS)

        def payloads():
            for t in windows_of(source, self.WINDOW_ROWS):
                # empty windows pass through (skipping would desync the
                # source cursor from the epoch count); body ignores them
                yield stack_vectors(t[feat])

        def body(state, epoch, X):
            if len(X) == 0:
                return IterationBodyResult(state)
            wc, wm, wm2 = _window_stats(X)
            count, mean, m2 = state
            if count == 0:
                return IterationBodyResult((wc, wm, wm2))
            return IterationBodyResult(_merge(count, mean, m2, wc, wm, wm2))

        state0 = (0.0, np.zeros(0), np.zeros(0))
        result = iterate(
            body, state0, cursor_adapter(source, payloads),
            config=IterationConfig(mode="hosted", jit=False),
            checkpoint=checkpoint, resume=resume)
        count, mean, m2 = result.state
        if count == 0:
            raise ValueError("OnlineStandardScaler.fit got an empty stream")

        model = OnlineStandardScalerModel()
        model.copy_params_from(self)
        model.set_model_data(Table({
            "mean": mean[None],
            "std": np.sqrt(np.maximum(m2 / count, 0.0))[None]}))
        model.model_version = result.num_epochs
        return model
