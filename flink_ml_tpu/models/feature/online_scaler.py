"""OnlineStandardScaler — streaming mean/variance over table windows.

The online counterpart of StandardScaler (Flink ML 2.x pairs batch feature
estimators with online variants, the way OnlineKMeans pairs with KMeans).

Numerics: per-window centered statistics (count, mean, M2) merge across
windows with Chan's parallel-Welford update, all in host float64.  The
naive E[x^2] - E[x]^2 route in f32 catastrophically cancels for data with
large means (std 1 at mean 1e4 underflows to 0), which is exactly the
regime a streaming scaler exists for.  The stats are pure host numpy: a
mean/M2 pass is PCIe-transfer-bound, and windows vary in length, so a
jitted version would recompile per distinct window size for no gain.
"""

from __future__ import annotations

import numpy as np

from ...api.stage import Estimator
from ...data.stream import windows_of
from ...data.table import Table
from ...linalg import stack_vectors
from ...utils import persist
from .scalers import StandardScalerModel, StandardScalerParams

__all__ = ["OnlineStandardScaler", "OnlineStandardScalerModel"]


def _window_stats(X: np.ndarray):
    """Per-window (count, mean, M2), centered, float64."""
    X = np.asarray(X, np.float64)
    mean = X.mean(axis=0)
    centered = X - mean
    return float(X.shape[0]), mean, (centered * centered).sum(axis=0)


def _merge(count, mean, m2, wc, wm, wm2):
    """Chan's parallel Welford merge, float64 on host."""
    total = count + wc
    delta = wm - mean
    new_mean = mean + delta * (wc / total)
    new_m2 = m2 + wm2 + delta * delta * (count * wc / total)
    return total, new_mean, new_m2


class OnlineStandardScalerModel(StandardScalerModel):
    """StandardScalerModel + the model version counter of the streaming
    fit (persisted, mirroring ``OnlineKMeansModel``)."""

    def __init__(self):
        super().__init__()
        self.model_version = 0

    def save(self, path: str) -> None:
        persist.save_metadata(self, path,
                              {"modelVersion": self.model_version})
        persist.save_model_arrays(path, "model",
                                  {"mean": self._mean, "std": self._std})

    @classmethod
    def load(cls, path: str) -> "OnlineStandardScalerModel":
        # array restore delegates to the parent (one source of truth for the
        # on-disk layout); only the version counter is ours
        model = super().load(path)
        model.model_version = int(
            persist.load_metadata(path).get("modelVersion", 0))
        return model


class OnlineStandardScaler(StandardScalerParams,
                           Estimator[OnlineStandardScalerModel]):
    def fit(self, *inputs) -> OnlineStandardScalerModel:
        """``fit(stream)``: an iterable of Tables (windows), or one Table
        (consumed as batches).  Returns when the stream ends."""
        (source,) = inputs
        feat = self.get_features_col()
        batches = windows_of(source, 4096)

        count = 0.0
        mean = None
        m2 = None
        versions = 0
        for t in batches:
            X = stack_vectors(t[feat])
            if len(X) == 0:
                continue
            wc, wm, wm2 = _window_stats(X)
            if mean is None:
                count, mean, m2 = wc, wm, wm2
            else:
                count, mean, m2 = _merge(count, mean, m2, wc, wm, wm2)
            versions += 1
        if mean is None:
            raise ValueError("OnlineStandardScaler.fit got an empty stream")

        model = OnlineStandardScalerModel()
        model.copy_params_from(self)
        model.set_model_data(Table({
            "mean": mean[None],
            "std": np.sqrt(np.maximum(m2 / count, 0.0))[None]}))
        model.model_version = versions
        return model
