"""Mask-aware loss functions for the linear-model family.

The reference snapshot contains only KMeans, but its BASELINE configs call
for LogisticRegression / LinearRegression / LinearSVC (the flink-ml-lib
linear family).  All losses share the margin form ``m = X @ w + b`` and are
weighted: padding rows carry weight 0, real rows carry the sample weight
(``HasWeightCol``), so padded shards contribute nothing to the psum.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["logistic_loss", "squared_loss", "hinge_loss", "LOSSES"]


def _weighted_mean(values, weights):
    # Epsilon only guards the all-padding batch (weight sum exactly 0, where
    # the numerator is 0 too); real weighted means keep their scale.
    return jnp.sum(values * weights) / jnp.maximum(jnp.sum(weights), 1e-12)


def logistic_loss(margin, labels, weights):
    """Binary log-loss on +-1 labels: log(1 + exp(-y * m)) — numerically via
    softplus."""
    y = labels * 2.0 - 1.0  # {0,1} -> {-1,+1}
    return _weighted_mean(jnp.logaddexp(0.0, -y * margin), weights)


def squared_loss(margin, labels, weights):
    """0.5 * (m - y)^2 (LinearRegression)."""
    return _weighted_mean(0.5 * jnp.square(margin - labels), weights)


def hinge_loss(margin, labels, weights):
    """max(0, 1 - y * m) on +-1 labels (LinearSVC)."""
    y = labels * 2.0 - 1.0
    return _weighted_mean(jnp.maximum(0.0, 1.0 - y * margin), weights)


LOSSES = {
    "logistic": logistic_loss,
    "squared": squared_loss,
    "hinge": hinge_loss,
}
