"""Fused mini-batch SGD trainer over a device mesh.

The TPU-native replacement for the reference's iteration-based model update
path: where flink-ml ships gradients over the network to a reduce operator
and feeds new weights back through the FeedbackChannel, here one epoch is an
inner ``lax.scan`` over mini-batches — the gradient psum over the mesh's data
axis is inserted by XLA and rides ICI — and the whole multi-epoch loop is a
single compiled program via ``iterate`` (fused mode).

Data layout: inputs are host-shuffled once (seeded), padded, and reshaped to
``(steps_per_epoch, batch, ...)`` with the batch dim sharded over the data
axis; weights/optimizer state are replicated.  Shapes are static — no
recompiles across epochs or batch positions.
"""

from __future__ import annotations

import inspect
import itertools
import time

from collections import OrderedDict
from dataclasses import astuple, dataclass, is_dataclass
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...data.prefetch import prefetch_to_device
from ...data.replay_cache import (
    DecodedReplayCache,
    batch_fingerprint,
    default_ram_budget,
)
from ...iteration import IterationBodyResult, IterationConfig, iterate
from ...iteration.checkpoint import CheckpointConfig, CheckpointManager
from ...obs.trace import tracer
from ...parallel.mesh import (
    default_mesh,
    assemble_process_local as _assemble_process_local,
    fetch_replicated as _fetch_replicated,
    mesh_process_count as _mesh_process_count,
    put_sharded as _put_epoch_tensor,
    replicate,
)

__all__ = ["SGDConfig", "sgd_fit", "sgd_fit_params", "sgd_fit_sparse",
           "sgd_fit_mixed", "sgd_fit_outofcore", "LinearState",
           "plan_epoch_layout", "prepare_epoch_tensor"]

LossFn = Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]


@dataclass
class SGDConfig:
    learning_rate: float = 0.1
    reg: float = 0.0            # l2 strength (on coefficients, not intercept)
    elastic_net: float = 0.0    # l1 mixing (0 = pure l2)
    #: None/0 = auto: 32 for dense fits; mixed/sparse hashed layouts grow
    #: the batch until the ELL routing layout fits its HBM budget, so the
    #: default product path plans the same kernel the bench times
    #: (:func:`resolve_global_batch_size`).
    global_batch_size: Optional[int] = None
    max_epochs: int = 20
    tol: float = 1e-6           # epoch-loss-change termination; <=0 disables
    seed: int = 0
    fit_intercept: bool = True
    #: MXU precision of the fused ELL kernels' in-kernel one-hot
    #: contractions.  "default" (one bf16 pass) measured 4.39 ms/step at
    #: bench shape vs 10.49 for "highest" (multi-pass f32) and 11.0 for
    #: the XLA oracle (TPU_FUSED_STEP_r04.txt), and passes the bench's
    #: epoch-level parity gate (rtol=1e-3): the contracted residuals are
    #: batch-normalized, so their ~2^-8 relative truncation lands below
    #: the f32 summation-order noise every ELL path already carries.
    #: "highest" restores bit-comparable-to-XLA gathers at ~2.4x the
    #: step cost.
    ell_precision: str = "default"
    #: How the data-parallel gradient sum is performed
    #: (:class:`~flink_ml_tpu.parallel.grad_reduce.GradReduceConfig`).
    #: ``None`` (default) and ``mode="exact"`` keep the legacy implicit
    #: GSPMD ``lax.psum`` path bit-identically; compressed modes
    #: (``topk`` error-feedback sparsification, ``int8`` block
    #: quantization, hierarchical ICI x DCN composition) route the DENSE
    #: trainers' gradients through an explicit
    #: :func:`~flink_ml_tpu.parallel.grad_reduce.reduce_gradients` —
    #: the EF residual rides the donated scan carry next to the weights
    #: and round-trips through checkpoints with them.
    grad_reduce: Optional[object] = None


#: Classic minibatch default when nothing layout-aware applies.
DEFAULT_GLOBAL_BATCH = 32

#: Auto-sizing never grows the batch past the bench-headline scale: a
#: bigger batch changes optimization dynamics more than it buys steps.
_AUTO_BATCH_CAP = 1 << 15


def resolve_global_batch_size(config: "SGDConfig", n: int,
                              num_features: Optional[int] = None,
                              layout_bytes_per_slot: int = 12) -> int:
    """The batch size a fit actually runs.  Explicit user choices pass
    through untouched.  Auto (None/0) resolves to 32 for dense fits; for
    the hashed mixed/sparse layouts it grows the batch (fewer steps) until
    the per-step ELL routing layout stack fits ``_ELL_LAYOUT_BUDGET_BYTES``
    — at the r2 default of 32, a 1M-row fit needs 32k steps of layout
    (~400 GB at 2^20 features) and :func:`plan_mixed_impl` silently fell
    back to XLA, so the product path and the bench ran different code
    (VERDICT r3 weak #2).  Deterministic in (n, num_features) only — the
    same fit plans the same batch on any backend."""
    if config.global_batch_size:
        return config.global_batch_size
    if num_features is None:
        return DEFAULT_GLOBAL_BATCH
    max_steps = max(1, _ELL_LAYOUT_BUDGET_BYTES
                    // (num_features * layout_bytes_per_slot))
    min_batch = -(-n // max_steps)
    return min(max(DEFAULT_GLOBAL_BATCH, min_batch), _AUTO_BATCH_CAP)


@dataclass
class LinearState:
    coefficients: np.ndarray    # (d,)
    intercept: float
    #: which update implementation the fit planned ("ell" / "xla" /
    #: "sharded" / "dense" / streaming variants) — surfaced so product
    #: callers can see what bench.py tags as lr_impl (VERDICT r3 task 3).
    #: Not part of persisted model data.
    planned_impl: Optional[str] = None


def plan_epoch_layout(n: int, global_batch_size: int, n_dev: int,
                      seed: int) -> Tuple[int, int, np.ndarray]:
    """Size the (steps, batch) epoch grid — batch divisible by the mesh's
    data axis — and the seeded row shuffle.  THE canonical batch-sizing
    arithmetic: WideDeep consumes it directly; the linear trainers layer
    process-sharding on top via :func:`_plan_epoch_layout_for_mesh`, which
    delegates here so the two can never diverge."""
    batch = max(global_batch_size, n_dev)
    batch += (-batch) % n_dev
    steps = max(1, -(-n // batch))
    perm = np.random.default_rng(seed).permutation(n)
    return steps, batch, perm


def _plan_epoch_layout_for_mesh(n_local: int, global_batch_size: int,
                                mesh, seed: int
                                ) -> Tuple[int, int, np.ndarray]:
    """Mesh-aware :func:`plan_epoch_layout`: on a mesh spanning P processes
    each process prepares its LOCAL (steps, batch/P, ...) slice of the
    global epoch tensor from its own ``n_local`` rows (equal across
    processes — validated below); single-process meshes reduce to the
    classic layout exactly."""
    n_dev = int(mesh.shape["data"])
    procs = _mesh_process_count(mesh)
    steps, batch, perm = plan_epoch_layout(
        n_local, global_batch_size, n_dev, seed)
    if procs == 1:
        return steps, batch, perm
    if batch % procs:
        raise ValueError(
            f"global batch {batch} is not divisible by the mesh's "
            f"{procs} processes (data axis {n_dev}); size the batch and "
            "data axis as multiples of the process count")
    local_batch = batch // procs
    steps = max(1, -(-n_local // local_batch))
    # Unequal per-process layouts would compile different programs on each
    # host and deadlock in the collectives; turn that into an immediate
    # error with one tiny cross-host gather.
    from jax.experimental import multihost_utils

    layouts = np.asarray(multihost_utils.process_allgather(
        np.asarray([steps, local_batch], np.int64)))
    if not np.all(layouts == layouts.reshape(-1, 2)[0]):
        raise ValueError(
            "multi-host fit requires every process to contribute the same "
            f"row count; got per-process (steps, local_batch) = "
            f"{layouts.reshape(-1, 2).tolist()}")
    return steps, local_batch, perm


def prepare_epoch_tensor(arr: np.ndarray, perm: np.ndarray, steps: int,
                         batch: int, pad_value: float = 0.0) -> np.ndarray:
    """Shuffle rows by ``perm``, pad to steps*batch, reshape to
    (steps, batch, ...)."""
    arr = arr[perm]
    total = steps * batch
    if arr.shape[0] < total:
        pad_shape = (total - arr.shape[0],) + arr.shape[1:]
        arr = np.concatenate([arr, np.full(pad_shape, pad_value, arr.dtype)])
    return arr.reshape((steps, batch) + arr.shape[1:])


def sgd_fit(loss_fn: LossFn, features: np.ndarray, labels: np.ndarray,
            weights: Optional[np.ndarray], config: SGDConfig,
            mesh=None) -> Tuple[LinearState, list]:
    """Train (w, b) minimizing ``loss_fn(margin, labels, weights) +
    reg * penalty(w)``.  Returns the fitted state and the per-epoch loss log.

    The elastic-net penalty matches the classic formulation:
    ``reg * ((1-alpha)/2 ||w||^2 + alpha ||w||_1)`` with the l1 part applied
    via proximal soft-thresholding after each step.
    """
    d = features.shape[1]
    init_params = {"w": jnp.zeros((d,), jnp.float32),
                   "b": jnp.zeros((), jnp.float32)}
    params, loss_log = sgd_fit_params(loss_fn, features, labels, weights,
                                      config, mesh, init_params=init_params)
    return LinearState(np.asarray(params["w"], np.float64),
                       float(params["b"]), planned_impl="dense"), loss_log


def sgd_fit_params(loss_fn: LossFn, features: np.ndarray, labels: np.ndarray,
                   weights: Optional[np.ndarray], config: SGDConfig,
                   mesh=None, *, init_params) -> Tuple[dict, list]:
    """Generic core behind :func:`sgd_fit`: trains any ``{"w", "b"}`` param
    pytree whose score is ``x @ w + b`` (vector w for the binary/regression
    family, a (d, classes) matrix for softmax).  ``loss_fn(scores, labels,
    weights)`` defines the objective; labels ride the epoch tensor as f32
    (exact for class ids < 2^24 — cast back inside the loss)."""
    mesh = mesh or default_mesh()
    n = features.shape[0]
    gr = _active_grad_reduce(config)
    if gr is None:
        batch_axis = "data"
        steps, batch, perm = _plan_epoch_layout_for_mesh(
            n, resolve_global_batch_size(config, n), mesh, config.seed)
    else:
        axes, n_dev_red, batch_axis = _grad_reduce_layout(gr, mesh)
        if axes == ("data",):
            steps, batch, perm = _plan_epoch_layout_for_mesh(
                n, resolve_global_batch_size(config, n), mesh, config.seed)
        else:
            # hierarchical: the batch shards over dcn x data; the fused
            # fit stays single-process (multi-host compressed training
            # rides sgd_fit_outofcore's per-process readers)
            if _mesh_process_count(mesh) > 1:
                raise ValueError(
                    "hierarchical grad_reduce in the fused fit requires a "
                    "single-process mesh; stream multi-host fits through "
                    "sgd_fit_outofcore")
            steps, batch, perm = plan_epoch_layout(
                n, resolve_global_batch_size(config, n), n_dev_red,
                config.seed)

    X = prepare_epoch_tensor(features.astype(np.float32), perm, steps, batch)
    y = prepare_epoch_tensor(labels.astype(np.float32), perm, steps, batch)
    w_host = (weights.astype(np.float32) if weights is not None
              else np.ones((n,), np.float32))
    w = prepare_epoch_tensor(w_host, perm, steps, batch, pad_value=0.0)

    X = _put_epoch_tensor(X, mesh, P(None, batch_axis, None))
    y = _put_epoch_tensor(y, mesh, P(None, batch_axis))
    w = _put_epoch_tensor(w, mesh, P(None, batch_axis))

    if gr is None:
        update = _linear_update(loss_fn, config)
        return _run_minibatch_epochs(update, (X, y, w), init_params, steps,
                                     config, mesh)
    from ...parallel import grad_reduce as GR

    update = _linear_update_reduced(loss_fn, config, mesh)
    init_params = dict(init_params)
    init_params[GR_STATE_KEY] = GR.init_state(gr, {
        k: init_params[k] for k in ("w", "b")}, n_dev_red)
    params, loss_log = _run_minibatch_epochs(update, (X, y, w), init_params,
                                             steps, config, mesh)
    gr_state = params.pop(GR_STATE_KEY, None)
    if gr_state is not None and GR.wants_overlap(gr):
        params = _apply_drain(params, gr_state, config)
    return params, loss_log


def _run_minibatch_epochs(update, data: tuple, init_params, steps: int,
                          config: SGDConfig, mesh, *,
                          place_params: bool = True) -> Tuple[dict, list]:
    """THE shared epoch driver behind sgd_fit / sgd_fit_sparse /
    sgd_fit_mixed: an inner scan of ``update`` over per-step slices of the
    (steps, batch, ...) device tensors in ``data``, wrapped in a fused
    ``iterate`` with tol termination.  One copy of the termination /
    loss-log logic so the three trainers can never diverge.  Multi-host:
    the tol-termination vote is computed identically on every host inside
    the fused while_loop (replicated scalars), so early stopping works
    without any cross-host round-trip per epoch."""

    from ...obs.probe import StepProbe

    def epoch_body(state, epoch, data):
        params, prev_loss, probe = state

        def batch_step(params, i):
            return update(params, *(a[i] for a in data))

        params, losses = jax.lax.scan(
            batch_step, params, jnp.arange(steps, dtype=jnp.int32))
        epoch_loss = jnp.mean(losses)
        # The full loss history rides in the carried state (a StepProbe
        # — obs/probe.py, the generalization of the fixed-size
        # NaN-prefilled buffer this driver used to hand-roll) so the
        # fused while_loop path — which only keeps the LAST epoch's
        # outputs — still yields the complete log in one fetch.
        probe = probe.record_at(epoch, loss=epoch_loss)
        termination = (jnp.abs(prev_loss - epoch_loss) > config.tol
                       if config.tol > 0 else None)
        return IterationBodyResult(
            feedback=(params, epoch_loss, probe), termination=termination)

    init_state = (replicate(init_params, mesh) if place_params
                  else init_params,
                  jnp.asarray(jnp.inf, jnp.float32),
                  StepProbe.create(("loss",), config.max_epochs))

    result = iterate(
        epoch_body, init_state, data,
        max_epochs=config.max_epochs,
        config=IterationConfig(mode="fused"),
    )
    params, _final_loss, probe = result.state
    params = _fetch_replicated(params)
    loss_log = list(probe.fetch(
        get=lambda v: _fetch_replicated(v))["loss"][:result.num_epochs])
    return params, loss_log


def _linear_update(loss_fn: LossFn, config: SGDConfig):
    """THE single-batch update — l2-regularized gradient step + l1 proximal
    soft-threshold — shared by the fused (sgd_fit) and streaming
    (sgd_fit_outofcore) paths so the two can never diverge.  Unjitted;
    callers place it inside their own compiled program."""
    lr = config.learning_rate
    reg, alpha = config.reg, config.elastic_net
    l2 = reg * (1.0 - alpha)
    l1 = reg * alpha

    def objective(params, xb, yb, wb):
        margin = xb @ params["w"] + params["b"]
        return loss_fn(margin, yb, wb) + 0.5 * l2 * jnp.sum(
            jnp.square(params["w"]))

    grad_fn = jax.value_and_grad(objective)

    def update(params, xb, yb, wb):
        value, grads = grad_fn(params, xb, yb, wb)
        new_w = params["w"] - lr * grads["w"]
        if l1 > 0:
            # proximal soft-threshold for the l1 part
            new_w = jnp.sign(new_w) * jnp.maximum(
                jnp.abs(new_w) - lr * l1, 0.0)
        new_b = params["b"] - (lr * grads["b"]
                               if config.fit_intercept else 0.0)
        return {"w": new_w, "b": new_b}, value

    return update


#: Reserved params-pytree key the compressed-reduction trainers use to
#: carry reducer state (EF residual / rounding key / the wire-protocol
#: tier's fill-in + union accounting) in the SAME donated scan carry as
#: the weights — which is exactly what makes it ride every existing
#: checkpoint cut and restore untouched.
GR_STATE_KEY = "_gr"


def _active_grad_reduce(config: SGDConfig):
    """The grad-reduce config IF it changes anything: ``None`` (and
    ``mode="exact"``) keep the legacy implicit-psum path — the unchanged,
    bit-identical default."""
    gr = config.grad_reduce
    if gr is None or gr.mode == "exact":
        return None
    return gr


def _grad_reduce_layout(gr, mesh):
    """(reduction axes, participant count, batch PartitionSpec entry) for
    a compressed fit on ``mesh`` — the shared
    :func:`~flink_ml_tpu.parallel.grad_reduce.mesh_layout` validation."""
    from ...parallel import grad_reduce as GR

    return GR.mesh_layout(gr, mesh)


def _linear_update_reduced(loss_fn: LossFn, config: SGDConfig, mesh):
    """Explicit-reduction twin of :func:`_linear_update` for the dense
    layout: per-device gradients of the GLOBAL weighted-mean loss are
    computed inside ``shard_map`` over the reduction axes and summed
    through :func:`~flink_ml_tpu.parallel.grad_reduce.reduce_gradients`
    (topk-EF / int8 / hierarchical per ``config.grad_reduce``).  The
    reducer state travels in ``params[GR_STATE_KEY]`` with a leading
    participant dim sharded over the reduction axes.

    Same regularization algebra as the exact path: the local weighted
    mean is re-normalized to the global denominator (the
    ``_mixed_update_sharded`` stance), the l2 term applies as exact
    decay on the replicated weight AFTER the reduction (it needs no
    communication, so it is never compressed), and l1 stays the proximal
    soft-threshold.

    ``config.grad_reduce.overlap`` swaps in the one-step-stale pipelined
    schedule (:func:`~flink_ml_tpu.parallel.grad_reduce.pipelined_reduce`):
    this step's gradient goes into the carried ``pending`` buffer and the
    PREVIOUS step's pending is reduced and applied — the reduction's
    bucket collectives have no data dependence on this step's
    forward/backward, so XLA's scheduler overlaps them.  The fit-end
    drain of the last pending gradient (+ EF residual) is the adopting
    fits' job (:func:`_apply_drain`)."""
    from ...parallel import grad_reduce as GR

    gr = config.grad_reduce
    lr = config.learning_rate
    reg, alpha = config.reg, config.elastic_net
    l2 = reg * (1.0 - alpha)
    l1 = reg * alpha
    overlap = GR.wants_overlap(gr)
    axes, _, batch_axis = _grad_reduce_layout(gr, mesh)
    x_spec = P(batch_axis, None)
    v_spec = P(batch_axis)
    st_spec = P(batch_axis)

    def device_fn(w, b, gr_state, xb, yb, wb):
        margin = xb @ w + b
        value_local, pull = jax.vjp(lambda m: loss_fn(m, yb, wb), margin)
        (r,) = pull(jnp.ones_like(value_local))
        # re-normalize the loss_fn's LOCAL weighted mean to the global
        # denominator so the objective equals the single-program one
        denom_local = jnp.maximum(jnp.sum(wb), 1e-12)
        denom = jax.lax.psum(denom_local, axes)
        value = jax.lax.psum(value_local * denom_local, axes) / denom
        r = r * (denom_local / denom)
        grads = {"w": jnp.tensordot(xb, r, axes=((0,), (0,))),
                 "b": jnp.sum(r, axis=0)}
        if overlap:
            red, new_state = GR.pipelined_reduce(
                grads, GR.squeeze_state(gr_state), gr)
        else:
            red, new_state = GR.reduce_gradients(
                grads, GR.squeeze_state(gr_state), gr)
        if l2 > 0:
            value = value + 0.5 * l2 * jnp.sum(jnp.square(w))
            w = w * (1.0 - lr * l2)
        new_w = w - lr * red["w"]
        if l1 > 0:
            new_w = jnp.sign(new_w) * jnp.maximum(
                jnp.abs(new_w) - lr * l1, 0.0)
        new_b = b - (lr * red["b"] if config.fit_intercept else 0.0)
        return new_w, new_b, GR.unsqueeze_state(new_state), value

    fn = _shard_map(
        device_fn, mesh,
        in_specs=(P(), P(), st_spec, x_spec, v_spec, v_spec),
        out_specs=(P(), P(), st_spec, P()))

    def update(params, xb, yb, wb):
        w, b, st, value = fn(params["w"], params["b"],
                             params[GR_STATE_KEY], xb, yb, wb)
        return {"w": w, "b": b, GR_STATE_KEY: st}, value

    return update


def _apply_drain(params: dict, gr_state: dict, config: SGDConfig) -> dict:
    """Fit-end drain of an overlapped run: one exact host-side apply of
    the participant-summed ``pending`` gradient plus the EF residual
    (:func:`~flink_ml_tpu.parallel.grad_reduce.drain_pending`) — the
    same decay / step / prox / bias tail as one in-loop update, so the
    overlapped trajectory ends with zero unsent mass instead of dropping
    its last gradient.  Runs AFTER the final loss log entry; checkpoint
    cuts never include it (resume re-runs the fit and drains at ITS
    end, which is what keeps crash+resume bit-exact vs uninterrupted)."""
    from ...parallel import grad_reduce as GR

    drain = GR.drain_pending(gr_state)
    lr = config.learning_rate
    reg, alpha = config.reg, config.elastic_net
    l2 = reg * (1.0 - alpha)
    l1 = reg * alpha
    w = np.asarray(params["w"], np.float32)
    if l2 > 0:
        w = w * np.float32(1.0 - lr * l2)
    w = w - np.float32(lr) * drain["w"]
    if l1 > 0:
        w = np.sign(w) * np.maximum(np.abs(w) - lr * l1, 0.0)
    b = np.asarray(params["b"], np.float32)
    if config.fit_intercept:
        b = b - np.float32(lr) * drain["b"]
    return {**params, "w": jnp.asarray(w), "b": jnp.asarray(b)}


# TPU random access is per-DMA-transaction bound, not bandwidth bound:
# an elementwise gather costs ~6-7 ns/element regardless of table size
# (measured honestly on v5e — loop-carried, nothing hoistable), while
# fetching whole lane-aligned rows and selecting the lane amortises the
# transaction: 512B rows (128 lanes f32) reach ~2.5 ns/slot and 1KB rows
# (256 lanes) ~1.7 ns/slot.  Gathers therefore use the widest row (256
# lanes) the weight size divides.  Scatter RMW does NOT benefit the same
# way (measured ~even with elementwise), so the scatter keeps 128-lane
# rows; the real scatter fix is the ELL kernel (`ops/ell_scatter.py`).
# The arithmetic is identical — blocked and elementwise paths produce
# bitwise-equal weights.
_BLOCK_LANES = 128
_GATHER_LANES = 256


# the blocked-gather half lives in ops/ell_scatter.py now (the kernel
# layer owns device-kernel helpers; model code imports DOWN, never the
# reverse) — re-bound here under the historical names for the updates
# below and for tests that exercise them through this module
from ...ops.ell_scatter import (  # noqa: E402
    blocked_gather as _blocked_gather,
    gather_weights as _gather_weights,
    use_blocked as _use_blocked,
)


def _blocked_scatter_add(w: jnp.ndarray, idx: jnp.ndarray,
                         updates_flat: jnp.ndarray) -> jnp.ndarray:
    """``w.at[idx.ravel()].add(updates_flat)`` via 128-lane row-scatter."""
    flat = idx.reshape(-1)
    hi, lo = flat // _BLOCK_LANES, flat % _BLOCK_LANES
    onehot = lo[:, None] == jnp.arange(_BLOCK_LANES, dtype=lo.dtype)[None, :]
    w2 = w.reshape(-1, _BLOCK_LANES).at[hi].add(
        updates_flat[:, None] * onehot)
    return w2.reshape(-1)


def _scatter_add_weights(w: jnp.ndarray, idx: jnp.ndarray,
                         updates_flat: jnp.ndarray) -> jnp.ndarray:
    if _use_blocked(w.shape[0]):
        return _blocked_scatter_add(w, idx, updates_flat)
    return w.at[idx.reshape(-1)].add(updates_flat)


def _finish_sparse_step(config: SGDConfig, *, sumsq=None, rsum=None):
    """Shared l2/apply/l1-prox/bias tail of the manual-gradient updates:
    the regularization algebra lives in ONE place so the sparse, mixed,
    ELL, and model-sharded paths stay identical to the dense autodiff
    semantics (l2 decay = ``w*(1-lr*l2)`` before the sparse gradient,
    exactly grad-of-``loss + l2/2 ||w||^2``; l1 via proximal
    soft-threshold after).

    ``sumsq``/``rsum`` override the two REDUCTIONS (||w||^2 and sum(r))
    for callers whose w/r are device-local shards needing a psum — the
    elementwise algebra never forks."""
    lr = config.learning_rate
    reg, alpha = config.reg, config.elastic_net
    l2 = reg * (1.0 - alpha)
    l1 = reg * alpha
    sumsq = sumsq or (lambda w: jnp.sum(jnp.square(w)))
    rsum = rsum or jnp.sum

    def finish(w, b, value, r, apply_grad):
        """``apply_grad(w)`` must add ``-lr * grad_loss`` to the (possibly
        l2-decayed) weight; ``r`` is dloss/dmargin for the bias step."""
        if l2 > 0:
            value = value + 0.5 * l2 * sumsq(w)
            w = w * (1.0 - lr * l2)
        w = apply_grad(w)
        if l1 > 0:
            w = jnp.sign(w) * jnp.maximum(jnp.abs(w) - lr * l1, 0.0)
        b = b - (lr * rsum(r) if config.fit_intercept else 0.0)
        return {"w": w, "b": b}, value

    return finish


def _sparse_update(loss_fn: LossFn, config: SGDConfig):
    """Single-batch update for hashed/sparse features ``(indices, values)``
    of fixed active count per row: the score is one gather + row reduce
    (``sum(values * w[indices])``) — the TPU-native replacement for a CSR
    SpMV.

    The weight gradient is applied as a direct in-place scatter-add of
    ``-lr * values * dloss/dmargin`` into the carried weight rather than by
    autodiff of the gather: ``jax.grad`` would materialise a dense (d,)
    cotangent (zero-fill + scatter + dense subtract = three O(d) HBM passes
    per step), while this form touches only the O(batch*nnz) active slots
    when unregularized.  ``loss_fn`` stays generic: dloss/dmargin comes
    from a vjp over the margin alone.  Gather/scatter go through the
    128-lane blocked views (see ``_BLOCK_LANES``) when the weight size
    allows.  l2 decay and the l1 proximal step are inherently dense and
    only cost their O(d) passes when enabled."""
    lr = config.learning_rate
    finish = _finish_sparse_step(config)

    def update(params, idx, vals, yb, wb):
        w, b = params["w"], params["b"]
        margin = jnp.sum(vals * _gather_weights(w, idx), axis=-1) + b
        value, pull = jax.vjp(lambda m: loss_fn(m, yb, wb), margin)
        (r,) = pull(jnp.ones_like(value))          # dloss/dmargin, (batch,)
        return finish(w, b, value, r, lambda w: _scatter_add_weights(
            w, idx, -lr * (vals * r[:, None]).reshape(-1)))

    return update


def _mixed_update(loss_fn: LossFn, config: SGDConfig):
    """Single-batch update for the Criteo-native layout: ``dense`` features
    occupying weight slots ``[0, dense.shape[-1])`` plus hashed ``cat``
    indices with implicit value 1.0 anywhere in ``[0, d)``.  The dense
    slots score and update through a tiny matvec (no gather/scatter at all
    — on TPU the random access IS the cost, measured ~8 ns/element), so
    only the categorical slots pay it; their gradient is just
    ``dloss/dmargin`` per slot.  Overlapping indices are handled exactly:
    both contributions simply add."""
    lr = config.learning_rate
    finish = _finish_sparse_step(config)

    def update(params, dense, cat, yb, wb):
        w, b = params["w"], params["b"]
        n_dense, n_cat = dense.shape[-1], cat.shape[-1]
        margin = (dense @ w[:n_dense]
                  + jnp.sum(_gather_weights(w, cat), axis=-1) + b)
        value, pull = jax.vjp(lambda m: loss_fn(m, yb, wb), margin)
        (r,) = pull(jnp.ones_like(value))

        def apply_grad(w):
            w = _scatter_add_weights(w, cat, jnp.repeat(-lr * r, n_cat))
            return w.at[:n_dense].add(-lr * (r @ dense))

        return finish(w, b, value, r, apply_grad)

    return update


def _ext_len(batch: int) -> int:
    """Length of the extended per-sample tables (:func:`_extended_r` and
    the ELL margin accumulator): batch plus a nonempty zero pad rounding
    up to whole 256-lane rows (pad slots carry ``src == batch``)."""
    return batch + (_GATHER_LANES - (batch % _GATHER_LANES)
                    or _GATHER_LANES)


def _extended_r(r: jnp.ndarray) -> jnp.ndarray:
    """r with a zero pad: padding slots carry ``src == batch`` and the pad
    rounds the gather table up to a whole number of 256-lane rows."""
    batch = r.shape[0]
    return jnp.concatenate(
        [r, jnp.zeros((_ext_len(batch) - batch,), jnp.float32)])


def _ell_margin(backend, precision, w, batch, src, pos, mask, ovf_idx,
                ovf_src, heavy_idx, heavy_cnt, val_ell=None, ovf_val=None):
    """Per-sample categorical margin ``sum_j v_j * w[idx_j]`` computed
    over the SAME ELL routing the scatter uses — the forward half of the
    r4 kernel plan (the ``w[cat]`` gather measured ~3.4 ms of the 7.79 ms
    bench-shape step; the Mosaic margin kernel replaces it with one-hot
    MXU contractions).  The in-grid implementation resolves from the
    kernel registry (op ``ell_margin``: the fused Mosaic kernel on TPU
    grids divisible into 8-row blocks, the XLA twin otherwise;
    ``backend`` forces one — tests pass ``"xla"`` for the oracle).
    Overflow via a tiny gather + extended-table scatter-add (pad entries
    carry ``ovf_src == batch`` and land in the discarded pad), heavy
    hitters via one ``(H,) @ (H, batch)`` matvec."""
    from ...kernels.registry import lookup

    entry = lookup("ell_margin", sig=(int(src.shape[0]),), backend=backend)
    mext = entry.fn(w, src, pos, mask, m_len=_ext_len(batch),
                    val=val_ell, precision=precision)
    o = w[ovf_idx] if ovf_val is None else ovf_val * w[ovf_idx]
    mext = mext.at[ovf_src].add(o, mode="drop")
    return mext[:batch] + w[heavy_idx] @ heavy_cnt.astype(jnp.float32)


def _apply_ell_categorical(backend, precision, lr, w, r, r_ext, src,
                           pos, mask, ovf_idx, ovf_src, heavy_idx,
                           heavy_cnt, val_ell=None, ovf_val=None):
    """THE single copy of the ELL gradient application shared by the
    mixed (implicit value 1.0) and generic sparse (explicit values)
    update builders: slot gather -> kernel scatter -> overflow scatter ->
    heavy-hitter matvec ((H, batch) @ (batch,) replaces thousands of
    per-slot updates; padding entries carry zero counts and add 0 at
    w[0]).

    The in-grid implementation resolves from the kernel registry (op
    ``ell_scatter_apply``): on TPU the slot gather + scatter run as ONE
    fused Mosaic kernel — the r4 ablation measured the standalone XLA
    u-gather as the dominant step cost (~5.6 ms of a 7.79 ms step;
    fused step 6.53 ms vs 8.92 ms XLA oracle) — with the gather +
    scatter-kernel pair as the registered fallback when the grid
    doesn't divide into the fused kernel's 8-row blocks, and the pure
    XLA lowering off TPU (``backend`` forces one)."""
    from ...kernels.registry import lookup

    entry = lookup("ell_scatter_apply", sig=(int(src.shape[0]),),
                   backend=backend)
    w = entry.fn(w, r_ext, src, pos, mask, lr=lr, val=val_ell,
                 precision=precision)
    o = r_ext[ovf_src] if ovf_val is None else ovf_val * r_ext[ovf_src]
    w = w.at[ovf_idx].add((-lr) * o)
    return w.at[heavy_idx].add((-lr) * (heavy_cnt.astype(jnp.float32) @ r))


def _mixed_update_ell(loss_fn: LossFn, config: SGDConfig,
                      backend=None):
    """Kernel-planned twin of :func:`_mixed_update`: same loss/
    regularization algebra, but BOTH halves of the categorical work —
    the forward margin gather and the backward scatter — go through the
    static ELL routing's fused Mosaic kernels (``ops/ell_scatter.py``)
    instead of XLA's per-element gather/scatter: measured 1.02 ms/step
    vs the 10.86 ms XLA oracle at bench shape, same run, v5e
    (TPU_FUSED_STEP_r04.txt).  The extra batch arguments (src, pos,
    mask, ovf_idx, ovf_src, heavy_idx, heavy_cnt) are the per-step
    layout stacks produced by ``ell_layout`` at fit time — the raw
    ``cat`` tensor itself is not an input; results differ from the XLA
    path only in f32 summation order (plus the documented
    ``ell_precision`` truncation of the one-hot contractions)."""
    lr = config.learning_rate
    finish = _finish_sparse_step(config)

    def update(params, dense, src, pos, mask, ovf_idx, ovf_src,
               heavy_idx, heavy_cnt, yb, wb):
        w, b = params["w"], params["b"]
        n_dense = dense.shape[-1]
        margin = (dense @ w[:n_dense]
                  + _ell_margin(backend, config.ell_precision,
                                w, dense.shape[0], src, pos,
                                mask, ovf_idx, ovf_src, heavy_idx,
                                heavy_cnt) + b)
        value, pull = jax.vjp(lambda m: loss_fn(m, yb, wb), margin)
        (r,) = pull(jnp.ones_like(value))
        r_ext = _extended_r(r)

        def apply_grad(w):
            w = _apply_ell_categorical(
                backend, config.ell_precision, lr, w, r, r_ext, src,
                pos, mask, ovf_idx, ovf_src, heavy_idx, heavy_cnt)
            return w.at[:n_dense].add(-lr * (r @ dense))

        return finish(w, b, value, r, apply_grad)

    return update


def _shard_map(fn, mesh, in_specs, out_specs):
    """jax.shard_map with the repo's compat shims — one shared copy in
    ``parallel/collectives.py`` (handles the older-JAX import path and
    turns the replication check off on every version, since pallas_call
    out_shapes carry no varying-mesh-axes annotation)."""
    from ...parallel.collectives import shard_map_fn

    return shard_map_fn(fn, mesh, in_specs=in_specs, out_specs=out_specs)


def _mixed_update_ell_sharded(loss_fn: LossFn, config: SGDConfig, mesh,
                              num_features: int, backend=None):
    """Data-parallel twin of :func:`_mixed_update_ell` (VERDICT r3 task 4:
    the pod-scale ELL path).  Each device routes only ITS batch shard's
    categorical slots through a device-LOCAL ELL grid — the layout stacks
    carry a leading device dim sharded over ``data``, with slot sources
    numbered inside the local shard — and emits a local delta over the
    full weight; one ``psum`` rides ICI to complete the scatter, exactly
    like the dense gradient's contraction.  Scatter compute and layout
    HBM both scale 1/D with the data axis; summation order differs from
    the single-device kernel only by the per-device partial-sum split."""
    lr = config.learning_rate
    finish = _finish_sparse_step(config)
    d_spec = P("data")
    layout_specs = ((P("data", None, None),) * 3 + (P("data", None),) * 3
                    + (P("data", None, None),))

    def _local_delta(r_l, src, pos, mask, ovf_idx, ovf_src, heavy_idx,
                     heavy_cnt):
        # layout blocks arrive as (1, ...) local slices: squeeze the
        # device dim; r_l is this device's residual shard
        r_ext = _extended_r(r_l)
        delta = _apply_ell_categorical(
            backend, config.ell_precision, lr,
            jnp.zeros((num_features,), jnp.float32), r_l,
            r_ext, src[0], pos[0], mask[0], ovf_idx[0], ovf_src[0],
            heavy_idx[0], heavy_cnt[0])
        return jax.lax.psum(delta, "data")

    ell_delta = _shard_map(
        _local_delta, mesh,
        in_specs=(d_spec,) + layout_specs,
        out_specs=P())

    def _local_margin(w, src, pos, mask, ovf_idx, ovf_src, heavy_idx,
                      heavy_cnt):
        # per-device margins of the device's own batch shard: its layout
        # slots cover exactly its samples (local src numbering), w is
        # replicated — no collective needed, margins reassemble over
        # 'data' (the local batch size is heavy_cnt's trailing dim)
        return _ell_margin(
            backend, config.ell_precision, w, heavy_cnt.shape[-1],
            src[0], pos[0], mask[0], ovf_idx[0], ovf_src[0],
            heavy_idx[0], heavy_cnt[0])

    ell_margin_sm = _shard_map(
        _local_margin, mesh,
        in_specs=(P(),) + layout_specs,
        out_specs=d_spec)

    def update(params, dense, src, pos, mask, ovf_idx, ovf_src,
               heavy_idx, heavy_cnt, yb, wb):
        w, b = params["w"], params["b"]
        n_dense = dense.shape[-1]
        margin = (dense @ w[:n_dense]
                  + ell_margin_sm(w, src, pos, mask, ovf_idx, ovf_src,
                                  heavy_idx, heavy_cnt) + b)
        value, pull = jax.vjp(lambda m: loss_fn(m, yb, wb), margin)
        (r,) = pull(jnp.ones_like(value))

        def apply_grad(w):
            w = w + ell_delta(r, src, pos, mask, ovf_idx, ovf_src,
                              heavy_idx, heavy_cnt)
            return w.at[:n_dense].add(-lr * (r @ dense))

        return finish(w, b, value, r, apply_grad)

    return update


def _sparse_update_ell_sharded(loss_fn: LossFn, config: SGDConfig, mesh,
                               num_features: int, backend=None):
    """Values-aware twin of :func:`_mixed_update_ell_sharded` for the
    generic (indices, values) layout — the same device-local-grid + psum
    scatter, with per-slot updates ``-lr * value * r`` carried by the
    layout's value arrays."""
    lr = config.learning_rate
    finish = _finish_sparse_step(config)
    layout_specs = ((P("data", None, None),) * 4 + (P("data", None),) * 4
                    + (P("data", None, None),))

    def _local_delta(r_l, src, pos, mask, val, ovf_idx, ovf_src, ovf_val,
                     heavy_idx, heavy_cnt):
        r_ext = _extended_r(r_l)
        delta = _apply_ell_categorical(
            backend, config.ell_precision, lr,
            jnp.zeros((num_features,), jnp.float32), r_l,
            r_ext, src[0], pos[0], mask[0], ovf_idx[0], ovf_src[0],
            heavy_idx[0], heavy_cnt[0], val_ell=val[0], ovf_val=ovf_val[0])
        return jax.lax.psum(delta, "data")

    ell_delta = _shard_map(
        _local_delta, mesh,
        in_specs=(P("data"),) + layout_specs,
        out_specs=P())

    def _local_margin(w, src, pos, mask, val, ovf_idx, ovf_src, ovf_val,
                      heavy_idx, heavy_cnt):
        # same stance as _mixed_update_ell_sharded: local layout covers
        # local samples, w replicated, margins reassemble over 'data'
        return _ell_margin(
            backend, config.ell_precision, w, heavy_cnt.shape[-1],
            src[0], pos[0], mask[0], ovf_idx[0], ovf_src[0],
            heavy_idx[0], heavy_cnt[0], val_ell=val[0],
            ovf_val=ovf_val[0])

    ell_margin_sm = _shard_map(
        _local_margin, mesh,
        in_specs=(P(),) + layout_specs,
        out_specs=P("data"))

    def update(params, src, pos, mask, val_ell, ovf_idx,
               ovf_src, ovf_val, heavy_idx, heavy_cnt, yb, wb):
        w, b = params["w"], params["b"]
        margin = ell_margin_sm(w, src, pos, mask, val_ell, ovf_idx,
                               ovf_src, ovf_val, heavy_idx, heavy_cnt) + b
        value, pull = jax.vjp(lambda m: loss_fn(m, yb, wb), margin)
        (r,) = pull(jnp.ones_like(value))

        def apply_grad(w):
            return w + ell_delta(r, src, pos, mask, val_ell, ovf_idx,
                                 ovf_src, ovf_val, heavy_idx, heavy_cnt)

        return finish(w, b, value, r, apply_grad)

    return update


def sgd_fit_sparse(loss_fn: LossFn, indices: np.ndarray, values: np.ndarray,
                   labels: np.ndarray, weights: Optional[np.ndarray],
                   num_features: int, config: SGDConfig,
                   mesh=None) -> Tuple[LinearState, list]:
    """Sparse-feature variant of :func:`sgd_fit`: rows are ``(indices
    (n, nnz) int32, values (n, nnz) f32)`` pairs (the
    :func:`flink_ml_tpu.linalg.stack_sparse_vectors` / hashed-FeatureHasher
    form) scored against a dense ``(num_features,)`` weight living in HBM.
    This is the Criteo-shaped path: 2^20+ hashed dims never materialise as a
    dense matrix; only the weight (4 MiB at 2^20 f32) is dense."""
    from .linear import check_sparse_indices

    check_sparse_indices(indices, num_features)
    mesh = mesh or default_mesh()
    n = indices.shape[0]
    steps, batch, perm = _plan_epoch_layout_for_mesh(
        n, resolve_global_batch_size(config, n, num_features,
                                     layout_bytes_per_slot=16),
        mesh, config.seed)

    idx = prepare_epoch_tensor(indices.astype(np.int32), perm, steps, batch)
    vals = prepare_epoch_tensor(values.astype(np.float32), perm, steps, batch)
    y = prepare_epoch_tensor(labels.astype(np.float32), perm, steps, batch)
    w_host = (weights.astype(np.float32) if weights is not None
              else np.ones((n,), np.float32))
    w = prepare_epoch_tensor(w_host, perm, steps, batch, pad_value=0.0)

    # the values-aware layout adds a fourth f32 grid (val): 16 B/slot/step
    impl = plan_mixed_impl(num_features, mesh, steps,
                           layout_bytes_per_slot=16, allow_sharded=True)
    n_dev_data = int(mesh.shape.get("data", 1))
    ell_sharded = impl == "ell" and n_dev_data > 1
    if ell_sharded:
        # per-device shard layouts, same stance as sgd_fit_mixed
        from ...ops.ell_scatter import ell_layout

        local = batch // n_dev_data
        lay = ell_layout(
            idx.reshape(steps * n_dev_data, local, idx.shape[-1]),
            num_features,
            values=vals.reshape(steps * n_dev_data, local, vals.shape[-1]))

        def dev_stack(a):
            return a.reshape((steps, n_dev_data) + a.shape[1:])

        extra = tuple(dev_stack(a) for a in (
            lay.src, lay.pos, lay.mask, lay.val, lay.ovf_idx, lay.ovf_src,
            lay.ovf_val, lay.heavy_idx, lay.heavy_cnt))
        update = _sparse_update_ell_sharded(
            loss_fn, config, mesh, num_features)
    elif impl == "ell":
        from ...ops.ell_scatter import ell_layout

        layout = ell_layout(idx, num_features, values=vals)
        extra = (layout.src, layout.pos, layout.mask, layout.val,
                 layout.ovf_idx, layout.ovf_src, layout.ovf_val,
                 layout.heavy_idx, layout.heavy_cnt)
        update = _sparse_update_ell(loss_fn, config)
    else:
        extra = ()
        update = _sparse_update(loss_fn, config)

    y = _put_epoch_tensor(y, mesh, P(None, "data"))
    w = _put_epoch_tensor(w, mesh, P(None, "data"))
    if ell_sharded:
        specs = ([P(None, "data", None, None)] * 4
                 + [P(None, "data", None)] * 4
                 + [P(None, "data", None, None)])
        extra = tuple(_put_epoch_tensor(a, mesh, s)
                      for a, s in zip(extra, specs))
    elif impl == "ell":
        extra = tuple(jax.device_put(a) for a in extra)  # single-device
    if impl in ("ell",):
        # margins and scatters both ride the layout: the raw
        # (steps, batch, nnz) idx/vals epoch tensors stay host-side
        epoch_args = extra + (y, w)
    else:
        idx = _put_epoch_tensor(idx, mesh, P(None, "data", None))
        vals = _put_epoch_tensor(vals, mesh, P(None, "data", None))
        epoch_args = (idx, vals) + extra + (y, w)

    params, loss_log = _run_minibatch_epochs(
        update, epoch_args,
        {"w": jnp.zeros((num_features,), jnp.float32),
         "b": jnp.zeros((), jnp.float32)}, steps, config, mesh)
    return LinearState(np.asarray(params["w"], np.float64),
                       float(params["b"]), planned_impl=impl), loss_log


# The ELL layout costs ~12 bytes per weight slot PER STEP (src + pos i32
# + mask f32 over a (num_features/128, 128) grid), independent of batch
# size.  Cap its device footprint: beyond this, many-step fits (small
# batches or huge hash spaces) would OOM HBM where the XLA path runs fine.
_ELL_LAYOUT_BUDGET_BYTES = 2 << 30


def plan_mixed_impl(num_features: int, mesh, steps: int = 1,
                    layout_bytes_per_slot: int = 12,
                    allow_sharded: bool = False,
                    allow_multiprocess: bool = False) -> str:
    """Which categorical-scatter implementation :func:`sgd_fit_mixed`
    runs: ``"ell"`` (the Pallas static-routing kernel,
    ``ops/ell_scatter.py``) on TPU when the weight size tiles into
    128-lane rows and the ``steps``-deep layout stack fits the per-device
    HBM budget, else ``"xla"``.

    ``allow_sharded=True`` (what ``sgd_fit_mixed`` passes) additionally
    admits data-axis meshes: each device routes its own batch shard
    through a device-local grid and one psum completes the scatter
    (:func:`_mixed_update_ell_sharded`) — the layout budget is
    per-device, so the check does not change with the axis size.
    ``allow_multiprocess=True`` extends that to process-spanning meshes —
    only for callers whose layout build is per-process-local (the
    STREAMING fit, whose decode workers build each host's own device
    stacks); the fused fit builds the whole global batch's layout in one
    process and stays single-process."""
    import jax as _jax

    from ...ops.ell_scatter import supported as _ell_supported

    try:
        n_dev = int(np.prod(list(mesh.shape.values())))
    except Exception:
        n_dev = len(mesh.devices.flat)
    data_only = n_dev == int(mesh.shape.get("data", 0))
    procs_ok = _mesh_process_count(mesh) == 1 or allow_multiprocess
    mesh_ok = n_dev == 1 or (allow_sharded and data_only and procs_ok)
    if (_jax.default_backend() == "tpu" and mesh_ok
            and _ell_supported(num_features)
            and steps * num_features * layout_bytes_per_slot
            <= _ELL_LAYOUT_BUDGET_BYTES):
        return "ell"
    return "xla"


def _sparse_update_ell(loss_fn: LossFn, config: SGDConfig,
                       backend=None):
    """Kernel-planned twin of :func:`_sparse_update` for the generic
    (indices, values) layout: per-slot updates are ``-lr * value * r``,
    carried by the layout's value arrays (``EllLayout.val`` /
    ``ovf_val`` / value-sum ``heavy_cnt``).  Same algebra as the XLA
    path up to f32 summation order."""
    lr = config.learning_rate
    finish = _finish_sparse_step(config)

    def update(params, src, pos, mask, val_ell, ovf_idx,
               ovf_src, ovf_val, heavy_idx, heavy_cnt, yb, wb):
        w, b = params["w"], params["b"]
        margin = _ell_margin(backend, config.ell_precision, w,
                             yb.shape[0], src, pos, mask,
                             ovf_idx, ovf_src, heavy_idx, heavy_cnt,
                             val_ell=val_ell, ovf_val=ovf_val) + b
        value, pull = jax.vjp(lambda m: loss_fn(m, yb, wb), margin)
        (r,) = pull(jnp.ones_like(value))
        r_ext = _extended_r(r)

        def apply_grad(w):
            return _apply_ell_categorical(
                backend, config.ell_precision, lr, w, r, r_ext, src,
                pos, mask, ovf_idx, ovf_src, heavy_idx, heavy_cnt,
                val_ell=val_ell, ovf_val=ovf_val)

        return finish(w, b, value, r, apply_grad)

    return update


def _place_zeros(shape: tuple, mesh, spec: P) -> jnp.ndarray:
    """A zero f32 array laid out under ``spec`` — built shard-by-shard via
    ``make_array_from_callback`` so it works identically on single-host
    and process-spanning meshes (where ``device_put`` to a
    non-fully-addressable sharding is not available)."""
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(
        shape, sharding,
        lambda idx: np.zeros(sharding.shard_shape(shape), np.float32))


def _mixed_update_sharded(loss_fn: LossFn, config: SGDConfig, mesh,
                          num_features: int, n_dense: int):
    """dp x model-parallel twin of :func:`_mixed_update`: the weight is
    SHARDED over the mesh's ``model`` axis (each device owns a contiguous
    ``num_features / M`` block) so 2^24+ hash spaces never replicate —
    the embedding-table pattern of
    ``widedeep.py::build_sharded_train_step`` applied to the flat LR
    weight.  Communication per step is three small collectives, all on
    per-batch vectors, never on the weight:

    - ``psum("model")`` of each shard's partial margins (batch,)
    - ``psum("data")`` of the weighted-loss numerator/denominator pair
      (the loss_fn's weighted mean re-normalized globally, so the result
      matches the replicated path exactly)
    - ``psum("data")`` of the owned-slot update block (shard-sized; this
      is the data-parallel gradient reduction)

    Each device scatters only the categorical slots it OWNS (masked
    local indices); the dense block lives on model-rank 0's shard.
    """
    M = int(mesh.shape["model"])
    if num_features % M:
        raise ValueError(
            f"num_features={num_features} must divide the model axis "
            f"({M}); pad the hash space")
    shard = num_features // M
    if n_dense > shard:
        raise ValueError(
            f"n_dense={n_dense} exceeds the per-device weight shard "
            f"{shard}; use fewer model shards")
    lr = config.learning_rate
    finish = _finish_sparse_step(
        config,
        sumsq=lambda w: jax.lax.psum(jnp.sum(jnp.square(w)), "model"),
        rsum=lambda r: jax.lax.psum(jnp.sum(r), "data"))

    def device_fn(w_shard, b, dense, cat, yb, wb):
        # w_shard (shard,) this device's block; batch args are LOCAL rows
        mrank = jax.lax.axis_index("model")
        off = mrank * shard
        loc = cat - off
        owned = (loc >= 0) & (loc < shard)
        locc = jnp.clip(loc, 0, shard - 1)
        gathered = jnp.where(owned, w_shard[locc], 0.0)
        margin_part = jnp.sum(gathered, axis=-1)
        on0 = (mrank == 0).astype(jnp.float32)
        margin_part = margin_part + on0 * (dense @ w_shard[:n_dense])
        margin = jax.lax.psum(margin_part, "model") + b

        value_local, pull = jax.vjp(lambda m: loss_fn(m, yb, wb), margin)
        (r_local,) = pull(jnp.ones_like(value_local))
        # re-normalize the loss_fn's LOCAL weighted mean to the global
        # denominator so sharded == replicated bit-for-bit in exact math
        denom_local = jnp.maximum(jnp.sum(wb), 1e-12)
        denom = jax.lax.psum(denom_local, "data")
        value = jax.lax.psum(value_local * denom_local, "data") / denom
        r = r_local * (denom_local / denom)

        def apply_grad(w_shard):
            delta = jnp.zeros_like(w_shard).at[locc.reshape(-1)].add(
                jnp.where(owned, -lr * r[:, None], 0.0).reshape(-1))
            delta = delta.at[:n_dense].add(on0 * (-lr) * (r @ dense))
            return w_shard + jax.lax.psum(delta, "data")

        return finish(w_shard, b, value, r, apply_grad)

    fn = _shard_map(
        device_fn, mesh,
        in_specs=(P("model"), P(), P("data", None), P("data", None),
                  P("data"), P("data")),
        out_specs=({"w": P("model"), "b": P()}, P()))

    def update(params, dense, cat, yb, wb):
        return fn(params["w"], params["b"], dense, cat, yb, wb)

    return update


def sgd_fit_mixed(loss_fn: LossFn, dense_features: np.ndarray,
                  cat_indices: np.ndarray, labels: np.ndarray,
                  weights: Optional[np.ndarray], num_features: int,
                  config: SGDConfig, mesh=None) -> Tuple[LinearState, list]:
    """Criteo-native variant of :func:`sgd_fit_sparse`: ``dense_features``
    (n, n_dense) occupy weight slots ``[0, n_dense)`` and ``cat_indices``
    (n, n_cat) are hashed slots with implicit value 1.0.  The dense slots
    never pay the per-element random-access cost (see
    :func:`_mixed_update`), which is why this layout is the fastest LR
    path on TPU for mixed dense/categorical data.

    Multi-host: pass a process-spanning mesh (``distributed.global_mesh``)
    and call from EVERY process with that process's own equal-sized row
    shard; the global batch is the concatenation over processes and the
    gradient reduction rides ICI/DCN.  The same contract applies to
    :func:`sgd_fit` / :func:`sgd_fit_sparse`."""
    from .linear import check_sparse_indices

    check_sparse_indices(cat_indices, num_features)
    n_dense = dense_features.shape[1]
    if n_dense > num_features:
        raise ValueError(f"n_dense={n_dense} exceeds "
                         f"num_features={num_features}")
    mesh = mesh or default_mesh()
    n = dense_features.shape[0]
    steps, batch, perm = _plan_epoch_layout_for_mesh(
        n, resolve_global_batch_size(config, n, num_features), mesh,
        config.seed)

    dense = prepare_epoch_tensor(dense_features.astype(np.float32), perm,
                                 steps, batch)
    cat = prepare_epoch_tensor(cat_indices.astype(np.int32), perm, steps,
                               batch)
    y = prepare_epoch_tensor(labels.astype(np.float32), perm, steps, batch)
    w_host = (weights.astype(np.float32) if weights is not None
              else np.ones((n,), np.float32))
    w = prepare_epoch_tensor(w_host, perm, steps, batch, pad_value=0.0)

    model_sharded = int(mesh.shape.get("model", 1)) > 1
    impl = ("sharded" if model_sharded
            else plan_mixed_impl(num_features, mesh, steps,
                                 allow_sharded=True))
    n_dev_data = int(mesh.shape.get("data", 1))
    ell_sharded = impl == "ell" and n_dev_data > 1
    place_params = True
    init_params = {"w": jnp.zeros((num_features,), jnp.float32),
                   "b": jnp.zeros((), jnp.float32)}
    if ell_sharded:
        # per-device shard layouts (VERDICT r3 task 4): slot sources are
        # numbered inside each device's local (batch/n_dev)-row shard, and
        # the stacks gain a device dim sharded over 'data'
        from ...ops.ell_scatter import ell_layout

        local = batch // n_dev_data
        lay = ell_layout(
            cat.reshape(steps * n_dev_data, local, cat.shape[-1]),
            num_features)

        def dev_stack(a):
            return a.reshape((steps, n_dev_data) + a.shape[1:])

        extra = tuple(dev_stack(a) for a in (
            lay.src, lay.pos, lay.mask, lay.ovf_idx, lay.ovf_src,
            lay.heavy_idx, lay.heavy_cnt))
        update = _mixed_update_ell_sharded(
            loss_fn, config, mesh, num_features)
    elif impl == "ell":
        # one-time static routing of every step's categorical slots
        # (amortised over max_epochs replays of the same epoch tensor)
        from ...ops.ell_scatter import ell_layout

        layout = ell_layout(cat, num_features)
        extra = (layout.src, layout.pos, layout.mask,
                 layout.ovf_idx, layout.ovf_src,
                 layout.heavy_idx, layout.heavy_cnt)
        update = _mixed_update_ell(loss_fn, config)
    elif impl == "sharded":
        # weight sharded over the model axis (2^24+ hash spaces never
        # replicate); see _mixed_update_sharded
        extra = ()
        update = _mixed_update_sharded(loss_fn, config, mesh, num_features,
                                       n_dense)
        init_params = {
            "w": _place_zeros((num_features,), mesh, P("model")),
            "b": _place_zeros((), mesh, P()),
        }
        place_params = False
    else:
        extra = ()
        update = _mixed_update(loss_fn, config)

    dense = _put_epoch_tensor(dense, mesh, P(None, "data", None))
    y = _put_epoch_tensor(y, mesh, P(None, "data"))
    w = _put_epoch_tensor(w, mesh, P(None, "data"))
    if ell_sharded:
        specs = ([P(None, "data", None, None)] * 3
                 + [P(None, "data", None)] * 3
                 + [P(None, "data", None, None)])
        extra = tuple(_put_epoch_tensor(a, mesh, s)
                      for a, s in zip(extra, specs))
    elif impl == "ell":
        extra = tuple(jax.device_put(a) for a in extra)  # single-device
    if impl in ("ell",):
        # the ELL updates never read the raw index tensor — margins and
        # scatters both ride the layout — so the (steps, batch, nnz)
        # epoch tensor stays host-side (~steps*batch*nnz*4 B of HBM)
        epoch_args = (dense,) + extra + (y, w)
    else:
        cat = _put_epoch_tensor(cat, mesh, P(None, "data", None))
        epoch_args = (dense, cat) + extra + (y, w)

    params, loss_log = _run_minibatch_epochs(
        update, epoch_args, init_params, steps, config,
        mesh, place_params=place_params)
    return LinearState(np.asarray(params["w"], np.float64),
                       float(params["b"]), planned_impl=impl), loss_log


def _reader_for_epoch(make_reader: Callable, epoch: int,
                      retry_policy=None):
    """Call the per-epoch reader factory, passing ``epoch=`` when the
    factory accepts it.  Per-epoch shuffled readers
    (``data.datacache.ShuffledCacheReader``) need the ACTUAL epoch number
    — a call-counting closure would desynchronize on checkpoint resume,
    which restarts mid-training at an arbitrary epoch.  Zero-arg
    factories keep working unchanged.

    ``retry_policy`` wraps the returned reader so transient pull
    failures retry with backoff.  The wrap happens HERE — at the raw
    reader, below the fit's generator adapters — because a generator
    that propagates an exception is dead forever: retrying above one
    would turn a healed transient into a silently truncated epoch
    (``robustness.retry.RetryingIterator``)."""

    def build():
        try:
            sig = inspect.signature(make_reader)
        except (TypeError, ValueError):
            return make_reader()
        for p in sig.parameters.values():
            # only an explicitly named, keyword-passable `epoch` opts in:
            # a bare **kwargs factory must NOT be force-fed an argument it
            # merely forwards, and a positional-only `epoch` cannot take
            # the keyword call
            if p.name == "epoch" and p.kind in (
                    inspect.Parameter.POSITIONAL_OR_KEYWORD,
                    inspect.Parameter.KEYWORD_ONLY):
                return make_reader(epoch=epoch)
        return make_reader()

    reader = build()
    if retry_policy is None:
        return reader
    from ...robustness.retry import RetryingIterator

    return RetryingIterator(reader, retry_policy)


def _has_cursor(reader) -> bool:
    """The DataCacheReader cursor protocol: seekable, fixed batch size,
    known length — the contract ``sgd_fit_outofcore`` relies on for
    checkpoint fast-forward and decoded-replay eligibility."""
    return (hasattr(reader, "seek") and hasattr(reader, "batch_rows")
            and hasattr(reader, "total_rows"))


def _seek_or_skip(reader, k: int):
    """Position a fresh reader ``k`` batches in: seek when it speaks the
    cursor protocol, else discard batches.  Returns an iterator."""
    if hasattr(reader, "seek") and hasattr(reader, "batch_rows"):
        rows = k * reader.batch_rows
        total = getattr(reader, "total_rows", None)
        reader.seek(rows if total is None else min(rows, total))
        return iter(reader)
    it = iter(reader)
    for _ in range(k):
        try:
            next(it)
        except StopIteration:
            break
    return it


# ---------------------------------------------------------------------------
# Step-program compile cache.  Crash->resume, elastic resize, and A/B
# refits re-enter sgd_fit_outofcore many times per process with the same
# (loss, config, mesh, layout); the update/chunk closures are pure
# functions of those inputs, so re-jitting a fresh closure per call pays
# the full XLA compile again for a program that cannot differ.  Keyed by
# value (SGDConfig is mutable — hash its field tuple, recursing into the
# frozen GradReduceConfig) plus the mesh's axis extents and device ids;
# an unhashable key (exotic loss object, custom grad_reduce) just skips
# the cache.  Bounded LRU so a long-lived trainer cycling many configs
# does not retain every executable forever.
_STEP_PROGRAM_CACHE: "OrderedDict" = OrderedDict()
_STEP_PROGRAM_CACHE_CAP = 64


def _step_program_key(kind: tuple, loss_fn, config: SGDConfig, mesh):
    """Hashable identity of a compiled step program, or None to skip.

    Only the config fields the update closures consume participate —
    host-loop knobs (max_epochs, tol, seed, batch size) must NOT
    fragment the key, or a refit at a different epoch budget would
    recompile an identical program.
    """
    gr = config.grad_reduce
    try:
        key = (kind, loss_fn,
               float(config.learning_rate), float(config.reg),
               float(config.elastic_net), bool(config.fit_intercept),
               str(config.ell_precision),
               type(gr).__name__, astuple(gr) if is_dataclass(gr) else gr,
               tuple(str(a) for a in mesh.axis_names),
               tuple(int(mesh.shape[a]) for a in mesh.axis_names),
               tuple(int(d.id) for d in np.ravel(mesh.devices)))
        hash(key)
    except Exception:
        return None
    return key


def _cached_step_program(key, build: Callable):
    """Return the cached jitted callable for ``key``, building on miss.

    Reusing the jit wrapper (not just the traced program) keeps XLA's
    per-shape executable cache attached to it, so a cache hit skips both
    the re-trace and the re-compile; donation semantics are per-call and
    unaffected by reuse.
    """
    if key is None:
        return build()
    fn = _STEP_PROGRAM_CACHE.get(key)
    if fn is None:
        fn = build()
        _STEP_PROGRAM_CACHE[key] = fn
        if len(_STEP_PROGRAM_CACHE) > _STEP_PROGRAM_CACHE_CAP:
            _STEP_PROGRAM_CACHE.popitem(last=False)
    else:
        _STEP_PROGRAM_CACHE.move_to_end(key)
    return fn


def sgd_fit_outofcore(loss_fn: LossFn, make_reader: Callable, *,
                      num_features: int, config: SGDConfig, mesh=None,
                      features_key: str = "features",
                      label_key: str = "label",
                      weight_key: Optional[str] = None,
                      indices_key: Optional[str] = None,
                      values_key: Optional[str] = None,
                      dense_key: Optional[str] = None,
                      prefetch_depth: int = 2,
                      prefetch_workers: int = 1,
                      prefetch_put_workers: int = 1,
                      prefetch_stats=None,
                      steps_per_dispatch: int = 8,
                      cache_decoded="auto",
                      decoded_ram_budget: Optional[int] = None,
                      stream_info: Optional[dict] = None,
                      ell_ovf_cap: Optional[int] = None,
                      ell_heavy_cap: int = 16,
                      checkpoint=None,
                      checkpoint_every_steps: int = 0,
                      resume: bool = False,
                      retry_policy=None,
                      publish_cb: Optional[Callable] = None,
                      step_probe: bool = False,
                      membership=None
                      ) -> Tuple[LinearState, list]:
    """Out-of-core variant of :func:`sgd_fit`: the dataset never has to fit
    in host RAM or HBM (the Criteo-1TB shape, BASELINE.md north star).

    ``make_reader()`` is called once per epoch and must return a fresh
    iterator of host batch dicts with fixed row count per batch (e.g.
    ``DataCacheReader(..., batch_rows=B)`` re-seeked to 0 — its fadvise
    readahead covers the disk side).  Batches are padded to the first
    batch's row count (padding rows carry weight 0), transferred via
    :func:`prefetch_to_device` so the host read/decode and the HBM transfer
    of batch N+1 overlap the jitted step on batch N, and consumed by one
    compiled update program — static shapes, zero recompiles across the
    epoch.

    With ``indices_key``/``values_key`` set the reader feeds **sparse**
    batches — ``(rows, nnz)`` hashed index/value pairs scored against the
    dense ``(num_features,)`` weight (the :func:`sgd_fit_sparse` layout);
    ``features_key`` is ignored.  With ``dense_key``+``indices_key`` the
    reader feeds the **mixed** Criteo-native layout instead — a dense
    block plus hashed categorical indices with implicit value 1.0 (the
    :func:`sgd_fit_mixed` layout, the fastest LR path on TPU).  Either
    way 2^20+ dims stream from disk without ever densifying.  On a
    single TPU device the mixed path plans the ELL scatter kernel: each
    batch's static routing builds in the prefetch decode workers
    (overlapping the device step) with fixed capacities
    (``ell_ovf_cap``/``ell_heavy_cap`` — one compiled program for every
    batch; an over-cap batch raises with sizing guidance).  The default
    ``ell_ovf_cap`` is deliberately generous (``max(1024, batch)``)
    because the cap cannot change mid-stream; the XLA overflow
    scatter's cost scales with the STATIC cap (~0.2 us per cap slot per
    step, r4 TPU_STEP_BREAKDOWN), so deployments whose collision rate
    is known should pass a tight ``ell_ovf_cap`` — in-memory fits size
    it from the measured need automatically.

    Unlike :func:`sgd_fit`, the READER owns the data layout:
    ``config.global_batch_size`` and ``config.seed`` are inert here — batch
    size is the reader's ``batch_rows`` and any shuffling must happen in the
    reader (e.g. shuffle when writing the cache, or shuffle segment order
    per epoch).

    **Chunked dispatch** (``steps_per_dispatch=W``, default 8): ``W``
    consecutive prefetched batches are stacked on the host into one
    device chunk (the prefetch pipeline's ``chunks=W`` mode — the
    ``device_put`` of chunk N+1 overlaps compute on chunk N) and one
    jitted ``lax.scan`` with a donated carry runs all ``W`` optimizer
    steps, so an epoch costs ``ceil(n_batches / W)`` dispatches instead
    of ``n_batches`` — the fixed per-dispatch host round-trip (dominant
    on tunneled/relay transports) amortizes ``W``-fold.  The final
    short chunk pads with a validity mask whose dead steps freeze the
    carry, so results are BIT-EXACT vs ``W=1`` (asserted in tests);
    mid-epoch checkpoint cuts land at chunk boundaries.  Process-
    spanning meshes force ``W=1`` (chunk assembly is per-process-local).
    The pipeline runs at ``ceil(prefetch_depth / W)`` CHUNKS of depth,
    floored at ONE — so chunked mode keeps at least ``W`` batches
    staged (plus the ``W``-batch chunk in compute), a ~``W/3``-fold
    device-staging increase over the classic per-batch pipeline at the
    default ``prefetch_depth=2``; memory-constrained deployments bound
    the footprint by lowering ``steps_per_dispatch`` (``W=1``
    reproduces the old footprint), and host-side assembly stages up to
    ``W`` decoded batches per in-flight chunk.  Dead (padded) steps
    COMPUTE and discard — the price of one compiled program for every
    chunk — so keep ``W`` well under the epoch's batch count: a 4-batch
    epoch at ``W=8`` runs 8 steps' compute for 4 batches' progress.

    A factory that accepts an ``epoch`` keyword is called
    with the actual epoch number — pair it with
    :class:`~...data.datacache.ShuffledCacheReader` for per-epoch
    reshuffling that stays exact across checkpoint resume (a
    call-counting closure would desynchronize, since resume restarts at
    an arbitrary epoch).

    **Multi-host** (r4): pass a process-spanning mesh and call from EVERY
    process with a reader over THAT process's data shard (the reference's
    parallelism-P source posture — each TaskManager reads its own split).
    The global batch is the per-step concatenation over processes in
    process order, assembled inside the prefetch pipeline
    (``make_array_from_process_local_data``); the gradient reduction rides
    the mesh like the in-memory fits.  SPMD contract: every process must
    deliver the SAME number of equal-sized batches per epoch — mismatched
    readers deadlock in the collectives.  The ELL streaming path works
    across processes too: each host's decode workers build the layouts
    for its OWN devices' row blocks, and the assembled global stacks
    drive the device-local-grid + psum update.

    **Decoded replay cache** (r4): multi-epoch streams pay the host decode
    (pad + casts + ELL routing build) once, not once per epoch — the first
    full epoch tees each decoded batch into host RAM up to
    ``decoded_ram_budget`` bytes (default: 25% of available RAM, capped at
    32 GiB), and later epochs replay the cached prefix straight into the
    ``device_put`` stage, re-decoding only the tail that did not fit.
    This is the TPU-native analog of the reference's replay path — round 0
    writes while passing through, later rounds re-read instead of
    re-running the upstream (``iteration/operator/ReplayOperator.java:62-311``)
    — lifted from raw records to *decoded* batches because on this host
    the decode, not the read, dominates (r4 bench: ~4 s decode vs ~25 ms
    compute per epoch).  ``cache_decoded="auto"`` (default) engages only
    when the reader speaks the cursor protocol (``seek``/``batch_rows``/
    ``total_rows``), and every replay epoch re-reads the FIRST raw batch
    and compares its digest against the recorded epoch's — a reader that
    legitimately varies its stream per epoch (re-shuffled segment order,
    per-epoch sampling) drops the cache and decodes normally instead of
    silently training on frozen epoch-0 data.  The guard is one batch
    deep: a reader that keeps batch 0 identical while reordering the
    rest defeats it — such readers should either declare
    ``epoch_varying = True`` or be run with ``False``.  Epoch-varying
    readers that are also BLOCK-ADDRESSABLE (``block_order`` — the
    :class:`ShuffledCacheReader` protocol) get the best of both:
    entries are keyed by block id, every epoch serves cached blocks in
    that epoch's fresh permutation and decodes+offers the misses, so
    reshuffling and decode-once compose (one raw-digest contract check
    per epoch on an anchor block catches readers whose block content
    drifts).  Epoch-varying readers WITHOUT ``block_order`` are simply
    never cached under "auto".  ``True`` forces
    caching for any reader with no probe (the caller owns the
    determinism guarantee), ``False`` disables.  A tripped guard latches
    recording off for the rest of the fit (a varying reader would just
    be dropped again every epoch).  Recording retains the decode
    outputs zero-copy; disk-backed views (memmap slices that pass
    through the decode uncopied) are materialized into RAM at tee time
    so the budget counts real RAM and replay never faults to disk.  ``stream_info`` (a dict, filled in place) reports the planned
    impl, cached batch count/bytes, and per-epoch wall seconds so callers
    can attribute record vs replay epochs.

    **Mid-epoch checkpoints** (``checkpoint`` + ``checkpoint_every_steps``):
    on a 1TB pass one epoch is hours, so an epoch-boundary-only cut (the
    ``iterate`` default) loses the whole pass on a crash — the reference
    checkpoints *inside* a superstep for the same reason
    (``checkpoint/Checkpoints.java:43-211``,
    ``operator/HeadOperator.java:323-335``).  Every
    ``checkpoint_every_steps`` batches the (params, loss accumulator,
    reader cursor) triple is cut; ``resume=True`` restarts exactly at that
    batch: the reader is re-seeked (``seek``/``batch_rows`` protocol — the
    ``DataCacheReader`` surface — or by skipping batches) and the epoch
    continues as if never interrupted — deterministic-replay exactness is
    asserted in tests/test_checkpoint.py.  Checkpoint cuts are validated
    (CRC manifest + commit marker): on resume a torn/corrupt newest cut
    is quarantined and the fit falls back to the previous valid one
    (``CheckpointManager.latest()``); ``robustness.resilient_fit`` wraps
    this fit to make the whole crash->restore->replay loop automatic.

    **Chunk-boundary publishes** (``publish_cb``): called as
    ``publish_cb(global_step, params_fn)`` at every cut point — each
    ``checkpoint_every_steps`` crossing and each epoch boundary, right
    AFTER the checkpoint save when a manager is attached, so the
    published state is never ahead of the durable one.  ``params_fn``
    is a ZERO-ARG thunk returning the cut's host ``{"w", "b"}`` pytree
    (reducer state stripped): the device->host fetch (a dispatch-stream
    fence) is paid only when the callback actually publishes, not at
    cuts its cadence policy skips.  The thunk must be consumed INSIDE
    the callback — the underlying buffers are donated to the next
    dispatch.  The train-while-serve driver
    (``flink_ml_tpu/online/driver.py``) encodes the result as a param
    delta and swaps it into the live serving generation.
    With an overlapped ``grad_reduce`` the published cut intentionally
    excludes the fit-end drain (the in-loop trajectory — the same state
    a checkpoint of that cut holds, which is what keeps crash->resume->
    republish bit-exact).

    **Retry** (``retry_policy``, a ``robustness.retry.RetryPolicy``):
    each epoch's reader is wrapped in a ``RetryingIterator`` — the wrap
    sits at the RAW reader, below the fit's generator adapters, so a
    healed transient can never kill the stream — and classified-
    transient pull failures cost a backoff sleep on the prefetch reader
    thread instead of the epoch; fatal errors still propagate (and then
    checkpoint-based recovery is the healing layer, not retry).  The
    reader must not consume a batch on a failed pull, or be idempotent
    at the failed position (seekable readers are).

    **Elastic membership** (``membership=``, an
    :class:`~flink_ml_tpu.parallel.elastic.ElasticCoordinator`): the
    fleet becomes a runtime input.  Once per chunk boundary the fit
    calls ``membership.poll(global_step)`` — the seam injected
    ``preempt``/``join`` faults and lease expiry flow through — and
    when membership moved, it cuts a boundary checkpoint (carrying
    mesh-shape metadata) and raises
    :class:`~flink_ml_tpu.parallel.elastic.ResizeRequested`:
    ``resilient_fit(elastic=...)`` rebuilds the mesh at the new dcn
    extent and re-enters with ``resume=True``, where the restore below
    re-shards the whole carry (params replicate; participant-stacked
    reducer state — EF residual, pending overlap buffer, adaptive
    policy, rounding keys, and the wire-protocol tier's per-round
    fill-in/union accounting — routes through
    :func:`~flink_ml_tpu.parallel.grad_reduce.reshard_state`).  A
    resize at a chunk boundary is bit-exact vs a fixed fleet of the
    new size restoring the same cut (same reduce order); a worker
    death mid-chunk degrades to the crash path and resumes onto the
    surviving fleet.  Elastic fits are single-process and dense-layout
    (the mixed/sparse ELL paths keep their fixed meshes for now); with
    no ``grad_reduce`` the batch shards over EVERY mesh axis jointly
    (dcn x data — exact data parallelism over the whole fleet), with a
    hierarchical ``grad_reduce`` the existing dcn-composed layout
    already does.

    **Step probe** (``step_probe=True``, ISSUE 13): a
    :class:`~flink_ml_tpu.obs.StepProbe` rides the donated chunk carry
    recording the per-step ``loss`` — zero host sync inside the scan
    (the probe is frozen on dead padded steps like the state, so the
    series is W-independent) and ONE batched device->host transfer per
    chunk boundary.  The concatenated per-step series lands in
    ``stream_info["step_trace"]`` (``{"loss": np.ndarray}``).  Chunked
    single-process fits only — the per-batch multi-host loop already
    fetches per step, so a probe would add nothing there (raises).
    """
    from ...parallel.mesh import local_axis_multiple

    mesh = mesh or default_mesh()
    n_dev = int(mesh.shape["data"])
    procs = _mesh_process_count(mesh)
    # each PROCESS runs its own reader over its own data shard; the
    # global batch is the concatenation over processes (the reference's
    # parallelism-P source posture).  Local rows pad to the local device
    # multiple along the DATA axis (clear errors for bad layouts live in
    # local_axis_multiple); every process must deliver the SAME batch
    # count per epoch (the SPMD contract — mismatches deadlock in the
    # collectives).
    n_local_dev = local_axis_multiple(mesh, "data")
    mixed = dense_key is not None and indices_key is not None
    sparse = indices_key is not None and not mixed
    if sparse and values_key is None:
        raise ValueError("indices_key requires values_key (or dense_key "
                         "for the mixed layout)")
    if dense_key is not None and indices_key is None:
        raise ValueError("dense_key requires indices_key")
    # mixed batches on a TPU data mesh route through the ELL kernel: the
    # per-batch routing builds in the PREFETCH decode workers, so the
    # host sort overlaps the device step like any other decode work.
    # Caps are static (one compiled program for every batch).  On a
    # multi-device data axis the decode builds PER-DEVICE shard layouts
    # and the update is the device-local-grid + psum variant (same
    # stance as the fused sgd_fit_mixed, r4).
    gr = _active_grad_reduce(config)
    if gr is not None and (mixed or sparse):
        # categorical/sparse layouts already ship sparse gradients by
        # construction (scatter supports bounded by the batch's slots);
        # compressing them again would pay EF state for nothing
        raise ValueError(
            "grad_reduce compression applies to the dense streaming "
            "layout; the sparse/mixed paths' gradients are already "
            "sparse by construction — drop grad_reduce or use the dense "
            "features layout")
    gr_batch_axis = "data"
    n_dev_red = n_dev
    if gr is not None:
        gr_axes, n_dev_red, gr_batch_axis = _grad_reduce_layout(gr, mesh)
        if gr_axes != ("data",):
            if procs > 1:
                raise ValueError(
                    "hierarchical grad_reduce streaming is single-process "
                    "for now; multi-host hybrid meshes reduce over the "
                    "data axis per host")
            # the batch shards over every reduction axis jointly
            n_local_dev = n_dev_red
    if membership is not None:
        if procs > 1:
            raise ValueError(
                "elastic membership is single-process: the coordinator "
                "owns the device pool of THIS process (multi-host "
                "elasticity needs a control plane, not a mesh reshape)")
        if mixed or sparse:
            raise ValueError(
                "elastic membership supports the dense streaming layout; "
                "the mixed/sparse ELL paths bake per-device routing into "
                "their compiled programs and keep a fixed mesh for now")
        if gr is None and len(mesh.axis_names) > 1:
            # exact data parallelism over the whole fleet: the batch
            # shards over every mesh axis jointly (dcn x data), so a
            # resized dcn extent changes the shard count, not the math
            gr_batch_axis = tuple(str(a) for a in mesh.axis_names)
            n_local_dev = int(np.prod([int(mesh.shape[a])
                                       for a in mesh.axis_names]))
            n_dev_red = n_local_dev
        elif gr is not None and membership.dcn_axis in mesh.shape \
                and membership.dcn_axis not in gr_axes:
            # a flat compressed config on an elastic (dcn, data) mesh
            # would silently REPLICATE the batch over the resizable
            # axis — every worker doing identical work, no elasticity
            raise ValueError(
                f"elastic membership with grad_reduce must reduce over "
                f"the elastic axis {membership.dcn_axis!r}: set "
                f"dcn_axis={membership.dcn_axis!r} (hierarchical) on "
                "the GradReduceConfig, or drop grad_reduce for the "
                "exact joint-sharded path")
    stream_ell = (mixed and plan_mixed_impl(
        num_features, mesh, allow_sharded=True,
        allow_multiprocess=True) == "ell")
    stream_sharded = stream_ell and n_dev > 1
    stream_impl = ("ell-stream" if stream_ell
                   else ("xla-stream" if (mixed or sparse)
                         else ("dense-stream-reduced" if gr is not None
                               else "dense-stream")))
    if stream_sharded:
        update = _mixed_update_ell_sharded(
            loss_fn, config, mesh, num_features)
    elif stream_ell:
        update = _mixed_update_ell(loss_fn, config)
    elif gr is not None:
        update = _linear_update_reduced(loss_fn, config, mesh)
    else:
        update = (_mixed_update(loss_fn, config) if mixed
                  else (_sparse_update if sparse
                        else _linear_update)(loss_fn, config))
    # mixed and sparse both plan "xla-stream" but build different update
    # closures, so the layout flags join the key alongside the impl name
    layout_sig = (stream_impl, bool(mixed), bool(sparse), num_features)
    step_key = _step_program_key(("outofcore-batch",) + layout_sig,
                                 loss_fn, config, mesh)
    batch_step = _cached_step_program(
        step_key, lambda: jax.jit(update, donate_argnums=0))

    manager: Optional[CheckpointManager] = None
    if isinstance(checkpoint, CheckpointManager):
        manager = checkpoint
    elif isinstance(checkpoint, CheckpointConfig):
        manager = CheckpointManager(checkpoint)
    if membership is not None and manager is None:
        raise ValueError(
            "elastic membership requires a checkpoint manager: a resize "
            "IS a restore onto the new mesh, so without durable cuts "
            "there is nothing to resize from")

    x_p = P(gr_batch_axis, None)
    v_p = P(gr_batch_axis)
    if stream_sharded:
        # layout stacks carry a leading device dim sharded over 'data'
        g3, g2 = P("data", None, None), P("data", None)
        specs = (x_p, g3, g3, g3, g2, g2, g2, g3, v_p, v_p)
    elif stream_ell:
        r_p = P()  # layout grids: single device
        # (dense, src, pos, mask, ovf_idx, ovf_src, heavy_idx,
        #  heavy_cnt, y, w) — the raw cat tensor never ships: margins
        # and scatters both ride the layout (r4)
        specs = (x_p, r_p, r_p, r_p, r_p, r_p, r_p, r_p, v_p, v_p)
    else:
        specs = ((x_p, x_p, v_p, v_p) if (sparse or mixed)
                 else (x_p, v_p, v_p))
    # process-spanning mesh: each process's decoded batch is its LOCAL
    # slice; assemble the global (non-fully-addressable) batch arrays
    put_fn = _assemble_process_local if procs > 1 else None

    # Chunked dispatch: W batches stack into one device chunk and run as
    # one donated-carry lax.scan — one dispatch per W steps.  W=1 is the
    # exact-equivalence fallback: one batch per dispatch through the
    # SAME scan program, so any two W values are bit-exact on the same
    # stream (XLA compiles the per-batch jit and the scan body slightly
    # differently, so sameness of the PROGRAM, not just the math, is
    # what the guarantee rides on).  Chunk assembly is per-process-
    # local, so process-spanning meshes keep the classic per-batch loop.
    W = max(1, int(steps_per_dispatch))
    chunked = procs == 1
    if step_probe and not chunked:
        raise ValueError(
            "step_probe=True needs the chunked single-process path: the "
            "per-batch multi-host loop dispatches per step already, so "
            "a probe would only duplicate what the host loop sees")
    if chunked:
        from ...data.prefetch import chunk_consumer_plan, masked_chunk_scan

        sharding, chunk_depth = chunk_consumer_plan(mesh, specs, W,
                                                    prefetch_depth)
        chunk_key = _step_program_key(
            ("outofcore-chunk",) + layout_sig + (bool(step_probe),),
            loss_fn, config, mesh)
        if step_probe:
            # the probe joins the donated carry (argnums 0-2): each
            # chunk's returned probe is fetched ONCE at the boundary and
            # a reset() probe (fresh buffers) feeds the next dispatch,
            # so donation never aliases a buffer the host still reads
            chunk_step = _cached_step_program(chunk_key, lambda: jax.jit(
                lambda params, loss_sum, probe, chunk, mask:
                masked_chunk_scan(update, params, loss_sum, chunk, mask,
                                  probe=probe),
                donate_argnums=(0, 1, 2)))
        else:
            chunk_step = _cached_step_program(chunk_key, lambda: jax.jit(
                lambda params, loss_sum, chunk, mask: masked_chunk_scan(
                    update, params, loss_sum, chunk, mask),
                donate_argnums=(0, 1)))
    else:
        W = 1
        sharding = tuple(NamedSharding(mesh, p) for p in specs)

    from ...utils.padding import FixedRowBatcher

    batcher = FixedRowBatcher(n_local_dev)   # shared fixed-row protocol

    def to_host_batch(batch):
        if sparse or mixed:
            from .linear import check_sparse_indices

            idx = np.asarray(batch[indices_key], np.int32)
            check_sparse_indices(idx, num_features)
            if mixed:
                feats = (np.asarray(batch[dense_key], np.float32), idx)
            else:
                feats = (idx, np.asarray(batch[values_key], np.float32))
        else:
            feats = (np.asarray(batch[features_key], np.float32),)
        y = np.asarray(batch[label_key], np.float32)
        w = (np.asarray(batch[weight_key], np.float32) if weight_key
             else np.ones((y.shape[0],), np.float32))
        # final partial batch: pad, weight 0 (batcher pins thread-safely)
        padded = batcher.pad(feats + (y, w), have=y.shape[0])
        if stream_ell:
            from ...ops.ell_scatter import ell_layout

            dense_p, cat_p = padded[0], padded[1]
            n_valid = y.shape[0]
            if n_valid < batcher.rows:
                # padding rows' indices become sentinels the layout
                # drops (zero-pads would fabricate a heavy index 0);
                # their margins are dense-part-only and carry weight 0
                cat_p = cat_p.copy()
                cat_p[n_valid:] = num_features
            if stream_sharded:
                # per-device shard layouts: slot sources numbered inside
                # each device's contiguous local row block (P("data")
                # shards dim 0 the same way)
                local = batcher.rows // n_local_dev
                cap = (ell_ovf_cap if ell_ovf_cap is not None
                       else max(1024, local))
                lay = ell_layout(
                    cat_p.reshape(n_local_dev, local, cat_p.shape[-1]),
                    num_features, pad_ovf_cap=cap,
                    pad_heavy_cap=ell_heavy_cap, device=False)
                return (dense_p,
                        lay.src, lay.pos, lay.mask, lay.ovf_idx,
                        lay.ovf_src, lay.heavy_idx,
                        lay.heavy_cnt) + padded[2:]
            cap = (ell_ovf_cap if ell_ovf_cap is not None
                   else max(1024, batcher.rows))
            lay = ell_layout(cat_p[None], num_features,
                             pad_ovf_cap=cap,
                             pad_heavy_cap=ell_heavy_cap, device=False)
            return (dense_p,
                    lay.src[0], lay.pos[0], lay.mask[0], lay.ovf_idx[0],
                    lay.ovf_src[0], lay.heavy_idx[0],
                    lay.heavy_cnt[0]) + padded[2:]
        return padded

    if cache_decoded not in (True, False, "auto"):
        raise ValueError('cache_decoded must be True, False, or "auto", '
                         f"got {cache_decoded!r}")
    replay_cache: Optional[DecodedReplayCache] = None
    guard_tripped = False       # replay guard found an epoch-varying reader
    recorded_epochs = 0
    _rec_cache: list = [None]   # this epoch's recording target (closure slot)
    # block-keyed mode (epoch-varying + block-addressable readers, e.g.
    # ShuffledCacheReader): reshuffle every epoch AND amortize decode —
    # the cache keys entries by BLOCK id, serving hits and
    # decoding+offering misses, with no record/replay phase boundary.
    # `block_mode` is decided once, at the fit's first reader.
    block_mode: Optional[bool] = None
    block_cache: Optional[DecodedReplayCache] = None

    def route(item):
        """Prefetch transform over tagged source items: ``("dec", t)`` is
        an already-decoded replay batch, ``("rec", i, b)`` decodes + tees
        into the recording cache, ``("raw", b)`` just decodes."""
        tag = item[0]
        if tag == "dec":
            return item[1]
        if tag == "blk":
            bid, raw = item[1], item[2]
            cached = block_cache.get(bid)
            if cached is not None:
                if bid == block_cache.anchor_key:
                    # per-block-determinism contract check, one block
                    # per epoch: a reader whose block content drifts
                    # between epochs must fail loudly, not train on
                    # stale decode outputs
                    if batch_fingerprint(raw) != block_cache.fingerprint:
                        raise ValueError(
                            f"block-addressable reader violated the "
                            f"block_order contract: block {bid}'s "
                            f"content changed between epochs; pass "
                            f"cache_decoded=False for such readers")
                return cached
            host = to_host_batch(raw)
            if block_cache.anchor_key is None:
                # digest only until an anchor exists — hashing every
                # miss would tax the decode path the cache shrinks
                block_cache.set_anchor(bid, batch_fingerprint(raw))
            block_cache.offer(bid, host)
            return host
        if tag == "rec":
            if item[1] == 0:
                # digest the raw (pre-decode) batch: the replay guard
                # re-reads batch 0 on later epochs and compares
                _rec_cache[0].fingerprint = batch_fingerprint(item[2])
            elif item[1] & (item[1] - 1) == 0:
                # power-of-two indices: cheap (log n hashes) mid-stream
                # anchors for the seekable replay guard's second probe
                _rec_cache[0].probe_fingerprints[item[1]] = \
                    batch_fingerprint(item[2])
            host = to_host_batch(item[2])
            _rec_cache[0].offer(item[1], host)
            return host
        return to_host_batch(item[1])

    init_params = {"w": jnp.zeros((num_features,), jnp.float32),
                   "b": jnp.zeros((), jnp.float32)}
    if gr is not None:
        from ...parallel import grad_reduce as GR

        # reducer state (EF residual / rounding key) joins the params
        # carry: every mid-epoch checkpoint cut and restore below
        # round-trips it with the weights for free
        init_params[GR_STATE_KEY] = GR.init_state(
            gr, {"w": init_params["w"], "b": init_params["b"]}, n_dev_red)
    params = replicate(init_params, mesh)
    loss_log: list = []
    prev_loss = float("inf")
    start_epoch = 0
    skip_steps = 0          # batches already consumed in start_epoch
    resume_loss_sum = None  # their accumulated loss
    resume_n_batches = 0
    global_step = 0         # checkpoint tick: total batches over all epochs
    add = jax.jit(jnp.add)

    if manager is not None and resume:
        restored = manager.restore_latest()
        if restored is not None:
            # NOTE: restored[0] is meta["epoch"] — the manager's save-slot
            # key, which our "train_epoch" meta key deliberately does NOT
            # collide with: the slot key is the global step, so post-resume
            # saves keep ascending and GC never deletes newer checkpoints.
            global_step, saved, meta = restored
            saved_params = saved["params"]
            if gr is not None and isinstance(saved_params, dict):
                from ...iteration.checkpoint import require_fleet_compat
                from ...parallel import grad_reduce as GR

                n_saved = GR.state_participants(
                    saved_params.get(GR_STATE_KEY))
                if n_saved is not None and n_saved != n_dev_red:
                    # resize-as-restore: the cut came from a different
                    # fleet — legal only when it says which one
                    # (mesh-shape metadata); the participant-stacked
                    # reducer state re-shards onto the new extent
                    require_fleet_compat(
                        meta, saved_participants=n_saved,
                        current_participants=n_dev_red,
                        path=manager.config.directory)
                    ici = (int(mesh.shape[gr.axis])
                           if gr.dcn_axis is not None else 1)
                    saved_params = dict(saved_params)
                    saved_params[GR_STATE_KEY] = GR.reshard_state(
                        saved_params[GR_STATE_KEY], n_dev_red,
                        ici_size=ici)
            params = replicate(jax.tree_util.tree_map(jnp.asarray,
                                                      saved_params), mesh)
            start_epoch = int(meta["train_epoch"])
            skip_steps = int(meta["step_in_epoch"])
            resume_n_batches = int(meta["n_batches"])
            if resume_n_batches:
                resume_loss_sum = jnp.asarray(saved["loss_sum"], jnp.float32)
            prev_loss = float(meta["prev_loss"])
            loss_log = list(meta["loss_log"])
            if meta.get("converged"):
                # The checkpointed run had already hit the tol stop:
                # continuing would train past the converged answer.
                host = jax.device_get(saved["params"])
                host_gr = host.pop(GR_STATE_KEY, None)
                if gr is not None and host_gr is not None:
                    from ...parallel import grad_reduce as GR

                    if GR.wants_overlap(gr):
                        # the original run drained at ITS return; a
                        # converged resume must reproduce that return
                        host = _apply_drain(host, host_gr, config)
                return LinearState(np.asarray(host["w"], np.float64),
                                   float(host["b"]),
                                   planned_impl=stream_impl), loss_log

    def _publish_params(params):
        """Host copy of the cut's params for ``publish_cb`` — reducer
        state (EF residual / pending) is trainer-internal, never
        served."""
        host = jax.device_get(_fetch_replicated(params))
        if isinstance(host, dict):
            host = {k: v for k, v in host.items() if k != GR_STATE_KEY}
        return host

    def _save(epoch, step_in_epoch, loss_sum, n_batches, converged=False):
        from ...iteration.checkpoint import mesh_shape_meta

        manager.save(global_step, {
            "params": params,
            "loss_sum": (loss_sum if loss_sum is not None
                         else jnp.zeros((), jnp.float32)),
        }, {
            "train_epoch": epoch, "step_in_epoch": step_in_epoch,
            "n_batches": n_batches, "prev_loss": prev_loss,
            "loss_log": loss_log, "converged": converged,
            # fleet identity: what a restore onto a DIFFERENT mesh
            # (elastic resize) needs to know it is re-sharding from
            **mesh_shape_meta(mesh, participant_count=n_dev_red),
        })

    epoch_secs: list = []
    dispatch_log: list = []   # jitted-step dispatches per epoch
    probe = None
    step_trace: Dict[str, list] = {}
    if step_probe:
        from ...obs.probe import StepProbe

        probe = StepProbe.create(("loss",), W)
    for epoch in range(start_epoch, config.max_epochs):
        t_epoch = time.perf_counter()
        rec_cache = None
        reader = None
        if block_mode is None and cache_decoded in (True, "auto") \
                and config.max_epochs > 1:
            reader = _reader_for_epoch(make_reader, epoch, retry_policy)
            block_mode = (getattr(reader, "epoch_varying", False)
                          and hasattr(reader, "block_order")
                          and hasattr(reader, "batch_rows"))
        if block_mode and cache_decoded in (True, "auto"):
            if reader is None:
                reader = _reader_for_epoch(make_reader, epoch, retry_policy)
            if block_cache is None:
                block_cache = DecodedReplayCache(
                    decoded_ram_budget if decoded_ram_budget is not None
                    else default_ram_budget())
            order = list(reader.block_order)
            skip = skip_steps if epoch == start_epoch else 0
            # resume mid-epoch: the reader's own (seed, epoch)
            # permutation is reconstructed by the factory; trim the
            # visit order to match the skipped position
            trimmed = order[skip:] if skip else order
            if batcher.rows is None:
                batcher.pin(int(reader.batch_rows))
            if hasattr(reader, "seek") and hasattr(reader, "read_batch"):
                # seekable: cache hits consult NO disk — only misses
                # and the once-per-epoch anchor contract check read raw
                def block_source(reader=reader, trimmed=trimmed,
                                 skip=skip):
                    anchor_checked = False
                    for i, bid in enumerate(trimmed):
                        cached = block_cache.get(bid)
                        if cached is not None:
                            if (bid == block_cache.anchor_key
                                    and not anchor_checked):
                                anchor_checked = True
                            else:
                                yield ("dec", cached)
                                continue
                        reader.seek((skip + i) * reader.batch_rows)
                        yield ("blk", bid, reader.read_batch())

                source = block_source()
            else:
                # seekless block reader: sequential read + discard for
                # hits (the protocol does not require seek).  The count
                # check makes a short epoch loud (ADVICE r4): zip would
                # silently truncate if the reader yields fewer batches
                # than block_order promises.
                def counted_blocks(reader=reader, trimmed=trimmed,
                                   skip=skip):
                    n = 0
                    for bid, b in zip(trimmed, _seek_or_skip(reader, skip)):
                        n += 1
                        yield ("blk", bid, b)
                    if n < len(trimmed):
                        raise ValueError(
                            f"block-addressable reader yielded {n} "
                            f"batches but block_order promises "
                            f"{len(trimmed)}; the epoch would silently "
                            "train on fewer blocks")

                source = counted_blocks()
        else:
            replay_ok = replay_cache is not None and replay_cache.ready
            if replay_ok and cache_decoded == "auto":
                # Replay guard: "auto" engaged on the cursor protocol, but the
                # protocol does not promise epoch-determinism (a reader may
                # legitimately re-shuffle segment order per epoch).  Re-read
                # the first raw batch and compare its digest against the
                # recorded epoch's; on mismatch drop the cache and decode
                # normally.  (``cache_decoded=True`` skips the probe — the
                # caller owns the determinism guarantee.)
                reader = _reader_for_epoch(make_reader, epoch, retry_policy)
                probe_it = iter(reader)
                probe_first = next(probe_it, None)
                probe_mismatch = False
                # re-position the probed reader at batch 0 either way
                if hasattr(reader, "seek") and hasattr(reader, "batch_rows"):
                    # seekable: also probe a deterministic MID-STREAM
                    # batch (ADVICE r4) — the largest power-of-two index
                    # the recorder digested.  A one-batch guard misses a
                    # reader that keeps batch 0 stable but shuffles the
                    # rest; seek makes the second probe nearly free.
                    mid_candidates = [
                        i for i in replay_cache.probe_fingerprints
                        if replay_cache.n_batches is None
                        or i < replay_cache.n_batches]
                    if mid_candidates:
                        mid = max(mid_candidates)
                        reader.seek(mid * int(reader.batch_rows))
                        probe_mid = next(iter(reader), None)
                        probe_mismatch = (
                            probe_mid is None
                            or batch_fingerprint(probe_mid)
                            != replay_cache.probe_fingerprints[mid])
                    reader.seek(0)
                else:
                    # generator-shaped reader: re-chain the consumed batch
                    reader = itertools.chain(
                        [] if probe_first is None else [probe_first], probe_it)
                if (probe_mismatch or probe_first is None
                        or replay_cache.fingerprint is None
                        or batch_fingerprint(probe_first)
                        != replay_cache.fingerprint):
                    # one-way latch: this reader varies per epoch, so a
                    # re-recorded cache would just be dropped again next
                    # epoch — stop paying the tee (RAM + hash) for the
                    # rest of the fit
                    replay_cache = None
                    replay_ok = False
                    guard_tripped = True
            if replay_ok and replay_cache.prefix_batches == replay_cache.n_batches:
                # the decoded cache holds the WHOLE epoch: the reader's disk
                # is not consulted (beyond the guard's one-batch probe)
                source = (("dec", t) for t in replay_cache.replay())
            else:
                if reader is None:
                    reader = _reader_for_epoch(make_reader, epoch, retry_policy)
                if epoch == start_epoch and skip_steps:
                    # fast-forward to the checkpointed cursor
                    reader = _seek_or_skip(reader, skip_steps)
                if batcher.rows is None and hasattr(reader, "batch_rows"):
                    batcher.pin(int(reader.batch_rows))
                if replay_ok:
                    # partial prefix: replay what fit, re-decode the tail
                    tail = _seek_or_skip(reader, replay_cache.prefix_batches)
                    source = itertools.chain(
                        (("dec", t) for t in replay_cache.replay()),
                        (("raw", b) for b in tail))
                else:
                    # readers that DECLARE per-epoch variance (e.g.
                    # ShuffledCacheReader.epoch_varying) are never recorded
                    # under "auto": a one-batch digest guard cannot prove a
                    # permutation identical (same first block != same
                    # order), so recording would be either wasted (guard
                    # trips) or silently wrong (1-in-n-blocks collision
                    # replays a frozen epoch and breaks resume exactness)
                    record = (config.max_epochs - epoch > 1
                              and not guard_tripped
                              and not (epoch == start_epoch and skip_steps)
                              and (cache_decoded is True
                                   or (cache_decoded == "auto"
                                       and _has_cursor(reader)
                                       and not getattr(reader, "epoch_varying",
                                                       False))))
                    if record:
                        rec_cache = DecodedReplayCache(
                            decoded_ram_budget if decoded_ram_budget is not None
                            else default_ram_budget())
                        _rec_cache[0] = rec_cache
                        source = (("rec", i, b) for i, b in enumerate(reader))
                    else:
                        source = (("raw", b) for b in reader)

        # Running on-device sum: memory stays flat over millions of batches
        # (a list of live per-batch scalars would grow O(n_batches)).
        loss_sum = resume_loss_sum
        n_batches = resume_n_batches
        step_in_epoch = skip_steps
        n_dispatches = 0
        resume_loss_sum, resume_n_batches, skip_steps = None, 0, 0
        # The pipeline generator is closed EXPLICITLY on every exit
        # (normal or exception): its teardown stops + joins the reader
        # threads, so a supervised restart (resilient_fit) never races a
        # zombie reader for the shared live source.  Relying on GC would
        # not do — the exception traceback pins the frames in a cycle
        # and the close happens arbitrarily late.
        if chunked:
            pipeline = prefetch_to_device(
                source, depth=chunk_depth,
                transform=route, sharding=sharding,
                workers=prefetch_workers,
                put_workers=prefetch_put_workers, stats=prefetch_stats,
                chunks=W)
        else:
            pipeline = prefetch_to_device(
                source, depth=prefetch_depth,
                transform=route, sharding=sharding,
                workers=prefetch_workers,
                put_workers=prefetch_put_workers, stats=prefetch_stats,
                put_fn=put_fn)
        try:
            if chunked:
                for chunk, mask, n_valid in pipeline:
                    # (retry_policy wraps the READER, not this pipeline: the
                    # source here is a generator chain, which dies on a
                    # propagated exception — a pipeline-level retry of it
                    # would read StopIteration and silently truncate)
                    if loss_sum is None:
                        loss_sum = jnp.zeros((), jnp.float32)
                    with tracer.span("train_chunk", cat="train",
                                     step=global_step + n_valid,
                                     epoch=epoch):
                        # span = dispatch wall (async): completion is
                        # fenced by the probe fetch below / the epoch-end
                        # loss fetch, never inside the loop
                        if probe is not None:
                            params, loss_sum, probe_out = chunk_step(
                                params, loss_sum, probe, chunk, mask)
                        else:
                            params, loss_sum = chunk_step(
                                params, loss_sum, chunk, mask)
                    if probe is not None:
                        # ONE batched transfer at the chunk boundary —
                        # the only fence the probe ever costs
                        for k, v in probe_out.fetch().items():
                            step_trace.setdefault(k, []).append(v)
                        probe = probe_out.reset()
                    n_batches += n_valid
                    step_in_epoch += n_valid
                    global_step += n_valid
                    n_dispatches += 1
                    # mid-epoch cuts land at chunk boundaries: save when the
                    # chunk crossed a checkpoint_every_steps multiple (and
                    # publish AFTER the save — never serve ahead of durable)
                    cut_done = False
                    if (checkpoint_every_steps > 0
                            and (manager is not None or publish_cb is not None)
                            and step_in_epoch // checkpoint_every_steps
                            > (step_in_epoch - n_valid)
                            // checkpoint_every_steps):
                        if manager is not None:
                            _save(epoch, step_in_epoch, loss_sum, n_batches)
                            cut_done = True
                        if publish_cb is not None:
                            publish_cb(global_step,
                                       lambda p=params: _publish_params(p))
                    # elastic membership: one poll per chunk boundary —
                    # injected preempt/join faults and lease expiry land
                    # here; a changed fleet cuts a boundary checkpoint
                    # and hands the resize to the supervisor (restore
                    # onto the new mesh)
                    if membership is not None \
                            and membership.poll(global_step):
                        if manager is not None and not cut_done:
                            _save(epoch, step_in_epoch, loss_sum,
                                  n_batches)
                        from ...parallel.elastic import ResizeRequested

                        raise ResizeRequested(
                            step=global_step,
                            fleet_size=membership.fleet_size,
                            membership_epoch=membership.membership_epoch)
            else:
                for dev_batch in pipeline:
                    params, value = batch_step(params, *dev_batch)
                    loss_sum = value if loss_sum is None else add(loss_sum, value)
                    n_batches += 1
                    step_in_epoch += 1
                    global_step += 1
                    n_dispatches += 1
                    if (checkpoint_every_steps > 0
                            and (manager is not None or publish_cb is not None)
                            and step_in_epoch % checkpoint_every_steps == 0):
                        if manager is not None:
                            _save(epoch, step_in_epoch, loss_sum, n_batches)
                        if publish_cb is not None:
                            publish_cb(global_step,
                                       lambda p=params: _publish_params(p))
        finally:
            pipeline.close()
        if loss_sum is None:
            raise ValueError("make_reader() returned an empty epoch")
        dispatch_log.append(n_dispatches)
        if rec_cache is not None:
            rec_cache.finish(step_in_epoch)
            replay_cache = rec_cache
            recorded_epochs += 1
            _rec_cache[0] = None
        t_now = time.perf_counter()
        epoch_secs.append(t_now - t_epoch)
        if tracer.enabled:
            tracer.add("train_epoch", t_epoch, t_now, cat="train",
                       epoch=epoch, step=global_step)
        epoch_loss = float(
            np.asarray(_fetch_replicated(loss_sum))) / n_batches
        loss_log.append(epoch_loss)
        stop = config.tol > 0 and abs(prev_loss - epoch_loss) <= config.tol
        if not stop:
            prev_loss = epoch_loss
        if manager is not None:
            _save(epoch + 1, 0, None, 0, converged=stop)  # epoch-boundary cut
        if publish_cb is not None:
            publish_cb(global_step, lambda p=params: _publish_params(p))
        if stop:
            break
    params = _fetch_replicated(params)
    final_gr_state = params.pop(GR_STATE_KEY, None)
    if gr is not None and final_gr_state is not None:
        from ...parallel import grad_reduce as GR

        if GR.wants_overlap(gr):
            params = _apply_drain(params, final_gr_state, config)
    if stream_info is not None:
        stream_info["impl"] = stream_impl
        stream_info["steps_per_dispatch"] = W
        stream_info["dispatches_per_epoch"] = dispatch_log
        if step_probe:
            stream_info["step_trace"] = {
                k: (np.concatenate(v) if v else np.zeros((0,), np.float32))
                for k, v in step_trace.items()}
        if block_cache is not None:
            stream_info["decoded_cache_mode"] = "block"
            stream_info["decoded_cache_batches"] = len(block_cache)
            stream_info["decoded_cache_bytes"] = block_cache.cached_bytes
        else:
            cached = (replay_cache.prefix_batches
                      if replay_cache is not None and replay_cache.ready
                      else 0)
            stream_info["decoded_cache_batches"] = cached
            stream_info["decoded_cache_recorded_epochs"] = recorded_epochs
            if guard_tripped:
                stream_info["decoded_cache_guard_tripped"] = True
            if cached:
                stream_info["decoded_cache_bytes"] = \
                    replay_cache.cached_bytes
                stream_info["decoded_cache_total_batches"] = \
                    replay_cache.n_batches
        stream_info["epoch_seconds"] = [round(s, 4) for s in epoch_secs]
    return LinearState(np.asarray(params["w"], np.float64),
                       float(params["b"]),
                       planned_impl=stream_impl), loss_log
