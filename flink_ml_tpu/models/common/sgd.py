"""Fused mini-batch SGD trainer over a device mesh.

The TPU-native replacement for the reference's iteration-based model update
path: where flink-ml ships gradients over the network to a reduce operator
and feeds new weights back through the FeedbackChannel, here one epoch is an
inner ``lax.scan`` over mini-batches — the gradient psum over the mesh's data
axis is inserted by XLA and rides ICI — and the whole multi-epoch loop is a
single compiled program via ``iterate`` (fused mode).

Data layout: inputs are host-shuffled once (seeded), padded, and reshaped to
``(steps_per_epoch, batch, ...)`` with the batch dim sharded over the data
axis; weights/optimizer state are replicated.  Shapes are static — no
recompiles across epochs or batch positions.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...iteration import IterationBodyResult, IterationConfig, iterate
from ...parallel.mesh import default_mesh, replicate

__all__ = ["SGDConfig", "sgd_fit", "LinearState", "plan_epoch_layout",
           "prepare_epoch_tensor"]

LossFn = Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]


@dataclass
class SGDConfig:
    learning_rate: float = 0.1
    reg: float = 0.0            # l2 strength (on coefficients, not intercept)
    elastic_net: float = 0.0    # l1 mixing (0 = pure l2)
    global_batch_size: int = 32
    max_epochs: int = 20
    tol: float = 1e-6           # epoch-loss-change termination; <=0 disables
    seed: int = 0
    fit_intercept: bool = True


@dataclass
class LinearState:
    coefficients: np.ndarray    # (d,)
    intercept: float


def plan_epoch_layout(n: int, global_batch_size: int, n_dev: int,
                      seed: int) -> Tuple[int, int, np.ndarray]:
    """Size the (steps, batch) epoch grid — batch divisible by the mesh's
    data axis — and the seeded row shuffle.  THE canonical sizing used by
    every mini-batch trainer (sgd_fit, WideDeep)."""
    batch = max(global_batch_size, n_dev)
    batch += (-batch) % n_dev
    steps = max(1, -(-n // batch))
    perm = np.random.default_rng(seed).permutation(n)
    return steps, batch, perm


def prepare_epoch_tensor(arr: np.ndarray, perm: np.ndarray, steps: int,
                         batch: int, pad_value: float = 0.0) -> np.ndarray:
    """Shuffle rows by ``perm``, pad to steps*batch, reshape to
    (steps, batch, ...)."""
    arr = arr[perm]
    total = steps * batch
    if arr.shape[0] < total:
        pad_shape = (total - arr.shape[0],) + arr.shape[1:]
        arr = np.concatenate([arr, np.full(pad_shape, pad_value, arr.dtype)])
    return arr.reshape((steps, batch) + arr.shape[1:])


def sgd_fit(loss_fn: LossFn, features: np.ndarray, labels: np.ndarray,
            weights: Optional[np.ndarray], config: SGDConfig,
            mesh=None) -> Tuple[LinearState, list]:
    """Train (w, b) minimizing ``loss_fn(margin, labels, weights) +
    reg * penalty(w)``.  Returns the fitted state and the per-epoch loss log.

    The elastic-net penalty matches the classic formulation:
    ``reg * ((1-alpha)/2 ||w||^2 + alpha ||w||_1)`` with the l1 part applied
    via proximal soft-thresholding after each step.
    """
    mesh = mesh or default_mesh()
    n_dev = int(mesh.shape["data"])
    n, d = features.shape
    steps, batch, perm = plan_epoch_layout(
        n, config.global_batch_size, n_dev, config.seed)

    X = prepare_epoch_tensor(features.astype(np.float32), perm, steps, batch)
    y = prepare_epoch_tensor(labels.astype(np.float32), perm, steps, batch)
    w_host = (weights.astype(np.float32) if weights is not None
              else np.ones((n,), np.float32))
    w = prepare_epoch_tensor(w_host, perm, steps, batch, pad_value=0.0)

    batch_sharded = NamedSharding(mesh, P(None, "data"))
    x_sharded = NamedSharding(mesh, P(None, "data", None))
    X = jax.device_put(X, x_sharded)
    y = jax.device_put(y, batch_sharded)
    w = jax.device_put(w, batch_sharded)

    lr = config.learning_rate
    reg, alpha = config.reg, config.elastic_net
    l2 = reg * (1.0 - alpha)
    l1 = reg * alpha

    def objective(params, xb, yb, wb):
        margin = xb @ params["w"] + params["b"]
        return loss_fn(margin, yb, wb) + 0.5 * l2 * jnp.sum(
            jnp.square(params["w"]))

    grad_fn = jax.value_and_grad(objective)

    def epoch_body(state, epoch, data):
        Xd, yd, wd = data
        params, prev_loss, loss_log = state

        def batch_step(params, batch_idx):
            value, grads = grad_fn(params,
                                   Xd[batch_idx], yd[batch_idx], wd[batch_idx])
            new_w = params["w"] - lr * grads["w"]
            if l1 > 0:
                # proximal soft-threshold for the l1 part
                new_w = jnp.sign(new_w) * jnp.maximum(
                    jnp.abs(new_w) - lr * l1, 0.0)
            new_b = params["b"] - (lr * grads["b"]
                                   if config.fit_intercept else 0.0)
            return {"w": new_w, "b": new_b}, value

        params, losses = jax.lax.scan(
            batch_step, params, jnp.arange(steps, dtype=jnp.int32))
        epoch_loss = jnp.mean(losses)
        # The full loss history rides in the carried state (a fixed-size
        # buffer indexed by epoch) so the fused while_loop path — which only
        # keeps the LAST epoch's outputs — still yields the complete log.
        loss_log = loss_log.at[epoch].set(epoch_loss)
        termination = (jnp.abs(prev_loss - epoch_loss) > config.tol
                       if config.tol > 0 else None)
        return IterationBodyResult(
            feedback=(params, epoch_loss, loss_log), termination=termination)

    init_params = replicate(
        {"w": jnp.zeros((d,), jnp.float32), "b": jnp.zeros((), jnp.float32)},
        mesh)
    init_state = (init_params, jnp.asarray(jnp.inf, jnp.float32),
                  jnp.full((config.max_epochs,), jnp.nan, jnp.float32))

    result = iterate(
        epoch_body, init_state, (X, y, w),
        max_epochs=config.max_epochs,
        config=IterationConfig(mode="fused"),
    )
    params, _final_loss, loss_buf = result.state
    params = jax.device_get(params)
    loss_log = list(np.asarray(jax.device_get(loss_buf))[:result.num_epochs])
    return LinearState(np.asarray(params["w"], np.float64),
                       float(params["b"])), loss_log
