"""Shared Estimator/Model bases for the linear family (LogisticRegression,
LinearRegression, LinearSVC) — one SGD skeleton, per-model loss + link."""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator, Model
from ...data.table import Table
from ...linalg import SparseVector, stack_sparse_vectors, stack_vectors
from ...params.shared import (
    HasElasticNet,
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasNumFeatures,
    HasPredictionCol,
    HasRawPredictionCol,
    HasRegParam,
    HasSeed,
    HasTol,
    HasWeightCol,
)
from ...utils import persist
from ...utils.padding import pad_rows_to_bucket
from .losses import LOSSES
from .sgd import (
    LinearState,
    SGDConfig,
    sgd_fit,
    sgd_fit_mixed,
    sgd_fit_outofcore,
    sgd_fit_sparse,
)

__all__ = ["LinearEstimatorParams", "LinearModelBase", "LinearEstimatorBase",
           "resolve_features", "check_sparse_indices"]


def check_sparse_indices(idx: np.ndarray, num_features: int) -> None:
    """Range-check hashed indices against the weight size.  A jitted gather
    silently CLAMPS out-of-range indices (piling every stray feature onto
    the last weight), so a hasher/model numFeatures mismatch would produce
    garbage scores with no diagnostic — the same trap ``_validate_cat_ids``
    guards in WideDeep."""
    if idx.size and (int(idx.min()) < 0 or int(idx.max()) >= num_features):
        raise ValueError(
            f"hashed index out of range for numFeatures={num_features} "
            f"(got index {int(idx.max()) if int(idx.min()) >= 0 else int(idx.min())}); "
            "the hasher and the model disagree on the hash-space size")


def _stable_margins(X, w, b):
    """``X @ w + b`` with a context-stable contraction for vector ``w``.

    An ``(n, d) @ (d,)`` matvec (and a k=1 GEMM) lowers to a LOOP FUSION
    whose accumulation order depends on whether the lhs is a program
    parameter or a fused producer — so the same values score to
    different last-ulp margins standalone vs inside a fused chain
    segment (``api/chain.py``).  A k>=2 GEMM materializes its operands
    and accumulates identically in every context (verified across
    d 8..512 / n 8..1024), so the binary case pads ``w`` with one zero
    column and takes column 0: bit-identical margins whether the
    features are a parameter (stagewise/serving) or produced mid-segment
    (fused chain).  Matrix ``w`` (multiclass) is already a k>=2 GEMM."""
    if w.ndim == 1:
        w2 = jnp.stack([w, jnp.zeros_like(w)], axis=-1)
        return (X @ w2)[:, 0] + b
    return X @ w + b


@jax.jit
def _jit_margins(X, w, b):
    """Module-level jit: repeated transform() calls are cache hits."""
    return _stable_margins(X, w, b)


def _linear_chain_kernel(static, params, cols):
    """Chain-terminal margins — expression-identical to ``_jit_margins``
    (the shared predict entry point), staged under a private column the
    host ``post`` maps to prediction/raw columns."""
    import jax.numpy as jnp

    from ...api.chain import as_matrix

    (fcol, mcol) = static
    X = as_matrix(cols[fcol])
    return {mcol: _stable_margins(X.astype(jnp.float32),
                                  params["w"], params["b"])}


@jax.jit
def _jit_sparse_margins(idx, vals, w, b):
    """Sparse score: one gather + row reduce (no dense matrix ever built)."""
    return jnp.sum(vals * w[idx], axis=-1) + b


@jax.jit
def _jit_mixed_margins(dense, cat, w, b):
    """Mixed score: matvec over the leading dense slots + gather over the
    hashed categorical slots (implicit value 1.0)."""
    return dense @ w[: dense.shape[-1]] + jnp.sum(w[cat], axis=-1) + b


def resolve_features(table: Table, col: str):
    """Resolve a features column into the device-facing form.

    Sparse/hashed features appear in a Table either as a column of
    :class:`SparseVector` objects, or as the hashed PAIR convention two
    columns ``{col}_indices (n, nnz) int`` + ``{col}_values (n, nnz)
    float`` (what ``FeatureHasher.set_sparse_output(True)`` emits), or as
    the MIXED Criteo-native convention ``{col}_dense (n, nd) float`` +
    ``{col}_indices (n, nc) int`` (dense block occupying weight slots
    ``[0, nd)`` plus hashed categorical with implicit value 1.0 — the
    fastest LR layout on TPU, see ``sgd.sgd_fit_mixed``).

    Returns ``("dense", X)``, ``("sparse", (indices, values, dim))``, or
    ``("mixed", (dense, cat))``; ``dim`` is the feature dimension if
    derivable from the data (SparseVector carries it) else 0 (pair/mixed
    columns: the caller must know numFeatures)."""
    if col not in table:
        idx_col, val_col = f"{col}_indices", f"{col}_values"
        dense_col = f"{col}_dense"
        if dense_col in table and idx_col in table:
            if val_col in table:
                raise ValueError(
                    f"ambiguous feature schema: {dense_col!r}, {idx_col!r} "
                    f"AND {val_col!r} all present — the mixed layout "
                    "carries implicit value 1.0, so it cannot coexist with "
                    "a values column; drop one of them")
            return "mixed", (np.asarray(table[dense_col], np.float32),
                             np.asarray(table[idx_col], np.int32))
        if idx_col in table and val_col in table:
            return "sparse", (np.asarray(table[idx_col], np.int32),
                              np.asarray(table[val_col], np.float32), 0)
        raise KeyError(
            f"No column {col!r} (nor {idx_col!r}/{val_col!r}, nor "
            f"{dense_col!r}/{idx_col!r}); available: "
            f"{table.column_names}")
    column = table[col]
    if column.dtype == object and len(column) \
            and isinstance(column[0], SparseVector):
        return "sparse", stack_sparse_vectors(column)
    return "dense", stack_vectors(column)


class LinearModelParams(HasFeaturesCol, HasPredictionCol, HasRawPredictionCol):
    pass


class LinearEstimatorParams(LinearModelParams, HasLabelCol, HasWeightCol,
                            HasMaxIter, HasLearningRate, HasRegParam,
                            HasElasticNet, HasGlobalBatchSize, HasTol,
                            HasSeed, HasNumFeatures):
    pass


class LinearModelBase(LinearModelParams, Model):
    """Holds (coefficients, intercept); subclasses map margins to the
    prediction / raw-prediction columns."""

    loss_name: str = "squared"

    def __init__(self):
        super().__init__()
        self._state: Optional[LinearState] = None

    # -- model data ---------------------------------------------------------
    def set_model_data(self, *inputs) -> "LinearModelBase":
        (table,) = inputs
        self._state = LinearState(
            coefficients=np.asarray(table["coefficients"][0], np.float64),
            intercept=float(table["intercept"][0]))
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({
            "coefficients": self._state.coefficients[None, :],
            "intercept": np.array([self._state.intercept]),
        })]

    def _require_model(self):
        if self._state is None:
            raise RuntimeError(
                f"{type(self).__name__} has no model data; fit the estimator "
                "or call set_model_data first")

    @property
    def loss_log(self) -> list:
        """Per-epoch training loss recorded by fit (empty when the model
        was built from set_model_data/load rather than trained)."""
        return list(getattr(self, "_loss_log", []) or [])

    @property
    def planned_impl(self) -> Optional[str]:
        """Which update implementation the fit planned ("ell" / "xla" /
        "sharded" / "dense" / "*-stream") — what bench.py tags as
        ``lr_impl``, surfaced on the product path (VERDICT r3 task 3).
        None when the model was loaded rather than trained."""
        return self._state.planned_impl if self._state is not None else None

    # -- inference ----------------------------------------------------------
    def _margins(self, table: Table) -> np.ndarray:
        """Margins at BUCKETED batch shapes: rows zero-pad to the shared
        power-of-two bucket (``utils/padding.py``) before the jitted score,
        so mixed batch sizes — offline transforms and the online serving
        micro-batches alike — hit a bounded set of compiled programs
        instead of retracing per shape.  Pad rows are sliced off; margins
        are row-independent, so real rows are bit-identical."""
        self._require_model()
        kind, feats = resolve_features(table, self.get_features_col())
        w = jnp.asarray(self._state.coefficients, jnp.float32)
        b = jnp.asarray(self._state.intercept, jnp.float32)
        if kind == "sparse":
            idx, vals, _ = feats
            check_sparse_indices(idx, self._state.coefficients.shape[0])
            (idx, vals), n = pad_rows_to_bucket((idx, vals))
            return np.asarray(_jit_sparse_margins(idx, vals, w, b),
                              np.float64)[:n]
        if kind == "mixed":
            dense, cat = feats
            check_sparse_indices(cat, self._state.coefficients.shape[0])
            (dense, cat), n = pad_rows_to_bucket((dense, cat))
            return np.asarray(_jit_mixed_margins(dense, cat, w, b),
                              np.float64)[:n]
        (X,), n = pad_rows_to_bucket((feats.astype(np.float32),))
        return np.asarray(_jit_margins(X, w, b), np.float64)[:n]

    def _decision(self, margins: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _raw(self, margins: np.ndarray) -> np.ndarray:
        return margins

    def transform_kernel(self, schema):
        """Chain TERMINAL for dense features: the in-segment kernel is
        expression-identical to the shared ``_margins`` predict entry
        point (one f32 matmul at the same padded bucket), and the host
        ``post`` applies the same f64 ``_decision``/``_raw`` mapping —
        fused output is bit-exact with stagewise ``transform``.  Sparse
        pair/mixed feature conventions stay on their own entry points
        (the chain substrate is dense column dicts)."""
        from ...api.chain import StageKernel, numeric_entry

        self._require_model()
        fcol = self.get_features_col()
        if numeric_entry(schema, fcol) is None:
            return None
        pred_col = self.get_prediction_col()
        raw_col = self.get_raw_prediction_col()
        margin_col = f"__chain_margins__{pred_col}"

        def post(host):
            m = host[margin_col].astype(np.float64)
            out = {pred_col: self._decision(m)}
            if raw_col:
                out[raw_col] = self._raw(m)
            return out

        return StageKernel(
            fn=_linear_chain_kernel, static=(fcol, margin_col),
            params={"w": np.asarray(self._state.coefficients, np.float32),
                    "b": np.float32(self._state.intercept)},
            consumes=(fcol,), produces=(margin_col,), post=post)

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        # dense features score through the kernel registry's shared
        # dispatch surface — the SAME (fn, static) plan the chain
        # terminal and the serving executor run, so offline transform,
        # fused pipelines, and serving share one compiled executable per
        # (schema, bucket).  Sparse/mixed layouts (and f32-unsafe int
        # batches) keep their own entry points below.
        from ...api.chain import apply_kernel_or_none

        kernel = self.transform_kernel(table.schema())
        cols = apply_kernel_or_none(kernel, table)
        if cols is not None:
            out = table
            for name in (n for n in cols if n not in kernel.produces):
                out = out.with_column(name, cols[name])
            return [out]
        m = self._margins(table)
        out = table.with_column(self.get_prediction_col(), self._decision(m))
        raw_col = self.get_raw_prediction_col()
        if raw_col:
            out = out.with_column(raw_col, self._raw(m))
        return [out]

    # -- persistence --------------------------------------------------------
    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {
            "coefficients": self._state.coefficients,
            "intercept": np.array([self._state.intercept]),
        })

    @classmethod
    def load(cls, path: str):
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._state = LinearState(
            coefficients=data["coefficients"].astype(np.float64),
            intercept=float(data["intercept"][0]))
        return model


class LinearEstimatorBase(LinearEstimatorParams, Estimator):
    """fit(): extract (X, y, weight), run the fused SGD loop, wrap the fitted
    state in the concrete model class."""

    loss_name: str = "squared"
    model_cls = None  # set by subclasses

    def _labels(self, table: Table) -> np.ndarray:
        return np.asarray(table[self.get_label_col()], np.float64)

    def fit(self, *inputs):
        (table,) = inputs
        kind, feats = resolve_features(table, self.get_features_col())
        y = self._labels(table)
        weight_col = self.get_weight_col()
        weights = (np.asarray(table[weight_col], np.float64)
                   if weight_col else None)

        if kind == "sparse":
            idx, vals, dim = feats
            num_features = self.get_num_features() or dim
            if not num_features:
                raise ValueError(
                    "hashed pair-column input needs numFeatures (the hash-"
                    "space size); call set_num_features")
            check_sparse_indices(idx, num_features)
            state, loss_log = sgd_fit_sparse(
                LOSSES[self.loss_name], idx, vals, y, weights,
                num_features, self._sgd_config())
        elif kind == "mixed":
            dense, cat = feats
            num_features = self.get_num_features()
            if not num_features:
                raise ValueError(
                    "mixed dense+hashed input needs numFeatures (the hash-"
                    "space size); call set_num_features")
            state, loss_log = sgd_fit_mixed(
                LOSSES[self.loss_name], dense, cat, y, weights,
                num_features, self._sgd_config())
        else:
            state, loss_log = sgd_fit(
                LOSSES[self.loss_name], feats, y, weights,
                self._sgd_config())

        model = self.model_cls()
        model.copy_params_from(self)
        model._state = state
        model._loss_log = loss_log
        return model

    def _sgd_config(self) -> SGDConfig:
        return SGDConfig(
            learning_rate=self.get_learning_rate(),
            reg=self.get_reg(),
            elastic_net=self.get_elastic_net(),
            global_batch_size=self.get_global_batch_size(),
            max_epochs=self.get_max_iter(),
            tol=self.get_tol(),
            seed=self.get_seed(),
        )

    def fit_outofcore(self, make_reader, *, num_features: int, mesh=None,
                      sparse: bool = False, mixed: bool = False,
                      checkpoint=None,
                      checkpoint_every_steps: int = 0, resume: bool = False,
                      **stream_kwargs):
        """Out-of-core ``fit``: the dataset streams from ``make_reader()``
        (a fresh per-epoch iterator of host batch dicts, e.g. a re-seeked
        ``DataCacheReader``) instead of living in RAM/HBM — the
        Criteo-scale input path (BASELINE.md north star).  Column names
        follow this estimator's params (featuresCol/labelCol/weightCol);
        with ``sparse=True`` the reader must carry the hashed pair columns
        ``{featuresCol}_indices`` / ``{featuresCol}_values`` instead, and
        with ``mixed=True`` the Criteo-native ``{featuresCol}_dense`` +
        ``{featuresCol}_indices`` pair (implicit categorical value 1.0).
        globalBatchSize and seed are inert here: the reader owns batch size
        and ordering (shuffle when writing the cache or vary segment order
        per epoch).  Extra keyword arguments (``cache_decoded``,
        ``decoded_ram_budget``, ``stream_info``, ``prefetch_*``,
        ``steps_per_dispatch``, ``ell_*``) forward to
        :func:`sgd_fit_outofcore` — in particular
        ``cache_decoded=False`` opts out of the decoded replay cache for
        readers that intentionally vary their stream per epoch, and
        ``steps_per_dispatch`` (default 8) sizes the chunked-scan
        dispatch (W batches per jitted dispatch, bit-exact at any W)."""
        feat = self.get_features_col()
        state, loss_log = sgd_fit_outofcore(
            LOSSES[self.loss_name], make_reader,
            num_features=num_features, config=self._sgd_config(), mesh=mesh,
            features_key=feat,
            label_key=self.get_label_col(),
            weight_key=self.get_weight_col() or None,
            indices_key=f"{feat}_indices" if (sparse or mixed) else None,
            values_key=f"{feat}_values" if sparse else None,
            dense_key=f"{feat}_dense" if mixed else None,
            checkpoint=checkpoint,
            checkpoint_every_steps=checkpoint_every_steps, resume=resume,
            **stream_kwargs)
        model = self.model_cls()
        model.copy_params_from(self)
        model._state = state
        model._loss_log = loss_log
        return model

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)

    @classmethod
    def load(cls, path: str):
        return persist.load_stage_param(path)


# ---------------------------------------------------------------------------
# kernel-registry entry: op ``linear_margins`` (stage convention).  The
# chain-terminal kernel fn IS the registered implementation — offline
# transform, fused pipelines, and the serving executor all dispatch this
# one (fn, static) plan through the registry's shared jit, so any
# consumer's warm-up is a compile-cache hit for the others.
# ---------------------------------------------------------------------------

def _register_linear_kernels() -> None:
    from ...kernels.registry import register_kernel

    register_kernel("linear_margins", "xla", _linear_chain_kernel,
                    convention="stage")


_register_linear_kernels()
