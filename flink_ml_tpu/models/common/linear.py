"""Shared Estimator/Model bases for the linear family (LogisticRegression,
LinearRegression, LinearSVC) — one SGD skeleton, per-model loss + link."""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator, Model
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.shared import (
    HasElasticNet,
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasPredictionCol,
    HasRawPredictionCol,
    HasRegParam,
    HasSeed,
    HasTol,
    HasWeightCol,
)
from ...utils import persist
from .losses import LOSSES
from .sgd import LinearState, SGDConfig, sgd_fit, sgd_fit_outofcore

__all__ = ["LinearEstimatorParams", "LinearModelBase", "LinearEstimatorBase"]


@jax.jit
def _jit_margins(X, w, b):
    """Module-level jit: repeated transform() calls are cache hits."""
    return X @ w + b


class LinearModelParams(HasFeaturesCol, HasPredictionCol, HasRawPredictionCol):
    pass


class LinearEstimatorParams(LinearModelParams, HasLabelCol, HasWeightCol,
                            HasMaxIter, HasLearningRate, HasRegParam,
                            HasElasticNet, HasGlobalBatchSize, HasTol,
                            HasSeed):
    pass


class LinearModelBase(LinearModelParams, Model):
    """Holds (coefficients, intercept); subclasses map margins to the
    prediction / raw-prediction columns."""

    loss_name: str = "squared"

    def __init__(self):
        super().__init__()
        self._state: Optional[LinearState] = None

    # -- model data ---------------------------------------------------------
    def set_model_data(self, *inputs) -> "LinearModelBase":
        (table,) = inputs
        self._state = LinearState(
            coefficients=np.asarray(table["coefficients"][0], np.float64),
            intercept=float(table["intercept"][0]))
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({
            "coefficients": self._state.coefficients[None, :],
            "intercept": np.array([self._state.intercept]),
        })]

    def _require_model(self):
        if self._state is None:
            raise RuntimeError(
                f"{type(self).__name__} has no model data; fit the estimator "
                "or call set_model_data first")

    # -- inference ----------------------------------------------------------
    def _margins(self, table: Table) -> np.ndarray:
        self._require_model()
        X = stack_vectors(table[self.get_features_col()]).astype(np.float32)
        w = jnp.asarray(self._state.coefficients, jnp.float32)
        b = jnp.asarray(self._state.intercept, jnp.float32)
        return np.asarray(_jit_margins(X, w, b), np.float64)

    def _decision(self, margins: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _raw(self, margins: np.ndarray) -> np.ndarray:
        return margins

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        m = self._margins(table)
        out = table.with_column(self.get_prediction_col(), self._decision(m))
        raw_col = self.get_raw_prediction_col()
        if raw_col:
            out = out.with_column(raw_col, self._raw(m))
        return [out]

    # -- persistence --------------------------------------------------------
    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {
            "coefficients": self._state.coefficients,
            "intercept": np.array([self._state.intercept]),
        })

    @classmethod
    def load(cls, path: str):
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._state = LinearState(
            coefficients=data["coefficients"].astype(np.float64),
            intercept=float(data["intercept"][0]))
        return model


class LinearEstimatorBase(LinearEstimatorParams, Estimator):
    """fit(): extract (X, y, weight), run the fused SGD loop, wrap the fitted
    state in the concrete model class."""

    loss_name: str = "squared"
    model_cls = None  # set by subclasses

    def _labels(self, table: Table) -> np.ndarray:
        return np.asarray(table[self.get_label_col()], np.float64)

    def fit(self, *inputs):
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()])
        y = self._labels(table)
        weight_col = self.get_weight_col()
        weights = (np.asarray(table[weight_col], np.float64)
                   if weight_col else None)

        state, loss_log = sgd_fit(
            LOSSES[self.loss_name], X, y, weights, self._sgd_config())

        model = self.model_cls()
        model.copy_params_from(self)
        model._state = state
        model._loss_log = loss_log
        return model

    def _sgd_config(self) -> SGDConfig:
        return SGDConfig(
            learning_rate=self.get_learning_rate(),
            reg=self.get_reg(),
            elastic_net=self.get_elastic_net(),
            global_batch_size=self.get_global_batch_size(),
            max_epochs=self.get_max_iter(),
            tol=self.get_tol(),
            seed=self.get_seed(),
        )

    def fit_outofcore(self, make_reader, *, num_features: int, mesh=None):
        """Out-of-core ``fit``: the dataset streams from ``make_reader()``
        (a fresh per-epoch iterator of host batch dicts, e.g. a re-seeked
        ``DataCacheReader``) instead of living in RAM/HBM — the
        Criteo-scale input path (BASELINE.md north star).  Column names
        follow this estimator's params (featuresCol/labelCol/weightCol).
        globalBatchSize and seed are inert here: the reader owns batch size
        and ordering (shuffle when writing the cache or vary segment order
        per epoch)."""
        state, loss_log = sgd_fit_outofcore(
            LOSSES[self.loss_name], make_reader,
            num_features=num_features, config=self._sgd_config(), mesh=mesh,
            features_key=self.get_features_col(),
            label_key=self.get_label_col(),
            weight_key=self.get_weight_col() or None)
        model = self.model_cls()
        model.copy_params_from(self)
        model._state = state
        model._loss_log = loss_log
        return model

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)

    @classmethod
    def load(cls, path: str):
        return persist.load_stage_param(path)
