"""Shared Estimator/Model plumbing for GBTClassifier / GBTRegressor."""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...api.stage import Estimator, Model
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.param import FloatParam, IntParam, ParamValidators
from ...params.shared import (
    HasFeaturesCol,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasPredictionCol,
)
from ...utils import persist
from .gbt import Forest, GBTConfig, predict_forest, train_forest

__all__ = ["GBTParams", "GBTModelBase", "GBTEstimatorBase"]


class GBTModelParams(HasFeaturesCol, HasPredictionCol):
    pass


class GBTParams(GBTModelParams, HasLabelCol, HasMaxIter, HasLearningRate):
    """``maxIter`` = number of trees (the boosting iterations);
    ``learningRate`` = shrinkage.  No seed: training is fully deterministic
    (no row/feature subsampling yet)."""

    REG_LAMBDA = FloatParam(
        "regLambda", "Leaf L2 regularization (XGBoost lambda).", default=1.0,
        validator=ParamValidators.gt_eq(0))

    def get_reg_lambda(self) -> float:
        return self.get(GBTParams.REG_LAMBDA)

    def set_reg_lambda(self, value: float):
        return self.set(GBTParams.REG_LAMBDA, value)

    MAX_DEPTH = IntParam("maxDepth", "Tree depth (internal levels).",
                         default=4, validator=ParamValidators.in_range(1, 12))
    MAX_BINS = IntParam("maxBins", "Histogram bins per feature.", default=64,
                        validator=ParamValidators.in_range(2, 256))
    MIN_CHILD_WEIGHT = FloatParam(
        "minChildWeight", "Minimum hessian sum per child.", default=1e-3,
        validator=ParamValidators.gt_eq(0))

    def get_max_depth(self) -> int:
        return self.get(GBTParams.MAX_DEPTH)

    def set_max_depth(self, value: int):
        return self.set(GBTParams.MAX_DEPTH, value)

    def get_max_bins(self) -> int:
        return self.get(GBTParams.MAX_BINS)

    def set_max_bins(self, value: int):
        return self.set(GBTParams.MAX_BINS, value)


class GBTModelBase(GBTModelParams, Model):
    """Holds the Forest arrays; subclasses map margins to predictions.

    Deliberately NOT chainable (no ``transform_kernel``): the shared
    predict entry points (``predict_forest[_softmax]``) accumulate tree
    margins in float64 on HOST across per-tree dispatches — an in-segment
    f32 accumulation could not stay bit-exact with them, so in a fused
    pipeline GBT breaks the chain and scores through its existing
    (bucket-padded, retrace-free) entry points.  ``api/chain.py``."""

    def __init__(self):
        super().__init__()
        self._forest: Optional[Forest] = None

    def _margins(self, table: Table) -> np.ndarray:
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        return predict_forest(X, self._forest)

    def _require_model(self) -> None:
        if self._forest is None:
            raise RuntimeError(
                f"{type(self).__name__} has no model data; call "
                "set_model_data() or fit the estimator first")

    # -- model data ---------------------------------------------------------
    def set_model_data(self, *inputs) -> "GBTModelBase":
        (t,) = inputs
        self._forest = Forest(
            feature=np.asarray(t["feature"], np.int32),
            threshold=np.asarray(t["threshold"], np.int32),
            value=np.asarray(t["value"], np.float32),
            bin_edges=np.asarray(t["binEdges"][0], np.float64),
            base_score=float(np.asarray(t["baseScore"])[0]),
            learning_rate=float(np.asarray(t["learningRate"])[0]),
        )
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        f = self._forest
        n_trees = f.feature.shape[0]
        return [Table({
            "feature": f.feature, "threshold": f.threshold, "value": f.value,
            "binEdges": np.broadcast_to(
                f.bin_edges[None], (n_trees,) + f.bin_edges.shape).copy(),
            "baseScore": np.full((n_trees,), f.base_score),
            "learningRate": np.full((n_trees,), f.learning_rate),
        })]

    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        f = self._forest
        persist.save_model_arrays(path, "model", {
            "feature": f.feature, "threshold": f.threshold, "value": f.value,
            "binEdges": f.bin_edges,
            "scalars": np.asarray([f.base_score, f.learning_rate])})

    @classmethod
    def load(cls, path: str):
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._forest = Forest(
            feature=data["feature"].astype(np.int32),
            threshold=data["threshold"].astype(np.int32),
            value=data["value"].astype(np.float32),
            bin_edges=data["binEdges"].astype(np.float64),
            base_score=float(data["scalars"][0]),
            learning_rate=float(data["scalars"][1]),
        )
        return model


class GBTEstimatorBase(GBTParams, Estimator):
    """Subclasses define ``_prepare_labels`` (-> float targets + label map),
    ``_grad_hess``, ``_base_score``, and ``model_cls``."""

    model_cls: type

    def _config(self) -> GBTConfig:
        return GBTConfig(
            num_trees=self.get_max_iter(),
            max_depth=self.get_max_depth(),
            learning_rate=self.get_learning_rate(),
            max_bins=self.get_max_bins(),
            reg_lambda=self.get_reg_lambda(),
            min_child_weight=self.get(GBTParams.MIN_CHILD_WEIGHT),
        )

    def fit(self, *inputs):
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        if len(X) == 0:
            raise ValueError(f"{type(self).__name__}.fit requires rows")
        # Label values thread through fit (never stored on the estimator):
        # concurrent fits on one estimator stay independent.
        y, label_values = self._prepare_labels(
            np.asarray(table[self.get_label_col()]))
        forest = train_forest(X, y, self._grad_hess, self._base_score(y),
                              self._config())
        model = self.model_cls()
        model.copy_params_from(self)
        model._forest = forest
        self._finalize_model(model, label_values)
        return model

    def fit_outofcore(self, make_reader, *, features_key: str = None,
                      label_key: str = None, work_dir: str = None,
                      sample_rows: int = 1 << 18):
        """Out-of-core ``fit`` (see ``gbt.train_forest_outofcore``): the
        dataset streams from ``make_reader()`` — a fresh iterator of host
        batch dicts per call (``{features_key: (b, d) float, label_key:
        (b,) labels}``, e.g. a re-seeked ``DataCacheReader``) — instead
        of living in RAM; per-row state is one f64 margin memmap.

        Binary-classification label note: the streamed labels must
        already be 0/1 floats (the in-core fit's arbitrary-label mapping
        needs the full label set up front)."""
        from .gbt import train_forest_outofcore

        def prepared_reader():
            for batch in make_reader():
                y = self._streaming_labels(
                    np.asarray(batch[label_key or self.get_label_col()]))
                yield {"features": np.asarray(
                    batch[features_key or self.get_features_col()]),
                    "label": y}

        # base score folds into the trainer's pass A over the same
        # leading sample (no extra head read of a slow source)
        forest = train_forest_outofcore(
            prepared_reader, self._grad_hess, self._base_score,
            self._config(), work_dir=work_dir, sample_rows=sample_rows)
        model = self.model_cls()
        model.copy_params_from(self)
        model._forest = forest
        self._finalize_model(model, self._streaming_label_values())
        return model

    def _streaming_labels(self, y_raw: np.ndarray) -> np.ndarray:
        """Per-batch label prep for fit_outofcore.  Unlike
        ``_prepare_labels``, this must be BATCH-LOCAL (no global label
        inventory); the default passes float targets through."""
        return np.asarray(y_raw, np.float64)

    def _streaming_label_values(self):
        """Label set installed on the streamed-fit model (None for
        regressors)."""
        return None

    def _finalize_model(self, model, label_values) -> None:
        """Hook for subclasses (e.g. install the label mapping)."""

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)

    @classmethod
    def load(cls, path: str):
        return persist.load_stage_param(path)
