"""Histogram-based gradient-boosted trees — the shared trainer.

Member of the later Flink ML 2.x library line (GBTClassifier/GBTRegressor).
CPU GBT implementations walk rows per node; the TPU-native formulation is
the histogram method with everything vectorized over rows:

- **Binning** (host, once): per-feature quantile bins -> int32 bin ids.
- **Histograms** (device): per level, one ``segment_sum`` over the flattened
  ``(node, feature, bin)`` key accumulates (grad, hess, count) for ALL nodes
  and features at once — the analog of the keyed shuffle+reduce a dataflow
  engine would run, fused on-chip.
- **Split finding** (device): cumulative sums over bins give every candidate
  split's left/right (G, H); the XGBoost gain
  ``G_L^2/(H_L+l) + G_R^2/(H_R+l) - G^2/(H+l)`` is argmaxed per node.
- **Routing** (device): rows step to ``2*node+1 (+1)`` by comparing their
  bin to the split threshold — no gather-scatter trees, just arrays.

Trees are complete binary arrays (node i's children are 2i+1/2i+2), so one
jitted ``build_level`` per depth serves every tree; the boosting loop runs
hosted (each tree depends on the previous residuals).
"""

from __future__ import annotations

import os

from dataclasses import dataclass
from functools import partial
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...kernels.aot import aot_jit

__all__ = ["GBTConfig", "bin_features", "train_forest", "predict_forest",
           "Forest", "SoftmaxForest", "train_forest_softmax",
           "predict_forest_softmax"]


@dataclass
class GBTConfig:
    num_trees: int = 20
    max_depth: int = 4            # levels of internal nodes
    learning_rate: float = 0.1
    max_bins: int = 64
    reg_lambda: float = 1.0
    min_child_weight: float = 1e-3
    #: out-of-core chunked dispatch: stack this many streamed batches
    #: into one device chunk and run each pass's per-batch device work
    #: as ONE jitted lax.scan — every histogram/leaf/margin pass costs
    #: ``ceil(n_batches / W)`` dispatches (and device transfers)
    #: instead of ``n_batches``.  Short final chunks pad with zero-
    #: gradient batches, which are inert in every additive pass.  1 =
    #: one dispatch per batch through the same scan program.  In-core
    #: training ignores it.  NOTE the device-memory trade: each transfer
    #: stages a ``(W, batch_device_rows, d)`` chunk — W times the
    #: per-batch staging — so deployments that sized
    #: ``batch_device_rows`` to fit HBM must either shrink it by W or
    #: set ``steps_per_dispatch=1`` to keep the old footprint.
    steps_per_dispatch: int = 8


@dataclass
class Forest:
    """(trees, nodes) arrays; node i's children are 2i+1 / 2i+2."""

    feature: np.ndarray       # (T, n_nodes) int32, -1 for leaf
    threshold: np.ndarray     # (T, n_nodes) int32 bin id: go left if <= thr
    value: np.ndarray         # (T, n_nodes) f32 leaf value
    bin_edges: np.ndarray     # (d, max_bins - 1) f64 quantile edges
    base_score: float
    learning_rate: float


def quantile_edges(X: np.ndarray, max_bins: int) -> np.ndarray:
    """Per-feature quantile edges (d, bins-1) — the sketch half of
    :func:`bin_features` (the out-of-core trainer needs only this from
    its bounded leading sample)."""
    d = X.shape[1]
    edges = np.empty((d, max_bins - 1))
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    for j in range(d):
        # duplicates collapse constant regions
        edges[j] = np.quantile(X[:, j], qs)
    return edges


def bin_features(X: np.ndarray, max_bins: int) -> Tuple[np.ndarray, np.ndarray]:
    """Quantile binning on host: (binned int32 (n, d), edges (d, bins-1))."""
    edges = quantile_edges(X, max_bins)
    return apply_bins(X, edges), edges


def apply_bins(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    binned = np.empty(X.shape, np.int32)
    for j in range(X.shape[1]):
        binned[:, j] = np.searchsorted(edges[j], X[:, j], side="left")
    return binned


@jax.jit
def apply_bins_device(X: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """Vectorized on-device twin of :func:`apply_bins`:
    ``bin = #edges strictly below x`` (== searchsorted side='left' for
    quantile edges), with NaN routed to the LAST bin exactly as
    np.searchsorted sorts it.  One fused (n, d, bins-1) compare+sum
    instead of a per-feature loop.

    Precision caveat: runs at the device dtype (f32 without jax x64), so
    rows within f32 rounding of an edge can bin differently from the
    f64 host path — use it for f32-native device-resident pipelines; the
    out-of-core trainer host-bins to stay bit-identical with in-core
    training AND with predict-time binning."""
    count = jnp.sum(X[:, :, None] > edges[None, :, :], axis=-1,
                    dtype=jnp.int32)
    return jnp.where(jnp.isnan(X), edges.shape[1], count)


#: histogram implementation: "auto" (the kernel registry picks — MXU
#: one-hot matmuls on TPU, where the systolic array beats segment_sum's
#: per-element random accumulation, XLA segment_sum elsewhere),
#: "segsum" (force the XLA scatter-adds, the r1-r4 path) or "mxu"
#: (force the double one-hot matmul).  Module-level so the bench can
#: measure both and a chip verdict can pin the default; both are exact
#: up to f32 summation order.
HIST_IMPL = "auto"


@partial(jax.jit, static_argnames=("n_nodes", "d", "bins"))
def _level_histograms_segsum(binned, node_ids, grad, hess, n_nodes: int,
                             d: int, bins: int):
    """segment_sum form: one scatter-add per (row, feature) key."""
    live = node_ids >= 0
    safe_node = jnp.where(live, node_ids, 0)
    # (node, feature, bin) -> flat key; dead rows land in a scratch key 0
    # with zero weights
    keys = (safe_node[:, None] * (d * bins)
            + jnp.arange(d, dtype=jnp.int32)[None, :] * bins
            + binned)                                           # (n, d)
    w = live.astype(grad.dtype)
    seg = n_nodes * d * bins
    flat = keys.reshape(-1)
    g_hist = jax.ops.segment_sum((grad * w)[:, None].repeat(d, 1).reshape(-1),
                                 flat, seg)
    h_hist = jax.ops.segment_sum((hess * w)[:, None].repeat(d, 1).reshape(-1),
                                 flat, seg)
    return (g_hist.reshape(n_nodes, d, bins),
            h_hist.reshape(n_nodes, d, bins))


@partial(jax.jit, static_argnames=("n_nodes", "d", "bins"))
def _level_histograms_mxu(binned, node_ids, grad, hess, n_nodes: int,
                          d: int, bins: int):
    """MXU form: hist[node, f, bin] = (onehot_node * value)^T @
    onehot_bin_f — histogramming as n x n_nodes x bins matmul
    contractions (no scatter anywhere), scanned over features so the
    transient one-hots stay at (n, n_nodes) + (n, bins).  ~2*n*nodes*
    bins MAC per (feature, value) — MXU work standing in for
    segment_sum's per-element random accumulation."""
    live = node_ids >= 0
    safe_node = jnp.where(live, node_ids, 0)
    w = live.astype(grad.dtype)
    # (n, n_nodes) one-hots pre-scaled by the two accumulated values —
    # rows of dead nodes carry zeros, so scratch-node pollution is moot
    node_oh = (safe_node[:, None]
               == jnp.arange(n_nodes, dtype=jnp.int32)[None, :])
    gv = jnp.where(node_oh, (grad * w)[:, None], 0.0)   # (n, n_nodes)
    hv = jnp.where(node_oh, (hess * w)[:, None], 0.0)

    def per_feature(_, f):
        bin_oh = (binned[:, f][:, None]
                  == jnp.arange(bins, dtype=jnp.int32)[None, :]
                  ).astype(grad.dtype)                  # (n, bins)
        g_f = jax.lax.dot_general(
            gv, bin_oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (n_nodes, bins)
        h_f = jax.lax.dot_general(
            hv, bin_oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return None, (g_f, h_f)

    _, (g_hist, h_hist) = jax.lax.scan(
        per_feature, None, jnp.arange(d, dtype=jnp.int32))
    # scan stacks (d, n_nodes, bins) -> (n_nodes, d, bins)
    return (jnp.transpose(g_hist, (1, 0, 2)),
            jnp.transpose(h_hist, (1, 0, 2)))


#: the dispatch table — unknown HIST_IMPL values raise KeyError instead
#: of silently running the wrong implementation
_HIST_IMPLS = {"segsum": _level_histograms_segsum,
               "mxu": _level_histograms_mxu}


def resolve_hist_impl(name: str = None) -> str:
    """Resolve a histogram impl name ("auto" -> the kernel registry's
    pick for this backend; "segsum"/"mxu" force) to a concrete
    ``_HIST_IMPLS`` key.  Unknown names raise KeyError — never a silent
    fallback."""
    name = HIST_IMPL if name is None else name
    if name == "auto":
        from ...kernels.registry import lookup

        backend = lookup("gbt_level_histograms").backend
        return {"xla": "segsum"}.get(backend, backend)
    if name not in _HIST_IMPLS:
        raise KeyError(name)
    return name


def _level_histograms(binned, node_ids, grad, hess, n_nodes: int,
                      d: int, bins: int):
    """Per-(node, feature, bin) grad/hess sums for one level — the
    ADDITIVE piece of split finding: the out-of-core trainer accumulates
    these over streamed batches and decides splits from the totals.
    Dispatches on :data:`HIST_IMPL` through :func:`resolve_hist_impl`."""
    return _HIST_IMPLS[resolve_hist_impl()](binned, node_ids, grad, hess,
                                            n_nodes, d, bins)


def _level_splits(g_hist, h_hist, reg_lambda: float,
                  min_child_weight: float):
    """Best (feature, bin, gain) per node from the level histograms."""
    n_nodes, d, bins = g_hist.shape
    g_tot = jnp.sum(g_hist, axis=(1, 2)) / d                    # per node
    h_tot = jnp.sum(h_hist, axis=(1, 2)) / d

    # candidate split at bin b: left = bins <= b (cumsum), right = rest
    g_left = jnp.cumsum(g_hist, axis=2)
    h_left = jnp.cumsum(h_hist, axis=2)
    g_right = g_tot[:, None, None] - g_left
    h_right = h_tot[:, None, None] - h_left

    def score(g, h):
        return g * g / (h + reg_lambda)

    gain = (score(g_left, h_left) + score(g_right, h_right)
            - score(g_tot, h_tot)[:, None, None])               # (nodes,d,bins)
    viable = ((h_left >= min_child_weight)
              & (h_right >= min_child_weight))
    gain = jnp.where(viable, gain, -jnp.inf)
    # never split on the last bin (empty right side by construction)
    gain = gain.at[:, :, -1].set(-jnp.inf)

    flat_gain = gain.reshape(n_nodes, d * bins)
    best = jnp.argmax(flat_gain, axis=1)
    best_gain = jnp.take_along_axis(flat_gain, best[:, None], 1)[:, 0]
    best_feature = (best // bins).astype(jnp.int32)
    best_bin = (best % bins).astype(jnp.int32)
    return best_feature, best_bin, best_gain


def _apply_split(binned, node_ids, best_feature, best_bin, best_gain):
    """Route live rows through the level's chosen splits: 2*node (+1 for
    right) in the next level's local numbering, -1 where the node did not
    split."""
    live = node_ids >= 0
    safe_node = jnp.where(live, node_ids, 0)
    row_bin = jnp.take_along_axis(binned, best_feature[safe_node][:, None],
                                  1)[:, 0]
    goes_right = row_bin > best_bin[safe_node]
    node_split = best_gain[safe_node] > 0
    return jnp.where(live & node_split,
                     2 * safe_node + goes_right.astype(jnp.int32), -1)


@partial(aot_jit, static_argnames=("n_nodes", "d", "bins", "reg_lambda",
                                   "min_child_weight", "hist_impl"))
def _build_level(binned, node_ids, grad, hess, n_nodes: int,
                 d: int, bins: int, reg_lambda: float,
                 min_child_weight: float, hist_impl: str = "segsum"):
    """One tree level for all ``n_nodes`` nodes at once
    (histograms -> splits -> routing; the three pieces are separate
    functions so the out-of-core trainer can accumulate histograms over
    batches and reuse the identical split/routing math).

    Returns (feature (n_nodes,), threshold (n_nodes,), gain (n_nodes,),
    new_node_ids (n,)).  ``node_ids`` are level-local in [0, n_nodes) with
    -1 marking rows already settled in a leaf.
    """
    g_hist, h_hist = _HIST_IMPLS[resolve_hist_impl(hist_impl)](
        binned, node_ids, grad, hess, n_nodes, d, bins)
    best_feature, best_bin, best_gain = _level_splits(
        g_hist, h_hist, reg_lambda, min_child_weight)
    new_ids = _apply_split(binned, node_ids, best_feature, best_bin,
                           best_gain)
    return best_feature, best_bin, best_gain, new_ids


@partial(aot_jit, static_argnames=("n_nodes", "reg_lambda"))
def _leaf_values(node_ids, grad, hess, n_nodes: int, reg_lambda: float):
    """Newton leaf weights -G/(H+lambda) for every level-local node."""
    live = node_ids >= 0
    safe = jnp.where(live, node_ids, 0)
    w = live.astype(grad.dtype)
    g = jax.ops.segment_sum(grad * w, safe, n_nodes)
    h = jax.ops.segment_sum(hess * w, safe, n_nodes)
    return -g / (h + reg_lambda)


def _train_one_tree(binned, g, h, d: int, config: GBTConfig):
    """Grow one tree against device gradients/hessians; returns the host
    (feature, threshold, value) node rows plus the tree's DEVICE in-sample
    prediction (margin scale, before learning-rate shrinkage)."""
    n = binned.shape[0]
    bins = config.max_bins
    depth = config.max_depth
    n_nodes_total = 2 ** (depth + 1) - 1
    feature_row = np.full((n_nodes_total,), -1, np.int32)
    threshold_row = np.zeros((n_nodes_total,), np.int32)
    value_row = np.zeros((n_nodes_total,), np.float32)

    node_ids = jnp.zeros((n,), jnp.int32)
    level_feature: List[np.ndarray] = []
    level_bin: List[np.ndarray] = []
    level_gain: List[np.ndarray] = []
    level_ids = [node_ids]
    for level in range(depth):
        n_nodes = 2 ** level
        # hist impl resolved to a CONCRETE name before it becomes a
        # static arg: "auto" would be ambiguous in the persistent AOT
        # key (the registry/autotune pick can differ across processes)
        f, b, gain, node_ids = _build_level(
            binned, node_ids, g, h, n_nodes, d, bins,
            config.reg_lambda, config.min_child_weight,
            hist_impl=resolve_hist_impl())
        level_feature.append(np.asarray(f))
        level_bin.append(np.asarray(b))
        level_gain.append(np.asarray(gain))
        level_ids.append(node_ids)

    # assemble the tree: internal nodes that actually split get
    # (feature, threshold); everything else becomes a leaf holding the
    # Newton value of the rows that stopped there
    base = 0
    for level in range(depth):
        n_nodes = 2 ** level
        split = level_gain[level] > 0
        feature_row[base:base + n_nodes] = np.where(
            split, level_feature[level], -1)
        threshold_row[base:base + n_nodes] = level_bin[level]
        # leaf value for rows that STOP at this level (their node did not
        # split): computed from the ids entering the level
        vals = np.asarray(_leaf_values(level_ids[level], g, h, n_nodes,
                                       config.reg_lambda))
        value_row[base:base + n_nodes] = np.where(split, 0.0, vals)
        base += n_nodes
    # deepest level: always leaves
    n_nodes = 2 ** depth
    vals = np.asarray(_leaf_values(level_ids[depth], g, h, n_nodes,
                                   config.reg_lambda))
    value_row[base:base + n_nodes] = vals

    # in-sample update reuses the DEVICE binned copy — predicting from the
    # host matrix would re-upload it once per tree
    pred = _predict_tree_jit(binned, jnp.asarray(feature_row),
                             jnp.asarray(threshold_row),
                             jnp.asarray(value_row), depth)
    return feature_row, threshold_row, value_row, pred


def _maybe_autotune_hist(binned, g, h, d: int, bins: int) -> None:
    """First-encounter autotune of the histogram backend (ISSUE 12):
    when several registry backends are AVAILABLE on this device (TPU has
    mxu + xla; CPU has one, so nothing to search) and a persistent cache
    root is configured, time both on a probe slice of the REAL binned
    data and record the winner — ``resolve_hist_impl("auto")`` then
    resolves through ``registry.lookup``, which honors the decision in
    this and every later process.  A recorded decision short-circuits
    (zero search cost)."""
    from ...kernels import autotune
    from ...kernels.registry import backends, lookup

    if HIST_IMPL != "auto" or not autotune.enabled():
        return
    avail = [b for b in backends("gbt_level_histograms")
             if lookup("gbt_level_histograms", backend=b).is_available()]
    if len(avail) < 2:
        return
    rows = min(int(binned.shape[0]), 8192)
    bp, gp, hp = binned[:rows], g[:rows], h[:rows]
    ids = jnp.zeros((rows,), jnp.int32)
    impl_of = {"xla": "segsum"}

    def runner(backend):
        impl = _HIST_IMPLS[impl_of.get(backend, backend)]
        return lambda: impl(bp, ids, gp, hp, 4, d, bins)

    autotune.choose("gbt_level_histograms", (),
                    {b: runner(b) for b in avail},
                    probe=f"real-data slice rows={rows} d={d} bins={bins} "
                          "n_nodes=4")


def train_forest(X: np.ndarray, y: np.ndarray,
                 grad_hess: Callable[[np.ndarray, np.ndarray],
                                     Tuple[np.ndarray, np.ndarray]],
                 base_score: float, config: GBTConfig) -> Forest:
    """Boost ``num_trees`` trees against ``grad_hess(y, pred)``."""
    n, d = X.shape
    binned_host, edges = bin_features(X, config.max_bins)
    binned = jnp.asarray(binned_host)
    n_nodes_total = 2 ** (config.max_depth + 1) - 1

    features = np.full((config.num_trees, n_nodes_total), -1, np.int32)
    thresholds = np.zeros((config.num_trees, n_nodes_total), np.int32)
    values = np.zeros((config.num_trees, n_nodes_total), np.float32)

    pred = np.full((n,), base_score, np.float64)
    for t in range(config.num_trees):
        g, h = grad_hess(y, pred)
        gd = jnp.asarray(g, jnp.float32)
        hd = jnp.asarray(h, jnp.float32)
        if t == 0:
            _maybe_autotune_hist(binned, gd, hd, d, config.max_bins)
        features[t], thresholds[t], values[t], tree_pred = _train_one_tree(
            binned, gd, hd, d, config)
        pred = pred + config.learning_rate * np.asarray(tree_pred, np.float64)

    return Forest(features, thresholds, values, edges, base_score,
                  config.learning_rate)


@partial(jax.jit, static_argnames=("n_nodes",))
def _leaf_sums(node_ids, grad, hess, n_nodes: int):
    """Per-node (G, H) sums — the additive form of :func:`_leaf_values`
    for streamed batches."""
    live = node_ids >= 0
    safe = jnp.where(live, node_ids, 0)
    w = live.astype(grad.dtype)
    return (jax.ops.segment_sum(grad * w, safe, n_nodes),
            jax.ops.segment_sum(hess * w, safe, n_nodes))


@partial(jax.jit, static_argnames=("level",))
def _route_to_level(binned, feature_rows, threshold_rows, level: int):
    """Node ids entering ``level`` by walking the assembled tree-so-far
    (level-major layout; ``feature == -1`` marks a non-splitting node,
    matching :func:`_apply_split`'s ``gain > 0`` routing exactly)."""
    ids = jnp.zeros((binned.shape[0],), jnp.int32)
    base = 0
    for lvl in range(level):
        live = ids >= 0
        safe = jnp.where(live, ids, 0)
        gnode = base + safe
        f = feature_rows[gnode]
        thr = threshold_rows[gnode]
        split = f >= 0
        row_bin = jnp.take_along_axis(binned, jnp.maximum(f, 0)[:, None],
                                      1)[:, 0]
        ids = jnp.where(live & split,
                        2 * safe + (row_bin > thr).astype(jnp.int32), -1)
        base += 2 ** lvl
    return ids


@partial(jax.jit, static_argnames=("level", "n_nodes", "d", "bins",
                                   "hist_impl"))
def _chunk_level_histograms(binned_c, g_c, h_c, feature_rows,
                            threshold_rows, g_init, h_init, level: int,
                            n_nodes: int, d: int, bins: int,
                            hist_impl: str):
    """Chunked histogram pass: one lax.scan accumulates the level
    histograms of a whole (W, rows, d) chunk in ONE dispatch — the
    per-batch route+histogram work is identical, only the dispatch
    boundary moves.  The RUNNING histograms ride in as the scan carry
    (``g_init``/``h_init``), so accumulation stays strictly per-batch
    sequential across chunk boundaries — f32 addition is
    non-associative, and summing each chunk separately would make the
    result W-dependent.  Zero-gradient (padding) batches add exact
    zeros."""
    def scan_step(carry, xs):
        gh_acc, hh_acc = carry
        b, g, h = xs
        ids = _route_to_level(b, feature_rows, threshold_rows, level)
        gh, hh = _HIST_IMPLS[resolve_hist_impl(hist_impl)](
            b, ids, g, h, n_nodes, d, bins)
        return (gh_acc + gh, hh_acc + hh), None

    (g_hist, h_hist), _ = jax.lax.scan(scan_step, (g_init, h_init),
                                       (binned_c, g_c, h_c))
    return g_hist, h_hist


@partial(jax.jit, static_argnames=("depth", "n_nodes"))
def _chunk_leaf_sums(binned_c, g_c, h_c, feature_rows, threshold_rows,
                     depth: int, n_nodes: int):
    """Chunked leaf-sum pass: stacked per-batch (G, H) node sums from one
    dispatch (kept per-batch so the host's f64 accumulation order matches
    the per-batch path exactly)."""
    def scan_step(_, xs):
        b, g, h = xs
        ids = _route_to_level(b, feature_rows, threshold_rows, depth)
        return None, _leaf_sums(ids, g, h, n_nodes)

    _, (gs, hs) = jax.lax.scan(scan_step, None, (binned_c, g_c, h_c))
    return gs, hs


@partial(jax.jit, static_argnames=("depth",))
def _chunk_tree_preds(binned_c, feature, threshold, value, depth: int):
    """Chunked margin pass: stacked (W, rows) tree predictions from one
    dispatch."""
    def scan_step(_, b):
        return None, _predict_tree_jit(b, feature, threshold, value, depth)

    _, preds = jax.lax.scan(scan_step, None, binned_c)
    return preds


def train_forest_outofcore(make_reader, grad_hess, base_score,
                           config: GBTConfig, *,
                           features_key: str = "features",
                           label_key: str = "label",
                           work_dir: Optional[str] = None,
                           sample_rows: int = 1 << 18,
                           batch_device_rows: int = 1 << 16) -> Forest:
    """Out-of-core :func:`train_forest`: the dataset streams from
    ``make_reader()`` (a fresh iterator of host batch dicts per call —
    the ``sgd_fit_outofcore`` protocol, but STRICTLY zero-arg and
    order-stable: unlike the sgd/kmeans streamers, epoch-aware or
    reshuffling factories are deliberately unsupported because the
    margin memmap is aligned to ROW ORDER across passes — every call
    must yield the same rows in the same order, or margins silently
    desynchronize.  A ``lambda epoch:`` factory fails loudly with a
    TypeError; a zero-arg factory that varies order per call is the
    caller's contract violation and cannot be detected here)
    instead of living in RAM/HBM, removing the one estimator family
    with a host-memory ceiling (VERDICT r2 task 9).

    Design: histogram building is ADDITIVE over row batches, so each tree
    level is one streamed pass accumulating ``_level_histograms`` on
    device, followed by the same ``_level_splits`` decision the in-core
    path uses — the classic out-of-core GBDT recipe, with the reference's
    replay-per-epoch posture (``ReplayOperator``) supplying the passes.

    - Bin edges come from the stream's leading ``sample_rows`` rows
      (quantile sketching on a bounded sample); each batch then bins
      through the HOST searchsorted (bit-identical to in-core training
      and to predict-time binning; see :func:`apply_bins_device` for why
      the f32 device variant is not used here).
    - The binned matrix is written once to a :class:`DataCacheWriter`
      cache in a fresh run directory under ``work_dir`` (uint8 when
      ``max_bins <= 256``: 4x smaller than the raw f32 stream), every
      later pass replays the cache, and the run directory is removed on
      return (margins included).
    - Per-row boosting margins live in a disk-backed memmap (float64,
      8 bytes/row — the only O(n) state).
    - ``base_score`` may be a float or a callable receiving the leading
      sample's labels (folds the estimator's base-score computation into
      pass A instead of an extra head read).

    Passes per tree: ``max_depth`` histogram passes + one leaf-sum pass +
    one margin-update pass.  Results match :func:`train_forest` on the
    same rows up to f32 accumulation order (asserted in tests).
    """
    import shutil
    import tempfile

    from ...data.datacache import DataCacheReader, DataCacheWriter

    bins = config.max_bins
    depth = config.max_depth

    # pass A: edges (and optionally the base score) from the leading sample
    sample: List[np.ndarray] = []
    sample_y: List[np.ndarray] = []
    seen = 0
    for batch in make_reader():
        sample.append(np.asarray(batch[features_key], np.float64))
        sample_y.append(np.asarray(batch[label_key], np.float64))
        seen += len(sample[-1])
        if seen >= sample_rows:
            break
    if not sample:
        raise ValueError("make_reader() returned an empty stream")
    Xs = np.concatenate(sample)[:sample_rows]
    d = Xs.shape[1]
    edges = quantile_edges(Xs, bins)
    if callable(base_score):
        base_score = float(base_score(np.concatenate(sample_y)[:sample_rows]))
    del sample, sample_y, Xs

    # pass B: binned cache + labels, in a unique per-fit run directory
    # (DataCacheWriter refuses dirty directories; retries and repeated
    # fits against one work_dir must each get a fresh cache)
    if work_dir is not None:
        os.makedirs(work_dir, exist_ok=True)
    run_dir = tempfile.mkdtemp(prefix="gbt-run-", dir=work_dir)
    try:
        cache_dir = os.path.join(run_dir, "binned")
        bin_dtype = np.uint8 if bins <= 256 else np.int32
        writer = DataCacheWriter(cache_dir, segment_rows=1 << 20)
        n = 0
        for batch in make_reader():
            X = np.asarray(batch[features_key], np.float64)
            b = apply_bins(X, edges).astype(bin_dtype)
            writer.append({"binned": b,
                           "label": np.asarray(batch[label_key],
                                               np.float64)})
            n += len(b)
        writer.finish()
        margins = np.memmap(os.path.join(run_dir, "margins.f64"),
                            np.float64, mode="w+", shape=(n,))
        margins[:] = base_score

        def cache_batches():
            """(slice, binned int32 HOST, y f64, margins f64) batches —
            host-side so the chunked passes stack W batches and pay one
            device transfer per chunk."""
            reader = DataCacheReader(cache_dir,
                                     batch_rows=batch_device_rows)
            start = 0
            for batch in reader:
                rows = len(batch["label"])
                sl = slice(start, start + rows)
                start += rows
                yield (sl, batch["binned"].astype(np.int32),
                       np.asarray(batch["label"], np.float64), margins[sl])

        return _boost_outofcore(cache_batches, margins, grad_hess,
                                base_score, edges, n, d, config)
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)


def _boost_outofcore(cache_batches, margins, grad_hess, base_score: float,
                     edges: np.ndarray, n: int, d: int,
                     config: GBTConfig) -> Forest:
    bins = config.max_bins
    depth = config.max_depth
    W = max(1, int(config.steps_per_dispatch))

    # Chunked dispatch (config.steps_per_dispatch): every streamed pass
    # stacks W batches into one (W, rows, d) device chunk and runs the
    # per-batch route/histogram/predict work as ONE jitted lax.scan —
    # ceil(n_batches / W) dispatches + transfers per pass instead of
    # n_batches.  Rows pad to the first batch's count and short final
    # chunks pad with whole zero batches: zero gradients/hessians make
    # every padded slot an exact no-op in the additive passes, and the
    # margin pass writes back only each real batch's real rows.
    def chunked_batches(need_gh: bool):
        """Yield (sls, binned_c (W, R, d) device i32, g_c, h_c (W, R)
        device f32 or None): ``sls`` lists the real batches' row
        slices.  Grouping rides the prefetch pipeline's ``_grouped``
        (one W-grouping protocol in the repo)."""
        from ...data.prefetch import _grouped

        rows_full: Optional[int] = None

        def emit(group):
            R = rows_full
            sls = [sl for sl, _, _, _ in group]
            if (len(group) == W
                    and all(b.shape[0] == R for _, b, _, _ in group)):
                # the steady case: equal full batches stack in one copy
                binned_c = np.stack([b for _, b, _, _ in group])
                if need_gh:
                    g_c = np.stack([g for _, _, g, _ in group])
                    h_c = np.stack([h for _, _, _, h in group])
            else:
                # ragged tail: zero-pad short rows / missing batches
                binned_c = np.zeros((W, R, d), np.int32)
                g_c = np.zeros((W, R), np.float32) if need_gh else None
                h_c = np.zeros((W, R), np.float32) if need_gh else None
                for j, (_, b, g, h) in enumerate(group):
                    binned_c[j, :b.shape[0]] = b
                    if need_gh:
                        g_c[j, :b.shape[0]] = g
                        h_c[j, :b.shape[0]] = h
            return (sls, jnp.asarray(binned_c),
                    jnp.asarray(g_c) if need_gh else None,
                    jnp.asarray(h_c) if need_gh else None)

        def prepared():
            for sl, binned_b, y_b, m_b in cache_batches():
                if need_gh:
                    g, h = grad_hess(y_b, m_b)
                    yield (sl, binned_b, np.asarray(g, np.float32),
                           np.asarray(h, np.float32))
                else:
                    yield (sl, binned_b, None, None)

        for group in _grouped(prepared(), W):
            if rows_full is None:
                rows_full = group[0][1].shape[0]
            yield emit(group)

    n_nodes_total = 2 ** (depth + 1) - 1
    features = np.full((config.num_trees, n_nodes_total), -1, np.int32)
    thresholds = np.zeros((config.num_trees, n_nodes_total), np.int32)
    values = np.zeros((config.num_trees, n_nodes_total), np.float32)

    for t in range(config.num_trees):
        feature_row = np.full((n_nodes_total,), -1, np.int32)
        threshold_row = np.zeros((n_nodes_total,), np.int32)
        value_row = np.zeros((n_nodes_total,), np.float32)
        base = 0
        for level in range(depth):
            n_nodes = 2 ** level
            # running histograms thread through every chunk's scan carry
            # (strictly sequential per-batch accumulation, W-independent)
            g_hist = jnp.zeros((n_nodes, d, bins), jnp.float32)
            h_hist = jnp.zeros((n_nodes, d, bins), jnp.float32)
            f_dev = jnp.asarray(feature_row)
            thr_dev = jnp.asarray(threshold_row)
            for _, binned_c, g_c, h_c in chunked_batches(True):
                g_hist, h_hist = _chunk_level_histograms(
                    binned_c, g_c, h_c, f_dev, thr_dev, g_hist, h_hist,
                    level, n_nodes, d, bins, HIST_IMPL)
            bf, bb, bg = _level_splits(g_hist, h_hist, config.reg_lambda,
                                       config.min_child_weight)
            bf, bb, bg = np.asarray(bf), np.asarray(bb), np.asarray(bg)
            split = bg > 0
            feature_row[base:base + n_nodes] = np.where(split, bf, -1)
            threshold_row[base:base + n_nodes] = bb
            # leaf value for rows that STOP at this level: Newton step on
            # the per-node totals the histograms already carry
            g_tot = np.asarray(jnp.sum(g_hist, axis=(1, 2))) / d
            h_tot = np.asarray(jnp.sum(h_hist, axis=(1, 2))) / d
            vals = -g_tot / (h_tot + config.reg_lambda)
            value_row[base:base + n_nodes] = np.where(split, 0.0, vals)
            base += n_nodes

        # deepest level: always leaves — one leaf-sum pass (per-batch
        # sums come back stacked; the host's f64 accumulation order
        # stays per-batch, identical to the unchunked path)
        n_nodes = 2 ** depth
        G = np.zeros((n_nodes,), np.float64)
        H = np.zeros((n_nodes,), np.float64)
        f_dev = jnp.asarray(feature_row)
        thr_dev = jnp.asarray(threshold_row)
        for sls, binned_c, g_c, h_c in chunked_batches(True):
            gs, hs = _chunk_leaf_sums(binned_c, g_c, h_c, f_dev, thr_dev,
                                      depth, n_nodes)
            gs = np.asarray(gs, np.float64)
            hs = np.asarray(hs, np.float64)
            for j in range(len(sls)):
                G += gs[j]
                H += hs[j]
        value_row[base:base + n_nodes] = (
            -G / (H + config.reg_lambda)).astype(np.float32)

        # margin-update pass
        feat_dev = jnp.asarray(feature_row)
        thr_dev = jnp.asarray(threshold_row)
        val_dev = jnp.asarray(value_row)
        for sls, binned_c, _, _ in chunked_batches(False):
            preds = np.asarray(_chunk_tree_preds(binned_c, feat_dev,
                                                 thr_dev, val_dev, depth),
                               np.float64)
            for j, sl in enumerate(sls):
                margins[sl] += (config.learning_rate
                                * preds[j, :sl.stop - sl.start])
        features[t], thresholds[t], values[t] = (feature_row,
                                                 threshold_row, value_row)
    margins.flush()
    return Forest(features, thresholds, values, edges, base_score,
                  config.learning_rate)


@dataclass
class SoftmaxForest:
    """K-class boosted forest: ``num_trees`` rounds x ``n_classes`` trees
    (the standard softmax objective — one tree per class per round, the
    XGBoost ``multi:softmax`` formulation)."""

    feature: np.ndarray       # (T, K, n_nodes) int32, -1 for leaf
    threshold: np.ndarray     # (T, K, n_nodes) int32
    value: np.ndarray         # (T, K, n_nodes) f32
    bin_edges: np.ndarray     # (d, max_bins - 1) f64
    base_scores: np.ndarray   # (K,) f64 log-priors
    learning_rate: float

    @property
    def n_classes(self) -> int:
        return self.feature.shape[1]


def _softmax_rows(m: np.ndarray) -> np.ndarray:
    e = np.exp(m - m.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


def train_forest_softmax(X: np.ndarray, y_ids: np.ndarray, n_classes: int,
                         config: GBTConfig) -> SoftmaxForest:
    """Multiclass boosting: each round trains one tree per class against the
    softmax gradients ``g_k = p_k - 1[y=k]``, ``h_k = p_k (1 - p_k)``; class
    margins start at the log-priors."""
    n, d = X.shape
    binned_host, edges = bin_features(X, config.max_bins)
    binned = jnp.asarray(binned_host)
    n_nodes_total = 2 ** (config.max_depth + 1) - 1
    T, K = config.num_trees, n_classes

    features = np.full((T, K, n_nodes_total), -1, np.int32)
    thresholds = np.zeros((T, K, n_nodes_total), np.int32)
    values = np.zeros((T, K, n_nodes_total), np.float32)

    priors = np.bincount(y_ids, minlength=K) / max(n, 1)
    base_scores = np.log(np.clip(priors, 1e-6, None))
    margins = np.tile(base_scores, (n, 1))
    onehot = (y_ids[:, None] == np.arange(K)[None, :]).astype(np.float64)

    for t in range(T):
        p = _softmax_rows(margins)
        for k in range(K):
            g = p[:, k] - onehot[:, k]
            h = np.maximum(p[:, k] * (1.0 - p[:, k]), 1e-12)
            (features[t, k], thresholds[t, k], values[t, k],
             tree_pred) = _train_one_tree(
                binned, jnp.asarray(g, jnp.float32),
                jnp.asarray(h, jnp.float32), d, config)
            margins[:, k] += config.learning_rate * np.asarray(tree_pred,
                                                               np.float64)

    return SoftmaxForest(features, thresholds, values, edges, base_scores,
                         config.learning_rate)


def predict_forest_softmax(X: np.ndarray, forest: SoftmaxForest) -> np.ndarray:
    """Per-class margins (n, K).  Rows zero-pad to the shared power-of-two
    bucket (``utils/padding.py``) so mixed batch sizes reuse one compiled
    tree-walk per bucket; routing is per-row, pad rows slice off."""
    from ...utils.padding import pad_rows_to_bucket

    binned = apply_bins(X, forest.bin_edges)
    (binned,), n = pad_rows_to_bucket((binned,))
    depth = int(np.log2(forest.feature.shape[2] + 1)) - 1
    margins = np.tile(forest.base_scores, (binned.shape[0], 1))
    binned_dev = jnp.asarray(binned)
    for t in range(forest.feature.shape[0]):
        for k in range(forest.n_classes):
            margins[:, k] += forest.learning_rate * np.asarray(
                _predict_tree_jit(binned_dev,
                                  jnp.asarray(forest.feature[t, k]),
                                  jnp.asarray(forest.threshold[t, k]),
                                  jnp.asarray(forest.value[t, k]), depth),
                np.float64)
    return margins[:n]


def _predict_tree(binned: np.ndarray, feature: np.ndarray,
                  threshold: np.ndarray, value: np.ndarray,
                  depth: int) -> np.ndarray:
    return np.asarray(_predict_tree_jit(
        jnp.asarray(binned), jnp.asarray(feature), jnp.asarray(threshold),
        jnp.asarray(value), depth))


@partial(aot_jit, static_argnames=("depth",))
def _predict_tree_jit(binned, feature, threshold, value, depth: int):
    n = binned.shape[0]
    node = jnp.zeros((n,), jnp.int32)       # global complete-tree index
    out = jnp.zeros((n,), jnp.float32)
    settled = jnp.zeros((n,), bool)
    for _ in range(depth + 1):
        feat = feature[node]
        is_leaf = feat < 0
        newly = is_leaf & ~settled
        out = jnp.where(newly, value[node], out)
        settled = settled | is_leaf
        row_bin = jnp.take_along_axis(binned, jnp.maximum(feat, 0)[:, None],
                                      1)[:, 0]
        child = 2 * node + 1 + (row_bin > threshold[node]).astype(jnp.int32)
        node = jnp.where(settled, node, jnp.minimum(child,
                                                    feature.shape[0] - 1))
    return out


def predict_forest(X: np.ndarray, forest: Forest) -> np.ndarray:
    """Sum of tree outputs, margin scale.  Rows zero-pad to the shared
    power-of-two bucket (``utils/padding.py``): one compiled tree-walk per
    bucket serves every batch size, pad rows slice off."""
    from ...utils.padding import pad_rows_to_bucket

    binned = apply_bins(X, forest.bin_edges)
    (binned,), n = pad_rows_to_bucket((binned,))
    depth = int(np.log2(forest.feature.shape[1] + 1)) - 1
    pred = np.full((binned.shape[0],), forest.base_score, np.float64)
    for t in range(forest.feature.shape[0]):
        pred += forest.learning_rate * _predict_tree(
            binned, forest.feature[t], forest.threshold[t],
            forest.value[t], depth)
    return pred[:n]


# ---------------------------------------------------------------------------
# kernel-registry entries: op ``gbt_level_histograms``.  The MXU form is
# the TPU default (PR 10 hot path: histogramming as one-hot systolic
# matmuls instead of segment_sum's per-element random accumulation —
# the decision-forest-literature TPU-histogram trick); segsum stays the
# registered XLA fallback and the forced oracle.  Both are exact up to
# f32 summation order, feeding the streamed histogram carry unchanged
# (accumulation over batches is a plain add either way).
# ---------------------------------------------------------------------------

def _register_gbt_kernels() -> None:
    from ...kernels.registry import register_kernel, tpu_only

    register_kernel("gbt_level_histograms", "mxu", _level_histograms_mxu,
                    priority=10, available=tpu_only)
    register_kernel("gbt_level_histograms", "xla", _level_histograms_segsum)


_register_gbt_kernels()
