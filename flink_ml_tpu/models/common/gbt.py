"""Histogram-based gradient-boosted trees — the shared trainer.

Member of the later Flink ML 2.x library line (GBTClassifier/GBTRegressor).
CPU GBT implementations walk rows per node; the TPU-native formulation is
the histogram method with everything vectorized over rows:

- **Binning** (host, once): per-feature quantile bins -> int32 bin ids.
- **Histograms** (device): per level, one ``segment_sum`` over the flattened
  ``(node, feature, bin)`` key accumulates (grad, hess, count) for ALL nodes
  and features at once — the analog of the keyed shuffle+reduce a dataflow
  engine would run, fused on-chip.
- **Split finding** (device): cumulative sums over bins give every candidate
  split's left/right (G, H); the XGBoost gain
  ``G_L^2/(H_L+l) + G_R^2/(H_R+l) - G^2/(H+l)`` is argmaxed per node.
- **Routing** (device): rows step to ``2*node+1 (+1)`` by comparing their
  bin to the split threshold — no gather-scatter trees, just arrays.

Trees are complete binary arrays (node i's children are 2i+1/2i+2), so one
jitted ``build_level`` per depth serves every tree; the boosting loop runs
hosted (each tree depends on the previous residuals).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Callable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["GBTConfig", "bin_features", "train_forest", "predict_forest",
           "Forest", "SoftmaxForest", "train_forest_softmax",
           "predict_forest_softmax"]


@dataclass
class GBTConfig:
    num_trees: int = 20
    max_depth: int = 4            # levels of internal nodes
    learning_rate: float = 0.1
    max_bins: int = 64
    reg_lambda: float = 1.0
    min_child_weight: float = 1e-3


@dataclass
class Forest:
    """(trees, nodes) arrays; node i's children are 2i+1 / 2i+2."""

    feature: np.ndarray       # (T, n_nodes) int32, -1 for leaf
    threshold: np.ndarray     # (T, n_nodes) int32 bin id: go left if <= thr
    value: np.ndarray         # (T, n_nodes) f32 leaf value
    bin_edges: np.ndarray     # (d, max_bins - 1) f64 quantile edges
    base_score: float
    learning_rate: float


def bin_features(X: np.ndarray, max_bins: int) -> Tuple[np.ndarray, np.ndarray]:
    """Quantile binning on host: (binned int32 (n, d), edges (d, bins-1))."""
    n, d = X.shape
    edges = np.empty((d, max_bins - 1))
    binned = np.empty((n, d), np.int32)
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    for j in range(d):
        e = np.quantile(X[:, j], qs)
        # strictly increasing edges (duplicates collapse constant regions)
        edges[j] = e
        binned[:, j] = np.searchsorted(e, X[:, j], side="left")
    return binned, edges


def apply_bins(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    binned = np.empty(X.shape, np.int32)
    for j in range(X.shape[1]):
        binned[:, j] = np.searchsorted(edges[j], X[:, j], side="left")
    return binned


@partial(jax.jit, static_argnames=("n_nodes", "d", "bins", "reg_lambda",
                                   "min_child_weight"))
def _build_level(binned, node_ids, grad, hess, n_nodes: int,
                 d: int, bins: int, reg_lambda: float,
                 min_child_weight: float):
    """One tree level for all ``n_nodes`` nodes at once.

    Returns (feature (n_nodes,), threshold (n_nodes,), gain (n_nodes,),
    new_node_ids (n,)).  ``node_ids`` are level-local in [0, n_nodes) with
    -1 marking rows already settled in a leaf.
    """
    n = binned.shape[0]
    live = node_ids >= 0
    safe_node = jnp.where(live, node_ids, 0)

    # (node, feature, bin) -> flat key; dead rows land in a scratch key 0
    # with zero weights
    keys = (safe_node[:, None] * (d * bins)
            + jnp.arange(d, dtype=jnp.int32)[None, :] * bins
            + binned)                                           # (n, d)
    w = live.astype(grad.dtype)
    seg = n_nodes * d * bins
    flat = keys.reshape(-1)
    g_hist = jax.ops.segment_sum((grad * w)[:, None].repeat(d, 1).reshape(-1),
                                 flat, seg)
    h_hist = jax.ops.segment_sum((hess * w)[:, None].repeat(d, 1).reshape(-1),
                                 flat, seg)
    g_hist = g_hist.reshape(n_nodes, d, bins)
    h_hist = h_hist.reshape(n_nodes, d, bins)

    g_tot = jnp.sum(g_hist, axis=(1, 2)) / d                    # per node
    h_tot = jnp.sum(h_hist, axis=(1, 2)) / d

    # candidate split at bin b: left = bins <= b (cumsum), right = rest
    g_left = jnp.cumsum(g_hist, axis=2)
    h_left = jnp.cumsum(h_hist, axis=2)
    g_right = g_tot[:, None, None] - g_left
    h_right = h_tot[:, None, None] - h_left

    def score(g, h):
        return g * g / (h + reg_lambda)

    gain = (score(g_left, h_left) + score(g_right, h_right)
            - score(g_tot, h_tot)[:, None, None])               # (nodes,d,bins)
    viable = ((h_left >= min_child_weight)
              & (h_right >= min_child_weight))
    gain = jnp.where(viable, gain, -jnp.inf)
    # never split on the last bin (empty right side by construction)
    gain = gain.at[:, :, -1].set(-jnp.inf)

    flat_gain = gain.reshape(n_nodes, d * bins)
    best = jnp.argmax(flat_gain, axis=1)
    best_gain = jnp.take_along_axis(flat_gain, best[:, None], 1)[:, 0]
    best_feature = (best // bins).astype(jnp.int32)
    best_bin = (best % bins).astype(jnp.int32)

    # route rows: live rows whose node split go to 2*node (+1 for right) in
    # the next level's local numbering
    row_bin = jnp.take_along_axis(binned, best_feature[safe_node][:, None],
                                  1)[:, 0]
    goes_right = row_bin > best_bin[safe_node]
    node_split = best_gain[safe_node] > 0
    new_ids = jnp.where(live & node_split,
                        2 * safe_node + goes_right.astype(jnp.int32), -1)
    return best_feature, best_bin, best_gain, new_ids


@partial(jax.jit, static_argnames=("n_nodes", "reg_lambda"))
def _leaf_values(node_ids, grad, hess, n_nodes: int, reg_lambda: float):
    """Newton leaf weights -G/(H+lambda) for every level-local node."""
    live = node_ids >= 0
    safe = jnp.where(live, node_ids, 0)
    w = live.astype(grad.dtype)
    g = jax.ops.segment_sum(grad * w, safe, n_nodes)
    h = jax.ops.segment_sum(hess * w, safe, n_nodes)
    return -g / (h + reg_lambda)


def _train_one_tree(binned, g, h, d: int, config: GBTConfig):
    """Grow one tree against device gradients/hessians; returns the host
    (feature, threshold, value) node rows plus the tree's DEVICE in-sample
    prediction (margin scale, before learning-rate shrinkage)."""
    n = binned.shape[0]
    bins = config.max_bins
    depth = config.max_depth
    n_nodes_total = 2 ** (depth + 1) - 1
    feature_row = np.full((n_nodes_total,), -1, np.int32)
    threshold_row = np.zeros((n_nodes_total,), np.int32)
    value_row = np.zeros((n_nodes_total,), np.float32)

    node_ids = jnp.zeros((n,), jnp.int32)
    level_feature: List[np.ndarray] = []
    level_bin: List[np.ndarray] = []
    level_gain: List[np.ndarray] = []
    level_ids = [node_ids]
    for level in range(depth):
        n_nodes = 2 ** level
        f, b, gain, node_ids = _build_level(
            binned, node_ids, g, h, n_nodes, d, bins,
            config.reg_lambda, config.min_child_weight)
        level_feature.append(np.asarray(f))
        level_bin.append(np.asarray(b))
        level_gain.append(np.asarray(gain))
        level_ids.append(node_ids)

    # assemble the tree: internal nodes that actually split get
    # (feature, threshold); everything else becomes a leaf holding the
    # Newton value of the rows that stopped there
    base = 0
    for level in range(depth):
        n_nodes = 2 ** level
        split = level_gain[level] > 0
        feature_row[base:base + n_nodes] = np.where(
            split, level_feature[level], -1)
        threshold_row[base:base + n_nodes] = level_bin[level]
        # leaf value for rows that STOP at this level (their node did not
        # split): computed from the ids entering the level
        vals = np.asarray(_leaf_values(level_ids[level], g, h, n_nodes,
                                       config.reg_lambda))
        value_row[base:base + n_nodes] = np.where(split, 0.0, vals)
        base += n_nodes
    # deepest level: always leaves
    n_nodes = 2 ** depth
    vals = np.asarray(_leaf_values(level_ids[depth], g, h, n_nodes,
                                   config.reg_lambda))
    value_row[base:base + n_nodes] = vals

    # in-sample update reuses the DEVICE binned copy — predicting from the
    # host matrix would re-upload it once per tree
    pred = _predict_tree_jit(binned, jnp.asarray(feature_row),
                             jnp.asarray(threshold_row),
                             jnp.asarray(value_row), depth)
    return feature_row, threshold_row, value_row, pred


def train_forest(X: np.ndarray, y: np.ndarray,
                 grad_hess: Callable[[np.ndarray, np.ndarray],
                                     Tuple[np.ndarray, np.ndarray]],
                 base_score: float, config: GBTConfig) -> Forest:
    """Boost ``num_trees`` trees against ``grad_hess(y, pred)``."""
    n, d = X.shape
    binned_host, edges = bin_features(X, config.max_bins)
    binned = jnp.asarray(binned_host)
    n_nodes_total = 2 ** (config.max_depth + 1) - 1

    features = np.full((config.num_trees, n_nodes_total), -1, np.int32)
    thresholds = np.zeros((config.num_trees, n_nodes_total), np.int32)
    values = np.zeros((config.num_trees, n_nodes_total), np.float32)

    pred = np.full((n,), base_score, np.float64)
    for t in range(config.num_trees):
        g, h = grad_hess(y, pred)
        features[t], thresholds[t], values[t], tree_pred = _train_one_tree(
            binned, jnp.asarray(g, jnp.float32), jnp.asarray(h, jnp.float32),
            d, config)
        pred = pred + config.learning_rate * np.asarray(tree_pred, np.float64)

    return Forest(features, thresholds, values, edges, base_score,
                  config.learning_rate)


@dataclass
class SoftmaxForest:
    """K-class boosted forest: ``num_trees`` rounds x ``n_classes`` trees
    (the standard softmax objective — one tree per class per round, the
    XGBoost ``multi:softmax`` formulation)."""

    feature: np.ndarray       # (T, K, n_nodes) int32, -1 for leaf
    threshold: np.ndarray     # (T, K, n_nodes) int32
    value: np.ndarray         # (T, K, n_nodes) f32
    bin_edges: np.ndarray     # (d, max_bins - 1) f64
    base_scores: np.ndarray   # (K,) f64 log-priors
    learning_rate: float

    @property
    def n_classes(self) -> int:
        return self.feature.shape[1]


def _softmax_rows(m: np.ndarray) -> np.ndarray:
    e = np.exp(m - m.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


def train_forest_softmax(X: np.ndarray, y_ids: np.ndarray, n_classes: int,
                         config: GBTConfig) -> SoftmaxForest:
    """Multiclass boosting: each round trains one tree per class against the
    softmax gradients ``g_k = p_k - 1[y=k]``, ``h_k = p_k (1 - p_k)``; class
    margins start at the log-priors."""
    n, d = X.shape
    binned_host, edges = bin_features(X, config.max_bins)
    binned = jnp.asarray(binned_host)
    n_nodes_total = 2 ** (config.max_depth + 1) - 1
    T, K = config.num_trees, n_classes

    features = np.full((T, K, n_nodes_total), -1, np.int32)
    thresholds = np.zeros((T, K, n_nodes_total), np.int32)
    values = np.zeros((T, K, n_nodes_total), np.float32)

    priors = np.bincount(y_ids, minlength=K) / max(n, 1)
    base_scores = np.log(np.clip(priors, 1e-6, None))
    margins = np.tile(base_scores, (n, 1))
    onehot = (y_ids[:, None] == np.arange(K)[None, :]).astype(np.float64)

    for t in range(T):
        p = _softmax_rows(margins)
        for k in range(K):
            g = p[:, k] - onehot[:, k]
            h = np.maximum(p[:, k] * (1.0 - p[:, k]), 1e-12)
            (features[t, k], thresholds[t, k], values[t, k],
             tree_pred) = _train_one_tree(
                binned, jnp.asarray(g, jnp.float32),
                jnp.asarray(h, jnp.float32), d, config)
            margins[:, k] += config.learning_rate * np.asarray(tree_pred,
                                                               np.float64)

    return SoftmaxForest(features, thresholds, values, edges, base_scores,
                         config.learning_rate)


def predict_forest_softmax(X: np.ndarray, forest: SoftmaxForest) -> np.ndarray:
    """Per-class margins (n, K)."""
    binned = apply_bins(X, forest.bin_edges)
    depth = int(np.log2(forest.feature.shape[2] + 1)) - 1
    margins = np.tile(forest.base_scores, (len(X), 1))
    binned_dev = jnp.asarray(binned)
    for t in range(forest.feature.shape[0]):
        for k in range(forest.n_classes):
            margins[:, k] += forest.learning_rate * np.asarray(
                _predict_tree_jit(binned_dev,
                                  jnp.asarray(forest.feature[t, k]),
                                  jnp.asarray(forest.threshold[t, k]),
                                  jnp.asarray(forest.value[t, k]), depth),
                np.float64)
    return margins


def _predict_tree(binned: np.ndarray, feature: np.ndarray,
                  threshold: np.ndarray, value: np.ndarray,
                  depth: int) -> np.ndarray:
    return np.asarray(_predict_tree_jit(
        jnp.asarray(binned), jnp.asarray(feature), jnp.asarray(threshold),
        jnp.asarray(value), depth))


@partial(jax.jit, static_argnames=("depth",))
def _predict_tree_jit(binned, feature, threshold, value, depth: int):
    n = binned.shape[0]
    node = jnp.zeros((n,), jnp.int32)       # global complete-tree index
    out = jnp.zeros((n,), jnp.float32)
    settled = jnp.zeros((n,), bool)
    for _ in range(depth + 1):
        feat = feature[node]
        is_leaf = feat < 0
        newly = is_leaf & ~settled
        out = jnp.where(newly, value[node], out)
        settled = settled | is_leaf
        row_bin = jnp.take_along_axis(binned, jnp.maximum(feat, 0)[:, None],
                                      1)[:, 0]
        child = 2 * node + 1 + (row_bin > threshold[node]).astype(jnp.int32)
        node = jnp.where(settled, node, jnp.minimum(child,
                                                    feature.shape[0] - 1))
    return out


def predict_forest(X: np.ndarray, forest: Forest) -> np.ndarray:
    """Sum of tree outputs, margin scale."""
    binned = apply_bins(X, forest.bin_edges)
    depth = int(np.log2(forest.feature.shape[1] + 1)) - 1
    pred = np.full((len(X),), forest.base_score, np.float64)
    for t in range(forest.feature.shape[0]):
        pred += forest.learning_rate * _predict_tree(
            binned, forest.feature[t], forest.threshold[t],
            forest.value[t], depth)
    return pred
