"""ClusteringEvaluator — mean silhouette coefficient.

Companion to the classification/regression evaluators (the Flink ML 2.x
evaluation surface).  The silhouette is all-pairs work, which is exactly
what the MXU is for: the (n, n) distance matrix is one pairwise expansion
matmul and the per-cluster mean distances are one ``D @ onehot`` matmul —
the whole metric is a single jitted program, no per-point host loops.

s(i) = (b_i - a_i) / max(a_i, b_i) with
    a_i = mean distance to OWN cluster (excluding self)
    b_i = min over other clusters of mean distance to that cluster;
singleton clusters score 0 by convention (sklearn's rule).
"""

from __future__ import annotations

from functools import partial
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import AlgoOperator
from ...data.table import Table
from ...distance import DistanceMeasure
from ...linalg import stack_vectors
from ...params.shared import HasDistanceMeasure, HasFeaturesCol, \
    HasPredictionCol

__all__ = ["ClusteringEvaluator"]


@partial(jax.jit, static_argnums=(0, 3))
def _silhouette(measure: DistanceMeasure, X, labels, k: int):
    D = measure.pairwise(X, X)                       # (n, n)
    onehot = jax.nn.one_hot(labels, k, dtype=X.dtype)  # (n, k)
    counts = jnp.sum(onehot, axis=0)                 # (k,)
    sums = D @ onehot                                # (n, k) dist sums

    own_count = counts[labels]
    # a_i: own-cluster mean excluding self (D[i,i] = 0 contributes nothing)
    a = jnp.take_along_axis(sums, labels[:, None], 1)[:, 0] \
        / jnp.maximum(own_count - 1.0, 1.0)
    # b_i: min mean distance over OTHER non-empty clusters
    means = sums / jnp.maximum(counts, 1.0)[None, :]
    own_or_empty = (jax.nn.one_hot(labels, k, dtype=bool)
                    | (counts[None, :] == 0))
    b = jnp.min(jnp.where(own_or_empty, jnp.inf, means), axis=1)

    s = (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-12)
    s = jnp.where(own_count > 1, s, 0.0)             # singleton convention
    s = jnp.where(jnp.isfinite(s), s, 0.0)           # all-in-one-cluster
    return jnp.mean(s)


class ClusteringEvaluator(HasDistanceMeasure, HasFeaturesCol,
                          HasPredictionCol, AlgoOperator):
    """transform(table with features + cluster predictions) -> one-row Table
    with the mean silhouette."""

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float32)
        labels_raw = np.asarray(table[self.get_prediction_col()])
        if len(X) != len(labels_raw):
            raise ValueError("features/prediction length mismatch")
        if len(X) < 2:
            raise ValueError("silhouette needs at least 2 rows")
        uniq, labels = np.unique(labels_raw, return_inverse=True)
        measure = DistanceMeasure.get_instance(self.get_distance_measure())
        value = float(_silhouette(measure, jnp.asarray(X),
                                  jnp.asarray(labels, jnp.int32),
                                  int(len(uniq))))
        return [Table({"silhouette": np.asarray([value])})]
