from .binary_evaluator import BinaryClassificationEvaluator  # noqa: F401
from .clustering_evaluator import ClusteringEvaluator  # noqa: F401
from .multiclass_evaluator import (  # noqa: F401
    MulticlassClassificationEvaluator,
)
from .ranking_evaluator import RankingEvaluator  # noqa: F401
from .regression_evaluator import RegressionEvaluator  # noqa: F401
