from .binary_evaluator import BinaryClassificationEvaluator  # noqa: F401
