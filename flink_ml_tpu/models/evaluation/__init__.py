from .binary_evaluator import BinaryClassificationEvaluator  # noqa: F401
from .multiclass_evaluator import (  # noqa: F401
    MulticlassClassificationEvaluator,
)
