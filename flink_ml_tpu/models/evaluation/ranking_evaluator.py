"""RankingEvaluator — top-k recommendation quality metrics.

Rounds out the evaluation family for the recommenders (ALS top-k scoring,
Swing similar-item lists): precision@k, recall@k, hitRate@k, NDCG@k and
MAP@k over per-row (ranked predictions, relevant items) pairs.  The
reference family ships no ranking evaluator; the metric definitions
follow the standard IR formulations (binary relevance, log2 discount,
ideal-DCG normalisation per row).

Inputs are object-array columns: ``predictionCol`` holds each row's
RANKED recommendation list, ``labelCol`` the row's set of relevant items.
Rows with no relevant items are skipped (undefined metrics).  Per-row
work is tiny ragged set arithmetic — a host loop, as with the other
evaluators' host-side finishing.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ...api.stage import AlgoOperator
from ...data.table import Table
from ...params.param import IntParam, ParamValidators, StringArrayParam
from ...params.shared import HasLabelCol, HasPredictionCol

__all__ = ["RankingEvaluator"]

_ALL_METRICS = ("precisionAtK", "recallAtK", "hitRateAtK", "ndcgAtK",
                "mapAtK")


def _item_list(cell) -> list:
    """Normalise one ragged cell into a list of items (None/NaN cells and
    entries mean 'nothing here')."""
    if cell is None:
        return []
    items = np.ravel(np.asarray(cell, dtype=object)).tolist()
    return [x for x in items
            if x is not None and not (isinstance(x, float) and np.isnan(x))]


class RankingEvaluator(HasPredictionCol, HasLabelCol, AlgoOperator):
    K = IntParam("k", "Ranking cutoff.", default=10,
                 validator=ParamValidators.gt(0))
    # param name matches the sibling evaluators' "metricsNames" so generic
    # param tooling treats the family uniformly
    METRICS = StringArrayParam(
        "metricsNames", "Subset of " + ", ".join(_ALL_METRICS) + ".",
        default=_ALL_METRICS,
        validator=lambda vals: vals is not None and len(vals) > 0
        and all(v in _ALL_METRICS for v in vals))

    def get_k(self) -> int:
        return self.get(RankingEvaluator.K)

    def set_k(self, value: int):
        return self.set(RankingEvaluator.K, value)

    def get_metrics(self):
        return self.get(RankingEvaluator.METRICS)

    def set_metrics(self, *names: str):
        return self.set(RankingEvaluator.METRICS, names)

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        preds = table[self.get_prediction_col()]
        labels = table[self.get_label_col()]
        k = self.get_k()
        # row-invariant discount machinery, hoisted out of the row loop
        discounts = 1.0 / np.log2(np.arange(2, k + 2))
        idcg_cum = np.cumsum(discounts)

        per_row = {m: [] for m in _ALL_METRICS}
        for pred, rel in zip(preds, labels):
            relevant = set(_item_list(rel))
            if not relevant:
                continue   # undefined: no relevant items for this row
            # dedupe, keeping rank order: a repeated item must not count
            # as several hits (it would push recall/MAP/NDCG past 1.0)
            ranked = list(dict.fromkeys(_item_list(pred)))[:k]
            hits = np.asarray([item in relevant for item in ranked], bool)
            n_hits = int(hits.sum())

            per_row["precisionAtK"].append(n_hits / k)
            per_row["recallAtK"].append(n_hits / len(relevant))
            per_row["hitRateAtK"].append(1.0 if n_hits else 0.0)

            # NDCG@k: binary gains, log2(position + 1) discount, ideal =
            # all relevant items packed at the top
            dcg = float((hits * discounts[: len(ranked)]).sum())
            idcg = float(idcg_cum[min(len(relevant), k) - 1])
            per_row["ndcgAtK"].append(dcg / idcg if idcg > 0 else 0.0)

            # MAP@k: mean over min(|relevant|, k) of precision at each hit
            if n_hits:
                ranks = np.flatnonzero(hits) + 1
                prec_at_hits = np.arange(1, n_hits + 1) / ranks
                per_row["mapAtK"].append(
                    float(prec_at_hits.sum()) / min(len(relevant), k))
            else:
                per_row["mapAtK"].append(0.0)

        if not per_row["precisionAtK"]:
            raise ValueError(
                "RankingEvaluator got no rows with relevant items")
        return [Table({m: np.asarray([float(np.mean(per_row[m]))])
                       for m in self.get_metrics()})]
