"""BinaryClassificationEvaluator — AUC-ROC / AUC-PR / accuracy as an
AlgoOperator (evaluation is a table -> metrics-table mapping, the Flink ML
evaluator shape).  The ROC integral is computed on device: one sort + two
cumulative sums."""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import AlgoOperator
from ...data.table import Table
from ...params.param import StringArrayParam
from ...params.shared import HasLabelCol, HasRawPredictionCol

__all__ = ["BinaryClassificationEvaluator"]

_SUPPORTED = ("areaUnderROC", "areaUnderPR", "accuracy")


@jax.jit
def _binary_metrics(scores, labels):
    order = jnp.argsort(-scores)
    s_sorted_neg = (-scores)[order]            # ascending in -score = desc
    y = labels[order]
    pos = jnp.sum(y)
    neg = y.shape[0] - pos
    tp = jnp.cumsum(y)
    fp = jnp.cumsum(1.0 - y)
    # Tied scores form ONE ROC/PR point: replace each row's counts with the
    # counts at the END of its tie group (rightmost equal score).  Diffs
    # within a group then vanish, so the integrals collapse to the group
    # boundaries — exact tie handling with static shapes.
    group_end = jnp.searchsorted(s_sorted_neg, s_sorted_neg,
                                 side="right") - 1
    tp_g = tp[group_end]
    fp_g = fp[group_end]
    tpr = tp_g / jnp.maximum(pos, 1.0)
    fpr = fp_g / jnp.maximum(neg, 1.0)
    precision = tp_g / jnp.maximum(tp_g + fp_g, 1.0)
    tpr_prev = jnp.concatenate([jnp.zeros(1), tpr[:-1]])
    fpr_prev = jnp.concatenate([jnp.zeros(1), fpr[:-1]])
    auc_roc = jnp.sum((fpr - fpr_prev) * (tpr + tpr_prev) / 2)
    auc_pr = jnp.sum((tpr - tpr_prev) * precision)
    accuracy = jnp.mean((scores > 0.5) == (labels > 0.5))
    return auc_roc, auc_pr, accuracy


class BinaryClassificationEvaluator(HasLabelCol, HasRawPredictionCol,
                                    AlgoOperator):
    METRICS = StringArrayParam(
        "metricsNames", "Metrics to compute.",
        default=("areaUnderROC", "areaUnderPR"),
        validator=lambda v: v is not None and all(m in _SUPPORTED for m in v))

    def set_metrics(self, *names: str):
        return self.set(BinaryClassificationEvaluator.METRICS, names)

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        scores = np.asarray(table[self.get_raw_prediction_col()], np.float32)
        labels = np.asarray(table[self.get_label_col()], np.float32)
        if scores.ndim != 1:
            raise ValueError("rawPrediction column must be scalar scores")
        auc_roc, auc_pr, acc = (float(x) for x in
                                _binary_metrics(jnp.asarray(scores),
                                                jnp.asarray(labels)))
        values = {"areaUnderROC": auc_roc, "areaUnderPR": auc_pr,
                  "accuracy": acc}
        names = self.get(BinaryClassificationEvaluator.METRICS)
        return [Table({name: np.asarray([values[name]]) for name in names})]
