"""BinaryClassificationEvaluator — AUC-ROC / AUC-PR / accuracy as an
AlgoOperator (evaluation is a table -> metrics-table mapping, the Flink ML
evaluator shape).  The ROC integral is computed on device: one sort + two
cumulative sums."""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import AlgoOperator
from ...data.table import Table
from ...params.param import StringArrayParam
from ...params.shared import HasLabelCol, HasRawPredictionCol

__all__ = ["BinaryClassificationEvaluator"]

_SUPPORTED = ("areaUnderROC", "areaUnderPR", "accuracy")


@jax.jit
def _binary_metrics(scores, labels):
    order = jnp.argsort(-scores)  # descending by score
    y = labels[order]
    pos = jnp.sum(y)
    neg = y.shape[0] - pos
    tp = jnp.cumsum(y)
    fp = jnp.cumsum(1.0 - y)
    tpr = tp / jnp.maximum(pos, 1.0)
    fpr = fp / jnp.maximum(neg, 1.0)
    precision = tp / jnp.maximum(tp + fp, 1.0)
    # trapezoidal AUCs with the (0,0) origin prepended
    auc_roc = jnp.sum((fpr - jnp.concatenate([jnp.zeros(1), fpr[:-1]]))
                      * (tpr + jnp.concatenate([jnp.zeros(1), tpr[:-1]])) / 2)
    auc_pr = jnp.sum((tpr - jnp.concatenate([jnp.zeros(1), tpr[:-1]]))
                     * precision)
    accuracy = jnp.mean((scores > 0.5) == (labels > 0.5))
    return auc_roc, auc_pr, accuracy


class BinaryClassificationEvaluator(HasLabelCol, HasRawPredictionCol,
                                    AlgoOperator):
    METRICS = StringArrayParam(
        "metricsNames", "Metrics to compute.",
        default=("areaUnderROC", "areaUnderPR"),
        validator=lambda v: v is not None and all(m in _SUPPORTED for m in v))

    def set_metrics(self, *names: str):
        return self.set(BinaryClassificationEvaluator.METRICS, names)

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        scores = np.asarray(table[self.get_raw_prediction_col()], np.float32)
        labels = np.asarray(table[self.get_label_col()], np.float32)
        if scores.ndim != 1:
            raise ValueError("rawPrediction column must be scalar scores")
        auc_roc, auc_pr, acc = (float(x) for x in
                                _binary_metrics(jnp.asarray(scores),
                                                jnp.asarray(labels)))
        values = {"areaUnderROC": auc_roc, "areaUnderPR": auc_pr,
                  "accuracy": acc}
        names = self.get(BinaryClassificationEvaluator.METRICS)
        return [Table({name: np.asarray([values[name]]) for name in names})]
