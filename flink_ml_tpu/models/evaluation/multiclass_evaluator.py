"""MulticlassClassificationEvaluator — accuracy / weighted F-measure.

Companion to the binary evaluator (the Flink ML 2.x evaluation surface).
All metrics derive from the (classes, classes) confusion matrix, computed
with one host ``np.bincount`` over the joint (true, predicted) key — exact
integer counts at any n (a one-hot f32 matmul loses exactness past 2^24
rows per cell and materializes O(n*classes) memory for no device win).
"""

from __future__ import annotations

from typing import List

import numpy as np

from ...api.stage import AlgoOperator
from ...data.table import Table
from ...params.param import StringArrayParam
from ...params.shared import HasLabelCol, HasPredictionCol

__all__ = ["MulticlassClassificationEvaluator"]

_SUPPORTED = ("accuracy", "weightedPrecision", "weightedRecall",
              "weightedFMeasure")


def _metrics(conf: np.ndarray) -> dict:
    total = conf.sum()
    tp = np.diag(conf)
    per_pred = conf.sum(axis=0)             # predicted-count per class
    per_true = conf.sum(axis=1)             # support per class
    precision = np.where(per_pred > 0, tp / np.maximum(per_pred, 1), 0.0)
    recall = np.where(per_true > 0, tp / np.maximum(per_true, 1), 0.0)
    f1 = np.where(precision + recall > 0,
                  2 * precision * recall
                  / np.maximum(precision + recall, 1e-12), 0.0)
    weights = per_true / max(total, 1)
    return {
        "accuracy": float(tp.sum() / max(total, 1)),
        "weightedPrecision": float((weights * precision).sum()),
        "weightedRecall": float((weights * recall).sum()),
        "weightedFMeasure": float((weights * f1).sum()),
    }


class MulticlassClassificationEvaluator(HasLabelCol, HasPredictionCol,
                                        AlgoOperator):
    METRICS = StringArrayParam(
        "metricsNames", "Metrics to compute.",
        default=("accuracy", "weightedFMeasure"),
        validator=lambda v: v is not None and all(m in _SUPPORTED for m in v))

    def set_metrics(self, *names: str):
        return self.set(MulticlassClassificationEvaluator.METRICS, names)

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        labels = np.asarray(table[self.get_label_col()])
        preds = np.asarray(table[self.get_prediction_col()])
        if len(labels) != len(preds):
            raise ValueError("label/prediction length mismatch")
        # joint class space: predictions outside the label set still count
        classes, _ = np.unique(np.concatenate([labels, preds]),
                               return_inverse=True)
        y = np.searchsorted(classes, labels)
        p = np.searchsorted(classes, preds)
        c = len(classes)
        conf = np.bincount(y * c + p, minlength=c * c).reshape(c, c)
        conf = conf.astype(np.float64)      # [true, predicted]
        values = _metrics(conf)
        names = self.get(MulticlassClassificationEvaluator.METRICS)
        return [Table({name: np.asarray([values[name]]) for name in names})]
