"""RegressionEvaluator — RMSE / MSE / MAE / R².

Companion to the binary/multiclass evaluators (the Flink ML 2.x evaluation
surface).  All metrics are one host float64 pass over (label, prediction) —
exact accumulation; a device f32 sum loses precision on the squared-error
scale long before the transfer cost is repaid.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ...api.stage import AlgoOperator
from ...data.table import Table
from ...params.param import StringArrayParam
from ...params.shared import HasLabelCol, HasPredictionCol, HasWeightCol

__all__ = ["RegressionEvaluator"]

_SUPPORTED = ("rmse", "mse", "mae", "r2")


class RegressionEvaluator(HasLabelCol, HasPredictionCol, HasWeightCol,
                          AlgoOperator):
    """transform(table) -> one Table row with the requested metrics.
    Weighted variants use the weight column when set (weighted means in
    every formula; R² uses the weighted label mean)."""

    METRICS = StringArrayParam(
        "metricsNames", "Metrics to compute.",
        default=("rmse", "r2"),
        validator=lambda v: v is not None and all(m in _SUPPORTED for m in v))

    def set_metrics(self, *names: str):
        return self.set(RegressionEvaluator.METRICS, names)

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        y = np.asarray(table[self.get_label_col()], np.float64)
        pred = np.asarray(table[self.get_prediction_col()], np.float64)
        if len(y) != len(pred):
            raise ValueError("label/prediction length mismatch")
        if len(y) == 0:
            raise ValueError("RegressionEvaluator needs at least one row")
        wcol = self.get_weight_col()
        w = (np.asarray(table[wcol], np.float64) if wcol
             else np.ones_like(y))
        wsum = w.sum()
        if wsum <= 0:
            raise ValueError("weights sum to zero")

        err = pred - y
        mse = float((w * err * err).sum() / wsum)
        mae = float((w * np.abs(err)).sum() / wsum)
        y_mean = (w * y).sum() / wsum
        ss_tot = float((w * (y - y_mean) ** 2).sum())
        ss_res = float((w * err * err).sum())
        # all-constant labels: perfect fit -> 1, anything else -> 0 (the
        # degenerate-variance convention)
        r2 = (1.0 - ss_res / ss_tot if ss_tot > 0
              else (1.0 if ss_res == 0 else 0.0))

        values = {"mse": mse, "rmse": float(np.sqrt(mse)), "mae": mae,
                  "r2": r2}
        names = self.get(RegressionEvaluator.METRICS)
        return [Table({name: np.asarray([values[name]]) for name in names})]
