"""AgglomerativeClustering — hierarchical clustering as an AlgoOperator.

Member of the Flink ML 2.x clustering surface (the reference snapshot ships
only KMeans).  Like its Flink ML counterpart it is an **AlgoOperator**, not
an Estimator: there is no model to fit — ``transform`` clusters the input
table directly.

Work split: hierarchical clustering is a small-n algorithm (the matrix is
n^2; the row guard enforces it), and its merge ordering is precision-
critical — so BOTH the pairwise matrix and the inherently-serial
Lance-Williams merge loop run on host in float64
(``DistanceMeasure.pairwise_host64``; the f32 device expansion cancels
catastrophically for data far from the origin).  The guard keeps the host
O(n^2 d) BLAS cost trivial; pre-cluster with KMeans to scale beyond it.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ...api.stage import AlgoOperator
from ...data.table import Table
from ...distance import DistanceMeasure
from ...linalg import stack_vectors
from ...params.param import IntParam, ParamValidators, StringParam
from ...params.shared import HasDistanceMeasure, HasFeaturesCol, HasPredictionCol

__all__ = ["AgglomerativeClustering"]

_MAX_ROWS = 20_000

# Lance-Williams coefficients: d(i∪j, k) = a_i d(i,k) + a_j d(j,k)
# + b d(i,j) + g |d(i,k) - d(j,k)|
_LINKAGES = ("average", "complete", "single", "ward")


class AgglomerativeClustering(HasDistanceMeasure, HasFeaturesCol,
                              HasPredictionCol, AlgoOperator):
    NUM_CLUSTERS = IntParam("numClusters", "Target number of clusters.",
                            default=2, validator=ParamValidators.gt_eq(1))
    LINKAGE = StringParam("linkage", "Cluster-distance criterion.",
                          default="ward",
                          validator=ParamValidators.in_array(_LINKAGES))

    def get_num_clusters(self) -> int:
        return self.get(AgglomerativeClustering.NUM_CLUSTERS)

    def set_num_clusters(self, value: int):
        return self.set(AgglomerativeClustering.NUM_CLUSTERS, value)

    def get_linkage(self) -> str:
        return self.get(AgglomerativeClustering.LINKAGE)

    def set_linkage(self, value: str):
        return self.set(AgglomerativeClustering.LINKAGE, value)

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        n = len(X)
        if n > _MAX_ROWS:
            raise ValueError(
                f"AgglomerativeClustering is O(n^2) in memory; {n} rows "
                f"exceeds the {_MAX_ROWS}-row guard — pre-cluster with "
                "KMeans or sample")
        k = self.get_num_clusters()
        if n == 0:
            return [table.with_column(self.get_prediction_col(),
                                      np.zeros((0,), np.int64))]
        if k > n:
            raise ValueError(f"numClusters={k} exceeds the {n} input rows")
        linkage = self.get_linkage()
        measure = DistanceMeasure.get_instance(self.get_distance_measure())
        if linkage == "ward" and measure.name != "euclidean":
            raise ValueError("ward linkage requires the euclidean measure")

        # The pairwise matrix is computed on HOST in float64: the merge
        # ordering is precision-critical, and the f32 device expansion
        # catastrophically cancels for data far from the origin (verified:
        # blobs at coords ~1000 collapse 55% of within-blob distances to 0).
        # n is guard-capped, so the host O(n^2 d) BLAS call is cheap.
        D = measure.pairwise_host64(X, X)
        if linkage == "ward":
            D = D * D  # ward's Lance-Williams runs on squared euclidean

        labels = _merge_loop(D, max(k, 1), linkage)
        return [table.with_column(self.get_prediction_col(), labels)]


def _merge_loop(D: np.ndarray, k: int, linkage: str) -> np.ndarray:
    """Sequential agglomeration with Lance-Williams distance updates and a
    per-row nearest-neighbour index, so each merge costs O(n) typical (full
    n^2 argmin per merge would make the loop O(n^3) scans).  Returns dense
    labels 0..k-1, numbered by each cluster's smallest row index."""
    n = D.shape[0]
    D = D.copy()
    np.fill_diagonal(D, np.inf)
    active = np.ones(n, bool)
    size = np.ones(n)
    parent = np.arange(n)
    nn_dist = D.min(axis=1)
    nn_idx = D.argmin(axis=1)

    def find(i):
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    for _ in range(n - k):
        cand = np.where(active, nn_dist, np.inf)
        i = int(np.argmin(cand))
        if not np.isfinite(cand[i]):
            break
        j = int(nn_idx[i])
        if j < i:
            i, j = j, i
        di, dj = D[i], D[j]
        if linkage == "single":
            new = np.minimum(di, dj)
        elif linkage == "complete":
            new = np.maximum(di, dj)
        elif linkage == "average":
            new = (size[i] * di + size[j] * dj) / (size[i] + size[j])
        else:  # ward on squared distances
            sk = size
            tot = size[i] + size[j] + sk
            new = ((size[i] + sk) * di + (size[j] + sk) * dj
                   - sk * D[i, j]) / tot
        new[~active] = np.inf
        new[i] = np.inf
        D[i, :] = new
        D[:, i] = new
        D[j, :] = np.inf
        D[:, j] = np.inf
        active[j] = False
        size[i] += size[j]
        parent[j] = i

        # maintain the NN index: row i changed entirely; any row whose NN
        # was i or j, or that found a closer neighbour in the updated column
        # i, is repaired (rescans are rare in practice -> ~O(n) per merge)
        nn_dist[i] = D[i].min()
        nn_idx[i] = D[i].argmin()
        changed = active.copy()
        changed[i] = False
        closer = changed & (new < nn_dist)
        nn_dist[closer] = new[closer]
        nn_idx[closer] = i
        stale = changed & ~closer & np.isin(nn_idx, (i, j))
        for m in np.nonzero(stale)[0]:
            nn_dist[m] = D[m].min()
            nn_idx[m] = D[m].argmin()

    roots = np.array([find(i) for i in range(n)])
    # every merge keeps the smaller index as the root, so roots sort in
    # first-appearance order and unique's inverse is already the dense label
    return np.unique(roots, return_inverse=True)[1].astype(np.int64)
