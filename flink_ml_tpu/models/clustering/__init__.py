from .kmeans import KMeans, KMeansModel, KMeansModelParams, KMeansParams  # noqa: F401
