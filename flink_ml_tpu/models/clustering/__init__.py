from .agglomerative import AgglomerativeClustering  # noqa: F401
from .kmeans import KMeans, KMeansModel, KMeansModelParams, KMeansParams  # noqa: F401
from .online_kmeans import OnlineKMeans, OnlineKMeansModel  # noqa: F401
