"""KMeans — the framework's vertical slice, TPU-native.

Capability mirror of ``flink-ml-lib/.../clustering/kmeans/KMeans.java:79-337``
+ ``KMeansModel.java:62-214`` + ``KMeansParams.java``/``KMeansModelParams``.

The reference implements one Lloyd's iteration as a dataflow subgraph:
broadcast centroids → two-input cache-and-assign operator
(``KMeans.java:238-315``) → keyed window reduce (``CentroidAccumulator``) →
parallelism-1 window average (``KMeans.java:172-196``) → feedback edge.  On
TPU the same epoch is three fused XLA ops on sharded arrays:

- assign   = pairwise-distance argmin (one MXU matmul via the
             ||x||^2 - 2xc + ||c||^2 expansion)
- reduce   = one-hot^T @ points matmul (MXU) — replaces the keyed shuffle +
             reduce; XLA inserts the psum over the data axis of the mesh
- feedback = centroids stay in HBM between epochs (donated buffers)

and the whole ``maxIter`` loop compiles into a single XLA program
(``iterate`` fused mode) — zero host round-trips, zero network shuffles
inside the iteration body.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator, Model
from ...data.table import Table
from ...distance import DistanceMeasure
from ...iteration import (
    IterationBodyResult,
    IterationConfig,
    Workset,
    iterate,
)
from ...linalg import stack_vectors
from ...params.param import (
    BoolParam,
    IntParam,
    ParamValidators,
    StringParam,
)
from ...params.shared import (
    HasDistanceMeasure,
    HasFeaturesCol,
    HasMaxIter,
    HasPredictionCol,
    HasSeed,
)
from ...parallel.mesh import (
    default_mesh,
    local_axis_multiple,
    fetch_replicated,
    mesh_process_count,
    put_sharded,
    replicate,
)
from ...utils import persist
from ...utils.padding import pad_rows_to_bucket, pad_rows_with_mask

__all__ = ["KMeans", "KMeansModel", "KMeansParams", "KMeansModelParams"]


class KMeansModelParams(HasDistanceMeasure, HasFeaturesCol, HasPredictionCol):
    """``KMeansModelParams.java`` mixin set."""


class KMeansParams(KMeansModelParams, HasSeed, HasMaxIter):
    """``KMeansParams.java``: adds K (>= 2) and the training-only params.

    ``tiePolicy`` (beyond-reference, TPU-specific) picks the Pallas fit
    kernel's handling of EXACTLY-tied point-to-centroid distances:

    - ``"first"`` (default): first-index argmin — EXACTLY the
      reference's and the XLA body's single-assignment Lloyd's
      semantics, ties included, computed without Mosaic's slow argmin
      loop (smallest tied column index via where/min/compare — cheaper
      than "split"'s division).
    - ``"split"``: fractional assignment across the tied minimisers
      (exact expected-assignment semantics: total cluster mass always
      sums to n).
    - ``"fast"`` (opt-in via ``setTiePolicy``; bench.py times whatever
      ``fit`` plans, i.e. the "first" default): a tied point
      counts toward EVERY minimizing centroid — its mass is
      double-counted, biasing the tied centroids' means toward it.  On
      continuous features exact f32 ties are measure-zero, so this is
      free; on DISCRETE/quantized features (integer grids, one-hot),
      distinct equidistant centroids are common and "fast" measurably
      changes the fit.  ~45% faster per iteration than "split" on v5e
      (r3 numbers; "first" re-measured r4).

    The XLA fallback path (non-TPU, small n, non-euclidean) always uses
    first-index argmin and ignores this param."""

    K = IntParam("k", "Number of clusters.", default=2,
                 validator=ParamValidators.gt_eq(2))
    INIT_MODE = StringParam(
        "initMode",
        "Initial centroid selection: 'random' (the reference's "
        "shuffle-take-k) or 'k-means++' (distance-weighted seeding, one "
        "fused device program).",
        default="random",
        validator=ParamValidators.in_array(["random", "k-means++"]))
    TIE_POLICY = StringParam(
        "tiePolicy",
        "Pallas-kernel handling of exactly-tied distances: 'first' "
        "(reference argmin semantics), 'fast', or 'split'.",
        default="first",
        validator=ParamValidators.in_array(["first", "fast", "split"]))
    WORKSET = BoolParam(
        "workset",
        "Delta/workset iteration mode: thread Hamerly center-movement "
        "bounds through the fused fit loop and exit the while_loop at "
        "Lloyd's fixed point instead of always running maxIter rounds.  "
        "Settled points keep cached assignments, shrinking the points "
        "SCORED per round (the report/bench accounting; the fused "
        "program still evaluates dense shapes, so the wall-clock win "
        "today is the early exit).  Off TPU the body is XLA — final "
        "centroids bit-identical to the XLA BSP fit (first-index "
        "argmin; tiePolicy does not apply).  On TPU the registry plans "
        "the fused scoring+stats kernel (op kmeans_workset_update) "
        "above the Pallas row threshold: same assignments, stats equal "
        "to f32 summation order.  The fit records a per-round "
        "convergence report in estimator.last_workset_report.",
        default=False)

    def get_workset(self) -> bool:
        return self.get(KMeansParams.WORKSET)

    def set_workset(self, value: bool):
        return self.set(KMeansParams.WORKSET, value)

    def get_k(self) -> int:
        return self.get(KMeansParams.K)

    def set_k(self, value: int):
        return self.set(KMeansParams.K, value)

    def get_tie_policy(self) -> str:
        return self.get(KMeansParams.TIE_POLICY)

    def set_tie_policy(self, value: str):
        return self.set(KMeansParams.TIE_POLICY, value)

    def get_init_mode(self) -> str:
        return self.get(KMeansParams.INIT_MODE)

    def set_init_mode(self, value: str):
        return self.set(KMeansParams.INIT_MODE, value)


def _prepare_points(points: np.ndarray, mesh, row_multiple: int = 1,
                    fill: str = "first_row",
                    cross_host_checked: bool = False) -> tuple:
    """Host -> device: pad rows to a multiple of the data-axis size (and of
    ``row_multiple`` per shard; mask marks real rows), shard the batch dim.

    On a process-spanning mesh ``points`` is THIS process's shard; each
    host pads to its local device multiple and the global array assembles
    over processes.  Equal padded counts are required — validated here
    unless the caller already allgathered row counts
    (``cross_host_checked``)."""
    from jax.sharding import PartitionSpec as P

    multiple = local_axis_multiple(mesh, row_multiple=row_multiple)
    padded, mask = pad_rows_with_mask(points, multiple, fill=fill)
    if mesh_process_count(mesh) > 1 and not cross_host_checked:
        from jax.experimental import multihost_utils

        rows = np.asarray(multihost_utils.process_allgather(
            np.asarray([padded.shape[0]], np.int64))).reshape(-1)
        if not np.all(rows == rows[0]):
            raise ValueError(
                "multi-host KMeans requires equal padded row counts per "
                f"process; got {rows.tolist()}")
    return (put_sharded(padded, mesh, P("data")),
            put_sharded(mask, mesh, P("data")))


@partial(jax.jit, static_argnums=0)
def _predict(measure: DistanceMeasure, pts, centroids):
    """Module-level jit (cache hit on every transform after the first;
    DistanceMeasure instances are registry singletons, hashable by id)."""
    return jnp.argmin(measure.pairwise(pts, centroids), axis=1)


def _kmeans_chain_kernel(static, params, cols):
    """Chain-fused nearest-centroid assign (same expression as
    ``_predict``; the measure singleton rides the plan-static tuple)."""
    from ...api.chain import as_matrix

    (fcol, acol, measure) = static
    pts = as_matrix(cols[fcol])
    dists = measure.pairwise(pts.astype(jnp.float32), params["centroids"])
    return {acol: jnp.argmin(dists, axis=1)}


def select_random_centroids(points: np.ndarray, k: int, seed: int) -> np.ndarray:
    """Semantics of ``KMeans.selectRandomCentroids`` (``KMeans.java:317-336``):
    shuffle all points with the seed, take k."""
    n = points.shape[0]
    if n < k:
        raise ValueError(f"Need at least k={k} points, got {n}")
    idx = np.random.default_rng(seed).permutation(n)[:k]
    return points[idx]


def select_kmeanspp_centroids(points: np.ndarray, k: int,
                              seed: int) -> np.ndarray:
    """k-means++ seeding (Arthur & Vassilvitskii 2007) as ONE fused device
    program: a ``fori_loop`` of k-1 rounds, each doing one (n, d) pass —
    the squared-distance-to-nearest-chosen vector updates incrementally
    (``d2 = min(d2, ||x - c||^2)``) and the next center draws
    categorically with probability proportional to ``d2``.  No
    per-round host round trip (through the axon tunnel a host-looped
    version would pay ~70 ms x k); beyond-reference init quality knob
    (the reference only has shuffle-take-k)."""
    n = points.shape[0]
    if n < k:
        raise ValueError(f"Need at least k={k} points, got {n}")
    out = _kmeanspp_run(jnp.asarray(points, jnp.float32),
                        jax.random.PRNGKey(seed), k)
    return np.asarray(out)


@partial(jax.jit, static_argnames=("k",))
def _kmeanspp_run(pts, key, k: int):
    key, sub = jax.random.split(key)
    first = jax.random.randint(sub, (), 0, pts.shape[0])
    chosen = jnp.zeros((k, pts.shape[1]), pts.dtype).at[0].set(pts[first])
    d2 = jnp.sum(jnp.square(pts - pts[first]), axis=1)

    def round_(i, carry):
        chosen, d2, key = carry
        key, sub = jax.random.split(key)
        # log-prob of d2 with zeros mapped to -inf (already-chosen
        # points can never repeat while any unchosen mass remains)
        logits = jnp.where(d2 > 0, jnp.log(d2), -jnp.inf)
        idx = jax.random.categorical(sub, logits)
        c = pts[idx]
        chosen = chosen.at[i].set(c)
        d2 = jnp.minimum(d2, jnp.sum(jnp.square(pts - c), axis=1))
        return chosen, d2, key

    chosen, _, _ = jax.lax.fori_loop(1, k, round_, (chosen, d2, key))
    return chosen


_INIT_MODES = {"random": select_random_centroids,
               "k-means++": select_kmeanspp_centroids}


def _stats_from_assign(k: int, points, mask, assign):
    """(sums, counts) from a per-point assignment vector — the reduce half
    of :func:`_assign_stats`, split out so the workset body (which merges
    cached and fresh assignments) runs the EXPRESSION-IDENTICAL einsum over
    all n points: identical assignments => bit-identical sums, which is
    what makes bound-filtered KMeans exact."""
    onehot = jax.nn.one_hot(assign, k, dtype=points.dtype) # (n, k)
    onehot = onehot * mask[:, None]                        # drop padding
    sums = jnp.einsum("nk,nd->kd", onehot, points)         # MXU reduce
    return sums, jnp.sum(onehot, axis=0)


def _assign_stats(measure: DistanceMeasure, k: int, points, mask,
                  centroids):
    """THE Lloyd's statistics: (sums (k, d), counts (k,)) of the masked
    points by nearest centroid — shared by the in-core epoch body and the
    out-of-core per-batch accumulation so the two can never diverge."""
    dists = measure.pairwise(points, centroids)            # (n, k)
    assign = jnp.argmin(dists, axis=1)                     # (n,)
    return _stats_from_assign(k, points, mask, assign)


def _update_centroids(centroids, sums, counts, xp=jnp):
    """Empty clusters keep their previous centroid (the reference's
    keyed-reduce would silently drop them; keeping is strictly better and
    identical when all clusters are non-empty, as in KMeansTest).
    ``xp`` lets the out-of-core path apply the identical policy on its
    host float64 accumulators (jnp would silently downcast to f32)."""
    counts = counts[:, None]
    return xp.where(counts > 0, sums / xp.maximum(counts, 1.0), centroids)


def kmeans_epoch_step(measure: DistanceMeasure, k: int):
    """One Lloyd's iteration as a pure jnp function (points, mask are closed
    over by ``iterate``'s static data)."""

    def body(centroids, epoch, data):
        points, mask = data
        sums, counts = _assign_stats(measure, k, points, mask, centroids)
        return IterationBodyResult(
            feedback=_update_centroids(centroids, sums, counts))

    return body


def workset_points_scored(active_fraction, n_real: int,
                          n_padded: int) -> np.ndarray:
    """Points scored per round, derived from the POST-round
    active-fraction trace: round 0 rescored every real point (BSP round
    0), round ``e`` scores round ``e-1``'s survivors (the fraction is
    over padded rows).  THE one copy of this convention — the fit report
    and the bench leg's FLOPs accounting both read it, so a trace
    semantics change cannot skew one silently."""
    frac = np.asarray(active_fraction, np.float64)
    if not frac.size:
        return np.zeros((0,))
    return np.concatenate([[float(n_real)], frac[:-1] * n_padded])


#: relative slack on the Hamerly bound decay: f32 rounding of
#: ``upper + drift`` / ``lower - drift`` may land BELOW the true bound, so
#: every decayed bound is nudged conservatively outward — a too-loose
#: bound only keeps a settled point active one more round (wasted score),
#: never freezes a point that could still flip (wrong centroids).
_WS_BOUND_SLACK = 1e-5


def kmeans_workset_update_xla(measure: DistanceMeasure, k: int, points,
                              centroids, prev_assign, active, pad_mask):
    """XLA backend of registry op ``kmeans_workset_update`` — the
    bound-filtered scoring + stats of one workset round, and the parity
    oracle the fused Pallas kernel is matrix-tested against.  Returns
    ``(assign, d_best, d_second, sums, counts)`` with ``assign`` already
    merged under the active mask (the settled points' cached
    assignments); ``d_best``/``d_second`` are the FRESH per-point
    distances — the caller keeps its old bounds where settled."""
    dists = measure.pairwise(points, centroids)             # (n, k)
    fresh = jnp.argmin(dists, axis=1).astype(jnp.int32)
    is_min = jnp.arange(k, dtype=jnp.int32)[None, :] == fresh[:, None]
    d_best = jnp.min(dists, axis=1)
    d_second = jnp.min(jnp.where(is_min, jnp.inf, dists), axis=1)
    assign = jnp.where(active > 0, fresh, prev_assign).astype(jnp.int32)
    sums, counts = _stats_from_assign(k, points, pad_mask, assign)
    return assign, d_best, d_second, sums, counts


def kmeans_workset_epoch_step(measure: DistanceMeasure, k: int, *,
                              block_n: Optional[int] = None,
                              interpret: bool = False):
    """One bound-filtered Lloyd's iteration as an ``iterate`` workset body
    (Hamerly 2010 adapted to the device-resident mask).

    ``block_n`` switches the scoring+stats block onto the fused Pallas
    kernel (``ops/kmeans_pallas.py::kmeans_workset_update`` — registry
    op ``kmeans_workset_update``): distances, first-index argmin, the
    second-best pass, the cached-assignment merge, AND the stats reduce
    run as one VMEM kernel, so the (n, k) intermediates never touch HBM.
    Per-point outputs are expression-identical to the XLA block below;
    the stats accumulate tile-sequentially (f32-summation-order
    equivalent, not bitwise — the registry plans it only on TPU, so the
    CPU tier's bit-exactness contract vs BSP is untouched).  The bound
    decay, settle detection, and centroid update are shared verbatim.

    Per-point bound state rides ``workset.bounds``: the cached assignment,
    an UPPER bound on the distance to the assigned centroid, and a LOWER
    bound on the distance to every other centroid.  A masked-out point is
    one whose ``upper < lower`` after decaying both by the centroids'
    movement — the triangle inequality then proves its argmin cannot have
    flipped, so its CACHED assignment feeds the stats reduce and the
    result is bit-identical to the BSP body (the reduce itself still runs
    the same einsum over all n points — identical assignments, identical
    f32 summation order).  What shrinks is the LOGICAL scoring work: the
    number of points whose (n, k) distance rows a round must re-score
    (``points_scored`` in the fit report / bench leg) — the fused
    fixed-shape program still evaluates densely, so that count is what a
    compacting backend banks, while the early exit below is the physical
    saving available today.

    The body drives the workset to empty at Lloyd's fixed point: a round
    with zero assignment flips produces bit-identical sums, hence zero
    centroid drift, hence no point left to rescore — the driver's
    active-fraction criterion then exits the ``lax.while_loop`` strictly
    before ``max_epochs`` whenever the fit converges early.

    Euclidean only: the bound decay leans on the triangle inequality in
    TRUE distance space (``EuclideanDistanceMeasure.pairwise`` returns
    root distances, not squares)."""
    if measure.name != "euclidean":
        raise ValueError(
            "workset KMeans requires the euclidean measure (Hamerly "
            f"bounds need the triangle inequality), got {measure.name!r}")

    def body(centroids, ws, epoch, data):
        points, pad_mask = data
        active = ws.mask                                    # (n,) f32 0/1
        prev_assign = ws.bounds["assign"]
        if block_n is not None:
            from ...ops.kmeans_pallas import kmeans_workset_update

            assign, d_best, d_second, sums, counts = kmeans_workset_update(
                points, centroids, prev_assign, active, pad_mask,
                block_n=block_n, interpret=interpret)
        else:
            assign, d_best, d_second, sums, counts = \
                kmeans_workset_update_xla(measure, k, points, centroids,
                                          prev_assign, active, pad_mask)
        on = active > 0
        # merge: active points take the fresh score, settled points keep
        # their cached assignment/bounds (provably identical); assign is
        # already merged by the scoring fn, so the flip count over it
        # equals the fresh-vs-cached count (inactive terms are masked)
        upper = jnp.where(on, d_best, ws.bounds["upper"])
        lower = jnp.where(on, d_second, ws.bounds["lower"])
        changed = jnp.sum(active * (assign != prev_assign))
        new_centroids = _update_centroids(centroids, sums, counts)

        drift = jnp.sqrt(jnp.maximum(
            jnp.sum(jnp.square(new_centroids - centroids), axis=1), 0.0))
        drift_max = jnp.max(drift)
        # conservative f32 decay (see _WS_BOUND_SLACK)
        upper = upper + drift[assign]
        upper = upper + jnp.abs(upper) * _WS_BOUND_SLACK
        lower = lower - drift_max
        lower = lower - jnp.abs(lower) * _WS_BOUND_SLACK
        # fixed point: nothing moved and nothing flipped => every future
        # BSP round is a bit-identical no-op — drain the workset entirely
        settled = jnp.logical_and(drift_max == 0.0, changed == 0.0)
        next_active = jnp.logical_and(upper >= lower,
                                      jnp.logical_not(settled))
        new_mask = jnp.where(pad_mask > 0,
                             next_active.astype(jnp.float32), 0.0)
        new_ws = Workset(new_mask, {"assign": assign, "upper": upper,
                                    "lower": lower})
        return IterationBodyResult(feedback=(new_centroids, new_ws))

    return body


def kmeans_epoch_step_pallas(k: int, mesh=None, *, block_n: int = 8192,
                             tie_policy: str = "first",
                             interpret: bool = False):
    """One Lloyd's iteration on the fused Pallas kernel
    (``ops/kmeans_pallas.py``): score/one-hot tiles stay in VMEM, HBM traffic
    drops ~12x vs the XLA expansion (~3.5x measured step speedup on v5e).

    ``tie_policy="first"`` (the default, what ``KMeans.fit`` plans via
    its ``tiePolicy`` param) keeps the XLA body's exact first-index
    argmin semantics; ``"split"`` gives fractional expected-assignment
    ties, ``"fast"`` assigns exactly-tied points to every minimizing
    centroid — see ``KMeansParams.TIE_POLICY``.

    Requires zero-filled padding (``fill="zero"``) with the per-shard row
    count a multiple of ``block_n``; euclidean metric only.  With a
    multi-device ``mesh``, per-shard partial sums meet in one ICI psum."""
    from ...ops import kmeans_pallas as kp

    sharded = mesh is not None and int(mesh.shape.get("data", 1)) > 1

    def body(centroids, epoch, data):
        points, mask = data
        if sharded:
            sums, counts = kp.update_stats_sharded(
                points, centroids, mesh, block_n=block_n,
                tie_policy=tie_policy, interpret=interpret)
        else:
            sums, counts = kp.kmeans_update_stats(
                points, centroids, block_n=block_n, tie_policy=tie_policy,
                interpret=interpret)
        n_pad = points.shape[0] - jnp.sum(mask)
        counts = kp.pad_correction(counts, centroids, n_pad,
                                   tie_policy=tie_policy)[:, None]
        # No clamp-to-1 here: "split" ties legally produce fractional counts
        # in (0, 1), which must divide as-is.
        safe = jnp.where(counts > 0, counts, 1.0)
        new_centroids = jnp.where(counts > 0, sums / safe, centroids)
        return IterationBodyResult(feedback=new_centroids)

    return body


# Pallas engages only above this row count — below it the XLA path is within
# noise and avoids kernel constraints (zero-fill, block divisibility).
_PALLAS_MIN_ROWS = 65536


def _plan_fit_impl(n: int, d: int, k: int, measure: DistanceMeasure,
                   mesh) -> tuple:
    """Pick (impl, block_n) for the BSP fit loop via registry op
    ``kmeans_update_stats`` (the Pallas entry's availability gate is the
    TPU backend; its supports predicate is the euclidean metric, the
    row-count threshold, and a viable VMEM block).  Padding rounds the
    per-shard row count up to the block (n=None below), so any supported
    block size works; pick_block_n takes the largest."""
    from ...kernels.registry import lookup
    from ...ops import kmeans_pallas as kp

    entry = lookup("kmeans_update_stats", sig=(n, d, k, measure.name))
    if entry.backend == "pallas":
        # measured-not-analytic when the autotune cache is configured
        # (ISSUE 12): the winner is persisted per (d, k, device kind),
        # so only the fleet's first process pays the search
        return "pallas", kp.pick_block_n_measured(d, k)
    return "xla", None


@dataclass(frozen=True)
class FitPlan:
    """THE per-fit shape/impl contract, derived once and shared by every
    KMeans fit path (in-core BSP, workset, out-of-core streaming) instead
    of each re-deriving k/d padding independently — the workset port must
    not fork a third copy of the padding rules."""

    impl: str                  # "xla" | "pallas"
    block_n: Optional[int]     # Pallas tile rows (None for xla)
    row_multiple: int          # per-shard row-count multiple for padding
    fill: str                  # pad_rows_with_mask fill policy
    k: int
    d: int

    def local_multiple(self, mesh) -> int:
        """Per-process padded-row multiple on ``mesh`` under this plan."""
        return local_axis_multiple(mesh, row_multiple=self.row_multiple)

    def init_workset(self, pad_mask) -> Workset:
        """The workset bound-state initializer: everything real starts
        active with vacuous bounds (+inf upper / -inf lower forces a full
        first-round rescore, exactly BSP round 0); padding rows are born
        settled so they are never scored OR counted active.  Every bound
        array derives elementwise from ``pad_mask`` so it inherits the
        mask's sharding — the while_loop carry stays consistently sharded
        on a multi-device mesh with no GSPMD resharding."""
        mask = pad_mask.astype(jnp.float32)
        zero = mask * 0.0
        return Workset(
            mask=mask,
            bounds={"assign": zero.astype(jnp.int32),
                    "upper": zero + jnp.asarray(jnp.inf, jnp.float32),
                    "lower": zero - jnp.asarray(jnp.inf, jnp.float32)})


def _fit_plan(n: int, d: int, k: int, measure: DistanceMeasure, mesh, *,
              workset: bool = False) -> FitPlan:
    """Build the shared :class:`FitPlan`.  The workset path plans via
    registry op ``kmeans_workset_update``: the fused scoring+stats
    Pallas kernel (PR 10) where available — TPU, euclidean, a viable
    VMEM block, and a single-device data axis (the sharded composition
    is future work) — else the XLA body, which is what every CPU tier
    runs (impl ``"pallas_ws"`` pads by the MASKED contract: the kernel
    takes the pad mask, so first-row fill stays safe).  The BSP path
    falls out of :func:`_plan_fit_impl` exactly as before."""
    if workset:
        from ...kernels.registry import lookup
        from ...ops import kmeans_pallas as kp

        data_devs = int(mesh.shape.get("data", 1)) if mesh else 1
        entry = lookup("kmeans_workset_update",
                       sig=(n, d, k, measure.name, data_devs))
        if entry.backend == "pallas":
            block_n = kp.pick_block_n_workset_measured(d, k)
            return FitPlan("pallas_ws", block_n, block_n, "first_row", k, d)
        return FitPlan("xla", None, 1, "first_row", k, d)
    impl, block_n = _plan_fit_impl(n, d, k, measure, mesh)
    row_multiple, fill = ((block_n, "zero") if impl == "pallas"
                          else (1, "first_row"))
    return FitPlan(impl, block_n, row_multiple, fill, k, d)


def kmeans_fit_outofcore(make_reader, k: int, *,
                         measure_name: str = "euclidean",
                         max_iter: int = 20, seed: int = 0, mesh=None,
                         features_key: str = "features",
                         prefetch_depth: int = 2) -> np.ndarray:
    """Out-of-core Lloyd's: the dataset streams from ``make_reader()``
    (a fresh per-epoch iterator of host batch dicts — the same protocol as
    ``sgd_fit_outofcore``) instead of living in HBM; this is the
    replay-per-epoch semantics of the reference's ReplayOperator
    (``operator/ReplayOperator.java:62-311``) at beyond-memory scale.

    Each epoch accumulates per-batch (sums, counts) partial statistics on
    device — batch N+1's host read and transfer overlap batch N's compute
    via ``prefetch_to_device`` — and the centroid update applies once per
    epoch (exact Lloyd's: identical result to the in-core fit on the same
    concatenated rows, asserted in tests).  Initial centroids are a
    seeded shuffle-take-k of the FIRST batch.

    Returns the final (k, d) centroids (host float32)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ...data.prefetch import prefetch_to_device

    mesh = mesh or default_mesh()
    if mesh_process_count(mesh) > 1:
        raise ValueError(
            "kmeans_fit_outofcore is single-host (the prefetch transfer "
            "and init read are per-process); run the reader on each host "
            "and use KMeans.fit with per-process shards for multi-host")
    measure = DistanceMeasure.get_instance(measure_name)

    from ...utils.padding import FixedRowBatcher

    # The shared FitPlan owns the padding rules (n=0: per-batch streaming
    # accumulation is below any Pallas residency threshold by
    # construction, so the plan always lands on the XLA impl) — no
    # independent re-derivation of the row multiple here.
    plan = _fit_plan(0, 1, k, measure, mesh)
    multiple = plan.local_multiple(mesh)
    sharding = NamedSharding(mesh, P("data"))
    # shared fixed-row protocol (first padded batch pins; ragged tail
    # zero-pads with mask 0)
    batcher = FixedRowBatcher(1)

    def to_host_batch(batch):
        pts = np.asarray(batch[features_key], np.float32)
        padded, mask = pad_rows_with_mask(pts, multiple, fill="zero")
        return batcher.pad((padded, mask), have=padded.shape[0])

    batch_stats = jax.jit(lambda c, pts, mask:
                          _assign_stats(measure, k, pts, mask, c))
    add2 = jax.jit(lambda a, b, c, d: (a + c, b + d))

    from ..common.sgd import _reader_for_epoch

    centroids = None
    for iteration in range(max_iter):
        # Two-level accumulation: f32 on device within a window sized so
        # counts stay in f32's exact-integer range (2^24), folded into a
        # host float64 total — billions of rows per epoch cannot silently
        # round away per-batch contributions.
        host_sums = host_counts = None
        sums = counts = None
        window_used = 0
        window = None

        def fold():
            nonlocal host_sums, host_counts, sums, counts, window_used
            if sums is None:
                return
            s64 = np.asarray(jax.device_get(sums), np.float64)
            c64 = np.asarray(jax.device_get(counts), np.float64)
            host_sums = s64 if host_sums is None else host_sums + s64
            host_counts = c64 if host_counts is None else host_counts + c64
            sums = counts = None
            window_used = 0

        # epoch-aware factories (the sgd_fit_outofcore protocol) receive
        # the Lloyd iteration number; Lloyd statistics are order-invariant
        # so per-epoch reshuffled readers change IO pattern only.  NOTE:
        # init below samples the FIRST batch — epoch-varying readers
        # change which rows that is, deterministically in (seed, epoch=0)
        for pts, mask in prefetch_to_device(
                _reader_for_epoch(make_reader, iteration),
                depth=prefetch_depth,
                transform=to_host_batch,
                sharding=(sharding, sharding)):
            if centroids is None:
                # init: seeded shuffle-take-k of the first batch's rows
                first = np.asarray(pts)[np.asarray(mask) > 0]
                centroids = jnp.asarray(
                    select_random_centroids(first, k, seed))
            if window is None:
                window = max(1, (1 << 23) // batcher.rows)
            s, c = batch_stats(centroids, pts, mask)
            if sums is None:
                sums, counts = s, c
            else:
                sums, counts = add2(sums, counts, s, c)
            window_used += 1
            if window_used >= window:
                fold()
        fold()
        if host_sums is None:
            raise ValueError("make_reader() returned an empty epoch")
        centroids = jnp.asarray(_update_centroids(
            np.asarray(jax.device_get(centroids), np.float64),
            host_sums, host_counts, xp=np).astype(np.float32))
    return np.asarray(jax.device_get(centroids), np.float32)


class KMeans(KMeansParams, Estimator["KMeansModel"]):
    """Estimator: Lloyd's algorithm for ``maxIter`` rounds
    (termination parity with ``TerminateOnMaxIterationNum``,
    ``common/iteration/TerminateOnMaxIterationNum.java:34-55``)."""

    def fit(self, *inputs) -> "KMeansModel":
        (table,) = inputs
        # report describes THIS fit only — a reused estimator must not
        # serve a stale report from an earlier workset fit
        self.last_workset_report = None
        mesh = default_mesh()
        k = self.get_k()
        measure = DistanceMeasure.get_instance(self.get_distance_measure())

        host_points = stack_vectors(table[self.get_features_col()]).astype(
            np.float32)
        n_for_plan = host_points.shape[0]
        multi_host = mesh_process_count(mesh) > 1
        if multi_host:
            # Every process passed its own shard.  ONE allgather of the
            # raw row counts runs before any other collective so every
            # host takes identical branches from identical facts: the
            # impl plan uses the GLOBAL row count (per-host planning
            # straddling the Pallas threshold would compile mismatched
            # collective programs -> deadlock), the host-0-shard-too-small
            # error raises on ALL hosts (raising on one strands the rest
            # in the init broadcast), and padded-count equality is
            # validated here rather than re-gathered downstream.
            from jax.experimental import multihost_utils

            rows = np.asarray(multihost_utils.process_allgather(
                np.asarray([host_points.shape[0]], np.int64))).reshape(-1)
            n_for_plan = int(rows.sum())
            if rows[0] < k:
                raise ValueError(
                    f"multi-host KMeans selects initial centroids from "
                    f"host 0's shard, which holds {int(rows[0])} rows "
                    f"< k={k}; give host 0 at least k rows")

        workset_mode = self.get_workset()
        plan = _fit_plan(n_for_plan, host_points.shape[1], k, measure, mesh,
                         workset=workset_mode)
        impl, block_n = plan.impl, plan.block_n
        row_multiple, fill = plan.row_multiple, plan.fill
        select_init = _INIT_MODES[self.get_init_mode()]
        if multi_host:
            from ...parallel.distributed import broadcast_from_host0

            multiple = plan.local_multiple(mesh)
            padded_rows = -(-rows // multiple) * multiple
            if not np.all(padded_rows == padded_rows[0]):
                raise ValueError(
                    "multi-host KMeans requires equal padded row counts "
                    f"per process; got {padded_rows.tolist()}")
            init = (select_init(host_points, k, self.get_seed())
                    if jax.process_index() == 0
                    else np.zeros((k, host_points.shape[1]), np.float32))
            init = np.asarray(broadcast_from_host0(init))
        else:
            init = select_init(host_points, k, self.get_seed())

        points, mask = _prepare_points(host_points, mesh,
                                       row_multiple=row_multiple, fill=fill,
                                       cross_host_checked=True)
        init_dev = replicate(init, mesh)

        if workset_mode:
            result = iterate(
                kmeans_workset_epoch_step(
                    measure, k,
                    block_n=block_n if impl == "pallas_ws" else None),
                init_dev,
                (points, mask),
                max_epochs=self.get_max_iter(),
                workset=plan.init_workset(mask),
                config=IterationConfig(mode="fused"),
            )
            self.last_workset_report = self._workset_report(
                result, n_real=n_for_plan, n_padded=int(points.shape[0]))
        else:
            body = (kmeans_epoch_step_pallas(k, mesh, block_n=block_n,
                                             tie_policy=self.get_tie_policy())
                    if impl == "pallas" else kmeans_epoch_step(measure, k))
            result = iterate(
                body,
                init_dev,
                (points, mask),
                max_epochs=self.get_max_iter(),
                config=IterationConfig(mode="fused"),
            )
        centroids = np.asarray(fetch_replicated(result.state))

        model = KMeansModel()
        model.copy_params_from(self)
        model.set_model_data(
            Table({"centroids": centroids[None, :, :]}))  # 1 row of (k, d)
        return model

    def _workset_report(self, result, *, n_real: int, n_padded: int) -> dict:
        """Convergence report of a workset fit: ``active_fraction[e]`` is
        the fraction left active AFTER round ``e`` (over padded rows), so
        the points actually SCORED in round ``e`` are the previous round's
        survivors — round 0 scores every real point (BSP round 0)."""
        trace = result.side.get("epoch_trace", {})
        frac = np.asarray(trace.get("active_fraction", ()), np.float64)
        scored = workset_points_scored(frac, n_real, n_padded)
        return {
            "rounds": result.num_epochs,
            "max_epochs": self.get_max_iter(),
            "n_points": int(n_real),
            "active_fraction": frac,
            "points_scored": scored,
        }

    def fit_outofcore(self, make_reader, *, mesh=None,
                      features_key: str = None) -> "KMeansModel":
        """Out-of-core ``fit`` (see :func:`kmeans_fit_outofcore`): the
        dataset streams from ``make_reader()`` — a fresh per-epoch
        iterator of host batch dicts (e.g. a re-seeked ``DataCacheReader``)
        — instead of living in RAM/HBM."""
        centroids = kmeans_fit_outofcore(
            make_reader, self.get_k(),
            measure_name=self.get_distance_measure(),
            max_iter=self.get_max_iter(), seed=self.get_seed(), mesh=mesh,
            features_key=features_key or self.get_features_col())
        model = KMeansModel()
        model.copy_params_from(self)
        model.set_model_data(Table({"centroids": centroids[None, :, :]}))
        return model

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)

    @classmethod
    def load(cls, path: str) -> "KMeans":
        return persist.load_stage_param(path)


class KMeansModel(KMeansModelParams, Model):
    """Batch prediction: one pairwise-distance matmul + argmin appended as the
    prediction column (the reference buffers rows until ``finish()`` then
    loops — ``KMeansModel.java:109-176``; here it's a single jitted call)."""

    def __init__(self):
        super().__init__()
        self._centroids: np.ndarray | None = None

    # -- model data ---------------------------------------------------------
    def set_model_data(self, *inputs) -> "KMeansModel":
        (table,) = inputs
        self._centroids = np.asarray(table["centroids"][0], dtype=np.float32)
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"centroids": self._centroids[None, :, :]})]

    def _require_model(self):
        if self._centroids is None:
            raise RuntimeError(
                "KMeansModel has no model data; fit a KMeans or call "
                "set_model_data first")

    def transform_kernel(self, schema):
        """Chain TERMINAL: the in-segment assign is expression-identical
        to ``_predict`` (pairwise + per-row argmin — pad rows inert), the
        host ``post`` applies the same int64 cast; bit-exact with the
        stagewise transform."""
        from ...api.chain import StageKernel, numeric_entry

        self._require_model()
        fcol = self.get_features_col()
        if numeric_entry(schema, fcol) is None:
            return None
        measure = DistanceMeasure.get_instance(self.get_distance_measure())
        pred_col = self.get_prediction_col()
        assign_col = f"__chain_assign__{pred_col}"

        def post(host):
            return {pred_col: host[assign_col].astype(np.int64)}

        return StageKernel(
            fn=_kmeans_chain_kernel,
            static=(fcol, assign_col, measure),
            params={"centroids": np.asarray(self._centroids, np.float32)},
            consumes=(fcol,), produces=(assign_col,), post=post)

    # -- inference ----------------------------------------------------------
    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        # numeric feature columns assign through the kernel registry's
        # shared dispatch surface — the SAME (fn, static) plan the chain
        # terminal and the serving executor run, so offline transform,
        # fused pipelines, and serving share one compiled executable per
        # (schema, bucket); object-dtype vector columns keep the legacy
        # stack_vectors entry point below
        from ...api.chain import apply_kernel_or_none

        kernel = self.transform_kernel(table.schema())
        cols = apply_kernel_or_none(kernel, table)
        if cols is not None:
            return [table.with_column(self.get_prediction_col(),
                                      cols[self.get_prediction_col()])]
        measure = DistanceMeasure.get_instance(self.get_distance_measure())
        points = stack_vectors(table[self.get_features_col()]).astype(
            np.float32)
        # bucketed batch shape: mixed request sizes share one compiled
        # assign program per power-of-two bucket (utils/padding.py); the
        # per-row argmin makes pad rows inert, sliced off below
        (padded,), n = pad_rows_to_bucket((points,))
        assign = np.asarray(
            _predict(measure, padded, jnp.asarray(self._centroids)))[:n]
        return [table.with_column(self.get_prediction_col(),
                                  assign.astype(np.int64))]

    # -- persistence --------------------------------------------------------
    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {"centroids": self._centroids})

    @classmethod
    def load(cls, path: str) -> "KMeansModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._centroids = data["centroids"].astype(np.float32)
        return model


# ---------------------------------------------------------------------------
# kernel-registry entries.  ``kmeans_assign`` (stage convention) is the
# transform/serving/chain dispatch op; ``kmeans_update_stats`` and
# ``kmeans_workset_update`` are the fit-planning ops whose supports
# predicates carry THIS model's planning policy (euclidean metric, the
# Pallas row-count threshold, viable VMEM blocks; the workset kernel
# additionally requires a single-device data axis — its sharded
# composition is future work).
# ---------------------------------------------------------------------------

def _pallas_stats_supported(sig: tuple) -> bool:
    from ...ops import kmeans_pallas as kp

    if len(sig) != 4:       # no/foreign sig: never auto-select pallas
        return False
    n, d, k, measure_name = sig
    return (measure_name == "euclidean" and n >= _PALLAS_MIN_ROWS
            and kp.pick_block_n(None, d, k) is not None)


def _pallas_workset_supported(sig: tuple) -> bool:
    from ...ops import kmeans_pallas as kp

    if len(sig) != 5:       # no/foreign sig: never auto-select pallas
        return False
    n, d, k, measure_name, data_devs = sig
    return (measure_name == "euclidean" and n >= _PALLAS_MIN_ROWS
            and data_devs == 1
            and kp.pick_block_n_workset(None, d, k) is not None)


def _register_kmeans_kernels() -> None:
    from ...kernels.registry import register_kernel, tpu_only
    from ...ops import kmeans_pallas as kp

    register_kernel("kmeans_assign", "xla", _kmeans_chain_kernel,
                    convention="stage")
    register_kernel("kmeans_update_stats", "pallas", kp.kmeans_update_stats,
                    priority=10, supports=_pallas_stats_supported,
                    available=tpu_only)
    register_kernel("kmeans_update_stats", "xla", _assign_stats)
    register_kernel("kmeans_workset_update", "pallas",
                    kp.kmeans_workset_update, priority=10,
                    supports=_pallas_workset_supported, available=tpu_only)
    register_kernel("kmeans_workset_update", "xla",
                    kmeans_workset_update_xla)


_register_kmeans_kernels()
