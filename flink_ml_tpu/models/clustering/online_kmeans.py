"""OnlineKMeans — streaming mini-batch KMeans.

The unbounded-iteration counterpart of KMeans (Flink ML pairs each bounded
estimator with an online variant; the capability maps to
``Iterations.iterateUnboundedStreams``, ``Iterations.java:118-127``).  Each
epoch consumes one window of the stream and applies a decayed mini-batch
centroid update

    c_k <- (c_k * n_k * alpha + sum_batch) / (n_k * alpha + count_batch)

where ``alpha`` is the decay factor (alpha=1: running mean over the whole
stream; alpha=0: each batch fully replaces the statistics).  The update is
the same fused assign+reduce used by batch KMeans; centroids and per-cluster
weights stay in HBM between windows.
"""

from __future__ import annotations

from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator
from ...data.stream import (cursor_adapter,
                            ensure_cursor_source, windows_of)
from ...data.table import Table
from ...distance import DistanceMeasure
from ...iteration import (
    IterationBodyResult,
    IterationConfig,
    IterationListener,
    iterate,
)
from ...linalg import stack_vectors
from ...params.param import FloatParam, ParamValidators
from .kmeans import KMeansModel, KMeansParams, select_random_centroids

__all__ = ["OnlineKMeans", "OnlineKMeansModel"]


class OnlineKMeansModel(KMeansModel):
    """KMeansModel + the model version counter of the streaming fit."""

    def __init__(self):
        super().__init__()
        self.model_version = 0

    def save(self, path: str) -> None:
        from ...utils import persist

        self._require_model()
        persist.save_metadata(self, path, {"modelVersion": self.model_version})
        persist.save_model_arrays(path, "model",
                                  {"centroids": self._centroids})

    @classmethod
    def load(cls, path: str) -> "OnlineKMeansModel":
        from ...utils import persist

        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._centroids = data["centroids"].astype(np.float32)
        model.model_version = int(
            persist.load_metadata(path).get("modelVersion", 0))
        return model


class OnlineKMeans(KMeansParams, Estimator[OnlineKMeansModel]):
    DECAY_FACTOR = FloatParam(
        "decayFactor", "Forgetting factor for old batch statistics.",
        default=1.0, validator=ParamValidators.in_range(0.0, 1.0))

    def get_decay_factor(self) -> float:
        return self.get(OnlineKMeans.DECAY_FACTOR)

    def set_decay_factor(self, v: float):
        return self.set(OnlineKMeans.DECAY_FACTOR, v)

    def __init__(self):
        super().__init__()
        self._initial_centroids: Optional[np.ndarray] = None

    def set_initial_model_data(self, table: Table) -> "OnlineKMeans":
        self._initial_centroids = np.asarray(table["centroids"][0], np.float32)
        return self

    def fit(self, *inputs, checkpoint=None,
            resume: bool = False) -> OnlineKMeansModel:
        """``fit(stream)``: an iterable of Tables (windows).  Returns when
        the stream ends.

        ``checkpoint``/``resume`` cut the (centroids, weights) state and
        the source cursor together (the OnlineLogisticRegression
        contract; wrap live feeds in ``data.wal.WindowLog``).
        Checkpointed fits must warm-start via ``set_initial_model_data``:
        sniffing init centroids from the first window would consume it
        BEFORE the checkpoint cursor repositions the stream."""
        (source,) = inputs
        k = self.get_k()
        alpha = self.get_decay_factor()
        measure = DistanceMeasure.get_instance(self.get_distance_measure())
        feat = self.get_features_col()

        if checkpoint is not None:
            if self._initial_centroids is None:
                raise ValueError(
                    "checkpointed streaming fit needs "
                    "set_initial_model_data: sniffing init centroids "
                    "would consume a window before the cursor restores")
            source = ensure_cursor_source(source, max(k, 256))
            first = None
        else:
            batches_sniff = windows_of(source, max(k, 256))
            first = next(batches_sniff, None)
            if first is None:
                raise ValueError("OnlineKMeans.fit got an empty stream")

        first_X = (stack_vectors(first[feat]).astype(np.float32)
                   if first is not None else None)
        if self._initial_centroids is not None:
            init = self._initial_centroids
            if init.shape[0] != k:
                raise ValueError(
                    f"initial model data has {init.shape[0]} centroids but "
                    f"k={k}")
        else:
            init = select_random_centroids(first_X, k, self.get_seed())

        @jax.jit
        def update(centroids, weights, X):
            dists = measure.pairwise(X, centroids)
            assign = jnp.argmin(dists, axis=1)
            onehot = jax.nn.one_hot(assign, k, dtype=X.dtype)
            sums = jnp.einsum("nk,nd->kd", onehot, X)
            counts = jnp.sum(onehot, axis=0)
            decayed = weights * alpha
            denom = decayed + counts
            new_centroids = jnp.where(
                counts[:, None] > 0,
                (centroids * decayed[:, None] + sums)
                / jnp.maximum(denom, 1e-12)[:, None],
                centroids)
            return new_centroids, denom

        def payloads():
            if first is not None:
                yield first_X
                stream = batches_sniff
            else:
                stream = windows_of(source, max(k, 256))
            for t in stream:
                yield stack_vectors(t[feat]).astype(np.float32)

        def body(state, epoch, X):
            centroids, weights = state
            new_c, new_w = update(centroids, weights, jnp.asarray(X))
            return IterationBodyResult((new_c, new_w))

        state0 = (jnp.asarray(init), jnp.zeros((k,), jnp.float32))
        result = iterate(body, state0, cursor_adapter(source, payloads),
                         config=IterationConfig(mode="hosted", jit=False),
                         checkpoint=checkpoint, resume=resume)
        if result.num_epochs == 0:
            # a real resume always lands at >= 1 epoch, so zero means an
            # empty stream either way
            raise ValueError("OnlineKMeans.fit got an empty stream")

        centroids = np.asarray(jax.device_get(result.state[0]))
        model = OnlineKMeansModel()
        model.copy_params_from(self)
        model.set_model_data(Table({"centroids": centroids[None]}))
        model.model_version = result.num_epochs
        return model
