"""FValueTest — F-regression test, continuous feature vs continuous label.

Member of the Flink ML 2.x stats surface (``org.apache.flink.ml.stats``
family alongside ChiSqTest and ANOVATest; the reference snapshot ships
none — SURVEY §2.8).  AlgoOperator: one output row per feature column
with (pValue, degreesOfFreedom, fValue), where
``F = r^2 / (1 - r^2) * (n - 2)`` from the Pearson correlation r.

TPU split (same stance as ANOVATest): the O(n*d) correlation reduction
is one jitted pass on device; the F ratio and its survival-function
p-value finish on host in float64.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import AlgoOperator
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.shared import HasFeaturesCol, HasLabelCol
from .anovatest import f_p_values

__all__ = ["FValueTest", "f_regression_scores"]


@jax.jit
def _pearson_r(X, y):
    Xc = X - jnp.mean(X, axis=0, keepdims=True)
    yc = y - jnp.mean(y)
    num = Xc.T @ yc
    den = jnp.sqrt(jnp.sum(Xc * Xc, axis=0) * jnp.sum(yc * yc))
    return num / jnp.maximum(den, 1e-30)


def f_regression_scores(X: np.ndarray, y: np.ndarray
                        ) -> Tuple[np.ndarray, np.ndarray, int]:
    """(f_values (d,), p_values (d,), dfd) for continuous features X
    against a continuous label y: F = r^2/(1-r^2) * (n-2), dof (1, n-2)."""
    n, d = X.shape
    r = np.asarray(_pearson_r(jnp.asarray(X, jnp.float32),
                              jnp.asarray(y, jnp.float32)), np.float64)
    r = np.clip(r, -1.0, 1.0)
    dfd = n - 2
    with np.errstate(divide="ignore", invalid="ignore"):
        # the 1e-300 floor keeps perfect correlation (r = +-1) FINITE and
        # astronomically large -> survival function underflows to p = 0;
        # a NaN r (degenerate input) stays NaN, which f_p_values maps to
        # p = 1 — so fValue and pValue always tell the same story
        f = r * r / np.maximum(1.0 - r * r, 1e-300) * dfd
    return f, f_p_values(f, np.ones(d), np.full(d, dfd)), dfd


class FValueTest(HasFeaturesCol, HasLabelCol, AlgoOperator):
    """transform(table) -> one Table with a row per feature column:
    (featureIndex, pValue, degreesOfFreedom, fValue).  Features and label
    are continuous."""

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        y = np.asarray(table[self.get_label_col()], np.float64)
        f, p, dfd = f_regression_scores(X, y)
        d = X.shape[1]
        return [Table({
            "featureIndex": np.arange(d, dtype=np.int64),
            "pValue": np.asarray(p, np.float64),
            # the reference family reports numSamples - 2 here (the
            # denominator dof), unlike ANOVA's summed-dofs convention
            "degreesOfFreedom": np.full(d, dfd, np.int64),
            "fValue": np.asarray(f, np.float64),
        })]
