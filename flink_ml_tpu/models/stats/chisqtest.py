"""ChiSqTest — Pearson's chi-squared independence test, feature vs label.

Member of the Flink ML 2.x stats surface.  AlgoOperator: one output row per
feature column with (pValue, degreesOfFreedom, statistic).

Contingency tables and statistics are exact host ``np.bincount`` integer
counts (tiny work; a per-feature jitted kernel would recompile for every
distinct (levels, labels) shape and sync three times per feature); the
p-values are the chi^2 survival function ``Q(df/2, x/2)`` evaluated on the
host in float64 (``scipy.special.gammaincc``) — the output column is
float64-typed and must carry genuine float64 precision, which a device f32
evaluation caps at ~1e-7 and flushes tiny p-values to 0.
"""

from __future__ import annotations

from typing import List

import numpy as np
from scipy.special import gammaincc

from ...api.stage import AlgoOperator
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.shared import HasFeaturesCol, HasLabelCol

__all__ = ["ChiSqTest"]


def _chi2_from_contingency(table: np.ndarray):
    """(r, c) observed counts -> (statistic, dof), exact host arithmetic."""
    total = table.sum()
    expected = (table.sum(1, keepdims=True) * table.sum(0, keepdims=True)
                / max(total, 1.0))
    # cells with zero expectation contribute nothing (their observed is 0
    # too, since a zero row/col sum forces zero observed)
    diff = table - expected
    stat = float(np.where(expected > 0,
                          diff * diff / np.maximum(expected, 1e-12),
                          0.0).sum())
    r_eff = int(np.any(table > 0, axis=1).sum())
    c_eff = int(np.any(table > 0, axis=0).sum())
    return stat, max((r_eff - 1) * (c_eff - 1), 0)


def _p_values(stats: np.ndarray, dofs: np.ndarray) -> np.ndarray:
    """Survival function of chi^2_dof at stat, vectorized over features in
    host float64: Q(dof/2, stat/2)."""
    stats = np.asarray(stats, np.float64)
    dofs = np.asarray(dofs, np.float64)
    return np.where(dofs > 0,
                    gammaincc(np.maximum(dofs, 1.0) / 2.0, stats / 2.0),
                    1.0)


class ChiSqTest(HasFeaturesCol, HasLabelCol, AlgoOperator):
    """transform(table) -> one Table with a row per feature column:
    (featureIndex, pValue, degreesOfFreedom, statistic).  Features and label
    must be categorical (their distinct values index the contingency
    table)."""

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()])
        y_raw = np.asarray(table[self.get_label_col()])
        _, y = np.unique(y_raw, return_inverse=True)
        n_label = int(y.max()) + 1 if len(y) else 0

        stats, dofs = [], []
        for j in range(X.shape[1]):
            _, xj = np.unique(X[:, j], return_inverse=True)
            n_feat = int(xj.max()) + 1 if len(xj) else 0
            contingency = np.bincount(
                xj * n_label + y, minlength=n_feat * n_label).reshape(
                    n_feat, n_label).astype(np.float64)
            stat, dof = _chi2_from_contingency(contingency)
            stats.append(stat)
            dofs.append(dof)

        ps = (_p_values(np.asarray(stats), np.asarray(dofs)) if stats
              else np.zeros(0))

        return [Table({
            "featureIndex": np.arange(X.shape[1], dtype=np.int64),
            "pValue": np.asarray(ps, np.float64),
            "degreesOfFreedom": np.asarray(dofs, np.int64),
            "statistic": np.asarray(stats, np.float64),
        })]
