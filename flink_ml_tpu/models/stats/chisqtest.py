"""ChiSqTest — Pearson's chi-squared independence test, feature vs label.

Member of the Flink ML 2.x stats surface.  AlgoOperator: one output row per
feature column with (pValue, degreesOfFreedom, statistic).

TPU-native shape: for each categorical feature, the contingency table is a
one-hot^T @ one-hot MXU matmul over the batch; the p-value is the
regularized upper incomplete gamma ``Q(df/2, x/2)``
(``jax.scipy.special.gammaincc``) evaluated on device.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import AlgoOperator
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.shared import HasFeaturesCol, HasLabelCol

__all__ = ["ChiSqTest"]


@jax.jit
def _chi2_from_contingency(table):
    """(r, c) observed counts -> (statistic, dof)."""
    total = jnp.sum(table)
    row = jnp.sum(table, axis=1, keepdims=True)
    col = jnp.sum(table, axis=0, keepdims=True)
    expected = row * col / jnp.maximum(total, 1.0)
    # cells with zero expectation contribute nothing (their observed is 0
    # too, since a zero row/col sum forces zero observed)
    diff = table - expected
    stat = jnp.sum(jnp.where(expected > 0, diff * diff
                             / jnp.maximum(expected, 1e-12), 0.0))
    r_eff = jnp.sum(jnp.any(table > 0, axis=1))
    c_eff = jnp.sum(jnp.any(table > 0, axis=0))
    dof = jnp.maximum((r_eff - 1) * (c_eff - 1), 0)
    return stat, dof


@jax.jit
def _p_value(stat, dof):
    """Survival function of chi^2_dof at stat: Q(dof/2, stat/2)."""
    return jnp.where(dof > 0,
                     jax.scipy.special.gammaincc(
                         jnp.maximum(dof, 1) / 2.0, stat / 2.0),
                     1.0)


class ChiSqTest(HasFeaturesCol, HasLabelCol, AlgoOperator):
    """transform(table) -> one Table with a row per feature column:
    (featureIndex, pValue, degreesOfFreedom, statistic).  Features and label
    must be categorical (their distinct values index the contingency
    table)."""

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()])
        y_raw = np.asarray(table[self.get_label_col()])
        _, y = np.unique(y_raw, return_inverse=True)
        n_label = int(y.max()) + 1 if len(y) else 0
        y_hot = jax.nn.one_hot(jnp.asarray(y), n_label, dtype=jnp.float32)

        stats, dofs, ps = [], [], []
        for j in range(X.shape[1]):
            _, xj = np.unique(X[:, j], return_inverse=True)
            n_feat = int(xj.max()) + 1 if len(xj) else 0
            x_hot = jax.nn.one_hot(jnp.asarray(xj), n_feat,
                                   dtype=jnp.float32)
            contingency = x_hot.T @ y_hot                  # (r, c) MXU
            stat, dof = _chi2_from_contingency(contingency)
            stats.append(float(stat))
            dofs.append(int(dof))
            ps.append(float(_p_value(stat, dof)))

        return [Table({
            "featureIndex": np.arange(X.shape[1], dtype=np.int64),
            "pValue": np.asarray(ps, np.float64),
            "degreesOfFreedom": np.asarray(dofs, np.int64),
            "statistic": np.asarray(stats, np.float64),
        })]
