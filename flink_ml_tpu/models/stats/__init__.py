from .chisqtest import ChiSqTest  # noqa: F401
