from .anovatest import ANOVATest  # noqa: F401
from .chisqtest import ChiSqTest  # noqa: F401
from .fvaluetest import FValueTest  # noqa: F401
