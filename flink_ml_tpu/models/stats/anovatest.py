"""ANOVATest — one-way analysis-of-variance F-test, feature vs label.

Member of the Flink ML 2.x stats surface (the reference snapshot's lib is
KMeans-only — SURVEY §2.8; this mirrors the library line's
``org.apache.flink.ml.stats`` package).  AlgoOperator: one output row per
feature column with (pValue, degreesOfFreedom, fValue).

TPU split: the O(n*d*k) per-class reductions are two one-hot matmuls on
device (labels one-hot (n,k) against the globally-centered features and
their squares — centering first keeps the f32 sums cancellation-safe),
while the final F ratio and its survival-function p-value run on host in
float64 (same stance as ChiSqTest: the p-value column must carry true
float64 precision).
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from scipy.special import fdtrc

from ...api.stage import AlgoOperator
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.shared import HasFeaturesCol, HasLabelCol

__all__ = ["ANOVATest", "anova_f_scores", "f_p_values"]


@jax.jit
def _class_moments(X, onehot):
    """Center features globally, then per-class sum / sum-of-squares via
    one-hot matmuls (the MXU path): returns (counts (k,), s (k,d), sq (k,d),
    total_sq (d,))."""
    Xc = X - jnp.mean(X, axis=0, keepdims=True)
    s = onehot.T @ Xc                      # (k, d) per-class sums
    sq = onehot.T @ (Xc * Xc)              # (k, d) per-class sq sums
    counts = jnp.sum(onehot, axis=0)       # (k,)
    return counts, s, sq, jnp.sum(Xc * Xc, axis=0)


def f_p_values(f: np.ndarray, dfn: np.ndarray, dfd: np.ndarray) -> np.ndarray:
    """Survival function of F(dfn, dfd) at f, host float64."""
    f = np.asarray(f, np.float64)
    valid = (np.asarray(dfn) > 0) & (np.asarray(dfd) > 0) & np.isfinite(f)
    return np.where(valid,
                    fdtrc(np.maximum(dfn, 1), np.maximum(dfd, 1),
                          np.maximum(f, 0.0)),
                    1.0)


def anova_f_scores(X: np.ndarray, y: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray, int, int]:
    """(f_values (d,), p_values (d,), dfn, dfd) for continuous features X
    against categorical labels y."""
    X = np.asarray(X, np.float64)
    _, y_idx = np.unique(np.asarray(y), return_inverse=True)
    n, d = X.shape
    k = int(y_idx.max()) + 1 if n else 0
    if k < 2 or n - k < 1:
        ones = np.ones(d)
        return np.zeros(d), ones, max(k - 1, 0), max(n - k, 0)

    onehot = jnp.asarray(np.eye(k, dtype=np.float32)[y_idx])
    counts, s, sq, total_sq = (np.asarray(a, np.float64) for a in
                               _class_moments(jnp.asarray(X, jnp.float32),
                                              onehot))
    nz = np.maximum(counts, 1.0)[:, None]
    ss_between = np.sum(s * s / nz, axis=0)        # Σ_g n_g (μ_g - μ)^2
    ss_within = np.maximum(total_sq - ss_between, 0.0)
    dfn, dfd = k - 1, n - k
    with np.errstate(divide="ignore", invalid="ignore"):
        f = (ss_between / dfn) / np.maximum(ss_within / dfd, 1e-300)
    f = np.where(np.isfinite(f), f, np.inf)
    return f, f_p_values(f, np.full(d, dfn), np.full(d, dfd)), dfn, dfd


class ANOVATest(HasFeaturesCol, HasLabelCol, AlgoOperator):
    """transform(table) -> one Table with a row per feature column:
    (featureIndex, pValue, degreesOfFreedom, fValue).  Features are
    continuous, the label categorical."""

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        y = np.asarray(table[self.get_label_col()])
        f, p, dfn, dfd = anova_f_scores(X, y)
        d = X.shape[1]
        return [Table({
            "featureIndex": np.arange(d, dtype=np.int64),
            "pValue": np.asarray(p, np.float64),
            "degreesOfFreedom": np.full(d, dfn + dfd, np.int64),
            "fValue": np.asarray(f, np.float64),
        })]
