"""OnlineLogisticRegression — streaming FTRL-proximal training.

BASELINE.json config 4: the unbounded-iteration capability
(``Iterations.iterateUnboundedStreams``, ``Iterations.java:118-127``).  The
reference's unbounded semantics — "epoch = one window of the stream, model
versions emitted continuously" — map to the hosted iteration driver with an
iterator data source: each epoch consumes one mini-batch from the stream,
runs one jitted FTRL update (weights + accumulators stay in HBM between
batches), and periodically snapshots a model version (the analog of the
model-data output stream).

FTRL-Proximal (per McMahan et al., the standard formulation):
    sigma = (sqrt(n + g^2) - sqrt(n)) / alpha
    z    += g - sigma * w
    n    += g^2
    w     = 0                                   if |z| <= l1
          = -(z - sign(z) l1) / ((beta + sqrt(n))/alpha + l2)   otherwise
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator
from ...data.stream import windows_of
from ...data.table import Table
from ...iteration import (
    EpochContext,
    IterationBodyResult,
    IterationConfig,
    IterationListener,
    iterate,
)
from ...params.param import FloatParam, IntParam, ParamValidators
from ..common.linear import check_sparse_indices, resolve_features
from ...params.shared import (
    HasElasticNet,
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasNumFeatures,
    HasRegParam,
    HasWeightCol,
)
from .logisticregression import LogisticRegressionModel
from ..common.sgd import DEFAULT_GLOBAL_BATCH, LinearState

__all__ = ["OnlineLogisticRegression", "OnlineLogisticRegressionModel"]


class OnlineLogisticRegressionModel(LogisticRegressionModel):
    """A LogisticRegressionModel that also carries the model version (the
    analog of the versioned model-data stream) and the full version history
    captured during streaming fit."""

    def __init__(self):
        super().__init__()
        self.model_version = 0
        self.version_history: List[LinearState] = []


class OnlineLogisticRegression(HasFeaturesCol, HasLabelCol, HasWeightCol,
                               HasGlobalBatchSize, HasRegParam, HasElasticNet,
                               HasNumFeatures,
                               Estimator[OnlineLogisticRegressionModel]):
    ALPHA = FloatParam("alpha", "FTRL alpha (learning-rate scale).",
                       default=0.1, validator=ParamValidators.gt(0))
    BETA = FloatParam("beta", "FTRL beta (learning-rate smoothing).",
                      default=0.1, validator=ParamValidators.gt_eq(0))
    MODEL_SAVE_INTERVAL = IntParam(
        "modelSaveInterval",
        "Emit a model version every N batches.",
        default=1, validator=ParamValidators.gt(0))

    def get_alpha(self) -> float:
        return self.get(OnlineLogisticRegression.ALPHA)

    def set_alpha(self, v: float):
        return self.set(OnlineLogisticRegression.ALPHA, v)

    def get_beta(self) -> float:
        return self.get(OnlineLogisticRegression.BETA)

    def set_beta(self, v: float):
        return self.set(OnlineLogisticRegression.BETA, v)

    def __init__(self):
        super().__init__()
        self._initial_model: Optional[np.ndarray] = None

    def set_initial_model_data(self, table: Table) -> "OnlineLogisticRegression":
        """Warm-start coefficients (the reference's setInitialModelData)."""
        self._initial_model = np.asarray(table["coefficients"][0], np.float64)
        return self

    # -- streaming fit ------------------------------------------------------
    def _batches(self, source) -> Iterator[tuple]:
        """Normalise the input into an iterator of host batches:
        ``("dense", X, y, w)`` or ``("sparse", (idx, vals), y, w, dim)``
        (hashed pair columns / SparseVector rows — the Criteo shape)."""
        feat, lab = self.get_features_col(), self.get_label_col()
        wcol = self.get_weight_col()
        batch = self.get_global_batch_size() or DEFAULT_GLOBAL_BATCH

        def extract(t: Table):
            kind, feats = resolve_features(t, feat)
            y = np.asarray(t[lab], np.float32)
            w = (np.asarray(t[wcol], np.float32) if wcol
                 else np.ones_like(y))
            if kind == "mixed":
                # FTRL's update is (indices, values)-shaped; re-encode the
                # mixed layout as dense slots [0, nd) + unit-value hashed
                dense, cat = feats
                nd = dense.shape[1]
                idx = np.concatenate(
                    [np.broadcast_to(np.arange(nd, dtype=np.int32),
                                     dense.shape), cat], axis=1)
                vals = np.concatenate(
                    [dense, np.ones(cat.shape, np.float32)], axis=1)
                return ("sparse", (idx, vals), y, w, 0)
            if kind == "sparse":
                idx, vals, dim = feats
                return ("sparse", (idx, vals), y, w, dim)
            return ("dense", feats.astype(np.float32), y, w, 0)

        for t in windows_of(source, batch):
            yield extract(t)

    def fit(self, *inputs, **kwargs) -> OnlineLogisticRegressionModel:
        """``fit(stream)`` where stream is a Table (windowed by
        globalBatchSize) or any iterable of Tables (a live unbounded feed).
        Returns when the stream ends; the model then holds the latest
        version plus history.

        ``checkpoint`` / ``resume`` (keyword-only) make the streaming fit
        restartable: the FTRL state and the SOURCE CURSOR checkpoint
        together (the reference's exactly-once posture, §3.4); on resume
        the stream repositions before any window is pulled.  For a
        genuinely live (non-replayable) feed, wrap it in
        :class:`flink_ml_tpu.data.wal.WindowLog` so
        consumed-but-uncheckpointed windows replay from its write-ahead
        log.  Checkpointed fits must ``set_num_features`` (sniffing the
        width would consume a live window before the cursor restores).
        A resumed fit's ``version_history`` holds only post-resume
        versions (earlier versions were emitted to the crashed process);
        ``model_version`` still counts all epochs."""
        (source,) = inputs
        checkpoint = kwargs.pop("checkpoint", None)
        resume = bool(kwargs.pop("resume", False))
        if kwargs:
            raise TypeError(f"unexpected kwargs: {sorted(kwargs)}")
        if checkpoint is not None:
            from ...data.stream import ensure_cursor_source

            source = ensure_cursor_source(
                source, self.get_global_batch_size() or DEFAULT_GLOBAL_BATCH)
        reg, alpha_mix = self.get_reg(), self.get_elastic_net()
        l1, l2 = reg * alpha_mix, reg * (1.0 - alpha_mix)
        alpha, beta = self.get_alpha(), self.get_beta()

        d = self.get_num_features()
        lead: list = []   # sniffed batches replayed ahead of the stream
        if not d:
            if checkpoint is not None:
                raise ValueError(
                    "checkpointed streaming fit needs set_num_features: "
                    "sniffing the feature width would consume a window "
                    "before the checkpoint cursor repositions the stream")
            batches = self._batches(source)
            first = next(batches, None)
            if first is None:
                raise ValueError(
                    "OnlineLogisticRegression.fit got an empty stream")
            if first[0] == "sparse":
                d = first[4]
                if not d:
                    raise ValueError(
                        "hashed pair-column input needs numFeatures (the "
                        "hash-space size); call set_num_features")
            else:
                d = first[1].shape[1]
            lead = [first]
        else:
            batches = None   # built lazily inside the adapter

        sparse_step = _make_sparse_ftrl_step(alpha, beta, l1, l2)
        dense_step = _make_ftrl_step(alpha, beta, l1, l2)

        w0 = (np.zeros((d,), np.float32) if self._initial_model is None
              else self._initial_model.astype(np.float32))
        state0 = {
            "w": jnp.asarray(w0),
            "z": jnp.zeros((d,), jnp.float32),
            "n": jnp.zeros((d,), jnp.float32),
        }

        kind_seen: dict = {}

        def payloads():
            stream = batches if batches is not None \
                else self._batches(source)
            import itertools
            for kind, feats, y, w, *_ in itertools.chain(lead, stream):
                sparse = kind == "sparse"
                if kind_seen.setdefault("sparse", sparse) != sparse:
                    raise ValueError(
                        "stream switched between dense and sparse features "
                        "mid-flight")
                if sparse:
                    check_sparse_indices(feats[0], d)
                elif feats.shape[1] != d:
                    raise ValueError(
                        f"dense stream width {feats.shape[1]} != "
                        f"numFeatures {d}; fix set_num_features (or unset "
                        "it to sniff the width)")
                yield feats, y, w

        def body(state, epoch, data):
            feats, y, w = data
            # pytree structure picks the kernel at trace time
            if isinstance(feats, tuple):
                idx, vals = feats
                new_state, loss = sparse_step(
                    state, jnp.asarray(idx), jnp.asarray(vals),
                    jnp.asarray(y), jnp.asarray(w))
            else:
                new_state, loss = dense_step(
                    state, jnp.asarray(feats), jnp.asarray(y),
                    jnp.asarray(w))
            return IterationBodyResult(new_state, outputs=loss)

        versions: List[LinearState] = []
        interval = self.get(OnlineLogisticRegression.MODEL_SAVE_INTERVAL)

        class VersionEmitter(IterationListener):
            def on_epoch_watermark_incremented(self, epoch, ctx: EpochContext):
                if (epoch + 1) % interval == 0:
                    w_host = np.asarray(jax.device_get(ctx.state["w"]),
                                        np.float64)
                    versions.append(LinearState(w_host, 0.0))

        from ...data.stream import cursor_adapter

        result = iterate(
            body, state0, cursor_adapter(source, payloads),
            config=IterationConfig(mode="hosted", jit=True),
            listeners=[VersionEmitter()],
            checkpoint=checkpoint, resume=resume,
        )
        if result.num_epochs == 0:
            # a real resume always lands at >= 1 (saves fire only after an
            # epoch), so zero epochs means an empty stream either way
            raise ValueError("OnlineLogisticRegression.fit got an empty stream")

        final_w = np.asarray(jax.device_get(result.state["w"]), np.float64)
        model = OnlineLogisticRegressionModel()
        model.copy_params_from(self)
        model._state = LinearState(final_w, 0.0)
        model.model_version = result.num_epochs
        model.version_history = versions
        return model


def _make_ftrl_step(alpha: float, beta: float, l1: float, l2: float):
    """One jitted FTRL-proximal update on a (possibly ragged, host-fed)
    batch.  Batches of differing sizes trigger at most one compile per
    distinct size; the final ragged batch is the only odd one out."""

    @jax.jit
    def step(state, X, y, sample_w):
        w, z, n = state["w"], state["z"], state["n"]
        margin = X @ w
        p = jax.nn.sigmoid(margin)
        weight_sum = jnp.maximum(jnp.sum(sample_w), 1e-12)
        g = X.T @ ((p - y) * sample_w) / weight_sum
        loss = (-jnp.sum(sample_w * (y * jnp.log(p + 1e-12)
                                     + (1 - y) * jnp.log(1 - p + 1e-12)))
                / weight_sum)

        sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / alpha
        z = z + g - sigma * w
        n = n + g * g
        new_w = jnp.where(
            jnp.abs(z) <= l1,
            0.0,
            -(z - jnp.sign(z) * l1) / ((beta + jnp.sqrt(n)) / alpha + l2))
        return {"w": new_w, "z": z, "n": n}, loss

    return step


def _make_sparse_ftrl_step(alpha: float, beta: float, l1: float, l2: float):
    """FTRL update for hashed ``(indices, values)`` batches: the gradient is
    one scatter-add into the dense coordinate space, after which the update
    is the standard per-coordinate FTRL formula — coordinates with g=0 are
    exact fixed points (sigma=0, z and n unchanged), so the dense formula IS
    the classic sparse/lazy FTRL, with O(d) elementwise work kept on-device
    in HBM."""

    @jax.jit
    def step(state, idx, vals, y, sample_w):
        w, z, n = state["w"], state["z"], state["n"]
        margin = jnp.sum(vals * w[idx], axis=-1)
        p = jax.nn.sigmoid(margin)
        weight_sum = jnp.maximum(jnp.sum(sample_w), 1e-12)
        r = (p - y) * sample_w / weight_sum
        g = jnp.zeros_like(w).at[idx.reshape(-1)].add(
            (vals * r[:, None]).reshape(-1))
        loss = (-jnp.sum(sample_w * (y * jnp.log(p + 1e-12)
                                     + (1 - y) * jnp.log(1 - p + 1e-12)))
                / weight_sum)

        sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / alpha
        z = z + g - sigma * w
        n = n + g * g
        new_w = jnp.where(
            jnp.abs(z) <= l1,
            0.0,
            -(z - jnp.sign(z) * l1) / ((beta + jnp.sqrt(n)) / alpha + l2))
        return {"w": new_w, "z": z, "n": n}, loss

    return step
