"""KNN classifier — brute-force k-nearest-neighbour voting on the MXU.

Part of the Flink ML 2.x library line (the reference snapshot ships only
KMeans).  CPU KNN implementations index (KD-trees etc.) to avoid the O(n*q)
distance matrix; on TPU the matrix IS the fast path — one MXU matmul per
query chunk via the shared ``DistanceMeasure.pairwise`` — so "fit" is just
storing the training set and "transform" is pairwise + ``lax.top_k`` +
one-hot vote.  Queries run in fixed-size chunks so the (chunk, n_train)
distance tile is bounded and the jit cache sees one shape.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator, Model
from ...data.table import Table
from ...distance import DistanceMeasure
from ...linalg import stack_vectors
from ...params.param import IntParam, ParamValidators
from ...params.shared import (
    HasDistanceMeasure,
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
)
from ...utils import persist

__all__ = ["KNNClassifier", "KNNClassifierModel"]

_QUERY_CHUNK = 4096


class KNNModelParams(HasDistanceMeasure, HasFeaturesCol, HasPredictionCol):
    K = IntParam("k", "Number of nearest neighbours to vote.", default=5,
                 validator=ParamValidators.gt_eq(1))

    def get_k(self) -> int:
        return self.get(KNNModelParams.K)

    def set_k(self, value: int):
        return self.set(KNNModelParams.K, value)


class KNNParams(KNNModelParams, HasLabelCol):
    pass


@partial(jax.jit, static_argnums=(0, 1, 2))
def _vote(measure: DistanceMeasure, k: int, n_classes: int,
          queries, train, train_cls):
    """(chunk, d) queries -> (chunk,) winning class index.  Ties in the vote
    resolve to the smallest class index (argmax-first semantics)."""
    dists = measure.pairwise(queries, train)                 # (chunk, n)
    _, idx = jax.lax.top_k(-dists, k)                        # k smallest
    votes = jax.nn.one_hot(train_cls[idx], n_classes)        # (chunk, k, c)
    return jnp.argmax(jnp.sum(votes, axis=1), axis=1)


class KNNClassifierModel(KNNModelParams, Model):
    def __init__(self):
        super().__init__()
        self._train: Optional[np.ndarray] = None     # (n, d)
        self._classes: Optional[np.ndarray] = None   # (n,) dense class ids
        self._labels: Optional[np.ndarray] = None    # original label values

    def set_model_data(self, *inputs) -> "KNNClassifierModel":
        # Two tables: per-row (features, classes) and per-class (labels) —
        # different leading dims, so they cannot share one Table.
        train_t, labels_t = inputs
        self._train = np.asarray(train_t["features"], np.float32)
        self._classes = np.asarray(train_t["classes"], np.int32)
        self._labels = np.asarray(labels_t["labels"])
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"features": self._train, "classes": self._classes}),
                Table({"labels": self._labels})]

    def _require_model(self) -> None:
        if self._train is None:
            raise RuntimeError("KNNClassifierModel has no model data; call "
                               "set_model_data() or fit a KNNClassifier "
                               "first")

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        measure = DistanceMeasure.get_instance(self.get_distance_measure())
        k = min(self.get_k(), len(self._train))
        X = stack_vectors(table[self.get_features_col()]).astype(np.float32)
        train = jnp.asarray(self._train)
        train_cls = jnp.asarray(self._classes)
        n_classes = len(self._labels)

        preds = np.empty((len(X),), np.int64)
        # Bucket the chunk to powers of two so small tables of varying sizes
        # share a handful of cached jit shapes instead of recompiling per
        # query count.
        chunk = min(_QUERY_CHUNK,
                    1 << max(int(np.ceil(np.log2(max(len(X), 1)))), 0))
        for start in range(0, len(X), chunk):
            q = X[start:start + chunk]
            if len(q) < chunk:  # pad to the one cached jit shape
                q = np.concatenate(
                    [q, np.zeros((chunk - len(q), X.shape[1]), np.float32)])
            got = np.asarray(_vote(measure, k, n_classes, jnp.asarray(q),
                                   train, train_cls))
            preds[start:start + chunk] = got[: len(X) - start]
        return [table.with_column(self.get_prediction_col(),
                                  self._labels[preds])]

    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {
            "features": self._train, "classes": self._classes,
            "labels": self._labels})

    @classmethod
    def load(cls, path: str) -> "KNNClassifierModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._train = data["features"].astype(np.float32)
        model._classes = data["classes"].astype(np.int32)
        model._labels = data["labels"]
        return model


class KNNClassifier(KNNParams, Estimator[KNNClassifierModel]):
    """fit = remember the training table (dense class ids + label mapping)."""

    def fit(self, *inputs) -> KNNClassifierModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float32)
        if len(X) == 0:
            raise ValueError("KNNClassifier.fit requires at least one row")
        y_raw = np.asarray(table[self.get_label_col()])
        labels, classes = np.unique(y_raw, return_inverse=True)

        model = KNNClassifierModel()
        model.copy_params_from(self)
        model._train = X
        model._classes = classes.astype(np.int32)
        model._labels = labels
        return model

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)

    @classmethod
    def load(cls, path: str) -> "KNNClassifier":
        return persist.load_stage_param(path)
