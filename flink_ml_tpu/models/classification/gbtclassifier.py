"""GBTClassifier — binary gradient-boosted trees, logistic loss.

Member of the later Flink ML 2.x library line.  See
``models/common/gbt.py`` for the TPU-native histogram trainer.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ...data.table import Table
from ...utils import persist
from ..common.gbt_stage import GBTEstimatorBase, GBTModelBase

__all__ = ["GBTClassifier", "GBTClassifierModel"]


def _sigmoid(x: np.ndarray) -> np.ndarray:
    return 0.5 * (1.0 + np.tanh(0.5 * x))


class GBTClassifierModel(GBTModelBase):
    def __init__(self):
        super().__init__()
        self._labels = np.asarray([0.0, 1.0])

    # -- model data: forest table + label-mapping table ---------------------
    def set_model_data(self, *inputs) -> "GBTClassifierModel":
        forest_t, labels_t = inputs
        super().set_model_data(forest_t)
        self._labels = np.asarray(labels_t["labels"])
        return self

    def get_model_data(self) -> List[Table]:
        return super().get_model_data() + [Table({"labels": self._labels})]

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        margins = self._margins(table)
        probs = _sigmoid(margins)
        pred = self._labels[(probs > 0.5).astype(np.int64)]
        out = table.with_column(self.get_prediction_col(), pred)
        return [out.with_column("rawPrediction", probs)]

    def save(self, path: str) -> None:
        super().save(path)
        persist.save_model_arrays(path, "labels", {"labels": self._labels})

    @classmethod
    def load(cls, path: str) -> "GBTClassifierModel":
        model = super().load(path)
        model._labels = persist.load_model_arrays(path, "labels")["labels"]
        return model


class GBTClassifier(GBTEstimatorBase):
    model_cls = GBTClassifierModel

    def _prepare_labels(self, y_raw: np.ndarray):
        labels, y = np.unique(y_raw, return_inverse=True)
        if len(labels) != 2:
            raise ValueError(
                f"GBTClassifier is binary; got {len(labels)} label values")
        return y.astype(np.float64), labels

    def _grad_hess(self, y, pred):
        p = _sigmoid(pred)
        return p - y, np.maximum(p * (1.0 - p), 1e-12)

    def _base_score(self, y) -> float:
        p = np.clip(y.mean(), 1e-6, 1 - 1e-6)
        return float(np.log(p / (1.0 - p)))

    def _finalize_model(self, model, label_values) -> None:
        model._labels = label_values
