"""GBTClassifier — gradient-boosted trees, binary (logistic loss) or
multiclass (softmax objective, one tree per class per round).

Member of the later Flink ML 2.x library line.  See
``models/common/gbt.py`` for the TPU-native histogram trainer.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...data.table import Table
from ...linalg import stack_vectors
from ...utils import persist
from ..common.gbt import (
    SoftmaxForest,
    _softmax_rows,
    predict_forest_softmax,
    train_forest_softmax,
)
from ..common.gbt_stage import GBTEstimatorBase, GBTModelBase


__all__ = ["GBTClassifier", "GBTClassifierModel"]


def _sigmoid(x: np.ndarray) -> np.ndarray:
    return 0.5 * (1.0 + np.tanh(0.5 * x))


class GBTClassifierModel(GBTModelBase):
    def __init__(self):
        super().__init__()
        self._labels = np.asarray([0.0, 1.0])
        self._soft: Optional[SoftmaxForest] = None   # multiclass forest

    def _require_model(self) -> None:
        if self._soft is None:
            super()._require_model()

    # -- model data: forest table + label-mapping table ---------------------
    def set_model_data(self, *inputs) -> "GBTClassifierModel":
        forest_t, labels_t = inputs
        # installing either representation fully replaces the other — a
        # stale forest from a previous set/fit must never answer transform()
        self._soft = None
        self._forest = None
        if "nClasses" in forest_t:
            k = int(np.asarray(forest_t["nClasses"])[0])
            feat = np.asarray(forest_t["feature"], np.int32)
            nodes = feat.shape[-1]
            self._soft = SoftmaxForest(
                feature=feat.reshape(-1, k, nodes),
                threshold=np.asarray(forest_t["threshold"],
                                     np.int32).reshape(-1, k, nodes),
                value=np.asarray(forest_t["value"],
                                 np.float32).reshape(-1, k, nodes),
                bin_edges=np.asarray(forest_t["binEdges"][0], np.float64),
                base_scores=np.asarray(forest_t["baseScores"][0], np.float64),
                learning_rate=float(np.asarray(forest_t["learningRate"])[0]),
            )
        else:
            super().set_model_data(forest_t)
        self._labels = np.asarray(labels_t["labels"])
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        if self._soft is None:
            return super().get_model_data() + [Table({"labels": self._labels})]
        f = self._soft
        n_trees, k, nodes = f.feature.shape
        forest_t = Table({
            "feature": f.feature.reshape(n_trees * k, nodes),
            "threshold": f.threshold.reshape(n_trees * k, nodes),
            "value": f.value.reshape(n_trees * k, nodes),
            "binEdges": np.broadcast_to(
                f.bin_edges[None], (n_trees * k,) + f.bin_edges.shape).copy(),
            "baseScores": np.broadcast_to(
                f.base_scores[None], (n_trees * k, k)).copy(),
            "learningRate": np.full((n_trees * k,), f.learning_rate),
            "nClasses": np.full((n_trees * k,), k, np.int64),
        })
        return [forest_t, Table({"labels": self._labels})]

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        if self._soft is not None:
            X = stack_vectors(table[self.get_features_col()]).astype(
                np.float64)
            probs = _softmax_rows(predict_forest_softmax(X, self._soft))
            pred = self._labels[np.argmax(probs, axis=1)]
        else:
            margins = self._margins(table)
            probs = _sigmoid(margins)
            pred = self._labels[(probs > 0.5).astype(np.int64)]
        out = table.with_column(self.get_prediction_col(), pred)
        return [out.with_column("rawPrediction", probs)]

    def save(self, path: str) -> None:
        if self._soft is None:
            super().save(path)
        else:
            f = self._soft
            persist.save_metadata(self, path, {"nClasses": f.n_classes})
            persist.save_model_arrays(path, "model", {
                "feature": f.feature, "threshold": f.threshold,
                "value": f.value, "binEdges": f.bin_edges,
                "baseScores": f.base_scores,
                "scalars": np.asarray([f.learning_rate])})
        persist.save_model_arrays(path, "labels", {"labels": self._labels})

    @classmethod
    def load(cls, path: str) -> "GBTClassifierModel":
        meta = persist.load_metadata(path)
        if "nClasses" in meta:
            model = persist.load_stage_param(path)
            data = persist.load_model_arrays(path, "model")
            model._soft = SoftmaxForest(
                feature=data["feature"].astype(np.int32),
                threshold=data["threshold"].astype(np.int32),
                value=data["value"].astype(np.float32),
                bin_edges=data["binEdges"].astype(np.float64),
                base_scores=data["baseScores"].astype(np.float64),
                learning_rate=float(data["scalars"][0]),
            )
        else:
            model = super().load(path)
        model._labels = persist.load_model_arrays(path, "labels")["labels"]
        return model


class GBTClassifier(GBTEstimatorBase):
    model_cls = GBTClassifierModel

    def fit(self, *inputs):
        (table,) = inputs
        labels, y_ids = np.unique(np.asarray(table[self.get_label_col()]),
                                  return_inverse=True)
        if len(labels) <= 2:
            return super().fit(table)   # binary: shared logistic path
        # multiclass: softmax objective, one tree per class per round
        X = stack_vectors(table[self.get_features_col()]).astype(np.float64)
        forest = train_forest_softmax(X, y_ids, len(labels), self._config())
        model = self.model_cls()
        model.copy_params_from(self)
        model._soft = forest
        model._labels = labels
        return model

    def _prepare_labels(self, y_raw: np.ndarray):
        labels, y = np.unique(y_raw, return_inverse=True)
        if len(labels) != 2:
            raise ValueError(
                f"GBTClassifier binary path needs 2 label values; got "
                f"{len(labels)}")
        return y.astype(np.float64), labels

    def _grad_hess(self, y, pred):
        p = _sigmoid(pred)
        return p - y, np.maximum(p * (1.0 - p), 1e-12)

    def _streaming_labels(self, y_raw: np.ndarray) -> np.ndarray:
        y = np.asarray(y_raw, np.float64)
        bad = ~np.isin(y, (0.0, 1.0))
        if bad.any():
            raise ValueError(
                "fit_outofcore needs 0/1 labels (a streamed fit cannot "
                f"inventory arbitrary label values); got {y[bad][:3]}")
        return y

    def _streaming_label_values(self):
        return np.asarray([0.0, 1.0])

    def _base_score(self, y) -> float:
        p = np.clip(y.mean(), 1e-6, 1 - 1e-6)
        return float(np.log(p / (1.0 - p)))

    def _finalize_model(self, model, label_values) -> None:
        model._labels = label_values
