"""SoftmaxRegression — multinomial logistic regression.

Part of the Flink ML 2.x library line (the reference snapshot ships only
KMeans; its binary LogisticRegression sibling here generalizes to K classes).
Reuses the fused mini-batch SGD core (``models/common/sgd.py``) verbatim:
the scores are one MXU matmul ``X @ W + b`` with ``W`` a (features, classes)
matrix, the loss is weighted cross-entropy, the gradient psum over the mesh's
data axis is inserted by XLA.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator, Model
from ...data.table import Table
from ...linalg import stack_vectors
from ...models.common.losses import _weighted_mean
from ...models.common.sgd import SGDConfig, sgd_fit_params
from ...params.shared import (
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasPredictionCol,
    HasRawPredictionCol,
    HasRegParam,
    HasSeed,
    HasTol,
    HasWeightCol,
)
from ...utils import persist

__all__ = ["SoftmaxRegression", "SoftmaxRegressionModel"]


def softmax_xent_loss(scores, labels, weights):
    """Weighted cross-entropy; ``labels`` arrive as f32 class ids (the SGD
    epoch tensor's dtype) and are cast back to indices here."""
    logp = jax.nn.log_softmax(scores, axis=-1)
    idx = labels.astype(jnp.int32)
    nll = -jnp.take_along_axis(logp, idx[:, None], axis=1)[:, 0]
    return _weighted_mean(nll, weights)


class SoftmaxRegressionModelParams(HasFeaturesCol, HasPredictionCol,
                                   HasRawPredictionCol):
    pass


class SoftmaxRegressionParams(SoftmaxRegressionModelParams, HasLabelCol,
                              HasWeightCol, HasMaxIter, HasLearningRate,
                              HasRegParam, HasGlobalBatchSize, HasTol,
                              HasSeed):
    pass


@jax.jit
def _jit_probs(X, W, b):
    return jax.nn.softmax(X @ W + b, axis=-1)


class SoftmaxRegressionModel(SoftmaxRegressionModelParams, Model):
    """Prediction = original label value of the argmax class; the raw
    prediction column holds the full per-class probability vectors."""

    def __init__(self):
        super().__init__()
        self._weights: Optional[np.ndarray] = None   # (features, classes)
        self._bias: Optional[np.ndarray] = None      # (classes,)
        self._labels: Optional[np.ndarray] = None    # original label values

    def set_model_data(self, *inputs) -> "SoftmaxRegressionModel":
        (t,) = inputs
        self._weights = np.asarray(t["coefficients"][0], np.float64)
        self._bias = np.asarray(t["intercepts"][0], np.float64)
        self._labels = np.asarray(t["labels"][0])
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"coefficients": self._weights[None],
                       "intercepts": self._bias[None],
                       "labels": self._labels[None]})]

    def _require_model(self) -> None:
        if self._weights is None:
            raise RuntimeError(
                "SoftmaxRegressionModel has no model data; call "
                "set_model_data() or fit a SoftmaxRegression first")

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        X = stack_vectors(table[self.get_features_col()]).astype(np.float32)
        probs = np.asarray(_jit_probs(
            jnp.asarray(X), jnp.asarray(self._weights, jnp.float32),
            jnp.asarray(self._bias, jnp.float32)))
        pred = self._labels[np.argmax(probs, axis=1)]
        out = table.with_column(self.get_prediction_col(), pred)
        return [out.with_column(self.get_raw_prediction_col(),
                                probs.astype(np.float64))]

    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {
            "coefficients": self._weights, "intercepts": self._bias,
            "labels": self._labels})

    @classmethod
    def load(cls, path: str) -> "SoftmaxRegressionModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._weights = data["coefficients"].astype(np.float64)
        model._bias = data["intercepts"].astype(np.float64)
        model._labels = data["labels"]
        return model


class SoftmaxRegression(SoftmaxRegressionParams,
                        Estimator[SoftmaxRegressionModel]):
    def fit(self, *inputs) -> SoftmaxRegressionModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()]).astype(np.float32)
        y_raw = np.asarray(table[self.get_label_col()])
        labels, y = np.unique(y_raw, return_inverse=True)
        if len(labels) < 2:
            raise ValueError("SoftmaxRegression requires >= 2 distinct "
                             f"label values, got {len(labels)}")
        sample_w = (np.asarray(table[self.get_weight_col()], np.float64)
                    if self.get_weight_col() else None)

        d, c = X.shape[1], len(labels)
        config = SGDConfig(
            learning_rate=self.get_learning_rate(),
            reg=self.get_reg(),
            global_batch_size=self.get_global_batch_size(),
            max_epochs=self.get_max_iter(),
            tol=self.get_tol(),
            seed=self.get_seed(),
        )
        params, _ = sgd_fit_params(
            softmax_xent_loss, X, y.astype(np.float64), sample_w, config,
            init_params={"w": jnp.zeros((d, c), jnp.float32),
                         "b": jnp.zeros((c,), jnp.float32)})

        model = SoftmaxRegressionModel()
        model.copy_params_from(self)
        model.set_model_data(Table({
            "coefficients": np.asarray(params["w"], np.float64)[None],
            "intercepts": np.asarray(params["b"], np.float64)[None],
            "labels": labels[None]}))
        return model

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)

    @classmethod
    def load(cls, path: str) -> "SoftmaxRegression":
        return persist.load_stage_param(path)
