from .logisticregression import LogisticRegression, LogisticRegressionModel  # noqa: F401
from .linearsvc import LinearSVC, LinearSVCModel  # noqa: F401
from .naivebayes import NaiveBayes, NaiveBayesModel  # noqa: F401
from .online_logisticregression import (  # noqa: F401
    OnlineLogisticRegression,
    OnlineLogisticRegressionModel,
)
from .softmaxregression import (  # noqa: F401
    SoftmaxRegression,
    SoftmaxRegressionModel,
)
from .knn import KNNClassifier, KNNClassifierModel  # noqa: F401
from .gbtclassifier import GBTClassifier, GBTClassifierModel  # noqa: F401
from .onevsrest import OneVsRest, OneVsRestModel  # noqa: F401
