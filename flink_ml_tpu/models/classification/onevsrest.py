"""OneVsRest — binary-to-multiclass meta-estimator.

Beyond-reference surface (the flink-ml snapshot has no meta-classifier;
the Spark ML `OneVsRest` shape): K one-vs-all copies of any binary
estimator train against indicator labels, and prediction is the argmax
of the per-class raw scores.  TPU note: each per-class fit is its own
jitted program over the SAME epoch tensors — the host relabeling is the
only per-class data work.

The base estimator must emit a raw-score column (set
``rawPredictionCol``; LogisticRegression and LinearSVC both do)."""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ...api.stage import Estimator, Model
from ...data.table import Table
from ...params.shared import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasRawPredictionCol,
)
from ...utils import persist

__all__ = ["OneVsRest", "OneVsRestModel"]


class OneVsRestModel(HasFeaturesCol, HasLabelCol, HasPredictionCol,
                     HasRawPredictionCol, Model):
    """Holds K fitted binary models + the label inventory; transform
    appends argmax predictions (original label values) and, when
    ``rawPredictionCol`` is set, the (n, K) score matrix."""

    def __init__(self):
        super().__init__()
        self.models: List[Model] = []
        self.label_values: Optional[np.ndarray] = None

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        if not self.models:
            raise ValueError("OneVsRestModel has no fitted sub-models")
        n = table.num_rows
        scores = []
        for sub in self.models:
            raw_col = sub.get_raw_prediction_col()
            (out,) = sub.transform(table)
            raw = np.asarray(out[raw_col], np.float64)
            if raw.shape not in ((n,), (n, 1)):
                raise ValueError(
                    f"base classifier raw column has shape {raw.shape}; "
                    "OneVsRest needs ONE score per row (shape (n,) or "
                    "(n, 1)) — a multiclass base does not compose")
            scores.append(raw.reshape(n))
        score_mat = np.stack(scores, axis=1)           # (n, K)
        pred_idx = np.argmax(score_mat, axis=1)
        pred = self.label_values[pred_idx]
        result = table.with_column(self.get_prediction_col(), pred)
        raw_col = self.get_raw_prediction_col()
        if raw_col:
            result = result.with_column(raw_col, score_mat)
        return [result]

    def save(self, path: str) -> None:
        import os

        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "labels",
                                  {"label_values": self.label_values})
        for i, sub in enumerate(self.models):
            sub.save(os.path.join(path, "models", f"{i:03d}"))

    @classmethod
    def load(cls, path: str) -> "OneVsRestModel":
        import os

        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "labels")
        model.label_values = data["label_values"]
        models_dir = os.path.join(path, "models")
        model.models = [
            persist.load_stage(os.path.join(models_dir, name))
            for name in sorted(os.listdir(models_dir))]
        return model


class OneVsRest(HasFeaturesCol, HasLabelCol, HasPredictionCol,
                HasRawPredictionCol, Estimator[OneVsRestModel]):
    """fit(table): one binary model per distinct label value (label k
    becomes 1, the rest 0).  The base estimator is a python object (set
    via ``set_classifier``), like CrossValidator's estimator."""

    def __init__(self, classifier=None):
        super().__init__()
        self._classifier = classifier

    def set_classifier(self, est) -> "OneVsRest":
        self._classifier = est
        return self

    def fit(self, *inputs) -> OneVsRestModel:
        (table,) = inputs
        if self._classifier is None:
            raise ValueError("OneVsRest needs set_classifier")
        y_raw = np.asarray(table[self.get_label_col()])
        label_values = np.unique(y_raw)
        if len(label_values) < 2:
            raise ValueError(
                f"OneVsRest needs >= 2 label values, got {label_values}")

        from ...api.model_selection import _clone_with

        models: List[Model] = []
        for value in label_values:
            sub_est = _clone_with(self._classifier, {})
            sub_est.set_label_col(self.get_label_col())
            sub_est.set_features_col(self.get_features_col())
            if not sub_est.get_raw_prediction_col():
                raise ValueError(
                    "the base classifier must set rawPredictionCol (the "
                    "per-class scores drive the argmax)")
            indicator = (y_raw == value).astype(np.float64)
            relabeled = table.with_column(self.get_label_col(), indicator)
            models.append(sub_est.fit(relabeled))

        model = OneVsRestModel()
        model.copy_params_from(self)
        model.models = models
        model.label_values = label_values
        return model

    def save(self, path: str) -> None:
        import os

        persist.save_metadata(self, path)
        if self._classifier is not None:
            self._classifier.save(os.path.join(path, "classifier"))

    @classmethod
    def load(cls, path: str) -> "OneVsRest":
        import os

        est = persist.load_stage_param(path)
        clf_dir = os.path.join(path, "classifier")
        if os.path.isdir(clf_dir):
            est._classifier = persist.load_stage(clf_dir)
        return est