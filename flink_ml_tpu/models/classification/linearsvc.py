"""LinearSVC — linear support-vector classifier (hinge loss).

BASELINE.json config 3; same fused-SGD skeleton as LogisticRegression.
Decision threshold on the margin is configurable (flink-ml's
``HasThreshold``-style param)."""

from __future__ import annotations

import numpy as np

from ...params.param import FloatParam
from ..common.linear import LinearEstimatorBase, LinearModelBase

__all__ = ["LinearSVC", "LinearSVCModel"]


class _HasThreshold:
    THRESHOLD = FloatParam(
        "threshold", "Decision threshold on the margin.", default=0.0)

    def get_threshold(self) -> float:
        return self.get(_HasThreshold.THRESHOLD)

    def set_threshold(self, value: float):
        return self.set(_HasThreshold.THRESHOLD, value)


class LinearSVCModel(_HasThreshold, LinearModelBase):
    loss_name = "hinge"

    def _decision(self, margins: np.ndarray) -> np.ndarray:
        return (margins > self.get_threshold()).astype(np.int64)


class LinearSVC(_HasThreshold, LinearEstimatorBase):
    """Labels are {0, 1} (converted to +-1 inside the hinge loss)."""

    loss_name = "hinge"
    model_cls = LinearSVCModel
