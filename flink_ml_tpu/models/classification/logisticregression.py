"""LogisticRegression — binary classifier, bounded-iteration SGD.

Capability target from BASELINE.json config 1 ("LogisticRegression (binary,
bounded-iteration SGD)"), with the param surface of flink-ml's linear
models.  The training loop is the shared fused SGD skeleton
(:mod:`flink_ml_tpu.models.common.sgd`): gradient psum over the mesh's data
axis replaces the reference's network-shuffled reduce, and weights stay in
HBM across epochs.
"""

from __future__ import annotations

import numpy as np

from ..common.linear import LinearEstimatorBase, LinearModelBase

__all__ = ["LogisticRegression", "LogisticRegressionModel"]


def _sigmoid(m: np.ndarray) -> np.ndarray:
    out = np.empty_like(m)
    pos = m >= 0
    out[pos] = 1.0 / (1.0 + np.exp(-m[pos]))
    e = np.exp(m[~pos])
    out[~pos] = e / (1.0 + e)
    return out


class LogisticRegressionModel(LinearModelBase):
    loss_name = "logistic"

    def _decision(self, margins: np.ndarray) -> np.ndarray:
        return (margins > 0).astype(np.int64)

    def _raw(self, margins: np.ndarray) -> np.ndarray:
        """Probability of the positive class."""
        return _sigmoid(margins)


class LogisticRegression(LinearEstimatorBase):
    """Labels are {0, 1} (converted to +-1 inside the logistic loss)."""

    loss_name = "logistic"
    model_cls = LogisticRegressionModel
