"""Multinomial Naive Bayes.

Part of the early Flink ML 2.x library surface (the reference snapshot ships
only KMeans, but the lib module is explicitly "the algorithm library" —
SURVEY §2.8).  TPU-native shape: smoothing-adjusted log-likelihoods are a
(classes, features) matrix, so scoring a batch is one MXU matmul
``X @ log_theta.T + log_prior``.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...api.stage import Estimator, Model
from ...data.table import Table
from ...linalg import stack_vectors
from ...params.param import FloatParam, ParamValidators
from ...params.shared import HasFeaturesCol, HasLabelCol, HasPredictionCol
from ...utils import persist

__all__ = ["NaiveBayes", "NaiveBayesModel"]


class NaiveBayesParams(HasFeaturesCol, HasLabelCol, HasPredictionCol):
    SMOOTHING = FloatParam("smoothing", "Laplace smoothing.", default=1.0,
                           validator=ParamValidators.gt_eq(0))

    def get_smoothing(self) -> float:
        return self.get(NaiveBayesParams.SMOOTHING)

    def set_smoothing(self, value: float):
        return self.set(NaiveBayesParams.SMOOTHING, value)


@jax.jit
def _scores(X, log_theta, log_prior):
    # With smoothing=0, log_theta holds -inf for zero-count features and a
    # zero count must contribute 0 — but 0 * -inf = nan through the matmul.
    # Clamping -inf to the most-negative finite float keeps the single MXU
    # matmul: count 0 contributes exactly 0, while any positive count
    # overflows back to -inf (the correct "impossible class" score).
    log_theta = jnp.maximum(log_theta, jnp.finfo(log_theta.dtype).min)
    return X @ log_theta.T + log_prior[None, :]


class NaiveBayesModel(NaiveBayesParams, Model):
    def __init__(self):
        super().__init__()
        self._log_theta: Optional[np.ndarray] = None   # (classes, features)
        self._log_prior: Optional[np.ndarray] = None   # (classes,)
        self._labels: Optional[np.ndarray] = None      # original label values

    def set_model_data(self, *inputs) -> "NaiveBayesModel":
        (t,) = inputs
        self._log_theta = np.asarray(t["logTheta"][0], np.float64)
        self._log_prior = np.asarray(t["logPrior"][0], np.float64)
        self._labels = np.asarray(t["labels"][0])
        return self

    def _require_model(self) -> None:
        if self._log_theta is None:
            raise RuntimeError("NaiveBayesModel has no model data; call "
                               "set_model_data() or fit a NaiveBayes first")

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"logTheta": self._log_theta[None],
                       "logPrior": self._log_prior[None],
                       "labels": self._labels[None]})]

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        X = stack_vectors(table[self.get_features_col()]).astype(np.float32)
        if np.any(X < 0):
            raise ValueError("Multinomial NaiveBayes requires non-negative "
                             "features (counts)")
        scores = np.asarray(_scores(
            jnp.asarray(X),
            jnp.asarray(self._log_theta, jnp.float32),
            jnp.asarray(self._log_prior, jnp.float32)))
        pred = self._labels[np.argmax(scores, axis=1)]
        return [table.with_column(self.get_prediction_col(), pred)]

    def save(self, path: str) -> None:
        self._require_model()
        persist.save_metadata(self, path)
        persist.save_model_arrays(path, "model", {
            "logTheta": self._log_theta, "logPrior": self._log_prior,
            "labels": self._labels})

    @classmethod
    def load(cls, path: str) -> "NaiveBayesModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model._log_theta = data["logTheta"].astype(np.float64)
        model._log_prior = data["logPrior"].astype(np.float64)
        model._labels = data["labels"]
        return model


class NaiveBayes(NaiveBayesParams, Estimator[NaiveBayesModel]):
    def fit(self, *inputs) -> NaiveBayesModel:
        (table,) = inputs
        X = stack_vectors(table[self.get_features_col()])
        if np.any(X < 0):
            raise ValueError("Multinomial NaiveBayes requires non-negative "
                             "features (counts)")
        y = np.asarray(table[self.get_label_col()])
        labels, inverse = np.unique(y, return_inverse=True)
        smoothing = self.get_smoothing()

        n_classes, n_features = len(labels), X.shape[1]
        counts = np.zeros((n_classes, n_features))
        np.add.at(counts, inverse, X)
        class_counts = np.bincount(inverse, minlength=n_classes)

        theta_num = counts + smoothing
        theta_den = counts.sum(axis=1, keepdims=True) + smoothing * n_features
        with np.errstate(divide="ignore"):
            # smoothing=0 legitimately yields log(0) = -inf: an unseen
            # feature/class pair has exactly zero likelihood, and -inf scores
            # propagate correctly through the argmax (tested).
            log_theta = np.log(theta_num) - np.log(theta_den)
            log_prior = np.log(class_counts) - np.log(class_counts.sum())

        model = NaiveBayesModel()
        model.copy_params_from(self)
        model._log_theta = log_theta
        model._log_prior = log_prior
        model._labels = labels
        return model
