"""GBTRegressor — gradient-boosted trees, squared loss.

Member of the later Flink ML 2.x library line.  See
``models/common/gbt.py`` for the TPU-native histogram trainer.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ...data.table import Table
from ..common.gbt_stage import GBTEstimatorBase, GBTModelBase

__all__ = ["GBTRegressor", "GBTRegressorModel"]


class GBTRegressorModel(GBTModelBase):
    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        self._require_model()
        return [table.with_column(self.get_prediction_col(),
                                  self._margins(table))]


class GBTRegressor(GBTEstimatorBase):
    model_cls = GBTRegressorModel

    def _prepare_labels(self, y_raw: np.ndarray):
        return np.asarray(y_raw, np.float64), None

    def _grad_hess(self, y, pred):
        return pred - y, np.ones_like(pred)

    def _base_score(self, y) -> float:
        return float(y.mean())
