"""LinearRegression — least-squares regression via the fused SGD skeleton.

BASELINE.json config 3 (flink-ml-lib regressors)."""

from __future__ import annotations

import numpy as np

from ..common.linear import LinearEstimatorBase, LinearModelBase

__all__ = ["LinearRegression", "LinearRegressionModel"]


class LinearRegressionModel(LinearModelBase):
    loss_name = "squared"

    def _decision(self, margins: np.ndarray) -> np.ndarray:
        return margins  # the prediction IS the margin


class LinearRegression(LinearEstimatorBase):
    loss_name = "squared"
    model_cls = LinearRegressionModel
