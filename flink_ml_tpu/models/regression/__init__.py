from .linearregression import LinearRegression, LinearRegressionModel  # noqa: F401
from .gbtregressor import GBTRegressor, GBTRegressorModel  # noqa: F401
