from .linearregression import LinearRegression, LinearRegressionModel  # noqa: F401
