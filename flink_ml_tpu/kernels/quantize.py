"""Per-channel max-abs int8 calibration for the serving path (ISSUE 18).

The serving-side counterpart of the wire-side quantizers (``parallel/
collectives.py`` ``quantized_all_reduce`` / ``fixed_point_all_reduce``):
the same ``scale = max|w| / 127`` contract, applied to *published model
params* instead of gradient blocks.  Calibration is data-free — scales
derive from the params alone, so they are captured wherever the params
are bound to a servable (``_KernelServable._build_kernel`` /
``CachedWideDeepServable._bind``).  Because ``rebind()`` re-runs those
bind paths on every delta publish, each generation re-derives its scales
from its own params — stale scales never serve (ARCHITECTURE.md "Int8
serving").

What never quantizes: biases and intercepts (``b``, ``wide_b``,
``mlp[i]["b"]``), the categorical id ``offsets`` (exact int adds), and
activations — int8 here is WEIGHT-ONLY storage compression.  The
compute contract is "dequantize then run the f32 expression": codes are
deterministic round-to-nearest at calibration time, dequantization is
one exact ``int8 -> f32`` cast and one f32 multiply, so a generation's
scores are bit-stable call-to-call (the hot-swap atomicity tests rely
on this) while agreeing with f32 only to the accuracy envelope the
parity matrix gates (rank/decision agreement, not bitwise).

Quantization (host, numpy — publish time, off the serving path) and
dequantization (jnp — traced into the serving kernels) are split so the
dequant helpers can ride inside jitted programs.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

__all__ = [
    "Q_MAX", "maxabs_scales", "quantize_channelwise", "dequantize",
    "quantize_rows", "dequantize_rows", "quantize_stage_params",
    "quantize_widedeep_rest", "dequantize_widedeep_rest",
    "quantized_ops",
]

#: symmetric int8 code range — ±127 (−128 unused, matching the wire
#: quantizers: a symmetric grid keeps dequantization a single multiply)
Q_MAX = 127.0


def _expand(scales: np.ndarray, ndim: int, axis: int):
    shape = [1] * ndim
    shape[axis] = -1
    return scales.reshape(shape)


def maxabs_scales(w: np.ndarray, channel_axis: Optional[int] = None
                  ) -> np.ndarray:
    """Per-channel (or per-tensor when ``channel_axis is None``) max-abs
    scales.  All-zero channels get scale 1.0 — their codes are all zero
    either way, and a zero scale would NaN the dequantized weights."""
    w = np.asarray(w, np.float32)
    if channel_axis is None:
        m = float(np.max(np.abs(w))) if w.size else 0.0
        return np.float32(m / Q_MAX if m > 0.0 else 1.0)
    axis = channel_axis % w.ndim
    reduce_axes = tuple(a for a in range(w.ndim) if a != axis)
    m = np.max(np.abs(w), axis=reduce_axes) if w.size \
        else np.zeros((w.shape[axis],), np.float32)
    scales = (m / Q_MAX).astype(np.float32)
    scales[scales == 0.0] = np.float32(1.0)
    return scales


def quantize_channelwise(w: np.ndarray,
                         channel_axis: Optional[int] = None
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """``w -> (codes int8, scales f32)`` with deterministic
    round-to-nearest-even (``np.rint``).  Stochastic rounding is the
    right call on the gradient wire (unbiased accumulation); for
    serving, determinism IS the contract — same params, same codes."""
    w = np.asarray(w, np.float32)
    scales = maxabs_scales(w, channel_axis)
    denom = scales if channel_axis is None \
        else _expand(scales, w.ndim, channel_axis % w.ndim)
    codes = np.clip(np.rint(w / denom), -Q_MAX, Q_MAX).astype(np.int8)
    return codes, scales


def dequantize(codes, scales, channel_axis: Optional[int] = None):
    """jnp dequantize — traced into serving kernels.  Exact cast + one
    f32 multiply; broadcast the per-channel scales along
    ``channel_axis``."""
    c = jnp.asarray(codes).astype(jnp.float32)
    if channel_axis is None:
        return c * scales
    axis = channel_axis % c.ndim
    shape = [1] * c.ndim
    shape[axis] = c.shape[axis]
    return c * jnp.reshape(jnp.asarray(scales), shape)


def quantize_rows(table: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-ROW calibration for gather-served tables (embeddings,
    centroids): one scale per leading-axis row, so a gathered row
    dequantizes from its own codes + its own scale — the layout the
    ``EmbeddingRowCache`` int8 pools store block-wise."""
    return quantize_channelwise(table, channel_axis=0)


def dequantize_rows(row_codes, row_scales):
    """Dequantize already-GATHERED rows: ``row_codes (..., row_dim)``
    with one scale per row (``row_scales (...,)``).  This is the
    gather-then-dequantize order — the full f32 table never
    materializes, on the cache hit path or off it."""
    return (jnp.asarray(row_codes).astype(jnp.float32)
            * jnp.asarray(row_scales)[..., None])


# ---------------------------------------------------------------------------
# per-op calibration recipes
# ---------------------------------------------------------------------------

def _q_tensor(w, channel_axis=None) -> Dict[str, np.ndarray]:
    codes, scales = quantize_channelwise(w, channel_axis)
    return {"q": codes, "s": scales}


def _q_linear(params: Dict[str, Any]) -> Dict[str, Any]:
    # vector w: one per-tensor scale (the single output channel);
    # multiclass (d, k): per-output-class scales on axis 1
    w = np.asarray(params["w"], np.float32)
    axis = None if w.ndim == 1 else 1
    return {"w": _q_tensor(w, axis),
            "b": np.asarray(params["b"], np.float32)}


def _q_kmeans(params: Dict[str, Any]) -> Dict[str, Any]:
    # centroids (k, d): per-centroid-row scales, so each centroid's
    # distance error is bounded by its own magnitude
    return {"centroids": _q_tensor(params["centroids"], 0)}


def quantize_widedeep_rest(net: Dict[str, Any]) -> Dict[str, Any]:
    """Quantize the NON-TABLE WideDeep leaves (``wide_dense`` /
    ``mlp`` matrices; ``wide_b`` and biases pass through) — shared by
    the full ``widedeep_scores`` recipe and the embedding-row cache's
    int8 servable, whose tables live in the cache pools instead."""
    return {
        "wide_dense": _q_tensor(net["wide_dense"]),
        "wide_b": np.asarray(net["wide_b"], np.float32),
        # mlp matrices: per-output-channel (axis 1); biases stay f32
        "mlp": [{"w": _q_tensor(layer["w"], 1),
                 "b": np.asarray(layer["b"], np.float32)}
                for layer in net["mlp"]],
    }


def dequantize_widedeep_rest(qrest: Dict[str, Any]) -> Dict[str, Any]:
    """jnp inverse of :func:`quantize_widedeep_rest` — the param dict
    ``forward_from_rows`` consumes, rebuilt in-program."""
    return {
        "wide_dense": dequantize(qrest["wide_dense"]["q"],
                                 qrest["wide_dense"]["s"]),
        "wide_b": qrest["wide_b"],
        "mlp": [{"w": dequantize(layer["w"]["q"], layer["w"]["s"], 1),
                 "b": layer["b"]} for layer in qrest["mlp"]],
    }


def _q_widedeep(params: Dict[str, Any]) -> Dict[str, Any]:
    net = params["net"]
    qnet = quantize_widedeep_rest(net)
    # 1-d tables get one per-tensor scale (a per-row scale on scalar
    # rows would cost MORE than the f32 it replaces); emb (V, E) goes
    # per-row — gathered rows dequantize locally
    qnet["wide_cat"] = _q_tensor(net["wide_cat"])
    qnet["emb"] = _q_tensor(net["emb"], 0)
    return {"net": qnet, "offsets": np.asarray(params["offsets"])}


#: op label -> calibration recipe; the keys double as the authoritative
#: list of serving ops with an "int8" registry backend
_RECIPES = {
    "linear_margins": _q_linear,
    "kmeans_assign": _q_kmeans,
    "widedeep_scores": _q_widedeep,
}


def quantized_ops() -> Tuple[str, ...]:
    """Ops with a publish-time int8 calibration recipe."""
    return tuple(sorted(_RECIPES))


def quantize_stage_params(op: str, params: Dict[str, Any]
                          ) -> Dict[str, Any]:
    """Calibrate + quantize a stage kernel's f32 param pytree into the
    pytree the op's "int8" registry backend expects.  KeyError for ops
    without a recipe — the servable surfaces that as "precision not
    supported" at bind time, not as a crash mid-serve."""
    try:
        recipe = _RECIPES[op]
    except KeyError:
        raise KeyError(
            f"no int8 calibration recipe for op {op!r} (have "
            f"{quantized_ops()}); serve this model at f32") from None
    return recipe(params)
