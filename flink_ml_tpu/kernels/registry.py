"""Unified kernel registry — ONE compiled surface for pipelines, serving,
and training (ROADMAP item 5).

Three kernel notions grew up independently in this repo: chain
``StageKernel`` segments (``api/chain.py``, PR 4), serving bucketed
executors (``serving/executor.py``, PR 2), and the ``ops/`` Pallas
kernels — each with its own dispatch, padding, and caching rules.  This
module collapses them into one registry with two faces:

- **Implementation lookup** (:func:`lookup`): ``(op, schema-signature,
  backend) -> KernelEntry``.  Training step builders resolve their hot
  path here instead of branching on ``use_pallas`` by hand
  (``models/common/sgd.py``'s ELL path, GBT's histogram impl, KMeans'
  fit plan, Wide&Deep's routed table gradient).  A Pallas implementation
  registered once is picked up by every consumer; the XLA lowering
  registered for the same op is the automatic non-TPU fallback (A/B
  parity asserted in ``tests/test_kernels.py``'s matrix).

- **Dispatch surface** (:func:`dispatch`): THE shared plan-static jit
  (moved here from ``api/chain.py``'s segment runner).  A "plan" is a
  tuple of ``(fn, static)`` stage pairs with params as runtime device
  arguments, so chain segments, the specialized serving executors, and
  the models' own predict entry points all hit ONE compile cache: the
  same ``(op, schema, bucket)`` warmed by any consumer is a cache hit
  for the others (lowering-counter-asserted).

Padding is NOT re-decided per consumer: every registered kernel names
one of the two documented contracts in ``utils/padding.py`` — the
masked pad-to-multiple rule (``pad_rows_with_mask``) or the maskless
zero-fill block rule (``pad_rows_to_block`` + the kernel's own
pad-correction), and the dispatch surface pads rows to the shared
power-of-two buckets (``pad_rows_to_bucket``) exactly as the predict
entry points always did.

Observability: compile-count / cache-hit / dispatch-latency gauges live
on :data:`kernel_stats` and publish into any ``MetricGroup`` (serving
endpoints re-export them per batch; ``bench.py::bench_kernels`` reports
them), so cross-consumer compile reuse — CV folds, hot-swap
generations, fused serving — is a measured number.
"""

from __future__ import annotations

import threading
import time

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from ..obs.trace import tracer

__all__ = [
    "KernelEntry",
    "KernelStats",
    "backends",
    "dispatch",
    "dispatch_count",
    "kernel_stats",
    "lookup",
    "ops",
    "register_kernel",
    "tpu_only",
]


def tpu_only() -> bool:
    """The default availability gate for Pallas/MXU-shaped entries."""
    return jax.default_backend() == "tpu"


@dataclass(frozen=True)
class KernelEntry:
    """One registered implementation of an op on one backend.

    ``fn``'s calling convention is per-``convention``:

    - ``"impl"`` — a raw device function; training step builders call
      it inside their own jitted step/scan (the enclosing program is
      the executable).  Most impl ops register ONE uniform signature
      across backends (the ELL ops, ``routed_table_grad``); the KMeans
      PLANNING ops intentionally do not — their backends take genuinely
      different operands (mask vs maskless contract, a measure
      singleton vs euclidean-only), so the lookup is a plan decision
      and the single backend branch lives NEXT TO the registration
      (``models/clustering/kmeans.py``), never at scattered call
      sites.  An op's calling convention is documented at its
      registration.
    - ``"stage"`` — the chain ``StageKernel`` convention
      ``fn(static, params, cols) -> {name: array}``; dispatched through
      the shared plan jit (:func:`dispatch`), where the ``(fn, static)``
      pair IS the compiled-program identity shared across consumers.

    ``supports(sig)`` is the shape/schema contract (e.g. the fused ELL
    kernels need ``rows % 8 == 0``); ``available()`` is the backend
    gate (Pallas entries default to TPU-only).  A *forced* backend
    lookup bypasses ``available`` — tests and bench A/B legs run Pallas
    kernels in interpret mode on CPU — but never ``supports``: a shape
    the kernel cannot express must fail loudly, not fall back silently.
    """

    op: str
    backend: str
    fn: Callable
    priority: int = 0
    supports: Optional[Callable[[tuple], bool]] = None
    available: Optional[Callable[[], bool]] = None
    convention: str = "impl"   # "impl" | "stage"

    def supports_sig(self, sig: tuple) -> bool:
        return self.supports is None or bool(self.supports(sig))

    def is_available(self) -> bool:
        return self.available is None or bool(self.available())


_REGISTRY: Dict[str, Dict[str, KernelEntry]] = {}
_REG_LOCK = threading.Lock()
# Catalog-load state has its OWN (reentrant) lock: the import must not
# run under _REG_LOCK — the catalog's modules call register_kernel,
# which takes it.  RLock so a registering module that itself looks
# something up at import time cannot self-deadlock.
_CATALOG_LOCK = threading.RLock()
_CATALOG_LOADED = [False]


def _ensure_catalog() -> None:
    """Import the modules that register kernels (idempotent, lazy — at
    first lookup, not at package import, so there is no import cycle
    between ``kernels`` and the model/op modules that register into
    it).  Concurrent first lookups serialize on the catalog lock so no
    thread ever reads a half-populated registry, and the loaded flag
    only latches AFTER a successful import — a transient import failure
    surfaces on every lookup until it actually succeeds, instead of
    permanently reporting 'unknown kernel op'."""
    if _CATALOG_LOADED[0]:
        return
    with _CATALOG_LOCK:
        if _CATALOG_LOADED[0]:
            return
        from . import catalog  # noqa: F401  (imports register as a side effect)
        _CATALOG_LOADED[0] = True


def register_kernel(op: str, backend: str, fn: Callable, *,
                    priority: int = 0,
                    supports: Optional[Callable[[tuple], bool]] = None,
                    available: Optional[Callable[[], bool]] = None,
                    convention: str = "impl") -> KernelEntry:
    """Register (or replace — module reloads must not duplicate) the
    implementation of ``op`` on ``backend``."""
    if convention not in ("impl", "stage"):
        raise ValueError(f"unknown convention {convention!r}")
    entry = KernelEntry(op=op, backend=backend, fn=fn, priority=priority,
                        supports=supports, available=available,
                        convention=convention)
    with _REG_LOCK:
        _REGISTRY.setdefault(op, {})[backend] = entry
    return entry


def ops() -> Tuple[str, ...]:
    _ensure_catalog()
    return tuple(sorted(_REGISTRY))


def backends(op: str) -> Tuple[str, ...]:
    _ensure_catalog()
    if op not in _REGISTRY:
        raise KeyError(f"unknown kernel op {op!r}; registered: {ops()}")
    return tuple(sorted(_REGISTRY[op]))


def lookup(op: str, sig: tuple = (), *,
           backend: Optional[str] = None) -> KernelEntry:
    """Resolve ``(op, schema-signature)`` to the best registered entry.

    ``backend`` forces a specific implementation (the bench A/B legs and
    the tests' XLA oracles): availability is bypassed — the caller owns
    running e.g. a Pallas kernel in interpret mode — but a PROVIDED
    ``sig`` still gates through ``supports``, so a shape outside the
    kernel's contract raises instead of silently computing the wrong
    thing.  A forced lookup with no sig returns the entry unchecked
    (the parity matrix probes kernels below their planning thresholds
    on purpose; the kernel's own shape validation still applies at call
    time)."""
    _ensure_catalog()
    table = _REGISTRY.get(op)
    if table is None:
        raise KeyError(f"unknown kernel op {op!r}; registered: {ops()}")
    if backend is not None:
        entry = table.get(backend)
        if entry is None:
            raise KeyError(
                f"op {op!r} has no backend {backend!r}; registered: "
                f"{tuple(sorted(table))}")
        if sig != () and not entry.supports_sig(sig):
            raise ValueError(
                f"op {op!r} backend {backend!r} does not support "
                f"signature {sig!r}")
        return entry
    cands = [e for e in table.values()
             if e.is_available() and e.supports_sig(sig)]
    if not cands:
        raise ValueError(
            f"no available backend of op {op!r} supports signature "
            f"{sig!r} (registered: {tuple(sorted(table))})")
    if len(cands) > 1:
        # a persisted autotune decision beats static priority: the
        # measured-best backend for this (op, sig) on THIS device kind,
        # recorded once by whichever process searched first (no-op —
        # None — when no cache root is configured or nothing is recorded)
        from . import autotune

        tuned = autotune.decided_backend(op, sig)
        if tuned is not None:
            for e in cands:
                if e.backend == tuned:
                    return e
    # deterministic: priority desc, backend name as the tiebreak
    cands.sort(key=lambda e: (-e.priority, e.backend))
    return cands[0]


# --------------------------------------------------------------------------
# observability
# --------------------------------------------------------------------------

class KernelStats:
    """Dispatcher-level accounting: how many distinct ``(plan, shapes)``
    programs compiled, how often later dispatches reused one, and what a
    dispatch costs wall-clock.

    ``compiles`` mirrors the shared jit's cache keying (plan identity +
    operand shapes/dtypes), so "second consumer was a cache hit" is a
    gauge — not only a lowering-counter assertion buried in tests.
    Latency is time-to-return of the (async) dispatch: steady-state it
    is the dispatch overhead, on a cold key it includes the compile
    (which is exactly what an operator wants to see spike)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.compiles = 0
        self.cache_hits = 0
        self.dispatches = 0
        self._lat_ema_ms = 0.0
        self._last_ms = 0.0
        self.per_op: Dict[str, Dict[str, int]] = {}
        #: cache-source accounting (ISSUE 12): where executables came
        #: from — persistent-cache loads vs live compiles — plus the
        #: failure ledger (quarantines never crash, so they MUST count)
        self.aot_hits = 0
        self.aot_misses = 0
        self.aot_stores = 0
        self.aot_store_failed = 0
        self.aot_quarantined = 0
        self.aot_unserializable = 0
        self._aot_load_ms = 0.0
        self._compile_ms = 0.0
        #: autotune decisions observed this process: "op|sig" -> the
        #: chosen backend/block, decision source, and search cost
        self.tuned_ops: Dict[str, Dict[str, Any]] = {}
        #: per-THREAD mirrors of (compiles, aot_hits, cache_hits) — the
        #: warm-up source attribution diffs these, so a hot-swap warming
        #: on the deploy thread is never mislabeled by the old
        #: generation's concurrent serving dispatches
        self._tls = threading.local()

    def _tls_bump(self, field: str) -> None:
        counts = getattr(self._tls, "counts", None)
        if counts is None:
            counts = self._tls.counts = {"compiles": 0, "aot_hits": 0,
                                         "cache_hits": 0}
        counts[field] += 1

    def thread_counts(self) -> Tuple[int, int, int]:
        """(compiles, aot_hits, cache_hits) recorded by THIS thread —
        the race-free warm-up probe (see :meth:`counts` for the
        process-wide view)."""
        counts = getattr(self._tls, "counts", None)
        if counts is None:
            return (0, 0, 0)
        return (counts["compiles"], counts["aot_hits"],
                counts["cache_hits"])

    def record_aot(self, op: str, *, event: str,
                   seconds: float = 0.0) -> None:
        """One persistent-cache event: ``hit`` (deserialized from disk,
        ``seconds`` = load wall), ``miss`` (live compile, ``seconds`` =
        compile wall), ``store``, ``quarantine`` (corrupt/skewed entry
        moved aside), ``unserializable`` (backend refused serialize)."""
        ms = seconds * 1e3
        with self._lock:
            if event == "hit":
                self.aot_hits += 1
                self._aot_load_ms += ms
            elif event == "miss":
                self.aot_misses += 1
                self._compile_ms += ms
            elif event == "store":
                self.aot_stores += 1
            elif event == "store_failed":
                self.aot_store_failed += 1
            elif event == "quarantine":
                self.aot_quarantined += 1
            elif event == "unserializable":
                self.aot_unserializable += 1
            else:
                raise ValueError(f"unknown AOT event {event!r}")
            if event == "hit":
                self._tls_bump("aot_hits")
            if event in ("hit", "miss"):
                rec = self.per_op.setdefault(
                    op, {"dispatches": 0, "compiles": 0, "cache_hits": 0})
                rec["aot_hits"] = rec.get("aot_hits", 0) \
                    + (1 if event == "hit" else 0)
                rec["aot_misses"] = rec.get("aot_misses", 0) \
                    + (1 if event == "miss" else 0)
                which = "aot_load_ms" if event == "hit" else "compile_ms"
                rec[which] = round(rec.get(which, 0.0) + ms, 3)

    def record_autotune(self, op: str, sig: tuple, choice: str, *,
                        kind: str, source: str, search_ms: float,
                        timings: Dict[str, float]) -> None:
        """One autotune resolution: ``source`` "measured" = a fresh
        search ran (and persisted, cache permitting); "cache" = a
        recorded winner was honored with zero search cost."""
        with self._lock:
            self.tuned_ops[f"{op}|{sig!r}"] = {
                "choice": choice, "kind": kind, "source": source,
                "search_ms": round(search_ms, 2), "timings_ms": timings,
            }

    def counts(self) -> Tuple[int, int, int]:
        """(compiles, aot_hits, cache_hits), process-wide.  The serving
        executors' warm-up attribution diffs :meth:`thread_counts`
        instead — this view races with concurrent serving threads."""
        with self._lock:
            return (self.compiles, self.aot_hits, self.cache_hits)

    def record(self, op: str, *, compiled: bool, seconds: float) -> None:
        ms = seconds * 1e3
        with self._lock:
            self.dispatches += 1
            if compiled:
                self.compiles += 1
                self._tls_bump("compiles")
            else:
                self.cache_hits += 1
                self._tls_bump("cache_hits")
            self._last_ms = ms
            self._lat_ema_ms = (0.8 * self._lat_ema_ms + 0.2 * ms
                                if self._lat_ema_ms else ms)
            rec = self.per_op.setdefault(
                op, {"dispatches": 0, "compiles": 0, "cache_hits": 0})
            rec["dispatches"] += 1
            rec["compiles" if compiled else "cache_hits"] += 1

    @property
    def dispatch_latency_ms(self) -> float:
        return self._lat_ema_ms

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "compiles": self.compiles,
                "cache_hits": self.cache_hits,
                "dispatches": self.dispatches,
                "dispatch_latency_ms": round(self._lat_ema_ms, 4),
                "last_dispatch_ms": round(self._last_ms, 4),
                "aot": {
                    "hits": self.aot_hits,
                    "misses": self.aot_misses,
                    "stores": self.aot_stores,
                    "store_failed": self.aot_store_failed,
                    "quarantined": self.aot_quarantined,
                    "unserializable": self.aot_unserializable,
                    "load_ms": round(self._aot_load_ms, 3),
                    "compile_ms": round(self._compile_ms, 3),
                },
                "tuned_ops": {k: dict(v)
                              for k, v in self.tuned_ops.items()},
                "per_op": {k: dict(v) for k, v in self.per_op.items()},
            }

    def publish(self, group) -> None:
        """Refresh gauges on ``group`` (the ``PrefetchStats.publish``
        idiom): serving endpoints re-export the registry's counters into
        their own metric subtree, ``bench.py`` into its report.  The
        cache-source gauges make cold-start composition a measured
        number: ``aot_load_ms`` vs ``compile_ms`` is literally 'what the
        persistent cache saved this process'."""
        snap = self.snapshot()
        for name in ("compiles", "cache_hits", "dispatches",
                     "dispatch_latency_ms", "last_dispatch_ms"):
            group.gauge(name).set(snap[name])
        for name in ("hits", "misses", "stores", "store_failed",
                     "quarantined", "unserializable", "load_ms",
                     "compile_ms"):
            group.gauge(f"aot_{name}").set(snap["aot"][name])
        group.gauge("tuned_ops").set(len(snap["tuned_ops"]))
        group.gauge("ops_seen").set(len(snap["per_op"]))


#: THE process-wide stats instance (one dispatch surface, one ledger).
kernel_stats = KernelStats()


# --------------------------------------------------------------------------
# the shared dispatch surface — ONE jit for every plan
# (moved verbatim from api/chain.py, which now delegates here)
# --------------------------------------------------------------------------

def _run_plan(plan: tuple, params_seq: tuple, one, cols: Dict[str, Any]):
    import jax.numpy as jnp

    out = dict(cols)
    for (fn, static), params in zip(plan, params_seq):
        produced = fn(static, params, out)
        # Rounding barrier: multiply every float output by a RUNTIME 1.0.
        # Without it LLVM contracts elementwise chains across the stage
        # boundary (a trailing mul fused into the next stage's add/sub as
        # one fma), skipping the intermediate rounding the stagewise path
        # performs — 1-ulp drift that breaks bit-exactness.  The compiler
        # cannot fold the mul (the value is a runtime argument), yet any
        # contraction THROUGH it is value-identical: fma(t, 1, c) rounds
        # to exactly t + c.  (jax.lax.optimization_barrier does not help
        # here — XLA duplicates producers into consumer fusions across
        # it.)  Integer columns are exact and pass through untouched.
        out.update({
            name: col * one
            if jnp.issubdtype(jnp.result_type(col), jnp.inexact) else col
            for name, col in produced.items()})
    return out


_ONE = np.float32(1.0)   # the runtime rounding-barrier operand

_JIT_LOCK = threading.Lock()
_PLAN_JIT: list = []


def _plan_jit() -> Callable:
    """The lazily-built shared jit.  static_argnums=0: the plan tuple of
    (fn, static) pairs IS the program identity; params/cols are runtime
    device args — a CrossValidator's k fold models, hot-swapped serving
    generations, and the models' own predict entry points all hit one
    cache entry per (plan, schema, bucket).  On TPU the column dict is
    donated: every consumer's cols are per-call transfer buffers (chain
    segments re-pad per batch, serving pads per request), dead after the
    call — donation lets XLA reuse the HBM allocation.  CPU ignores
    donation, so it is skipped there to avoid spurious warnings (the
    stance ``serving/executor.py`` always took)."""
    if not _PLAN_JIT:
        with _JIT_LOCK:
            if not _PLAN_JIT:
                donate = (3,) if tpu_only() else ()
                _PLAN_JIT.append(jax.jit(_run_plan, static_argnums=(0,),
                                         donate_argnums=donate))
    return _PLAN_JIT[0]


_SEEN_KEYS: set = set()
_DISPATCHES = [0]


def _shape_key(params_seq, cols) -> tuple:
    leaves, treedef = jax.tree_util.tree_flatten((params_seq, cols))
    return (treedef,
            tuple((np.shape(leaf), np.result_type(leaf).str)
                  for leaf in leaves))


_PLAN_KEY_MEMO: Dict[Any, str] = {}


def _persistent_plan_key(cache, plan: tuple, shape_key: tuple) -> str:
    """The durable form of the in-memory dispatch key: plan identity by
    qualified names + bytecode fingerprints (``aot.plan_token``) instead
    of object identity, shapes by their existing repr, the environment
    fingerprint folded in by the cache."""
    memo = (plan, shape_key)
    with _JIT_LOCK:
        key = _PLAN_KEY_MEMO.get(memo)
    if key is None:
        from .aot import plan_token

        treedef, shapes = shape_key
        key = cache.key_for("plan", plan_token(plan),
                            repr((str(treedef), shapes)))
        with _JIT_LOCK:
            _PLAN_KEY_MEMO[memo] = key
    return key


def dispatch(plan: tuple, params_seq: tuple, cols: Dict[str, Any], *,
             op: Optional[str] = None) -> Dict[str, Any]:
    """Run ``plan`` over ``cols`` through THE shared jit, with compile /
    cache-hit / latency accounting.  ``op`` labels the per-op counters
    (defaults to the stage fns' names).

    With a persistent AOT cache configured (``kernels/aot.py``), the
    compiled program for each (plan, shapes) key is held as an explicit
    ``jax.stages.Compiled`` — loaded from the cache dir when a previous
    process already compiled it (cold-start becomes a deserialize),
    compiled-and-stored otherwise.  Either way the executable is the
    SAME lowered program the shared jit would run, so outputs are
    bit-identical across the two paths (asserted in
    ``tests/test_aot_cache.py``)."""
    label = op or "+".join(fn.__name__ for fn, _ in plan)
    key = (plan, _shape_key(params_seq, cols))
    with _JIT_LOCK:
        seen = key in _SEEN_KEYS
        _SEEN_KEYS.add(key)
        _DISPATCHES[0] += 1
    from .aot import active_cache

    cache = active_cache()
    # the dispatch span measures time-to-return of the ASYNC dispatch
    # (the same wall kernel_stats records): steady-state it is the
    # dispatch overhead, on a cold key it includes the compile.  The
    # device-execute completion is a separate, FENCED span recorded by
    # the consumer that fetches the output (api/chain.py::run_kernel) —
    # never a block inside this hot path.
    if cache is None:
        t0 = time.perf_counter()
        with tracer.span("registry_dispatch", cat="kernel", op=label):
            out = _plan_jit()(plan, params_seq, _ONE, cols)
        kernel_stats.record(label, compiled=not seen,
                            seconds=time.perf_counter() - t0)
        return out
    pkey = _persistent_plan_key(cache, plan, key[1])
    compiled, source = cache.load_or_build(
        pkey,
        lambda: _plan_jit().lower(plan, params_seq, _ONE, cols).compile(),
        label=label)
    t0 = time.perf_counter()
    with tracer.span("registry_dispatch", cat="kernel", op=label):
        try:
            out = compiled(params_seq, _ONE, cols)
        except TypeError:
            # an operand aspect the shape key cannot see (weak types)
            # diverged from the lowering — correctness comes first: run the
            # plain jit path for this call, keep the entry for callers it fits
            out = _plan_jit()(plan, params_seq, _ONE, cols)
    kernel_stats.record(label, compiled=(source == "compile"),
                        seconds=time.perf_counter() - t0)
    return out


def dispatch_count() -> int:
    """Shared-jit invocations so far (one per segment/kernel run) — the
    bench_pipeline A/B evidence, previously ``api.chain.dispatch_count``."""
    return _DISPATCHES[0]
