from . import aot, autotune  # noqa: F401
from .registry import (  # noqa: F401
    KernelEntry,
    KernelStats,
    backends,
    dispatch,
    dispatch_count,
    kernel_stats,
    lookup,
    ops,
    register_kernel,
    tpu_only,
)
