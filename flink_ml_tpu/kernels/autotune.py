"""Registry autotuning — measured backend/block choices, persisted.

The registry's ``lookup`` used to pick entries by static priority, and
the Pallas kernels picked their tile sizes by an analytic VMEM descent
(``ops/kmeans_pallas.py::_pick_block``).  Both are guesses about a
machine the process is actually standing on.  This module replaces the
guess with a measurement, once per fleet:

- :func:`choose` times every candidate (one warm-up call so compile cost
  never pollutes the ranking, then best-of-``repeats`` over ``iters``
  calls, device-synced), picks the winner, and commits the decision to
  the AOT cache root (``kernels/aot.py``, ``autotune/`` subdir — same
  durability contract as the executables).
- A recorded decision is honored WITHOUT re-search by every later call
  in this process and by every later process pointed at the cache root:
  ``registry.lookup`` consults :func:`decided_backend` when several
  backends are available for an op, and the block-size pickers consult
  :func:`decided_choice` before re-running the search.
- Decisions are keyed by ``(op, sig)`` + (backend, device kind): a
  decision measured on one chip generation never leaks onto another.
- Everything degrades to the analytic/priority behavior when no cache
  root is configured — autotuning is an opt-in of the same env knob as
  the executable cache.

Accounting rides :data:`~flink_ml_tpu.kernels.registry.kernel_stats`
(``tuned_ops``): which ops were tuned, what won, whether the decision
was measured fresh or loaded, and what the search cost — so the
cold-start composition is a number, not a vibe.
"""

from __future__ import annotations

import time

from typing import Callable, Dict, Optional, Tuple

__all__ = [
    "choose",
    "decided_backend",
    "decided_choice",
    "enabled",
    "measure",
]


def enabled() -> bool:
    """True when a persistent cache root is configured — the autotuner's
    opt-in gate (searches without a place to persist the winner would
    re-pay the search every process, the exact disease this cures)."""
    from .aot import active_cache

    return active_cache() is not None


def _sig_repr(sig: tuple) -> str:
    return repr(tuple(sig))


def get_decision(op: str, sig: tuple = ()) -> Optional[Dict]:
    """The recorded decision for ``(op, sig)``, or None (disabled /
    never measured / measured for a different device)."""
    from .aot import active_cache

    cache = active_cache()
    if cache is None:
        return None
    return cache.get_decision(op, _sig_repr(sig))


def decided_backend(op: str, sig: tuple = ()) -> Optional[str]:
    """The measured-best BACKEND for ``(op, sig)`` — what
    ``registry.lookup`` consults when several entries are available."""
    dec = get_decision(op, sig)
    if dec is not None and dec.get("kind") == "backend":
        return dec["choice"]
    return None


def decided_choice(op: str, sig: tuple = ()) -> Optional[str]:
    """The measured-best choice token of any kind (block sizes record
    ``kind="block"`` with the block as a string token)."""
    dec = get_decision(op, sig)
    return dec["choice"] if dec is not None else None


def measure(candidates: Dict[str, Callable[[], object]], *,
            iters: int = 3, repeats: int = 2) -> Dict[str, float]:
    """Wall-time each candidate thunk: one untimed warm-up call
    (compile + transfer costs stay out of the ranking), then
    best-of-``repeats`` averages over ``iters`` synced calls — the
    ``bench.py::timed`` discipline, so a one-off GC pause cannot crown
    the wrong winner.  Returns ``{name: best_ms_per_call}``."""
    import jax

    timings: Dict[str, float] = {}
    for name, thunk in candidates.items():
        jax.block_until_ready(thunk())          # compile + warm
        best = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = None
            for _ in range(iters):
                out = thunk()
            jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / iters
            best = dt if best is None else min(best, dt)
        timings[name] = best * 1e3
    return timings


def choose(op: str, sig: tuple,
           candidates: Dict[str, Callable[[], object]], *,
           kind: str = "backend", iters: int = 3, repeats: int = 2,
           probe: str = "") -> Tuple[str, Dict]:
    """Resolve ``(op, sig)`` to the measured-best candidate name.

    A recorded decision whose choice is still among ``candidates`` is
    returned WITHOUT running anything (source ``"cache"``).  Otherwise
    every candidate is measured (source ``"measured"``), the winner is
    persisted to the cache root when one is configured, and
    ``kernel_stats.tuned_ops`` records the decision either way.
    ``probe`` documents what the thunks actually ran (shape, rows) so a
    reader of the decision file can judge its transferability."""
    from .aot import active_cache
    from .registry import kernel_stats

    cache = active_cache()
    dec = cache.get_decision(op, _sig_repr(sig)) if cache else None
    if dec is not None and dec.get("choice") in candidates:
        kernel_stats.record_autotune(op, sig, dec["choice"],
                                     kind=dec.get("kind", kind),
                                     source="cache",
                                     search_ms=0.0,
                                     timings=dec.get("timings_ms", {}))
        return dec["choice"], dec
    t0 = time.perf_counter()
    timings = measure(candidates, iters=iters, repeats=repeats)
    search_ms = (time.perf_counter() - t0) * 1e3
    choice = min(timings, key=timings.get)
    decision = {
        "format": 1,
        "op": op,
        "sig": _sig_repr(sig),
        "kind": kind,
        "choice": choice,
        "timings_ms": {k: round(v, 4) for k, v in timings.items()},
        "search_ms": round(search_ms, 2),
        "probe": probe,
        "device": ({"backend": cache.fingerprint["backend"],
                    "device_kind": cache.fingerprint["device_kind"]}
                   if cache else None),
    }
    if cache is not None:
        cache.record_decision(decision)
    kernel_stats.record_autotune(op, sig, choice, kind=kind,
                                 source="measured", search_ms=search_ms,
                                 timings=decision["timings_ms"])
    return choice, decision
